"""TPU erasure-code kernels: GF(2^w) region matmul as bit-plane GF(2)
matrix multiply on the MXU.

Every GF(2^w) multiply-by-constant is linear over GF(2), so an (m x k)
GF coding matrix expands to an (m*w x k*w) 0/1 bitmatrix (the same
expansion jerasure uses for its XOR schedules — see
ceph_tpu.ec.matrices.matrix_to_bitmatrix).  Encoding a batch of chunks
is then

    parity_bits = (B @ data_bits) mod 2

i.e. one int8 matmul on the MXU plus cheap shift/mask pack/unpack on
the VPU — no gathers, no scalar GF tables, batch axis as wide as all
in-flight stripes (the reference's per-4KiB-call path,
src/erasure-code/isa/ErasureCodeIsa.cc:129 ec_encode_data, iterates on
the CPU instead).

Two implementations:
  * encode_xla / make_encoder — pure XLA (unpack, dot_general, pack),
    fused by the compiler; works on any backend.
  * pallas kernel (make_encoder(..., use_pallas=True)) — tiles the
    batch axis and keeps the 8x bit-plane expansion in VMEM only, so
    HBM traffic stays (k+m)/k of the payload.

Decode reuses the same kernel with the inverted matrix (host-side
inversion, cached by erasure signature like ErasureCodeIsaTableCache).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import matrices

# ---------------------------------------------------------------------------
# bit-plane helpers
# ---------------------------------------------------------------------------


def _unpack_bits(data: jax.Array, w: int) -> jax.Array:
    """(k, n) uint8/uint16/uint32 words -> (k*w, n) int8 bit-planes,
    row j*w + x = bit x of word j (matching matrix_to_bitmatrix column
    order)."""
    k, n = data.shape
    d = data.astype(jnp.int32)
    planes = jnp.stack([(d >> x) & 1 for x in range(w)], axis=1)  # (k, w, n)
    return planes.reshape(k * w, n).astype(jnp.int8)


def _pack_bits(bits: jax.Array, w: int, dtype) -> jax.Array:
    """(m*w, n) int32 0/1 -> (m, n) packed words."""
    mw, n = bits.shape
    m = mw // w
    planes = bits.reshape(m, w, n).astype(jnp.uint32)
    weights = jnp.asarray([(1 << x) & 0xFFFFFFFF for x in range(w)],
                          dtype=jnp.uint32)[None, :, None]
    return jnp.sum(planes * weights, axis=1).astype(dtype)


def _word_dtype(w: int):
    return {8: jnp.uint8, 16: jnp.uint16, 32: jnp.uint32}[w]


# ---------------------------------------------------------------------------
# XLA path
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("w",))
def encode_xla(bitmatrix: jax.Array, data: jax.Array, w: int = 8) -> jax.Array:
    """bitmatrix (m*w, k*w) int8; data (k, n) words -> (m, n) words."""
    bits = _unpack_bits(data, w)
    acc = jax.lax.dot_general(
        bitmatrix.astype(jnp.int8), bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return _pack_bits(acc & 1, w, data.dtype)


# ---------------------------------------------------------------------------
# Pallas path (TPU): keep the bit-plane expansion in VMEM
# ---------------------------------------------------------------------------


def _ec_tile_kernel(b_ref, d_ref, o_ref, *, w: int, k: int, m: int):
    d = d_ref[...].astype(jnp.int32)                       # (k, T)
    planes = jnp.stack([(d >> x) & 1 for x in range(w)], axis=1)
    bits = planes.reshape(k * w, d.shape[1]).astype(jnp.int8)
    acc = jax.lax.dot_general(
        b_ref[...], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32) & 1              # (m*w, T)
    pl = acc.reshape(m, w, d.shape[1])
    packed = pl[:, 0, :]
    for x in range(1, w):
        packed = packed | (pl[:, x, :] << x)
    o_ref[...] = packed.astype(o_ref.dtype)


def _encode_pallas(bitmatrix: np.ndarray, w: int, k: int, m: int,
                   tile: int = 16384):
    from jax.experimental import pallas as pl

    bm = jnp.asarray(bitmatrix, dtype=jnp.int8)
    # mosaic lowering is TPU-only; elsewhere run the kernel interpreted
    interpret = jax.default_backend() != "tpu"

    # index maps must yield int32 — under x64 (on for bit-exact CRUSH)
    # plain ints trace as i64, which mosaic cannot legalize
    i32 = jnp.int32

    @jax.jit
    def run(data: jax.Array) -> jax.Array:
        n = data.shape[1]
        pad = (-n) % tile
        if pad:
            data = jnp.pad(data, ((0, 0), (0, pad)))
        np_ = n + pad
        grid = (np_ // tile,)
        kern = functools.partial(_ec_tile_kernel, w=w, k=k, m=m)
        out = pl.pallas_call(
            kern,
            grid=grid,
            in_specs=[
                pl.BlockSpec((m * w, k * w), lambda i: (i32(0), i32(0))),
                pl.BlockSpec((k, tile), lambda i: (i32(0), i32(i))),
            ],
            out_specs=pl.BlockSpec((m, tile), lambda i: (i32(0), i32(i))),
            out_shape=jax.ShapeDtypeStruct((m, np_), data.dtype),
            interpret=interpret,
        )(bm, data)
        return out[:, :n] if pad else out

    return run


# ---------------------------------------------------------------------------
# XOR-schedule kernel on the bit-sliced ("planes8") chunk layout
# ---------------------------------------------------------------------------
#
# The MXU matmul path above is capped by the tiny M=m*w dimension (~5% MXU
# utilization).  The VPU path below reaches HBM bandwidth instead: chunks are
# stored bit-sliced — the same packetized layout jerasure's schedule encode
# uses on disk for cauchy/liberation codes — so encode degenerates to
# full-width vector XORs chosen by the bitmatrix, with no unpacking at all.
#
# planes8 layout of one chunk of L bytes (w=8): bit-plane x (bit x of every
# data byte) is packed little-endian into L/8 bytes and laid out as 8 sublane
# rows of L/64 columns; a chunk is a (64, L/64) uint8 array, a k-chunk stripe
# batch is (k*64, P) with P = total columns.


def bytes_to_planes8(chunks: np.ndarray) -> np.ndarray:
    """(k, L) uint8 byte-layout chunks -> (k*64, L//64) planes8."""
    k, L = chunks.shape
    bits = np.unpackbits(chunks.reshape(k, L, 1), axis=2, bitorder="little")
    planes = []
    for j in range(k):
        for x in range(8):
            pb = np.packbits(bits[j, :, x], bitorder="little")  # (L/8,)
            planes.append(pb.reshape(8, L // 64))
    return np.concatenate(planes, axis=0)


def planes8_to_bytes(planes: np.ndarray, nchunks: int) -> np.ndarray:
    """(nchunks*64, P) planes8 -> (nchunks, P*64) byte-layout chunks."""
    rows, P = planes.shape
    L = P * 64
    out = np.zeros((nchunks, L), dtype=np.uint8)
    for j in range(nchunks):
        byte_bits = np.zeros((L, 8), dtype=np.uint8)
        for x in range(8):
            pb = planes[j * 64 + x * 8:(j * 64) + (x + 1) * 8].reshape(L // 8)
            byte_bits[:, x] = np.unpackbits(pb, bitorder="little")
        out[j] = np.packbits(byte_bits, axis=1, bitorder="little").reshape(L)
    return out


def _xor_schedule_pallas(bitmatrix: np.ndarray, tile: int):
    """Compiled planes8 encode: (in_rows*8, P) -> (out_rows*8, P)."""
    from jax.experimental import pallas as pl

    out_rows, in_rows = bitmatrix.shape
    bm = np.asarray(bitmatrix, dtype=bool)
    interpret = jax.default_backend() != "tpu"
    i32 = jnp.int32

    def kern(d_ref, o_ref):
        for i in range(out_rows):
            srcs = [j for j in range(in_rows) if bm[i, j]]
            if not srcs:
                o_ref[8 * i:8 * i + 8, :] = jnp.zeros(
                    (8, d_ref.shape[1]), dtype=o_ref.dtype)
                continue
            acc = d_ref[8 * srcs[0]:8 * srcs[0] + 8, :]
            for j in srcs[1:]:
                acc = acc ^ d_ref[8 * j:8 * j + 8, :]
            o_ref[8 * i:8 * i + 8, :] = acc

    @jax.jit
    def run(planes: jax.Array) -> jax.Array:
        P = planes.shape[1]
        if P % tile:
            raise ValueError(
                "plane column count %d must be a multiple of tile %d"
                % (P, tile))
        return pl.pallas_call(
            kern,
            grid=(P // tile,),
            in_specs=[pl.BlockSpec((in_rows * 8, tile),
                                   lambda i: (i32(0), i32(i)))],
            out_specs=pl.BlockSpec((out_rows * 8, tile),
                                   lambda i: (i32(0), i32(i))),
            out_shape=jax.ShapeDtypeStruct((out_rows * 8, P), planes.dtype),
            interpret=interpret,
        )(planes)

    return run


class PlanesEncoder:
    """HBM-bandwidth-bound encode/decode on the planes8 layout (w=8).

    `planes` is (k*64, P); returns (m*64, P). Batch many stripes by
    concatenating their chunk planes along the column axis; P must be a
    multiple of `tile`.
    """

    def __init__(self, matrix: list[list[int]], tile: int = 2048):
        self.m = len(matrix)
        self.k = len(matrix[0])
        self.w = 8
        self.matrix = matrix
        self.tile = tile
        self._bitmatrix = np.array(
            matrices.matrix_to_bitmatrix(self.k, self.m, 8, matrix),
            dtype=np.int8)
        self._fn = _xor_schedule_pallas(self._bitmatrix, tile)
        self._decoders: dict[tuple, object] = {}

    def __call__(self, planes: jax.Array) -> jax.Array:
        return self._fn(planes)

    def encode_stripes(self, stripes: np.ndarray) -> np.ndarray:
        """(batch, k, chunk_bytes) byte-layout -> (batch, m, chunk_bytes);
        convenience wrapper that converts layouts on the host."""
        b, k, c = stripes.shape
        if (b * c) % 64:
            raise ValueError(
                "batch*chunk_bytes=%d must be a multiple of 64 for the "
                "planes8 layout" % (b * c))
        planes = bytes_to_planes8(
            stripes.transpose(1, 0, 2).reshape(k, b * c))
        pad = (-planes.shape[1]) % self.tile
        if pad:
            planes = np.pad(planes, ((0, 0), (0, pad)))
        out = np.asarray(self._fn(jnp.asarray(planes)))
        if pad:
            out = out[:, :-pad]
        parity = planes8_to_bytes(out, self.m)   # (m, b*c)
        return parity.reshape(self.m, b, c).transpose(1, 0, 2)

    def decode_rows(self, erased: tuple[int, ...],
                    survivors: tuple[int, ...]):
        """Compiled planes8 reconstruction of `erased` from the first k
        of `survivors` (bit-level inversion, cached per signature)."""
        key = (erased, survivors[:self.k])
        fn = self._decoders.get(key)
        if fn is None:
            k, w = self.k, self.w
            rows = matrices.survivor_bitrows(
                k, w, self._bitmatrix, survivors)
            inv = np.array(matrices.gf2_invert(rows), dtype=np.int8)
            want = []
            for e in erased:
                if e < k:
                    want.extend(inv[e * w:(e + 1) * w])
                else:
                    # parity rows re-encoded through the inverse
                    comp = (self._bitmatrix[(e - k) * w:(e - k + 1) * w]
                            .astype(np.int32) @ inv.astype(np.int32)) & 1
                    want.extend(comp.astype(np.int8))
            fn = _xor_schedule_pallas(np.array(want, dtype=np.int8),
                                      self.tile)
            self._decoders[key] = fn
        return fn


# ---------------------------------------------------------------------------
# Fused byte-layout kernel: in-VMEM planes8 transpose + XOR schedule
# ---------------------------------------------------------------------------
#
# The PlanesEncoder above is HBM-bound but needs its input bit-sliced —
# and the cluster stores shards in ordinary byte layout, so round 3's
# write path fell back to the (MXU-underutilised) matmul kernel at ~5%
# utilisation.  This kernel closes that gap without changing the shard
# layout: chunks stream in byte layout, and the bytes<->planes8
# conversion happens *inside* the kernel as an 8x8 bit transpose done
# with a SWAR butterfly over uint32 lanes (3 masked swap rounds, 72
# vector ops per 8 segment vectors — the in-register transpose8 trick),
# so HBM traffic stays (k+m)/k of payload and the XOR schedule runs on
# full-width vectors.  The intra-kernel plane layout packs bit s from
# lane-segment s rather than from adjacent bytes; any fixed positional
# permutation commutes with the elementwise XOR schedule and the unpack
# butterfly (an involution) restores exact byte order, so outputs are
# bit-identical to the host codecs (pinned by tests).
#
# Replaces the reference's per-call CPU SIMD encode
# (src/erasure-code/isa/ErasureCodeIsa.cc:129 ec_encode_data;
# src/osd/ECBackend.cc:1539 submit_transaction -> ECUtil::encode).

_M4LO = np.uint32(0x0F0F0F0F)
_M4HI = np.uint32(0xF0F0F0F0)
_M2LO = np.uint32(0x33333333)
_M2HI = np.uint32(0xCCCCCCCC)
_M1LO = np.uint32(0x55555555)
_M1HI = np.uint32(0xAAAAAAAA)


def _bit_transpose8(v: list) -> list:
    """8x8 bit transpose across eight uint32 vectors (per byte slot):
    returns t with t[x] byte-bit s == v[s] byte-bit x.  Involution."""
    s4 = np.uint32(4)
    s2 = np.uint32(2)
    s1 = np.uint32(1)
    w = [None] * 8
    for i in range(4):
        a, b = v[i], v[i + 4]
        w[i] = (a & _M4LO) | ((b & _M4LO) << s4)
        w[i + 4] = ((a >> s4) & _M4LO) | (b & _M4HI)
    u = [None] * 8
    for g in (0, 4):
        for i in (0, 1):
            a, b = w[g + i], w[g + i + 2]
            u[g + i] = (a & _M2LO) | ((b & _M2LO) << s2)
            u[g + i + 2] = ((a >> s2) & _M2LO) | (b & _M2HI)
    t = [None] * 8
    for g in (0, 2, 4, 6):
        a, b = u[g], u[g + 1]
        t[g] = (a & _M1LO) | ((b & _M1LO) << s1)
        t[g + 1] = ((a >> s1) & _M1LO) | (b & _M1HI)
    return t


def _fused_xor_pallas(bitmatrix: np.ndarray, tile_lanes: int):
    """Compiled byte-layout encode: (k, P) uint32 -> (m, P) uint32.

    bitmatrix is (m*8, k*8) with col j*8+x = bit x of data chunk j,
    row i*8+y = bit y of parity chunk i (matrix_to_bitmatrix order).
    tile_lanes must be a multiple of 1024 (8 segments x 128 lanes).
    """
    from jax.experimental import pallas as pl

    out_bits, in_bits = bitmatrix.shape
    if out_bits % 8 or in_bits % 8:
        raise ValueError("bitmatrix dims must be multiples of 8")
    k = in_bits // 8
    m = out_bits // 8
    if tile_lanes % 1024:
        raise ValueError("tile_lanes must be a multiple of 1024")
    bm = np.asarray(bitmatrix, dtype=bool)
    interpret = jax.default_backend() != "tpu"
    i32 = jnp.int32
    # Sublane utilization: every ALU op (transpose butterflies and the
    # XOR schedule) runs on (R, seg) operands — R subtiles of each
    # chunk row stacked in sublanes — instead of height-1 rows that
    # would waste 7/8 of the VPU.  Largest R whose segments stay
    # lane-aligned wins.
    R = next(r for r in (8, 4, 2, 1)
             if tile_lanes % (8 * r * 128) == 0)
    seg = tile_lanes // (8 * R)

    def kern(d_ref, o_ref):
        # pack: per chunk row, 8 lane segments per subtile -> planes
        planes = []                      # planes[j][x]: (R, seg)
        for j in range(k):
            v = [jnp.concatenate(
                    [d_ref[j:j + 1, (r * 8 + s) * seg:
                           (r * 8 + s + 1) * seg] for r in range(R)],
                    axis=0) for s in range(8)]
            planes.append(_bit_transpose8(v))
        # XOR schedule on full-height (R, seg) plane blocks
        q = []
        for i in range(out_bits):
            srcs = [c for c in range(in_bits) if bm[i, c]]
            if not srcs:
                q.append(jnp.zeros((R, seg), dtype=jnp.uint32))
                continue
            j, x = divmod(srcs[0], 8)
            acc = planes[j][x]
            for c in srcs[1:]:
                j, x = divmod(c, 8)
                acc = acc ^ planes[j][x]
            q.append(acc)
        # unpack per parity chunk: transpose back, scatter segments
        for i in range(m):
            segs = _bit_transpose8([q[i * 8 + y] for y in range(8)])
            for s in range(8):
                for r in range(R):
                    o_ref[i:i + 1, (r * 8 + s) * seg:
                          (r * 8 + s + 1) * seg] = segs[s][r:r + 1, :]

    @jax.jit
    def run(data32: jax.Array) -> jax.Array:
        P = data32.shape[1]
        pad = (-P) % tile_lanes
        if pad:
            data32 = jnp.pad(data32, ((0, 0), (0, pad)))
        Pp = P + pad
        out = pl.pallas_call(
            kern,
            grid=(Pp // tile_lanes,),
            in_specs=[pl.BlockSpec((k, tile_lanes),
                                   lambda i: (i32(0), i32(i)))],
            out_specs=pl.BlockSpec((m, tile_lanes),
                                   lambda i: (i32(0), i32(i))),
            out_shape=jax.ShapeDtypeStruct((m, Pp), jnp.uint32),
            interpret=interpret,
        )(data32)
        return out[:, :P] if pad else out

    return run


def _reconstruction_rows(matrix: list[list[int]], k: int, w: int,
                         erased: tuple[int, ...],
                         survivors: tuple[int, ...]) -> list[list[int]]:
    """GF rows that rebuild `erased` chunks from the first k usable
    survivors: invert the surviving rows, compose parity rows through
    the inverse (the decode-as-encode reformulation both device
    encoders share)."""
    inv, _chosen = matrices.decoding_matrix(
        k, w, matrix, list(erased), list(survivors))
    rows = []
    for e in erased:
        if e < k:
            rows.append(inv[e])
        else:
            coeff = matrix[e - k]
            rows.append([
                functools.reduce(
                    lambda a, t: a ^ t,
                    (matrices.gf_mul(coeff[j], inv[j][i], w)
                     for j in range(k)), 0)
                for i in range(k)])
    return rows


class FusedEncoder:
    """Byte-layout encode/reconstruct at HBM bandwidth (w=8 only).

    Drop-in for DeviceEncoder where w == 8: `data` is (k, n) uint8
    words in ordinary byte layout; returns (m, n) parity bytes,
    bit-identical to the host codecs.  run32 is the device-resident
    entry point on (k, n//4) uint32 views (free reinterpretation of
    the same bytes, little-endian lanes).

    Ragged-segment friendliness: the kernel pads its input to a tile
    multiple, so a fixed big tile would hand a small bucket-ladder
    segment (ec.batcher ragged staging) back all the padding the
    ladder just removed.  The tile therefore ADAPTS: inputs smaller
    than `tile_bytes` compile against the largest halving of the tile
    that still covers them (floored at the 1024-lane VPU alignment),
    one cached program per clamped tile — the tile ladder mirrors the
    bucket ladder, so segment programs stay few and pad stays
    sub-tile.
    """

    def __init__(self, matrix: list[list[int]], tile_bytes: int = 32768):
        self.m = len(matrix)
        self.k = len(matrix[0])
        self.w = 8
        self.matrix = matrix
        self.tile_bytes = tile_bytes
        bm = np.array(
            matrices.matrix_to_bitmatrix(self.k, self.m, 8, matrix),
            dtype=np.int8)
        self._bitmatrix = bm
        self._fns: dict[int, object] = {}   # tile_lanes -> compiled
        self._decoders: dict[tuple, "FusedEncoder"] = {}

    def _tile_lanes_for(self, lanes: int) -> int:
        """Clamped tile (uint32 lanes) for an input of `lanes`: halve
        the configured tile while it still over-covers the input,
        never below the 1024-lane alignment _fused_xor_pallas needs."""
        tile = self.tile_bytes // 4
        while tile > 1024 and tile >= 2 * max(1, lanes):
            tile //= 2
        return max(tile, 1024)

    def _fn_for(self, lanes: int):
        tile = self._tile_lanes_for(lanes)
        fn = self._fns.get(tile)
        if fn is None:
            fn = _fused_xor_pallas(self._bitmatrix, tile)
            self._fns[tile] = fn
        return fn

    def run32(self, data32: jax.Array) -> jax.Array:
        """(k, P) uint32 -> (m, P) uint32, device-resident."""
        return self._fn_for(data32.shape[1])(data32)

    @property
    def program_count(self) -> int:
        """Distinct compiled tile programs this encoder holds — the
        encoder-side ground truth the dispatch-stream bench reports
        beside the runtime's note_program bookkeeping (the two must
        agree on 'a handful': slots reuse the fixed tile family)."""
        return len(self._fns)

    def __call__(self, data: np.ndarray) -> np.ndarray:
        k, n = data.shape
        pad = (-n) % 4
        if pad or data.dtype != np.uint8:
            data = np.ascontiguousarray(data, dtype=np.uint8)
        if pad:
            data = np.pad(data, ((0, 0), (0, pad)))
        d32 = np.ascontiguousarray(data).view(np.uint32)
        out = np.asarray(self._fn_for(d32.shape[1])(jnp.asarray(d32)))
        out8 = out.view(np.uint8)
        return out8[:, :n] if pad else out8

    def decoder_for(self, erased: tuple[int, ...],
                    survivors: tuple[int, ...]) -> "FusedEncoder":
        """Reconstruction rows through the same fused kernel (cached
        per erasure signature, like ErasureCodeIsaTableCache)."""
        key = (erased, survivors[:self.k])
        dec = self._decoders.get(key)
        if dec is None:
            rows = _reconstruction_rows(self.matrix, self.k, self.w,
                                        erased, survivors)
            dec = FusedEncoder(rows, self.tile_bytes)
            self._decoders[key] = dec
        return dec


# ---------------------------------------------------------------------------
# public surface
# ---------------------------------------------------------------------------


class DeviceEncoder:
    """Compiled encode (and decode) for one (matrix, w) on the current
    backend. `data` is (k, n) words; n is the flattened batch of all
    in-flight stripes — pad n to the tile size for the pallas path."""

    def __init__(self, matrix: list[list[int]], w: int = 8,
                 use_pallas: bool = False, tile: int = 16384):
        self.m = len(matrix)
        self.k = len(matrix[0])
        self.w = w
        self.matrix = matrix
        self.tile = tile
        bm = np.array(
            matrices.matrix_to_bitmatrix(self.k, self.m, w, matrix),
            dtype=np.int8)
        self._bm = jnp.asarray(bm)
        if use_pallas:
            self._fn = _encode_pallas(bm, w, self.k, self.m, tile)
        else:
            self._fn = functools.partial(encode_xla, self._bm, w=self.w)
        self._decoders: dict[tuple, "DeviceEncoder"] = {}
        self._shapes: set[tuple] = set()    # traced input shapes

    def __call__(self, data: jax.Array) -> jax.Array:
        self._shapes.add((int(data.shape[0]), int(data.shape[1])))
        return self._fn(data)

    @property
    def program_count(self) -> int:
        """Distinct input shapes this encoder has traced (one XLA
        program each under jit's shape-keyed cache) — the encoder-side
        ground truth for the dispatch-stream bench's compile-budget
        cross-check."""
        return len(self._shapes)

    def encode_batch(self, stripes: np.ndarray) -> jax.Array:
        """(batch, k, chunk_bytes) uint8 -> (batch, m, chunk_bytes)."""
        b, k, c = stripes.shape
        flat = jnp.asarray(stripes).transpose(1, 0, 2).reshape(k, b * c)
        out = self._fn(flat)
        return out.reshape(self.m, b, c).transpose(1, 0, 2)

    def decoder_for(self, erased: tuple[int, ...],
                    survivors: tuple[int, ...]) -> "DeviceEncoder":
        """Compiled reconstruction: rows = erased chunk ids, inputs = the
        first k survivors. Cached per erasure signature."""
        key = (erased, survivors[:self.k])
        dec = self._decoders.get(key)
        if dec is None:
            rows = _reconstruction_rows(self.matrix, self.k, self.w,
                                        erased, survivors)
            dec = DeviceEncoder(rows, self.w)
            self._decoders[key] = dec
        return dec


@functools.lru_cache(maxsize=64)
def encoder_for_profile(plugin: str, technique: str, k: int, m: int,
                        w: int = 8, use_pallas: bool = False) -> DeviceEncoder:
    """Device encoder for the common matrix-backed profiles."""
    if plugin == "isa":
        mat = (matrices.isa_rs_vandermonde_matrix(k, m)
               if technique == "reed_sol_van"
               else matrices.isa_cauchy_matrix(k, m))
        return DeviceEncoder(mat, 8, use_pallas)
    if technique == "reed_sol_van":
        mat = matrices.reed_sol_vandermonde_coding_matrix(k, m, w)
    elif technique == "reed_sol_r6_op":
        mat = matrices.reed_sol_r6_coding_matrix(k, w)
    elif technique == "cauchy_orig":
        mat = matrices.cauchy_original_coding_matrix(k, m, w)
    elif technique == "cauchy_good":
        mat = matrices.cauchy_good_general_coding_matrix(k, m, w)
    else:
        raise ValueError("no device path for technique %r" % technique)
    return DeviceEncoder(mat, w, use_pallas)
