"""Watch/notify: object interest registration + event fan-out.

Condensed analog of src/osd/Watch.cc + PrimaryLogPG's watch/notify op
handling: a client registers a watch on an object at the PG primary
("watch" op); any client's "notify" op makes the primary deliver the
payload to every live watcher (MWatchNotify) and complete the notify
once all have acked or the timeout lapses (the reference's
notify_timeout).  Watches here live in primary memory and die with the
connection (ms_handle_reset) or an interval change — the client
re-registers on map change, which is also how the reference's clients
behave after a primary migration (librados re-watch on notify_resend).
"""

from __future__ import annotations

import asyncio

from ..msg.messages import MWatchNotify


class WatchRegistry:
    """Per-daemon watch state (primary side)."""

    def __init__(self, osd):
        self.osd = osd
        # (pool, ps, oid) -> set[conn]
        self.watches: dict[tuple, set] = {}
        self._notify_id = 0
        # notify_id -> {"waiting": set[conn], "event": Event}
        self._notifies: dict[int, dict] = {}

    def watch(self, pg, oid: str, conn) -> None:
        key = (pg.pool_id, pg.ps, oid)
        self.watches.setdefault(key, set()).add(conn)

    def unwatch(self, pg, oid: str, conn) -> None:
        key = (pg.pool_id, pg.ps, oid)
        entry = self.watches.get(key)
        if entry is not None:
            entry.discard(conn)
            if not entry:
                del self.watches[key]

    def pg_reset(self, pool_id: int, ps: int) -> None:
        """Interval change: registrations die with the old acting set
        (clients re-watch at the new primary on the map change)."""
        for key in [k for k in self.watches
                    if k[0] == pool_id and k[1] == ps]:
            del self.watches[key]

    def conn_reset(self, conn) -> None:
        for key in list(self.watches):
            self.watches[key].discard(conn)
            if not self.watches[key]:
                del self.watches[key]
        for st in self._notifies.values():
            st["waiting"].discard(conn)
            if not st["waiting"] and not st["event"].is_set():
                st["event"].set()

    async def notify(self, pg, oid: str, payload: bytes,
                     timeout: float = 5.0) -> int:
        """Deliver to every watcher; returns the number that acked."""
        key = (pg.pool_id, pg.ps, oid)
        watchers = set(self.watches.get(key, set()))
        if not watchers:
            return 0
        self._notify_id += 1
        nid = self._notify_id
        ev = asyncio.Event()
        st = {"waiting": set(watchers), "acked": set(), "event": ev}
        self._notifies[nid] = st
        for conn in watchers:
            conn.send(MWatchNotify(pool=pg.pool_id, ps=pg.ps, oid=oid,
                                   notify_id=nid, payload=payload,
                                   ack=False))
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        self._notifies.pop(nid, None)
        # count explicit acks only: a watcher whose connection died
        # mid-notify is a timed-out returnee, not an ack (the reference
        # reports such watchers in the notify timeout list)
        return len(st["acked"])

    def handle_ack(self, conn, msg: MWatchNotify) -> None:
        st = self._notifies.get(msg.notify_id)
        if st is None:
            return
        st["acked"].add(conn)
        st["waiting"].discard(conn)
        if not st["waiting"]:
            st["event"].set()
