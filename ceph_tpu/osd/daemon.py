"""OSD daemon: the data-plane process serving PGs.

Condensed analog of src/osd/OSD.cc + PrimaryLogPG.cc for the replicated
path, on asyncio:

boot      OSD::init (OSD.cc:3592): mount store, load PGs from
          collections, subscribe to the monitor, MOSDBoot, consume maps.
maps      handle_osd_map / advance_map: apply incrementals in order;
          interval changes drive per-PG peering (PeeringState AdvMap).
ops       ms_fast_dispatch -> dequeue_op -> PrimaryLogPG::do_request:
          primary executes the op list (do_osd_ops interpreter),
          replicates via MOSDRepOp (ReplicatedBackend::submit_transaction,
          ReplicatedBackend.cc:465), acks -> client reply.
peering   GetInfo/GetLog via MOSDPGQuery -> MOSDPGLog; authoritative log
          selection (find_best_info), activation MOSDPGLog to replicas,
          missing-set computation.
recovery  log-based: pull objects the primary lacks (MOSDPGPull ->
          MOSDPGPush), push to replicas missing them; whole-object
          granularity (recovery_state flow of ECBackend/ReplicatedBackend
          simplified to PushOp full-object form).
failure   OSD<->OSD heartbeats (OSD.cc:5436,5575) -> MOSDFailure reports
          to the monitor with failed_for durations.

The heavy mapping work (which PGs live here) runs through the same
pg_to_up_acting_osds pipeline every node computes; bulk priming for
large pools can use parallel.mapping.OSDMapMapping.
"""

from __future__ import annotations

import asyncio
import time

from ..msg import Messenger
from ..msg.messenger import ms_compress_from_conf, Policy
from ..msg.messages import (MConfig, MMonSubscribe, MOSDAlive,
                            MOSDBackoff, MOSDBoot,
                            MOSDECSubOpRead, MOSDECSubOpReadReply,
                            MOSDECSubOpWrite, MOSDECSubOpWriteReply,
                            MOSDFailure, MOSDMapMsg, MOSDOp,
                            MOSDOpReply, MOSDPGLog, MOSDPGPush,
                            MOSDPGPushReply, MOSDPGQuery, MOSDPing,
                            MOSDRepOp, MOSDRepOpReply, MOSDRepScrub,
                            MOSDRepScrubMap, MOSDScrub, MWatchNotify)
from ..models.crushmap import ITEM_NONE
from ..store.memstore import MemStore
from ..store.objectstore import (NotFound, ObjectStore, Transaction,
                                 coll_t, hobject_t)
from ..utils import denc
from ..utils.context import Context
from .osdmap import OSDMap, consume_map_payload, pg_t
from .pg import (PG, STATE_ACTIVE, STATE_PEERING, STATE_REPLICA,
                 LogEntry, PGInfo)


class OSD:
    def __init__(self, whoami: int, mon_addr,
                 ctx: Context | None = None,
                 store: ObjectStore | None = None):
        self.whoami = whoami
        # one address or the monmap list: maps are subscribed from one
        # mon (rotating on faults), state reports (boot/failure/alive)
        # are broadcast to all so the current leader always sees them
        self.mon_addrs = ([mon_addr] if isinstance(mon_addr, str)
                          else list(mon_addr))
        self._mon_i = whoami % max(1, len(self.mon_addrs))
        self.ctx = ctx or Context("osd.%d" % whoami)
        from ..store import create_store

        self.store = store or create_store(self.ctx.conf, whoami)
        from ..msg.auth import AuthContext
        self.msgr = Messenger(
            "osd.%d" % whoami,
            auth=AuthContext.from_conf(self.ctx.conf),
            compress=ms_compress_from_conf(self.ctx.conf))
        self.msgr.peer_policy["osd"] = Policy.lossless_peer()
        self.msgr.add_dispatcher(self)
        from .cls import default_handler
        from .ecbackend import ECPGBackend
        from .scheduler import OpScheduler
        from .scrubber import Scrubber
        from .watch import WatchRegistry

        self.cls_handler = default_handler()
        # bound at start(): this OSD's mesh chip (ChipRuntime) —
        # deterministic OSD->chip affinity, the per-chip isolation
        # domain its EC flushes and bulk mapping dispatch on
        self.device_chip = None
        self.ec = ECPGBackend(self)
        self.scrubber = Scrubber(self)
        self.watches = WatchRegistry(self)
        # request-level observability (TrackedOp/OpTracker): every
        # client op / sub-op registers here with its trace id; the
        # admin socket serves dump_ops_in_flight & friends and the
        # heartbeat loop beacons the slow-op count to the mon
        from ..trace import LogClient, OpTracker
        self.optracker = OpTracker(self.ctx, "osd.%d" % whoami)
        # cluster-log handle (LogClient): daemon events reach the
        # mon's LogMonitor (paxos-committed `log last`); entries are
        # broadcast like beacons and re-flushed until a mon acks the
        # commit
        self.clog = LogClient(self.ctx, "osd.%d" % whoami,
                              send_fn=self._send_mons)
        # crash reports recovered from the store at mount, shipped to
        # the mons until acked (MCrashReport -> crash table)
        self._crash_pending: list[dict] = []
        self._crash_ship_stamp = 0.0
        # unhandled exceptions escaping spawned tasks become crash
        # reports in the daemon's own store (the post-mortem artifact
        # that survives the process)
        self.msgr.crash_hook = self._record_crash
        self.perf = self.ctx.perf.create("osd")
        self.perf.add_u64("ops", "client ops completed")
        self.perf.add_u64("dup_ops",
                          "client resends answered from the reqid"
                          " journal")
        self.perf.add_u64("slow_ops",
                          "in-flight ops past osd_op_complaint_time")
        self.perf.add_hist("op_queue_wait",
                           "mClock shard queue wait (us, pow2)")
        self.perf.add_hist("op_subop_rtt",
                           "replicated sub-op round trip (us, pow2)")
        self.perf.add_hist("op_ec_batch_wait",
                           "EC encode incl device batch wait"
                           " (us, pow2)")
        self.perf.add_hist("op_ec_device_dispatch",
                           "device EC batch flush time (us, pow2)")
        # integrity plane: scrub rounds, what they found/fixed, and
        # how the digests were computed (device lanes vs host loop)
        self.perf.add_u64("scrubs", "shallow scrub rounds completed")
        self.perf.add_u64("deep_scrubs", "deep scrub rounds completed")
        self.perf.add_u64("scrub_errors_found",
                          "inconsistencies flagged by scrubs")
        self.perf.add_u64("scrub_repaired",
                          "divergent copies rewritten by repair"
                          " scrubs")
        self.perf.add_u64("scrub_digest_device",
                          "scrub digests computed in device crc32"
                          " lanes")
        self.perf.add_u64("scrub_digest_host",
                          "scrub digests computed by the host"
                          " fallback loop")
        self.perf.add_u64("comp_paced_ops",
                          "compression-pool ops paced through the"
                          " background device class")
        self.perf.add_u64("comp_device_blobs",
                          "writefull blobs whose tlz match planning"
                          " dispatched on this daemon's chip")
        self.perf.add_u64("comp_host_blobs",
                          "writefull blobs tlz-compressed on the"
                          " host reference (degraded path)")
        self.perf.add_u64("comp_size_mismatches",
                          "reads refused because comp-size disagreed"
                          " with the decompressed length")
        # data-reduction plane: dedup-pool ops paced through the
        # background class, how the chunk/fingerprint kernels ran
        # (device lanes vs host fallback), and what the chunk store
        # absorbed vs deduplicated
        self.perf.add_u64("dedup_paced_ops",
                          "dedup-pool ops paced through the"
                          " background device class")
        self.perf.add_u64("dedup_chunk_device",
                          "write batches whose chunk boundaries"
                          " resolved from device candidate masks")
        self.perf.add_u64("dedup_chunk_host",
                          "write batches chunked by the host"
                          " reference (degraded path)")
        self.perf.add_u64("dedup_fp_device",
                          "write batches fingerprinted in device"
                          " crc32 lanes")
        self.perf.add_u64("dedup_fp_host",
                          "write batches fingerprinted by the host"
                          " fallback loop")
        self.perf.add_u64("dedup_chunks_stored",
                          "chunks this osd stored as new chunk-pool"
                          " objects")
        self.perf.add_u64("dedup_chunks_deduped",
                          "chunks answered by an existing chunk-pool"
                          " object (a ref, no bytes)")
        self.perf.add_u64("dedup_bytes_saved",
                          "logical bytes deduplicated away (refs"
                          " instead of stored copies)")
        # the primary's side of the data-reduction plane (chunking,
        # fingerprints, refcounted chunk store, internal objecter)
        from ..dedup import DedupPlane
        self.dedup = DedupPlane(self)
        # repair-traffic plane: what recovery actually moved, split
        # by whether the minimal-shard-set (targeted) repair served
        # it or the whole-object read + re-encode fallback did
        self.perf.add_u64("repair_bytes_read",
                          "survivor shard bytes read to rebuild"
                          " lost shards")
        self.perf.add_u64("repair_bytes_moved",
                          "rebuilt shard bytes written/pushed by"
                          " recovery")
        self.perf.add_u64("repair_targeted",
                          "shards rebuilt from the codec's minimal"
                          " shard set")
        self.perf.add_u64("repair_full",
                          "shards rebuilt via whole-object read +"
                          " re-encode")
        # network observability plane: messenger lossless-resend /
        # replay / mark_down totals surfaced as per-daemon counters,
        # plus the per-peer heartbeat RTT tracker (admin:
        # dump_osd_network; beacon net slice -> OSD_SLOW_PING_TIME)
        self.perf.add_u64("msgr_resends",
                          "lossless payloads requeued for session"
                          " replay after reconnect")
        self.perf.add_u64("msgr_replays",
                          "duplicate frames absorbed by seq dedup"
                          " after reconnect")
        self.perf.add_u64("msgr_mark_downs",
                          "administrative connection teardowns")
        from .network import OsdNetwork
        self.network = OsdNetwork(self.ctx)
        self._net_prev: dict | None = None
        self._beacon_stamp = 0.0
        # one periodic scrub at a time per daemon (the reference's
        # scrubs_local bound collapsed to 1)
        self._scrub_running = False
        # long-flow progress rows (recovery drains, scrub sweeps):
        # shipped in osd_stats["progress"] each MMgrReport
        from .progress import ProgressTracker
        self.progress = ProgressTracker()
        # client write-size histogram (pow2 byte buckets, cumulative):
        # reported to the mgr for the cluster op-size profile and used
        # to derive workload-aware device warmup buckets (bucket i
        # counts writes of [2^i, 2^(i+1)) payload bytes)
        self.op_size_hist: list[int] = [0] * 32
        # tenant SLO plane: per-tenant stage histograms (pow2 µs
        # buckets, cumulative — the same shape as the perf hists) and
        # good/bad op counters, shipped in MMgrReport osd_stats so the
        # mgr's SLO engine can evaluate per-tenant burn rates.
        # Cardinality is conf-bounded (`tenant_tracking_max`):
        # overflow tenants fold into the "other" bucket rather than
        # growing the report without bound.
        self.tenant_stages: dict[str, dict[str, list[int]]] = {}
        self.tenant_ops: dict[str, dict[str, int]] = {}
        self.optracker.on_retire = self._note_op_retired
        # sharded mClock op queue (ShardedOpWQ + mClockScheduler);
        # tenant-stamped client ops run under per-tenant RWL tag books
        self.sched = OpScheduler(self.ctx)
        self.sched.on_wait = self._note_queue_wait
        # epoch-0 empty map is the universal incremental base
        self.osdmap: OSDMap = OSDMap()
        self.pgs: dict[pg_t, PG] = {}
        self.booted = False
        self.stopping = False
        self._boot_sent_epoch = -1
        self._rep_tid = 0
        self._backoff_id = 0        # monotonic MOSDBackoff ids
        self._waiting_for_map: list = []
        # heartbeat state: peer -> last seen stamp
        self.hb_last_rx: dict[int, float] = {}
        # last observed pg_num per pool: a growth triggers the local
        # in-place PG split before mappings recompute
        self._pool_pg_num: dict[int, int] = {}
        self._tasks = []

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self.store.mount()
        # previous incarnation's crash reports (the reboot ships them
        # to the mons; the paxos-committed ack clears them here)
        from ..utils import crash as crashmod
        self._crash_pending = crashmod.pending_crashes(self.store)
        # clog seq floor: resume ABOVE the previous incarnation's
        # last-used seq (persisted per emit) so the LogMonitor's
        # (who, inc, seq) dedup never swallows reborn entries and
        # pre-restart unacked entries cannot supersede them.  A WIPED
        # store lost the floor — mint a fresh (larger) boot
        # incarnation instead, so seqs restarting from 1 re-key as
        # new entries rather than replaying committed ones
        clog_inc = crashmod.load_clog_incarnation(self.store)
        if not clog_inc:
            clog_inc = crashmod.new_clog_incarnation()
            crashmod.save_clog_incarnation(self.store, clog_inc)
        self.clog.resume_above(crashmod.load_clog_seq(self.store),
                               incarnation=clog_inc)
        self.clog.on_seq = \
            lambda s: crashmod.save_clog_seq(self.store, s)
        if self._crash_pending:
            self.ctx.log.info(
                "osd", "osd.%d found %d pending crash report(s)"
                % (self.whoami, len(self._crash_pending)))
        addr = await self.msgr.bind(host, port)
        self.sched.start(self.msgr.spawn)
        self._load_pgs()
        # device runtime: adopt this daemon's queue bounds, bind this
        # OSD to its mesh chip (deterministic affinity — co-located
        # daemons land on distinct chips, so one chip's loss degrades
        # only its own OSDs), and beacon fallback transitions
        # immediately (a mapping storm or chip loss must reach the
        # mon's health checks within one beacon, not one reporting
        # interval)
        from ..device.runtime import DeviceRuntime
        rt = DeviceRuntime.get()
        rt.configure(self.ctx.conf)
        self.device_chip = rt.chip_for(self.whoami)
        self.device_chip.add_listener(self._on_device_state)
        mon = self.msgr.connect_to(self.mon_addr, entity_hint="mon.0")
        mon.send(MMonSubscribe(start=1))
        self._tasks.append(self.msgr.spawn(self._mon_watchdog()))
        self._tasks.append(self.msgr.spawn(self._heartbeat_loop()))
        return addr

    def _on_device_state(self, fallback: bool) -> None:
        """This OSD's mesh chip poisoned/healed: beacon the new state
        now, and tell the cluster log (the daemon-origin side of the
        per-chip DEVICE_FALLBACK story; the mon clogs the health
        edge, naming the chip)."""
        if self.stopping or not self.booted:
            return
        chip = (self.device_chip.index
                if self.device_chip is not None else 0)
        self.ctx.log.info(
            "osd", "osd.%d device chip %d %s"
            % (self.whoami, chip,
               "LOST -> host fallback" if fallback else "healed"))
        if fallback:
            self.clog.warn("osd.%d device chip %d lost, serving from "
                           "host paths" % (self.whoami, chip))
        else:
            self.clog.info("osd.%d device chip %d healed"
                           % (self.whoami, chip))
        self._beacon_stamp = 0.0        # bypass the report interval
        self._maybe_send_beacon()

    # -- crash telemetry (utils.crash + the mon's crash table) -------------

    def _record_crash(self, exc: BaseException) -> str | None:
        """Write a crash report — stack, LogRing tail, identity —
        into this daemon's OWN store (the artifact that survives the
        process), queued for shipping to the mons."""
        from ..utils import crash as crashmod
        try:
            report = crashmod.build_report(
                "osd.%d" % self.whoami, exc,
                fsid=getattr(self.osdmap, "fsid", "") or "",
                epoch=self.osdmap.epoch if self.osdmap else 0,
                ring=self.ctx.log.ring,
                tail=int(self.ctx.conf.get("osd_crash_ring_tail",
                                           100)))
            crashmod.save_crash(self.store, report)
        except Exception:
            return None     # the crash path must never crash
        self._crash_pending.append(report)
        self.ctx.log.error(
            "osd", "osd.%d crash recorded (%s): %s: %s"
            % (self.whoami, report["crash_id"],
               report["exc_type"], report["exc_msg"]))
        return report["crash_id"]

    def simulate_crash(self, exc: BaseException) -> str | None:
        """Test/thrasher hook: die on an injected exception exactly
        like an unhandled one — raise it for a real traceback, record
        the report, leave the daemon to be hard-stopped by the
        caller."""
        try:
            raise exc
        except type(exc) as caught:
            return self._record_crash(caught)

    def _maybe_ship_crashes(self) -> None:
        """Re-broadcast pending crash reports to every mon until the
        committed-table ack clears them (paced like beacons)."""
        if not self._crash_pending:
            return
        now = time.monotonic()
        if now - self._crash_ship_stamp < \
                self.ctx.conf["osd_beacon_report_interval"]:
            return
        self._crash_ship_stamp = now
        from ..msg.messages import MCrashReport
        self._send_mons(MCrashReport(
            reports=[dict(r) for r in self._crash_pending]))

    def _handle_crash_ack(self, crash_ids) -> None:
        from ..utils import crash as crashmod
        acked = set(crash_ids or [])
        if not acked:
            return
        for r in list(self._crash_pending):
            if r.get("crash_id") in acked:
                self._crash_pending.remove(r)
                try:
                    crashmod.remove_crash(self.store, r["crash_id"])
                except Exception:
                    pass

    async def wait_for_boot(self, timeout: float = 10.0) -> None:
        from ..utils.backoff import wait_for
        await wait_for(lambda: self.booted, timeout,
                       what="osd.%d boot" % self.whoami)

    async def shutdown(self) -> None:
        self.stopping = True
        self.sched.stop()
        await self.msgr.shutdown()
        self.store.umount()

    @property
    def mon_addr(self) -> str:
        return self.mon_addrs[self._mon_i % len(self.mon_addrs)]

    # -- observability helpers ---------------------------------------------

    def _note_queue_wait(self, klass: str, seconds: float,
                         tenant: str | None = None) -> None:
        from .scheduler import K_CLIENT
        if klass == K_CLIENT:
            self.perf.hist_sample("op_queue_wait", seconds)
            if tenant is not None:
                self.note_tenant_stage(tenant, "queue_wait", seconds)

    # -- tenant SLO accounting ---------------------------------------------

    def _tenant_key(self, tenant: str) -> str:
        """Bound tenant-label cardinality: past `tenant_tracking_max`
        distinct tenants, new ones fold into "other" (known tenants
        keep their own rows)."""
        if tenant in self.tenant_stages or tenant in self.tenant_ops:
            return tenant
        cap = int(self.ctx.conf.get("tenant_tracking_max", 64))
        known = set(self.tenant_stages) | set(self.tenant_ops)
        if len(known - {"other"}) >= cap:
            return "other"
        return tenant

    def note_tenant_stage(self, tenant: str, stage: str,
                          seconds: float) -> None:
        """One stage-latency sample for one tenant (pow2 µs buckets,
        cumulative — the per-tenant mirror of the op_* perf hists the
        SLO engine derives window deltas from)."""
        key = self._tenant_key(tenant)
        hist = self.tenant_stages.setdefault(key, {}).setdefault(
            stage, [0] * 32)
        us = max(1, int(seconds * 1e6))
        i = min(len(hist) - 1, max(0, us.bit_length() - 1))
        hist[i] += 1

    def note_tenant_op(self, tenant: str, ok: bool) -> None:
        key = self._tenant_key(tenant)
        row = self.tenant_ops.setdefault(key, {"ops": 0, "errors": 0})
        row["ops"] += 1
        if not ok:
            row["errors"] += 1

    # final events that count as availability failures for the
    # tenant's error budget (an errored reply; parked/dropped ops are
    # re-sent by the client and complete under a later record)
    _BAD_FINISH = frozenset({"error_reply", "ec_error_reply",
                             "no_such_pool"})

    def _note_op_retired(self, op) -> None:
        """OpTracker retire hook: end-to-end latency + availability
        accounting for tenant-stamped PRIMARY client ops (sub-ops are
        stages of the primary's sample, not ops of their own)."""
        if op.tenant is None or not op.desc.startswith("osd_op("):
            return
        final = op.events[-1][1]
        if final in ("dropped_not_primary", "dropped_pool_deleted",
                     "dropped_interval_change",
                     "dropped_wrong_pg_after_split"):
            return      # the client re-targets; not a completed op
        self.note_tenant_stage(op.tenant, "total", op.age)
        self.note_tenant_op(op.tenant, final not in self._BAD_FINISH)

    def note_op_size(self, nbytes: int) -> None:
        """Record one client write's payload size in the pow2
        histogram (feeds workload-aware device warmup + the mgr)."""
        if nbytes <= 0:
            return
        i = min(len(self.op_size_hist) - 1,
                max(0, int(nbytes).bit_length() - 1))
        self.op_size_hist[i] += 1

    def _track(self, msg, desc: str):
        """Register (once) a tracked op for an incoming message; the
        record rides the message object so park/requeue cycles keep
        one timeline (OpRequest wraps the Message the same way)."""
        top = getattr(msg, "_top", None)
        if top is None:
            top = self.optracker.create(
                desc, trace=getattr(msg, "trace", None),
                tenant=getattr(msg, "tenant", None))
            msg._top = top
            top.mark_event("queued")
        return top

    @staticmethod
    def _op_event(msg, event: str) -> None:
        top = getattr(msg, "_top", None)
        if top is not None:
            top.mark_event(event)

    @staticmethod
    def _op_finish(msg, event: str = "done") -> None:
        top = getattr(msg, "_top", None)
        if top is not None:
            top.finish(event)

    def _send_mons(self, msg) -> None:
        for i, addr in enumerate(self.mon_addrs):
            self.msgr.send_to(addr, msg, entity_hint="mon.%d" % i)

    async def _mon_watchdog(self) -> None:
        """A peon that stops leading (or a dead mon) leaves our boot
        unacknowledged: while unbooted, re-broadcast under a jittered
        exponential ramp (a mon outage must not see every OSD retry
        in lockstep every second).  While booted, periodically RENEW
        the map subscription (MonClient::renew_subs): map publication
        is fire-and-forget, so an epoch silently lost to a partition
        or dropped frame would otherwise leave this osd behind until
        the next commit happens to flow."""
        from ..utils.backoff import ExpBackoff
        bo = ExpBackoff(base=1.0, cap=8.0, rng=self.msgr.rng)
        renew_at = 0.0
        while not self.stopping:
            if self.booted:
                bo.reset()
                await asyncio.sleep(1.0)
                now = time.monotonic()
                if now >= renew_at:
                    renew_at = now + self.ctx.conf[
                        "mon_subscribe_renew_interval"]
                    self.msgr.send_to(
                        self.mon_addr,
                        MMonSubscribe(start=self.osdmap.epoch + 1),
                        entity_hint="mon.0")
                continue
            await bo.sleep()
            if not self.booted and self._boot_sent_epoch >= 0:
                self._boot_sent_epoch = -1
                self._send_boot()

    def _load_pgs(self) -> None:
        """Recreate PG objects from on-disk collections (OSD::load_pgs)."""
        for cid in self.store.list_collections():
            if not cid.is_pg():
                continue
            pool_s, ps_s = cid.name.split(".")
            pg = PG(self, int(pool_s), int(ps_s, 16))
            if pg.load():
                self.pgs[pg_t(pg.pool_id, pg.ps)] = pg

    # -- dispatch ----------------------------------------------------------

    def ms_handle_reset(self, conn) -> None:
        """A lossy fault on the monitor link drops our subscription on
        the mon side: re-subscribe from our current epoch."""
        self.watches.conn_reset(conn)
        if conn.peer_addr in self.mon_addrs and not self.stopping:
            if conn.peer_addr == self.mon_addr:
                self._mon_i = (self._mon_i + 1) % len(self.mon_addrs)
            self.msgr.send_to(self.mon_addr,
                              MMonSubscribe(start=self.osdmap.epoch + 1),
                              entity_hint="mon.0")

    def ms_dispatch(self, conn, msg) -> bool:
        """Fast paths (map/peering/heartbeat/completion replies) run
        inline; op-class work (client ops, rep/EC sub-ops, recovery
        pushes, scrub chunks) goes through the sharded mClock queue
        (OSD::ms_fast_dispatch -> enqueue_op -> ShardedOpWQ,
        OSD.cc:7360,9554)."""
        from .scheduler import K_CLIENT, K_RECOVERY, K_SCRUB

        def q(key, klass, fn, tenant=None):
            if self.sched.running:
                self.sched.enqueue(key, klass, fn, tenant=tenant)
            else:           # not started (unit-test direct dispatch)
                r = fn()
                if asyncio.iscoroutine(r):
                    # async handlers (scrub map builds) still run
                    asyncio.ensure_future(r)

        if isinstance(msg, MConfig):
            self.ctx.conf.apply_mon_values(msg.values or {})
            return True
        from ..msg.messages import MCrashReportAck, MLogAck
        if isinstance(msg, MLogAck):
            self.clog.handle_ack(msg.who, int(msg.last or 0),
                                 inc=getattr(msg, "inc", None))
            return True
        if isinstance(msg, MCrashReportAck):
            self._handle_crash_ack(msg.crash_ids)
            return True
        if isinstance(msg, MOSDMapMsg):
            self._handle_osd_map(msg)
        elif isinstance(msg, MOSDOp):
            ops_s = ",".join(o.get("op", "?")
                             for o in (msg.ops or []))
            self._track(msg, "osd_op(%s tid=%s %d.%x %s [%s])"
                        % (msg.src, msg.tid, msg.pool, msg.ps,
                           msg.oid, ops_s))
            q((msg.pool, msg.ps), K_CLIENT,
              lambda: self._handle_op(conn, msg),
              tenant=getattr(msg, "tenant", None))
        elif isinstance(msg, MOSDRepOp):
            self._track(msg, "rep_op(%s tid=%s %d.%x)"
                        % (msg.src, msg.tid, msg.pool, msg.ps))
            q((msg.pool, msg.ps), K_CLIENT,
              lambda: self._handle_repop(conn, msg),
              tenant=getattr(msg, "tenant", None))
        elif isinstance(msg, MOSDRepOpReply):
            self._handle_repop_reply(msg)
        elif isinstance(msg, MOSDOpReply):
            # reply to one of OUR internal ops (the dedup plane's
            # objecter acting as a chunk-pool client): route by tid
            return self.dedup.objecter.on_reply(msg)
        elif isinstance(msg, MOSDPGQuery):
            self._handle_pg_query(conn, msg)
        elif isinstance(msg, MOSDPGLog):
            self._handle_pg_log(conn, msg)
        elif isinstance(msg, MOSDPGPush):
            q((msg.pool, msg.ps), K_RECOVERY,
              lambda: self._handle_pg_push(conn, msg))
        elif isinstance(msg, MOSDPGPushReply):
            self._handle_pg_push_reply(msg)
        elif isinstance(msg, MOSDPing):
            self._handle_ping(conn, msg)
        elif isinstance(msg, MWatchNotify):
            self.watches.handle_ack(conn, msg)
        elif isinstance(msg, MOSDScrub):
            # operator-requested scrub (mon `pg scrub|deep-scrub|
            # repair`): runs asynchronously on the primary.  One
            # scrub per PG at a time — a retried command must not
            # interleave two repair passes over the same objects.
            pg = self.pgs.get(pg_t(msg.pool, msg.ps))
            if pg is None or not pg.is_primary():
                # schedule-time race (PG not instantiated yet, or
                # primaryship moved): visible, like the reference's
                # no-op scrub scheduling
                self.ctx.log.info(
                    "osd", "osd.%d ignoring scrub request for "
                    "%d.%x (not primary here)"
                    % (self.whoami, msg.pool, msg.ps))
            elif getattr(pg, "_scrub_cmd_running", False):
                self.ctx.log.info(
                    "osd", "pg %s scrub already running" % pg.pgid)
            else:
                pg._scrub_cmd_running = True

                async def run_scrub(pg=pg, deep=bool(msg.deep),
                                    repair=bool(msg.repair)):
                    try:
                        await self.scrubber.scrub_pg(
                            pg, deep=deep, repair=repair)
                    finally:
                        pg._scrub_cmd_running = False

                self.msgr.spawn(run_scrub())
        elif isinstance(msg, MOSDRepScrub):
            q((msg.pool, msg.ps), K_SCRUB,
              lambda: self.scrubber.handle_rep_scrub(conn, msg))
        elif isinstance(msg, MOSDRepScrubMap):
            self.scrubber.handle_rep_scrub_map(msg)
        elif isinstance(msg, MOSDECSubOpWrite):
            self._track(msg, "ec_sub_write(%s tid=%s %d.%x shard=%s)"
                        % (msg.src, msg.tid, msg.pool, msg.ps,
                           msg.shard))
            q((msg.pool, msg.ps), K_CLIENT,
              lambda: self.ec.handle_sub_write(conn, msg),
              tenant=getattr(msg, "tenant", None))
        elif isinstance(msg, MOSDECSubOpWriteReply):
            self.ec.handle_sub_write_reply(msg)
        elif isinstance(msg, MOSDECSubOpRead):
            q((msg.pool, msg.ps), K_CLIENT,
              lambda: self.ec.handle_sub_read(conn, msg))
        elif isinstance(msg, MOSDECSubOpReadReply):
            self.ec.handle_sub_read_reply(msg)
        else:
            return False
        return True

    # -- map handling ------------------------------------------------------

    def _handle_osd_map(self, msg: MOSDMapMsg) -> None:
        """Advance EPOCH BY EPOCH (OSD::advance_map walks every map):
        PGs must observe each intermediate interval so past_intervals
        records the acting sets that could have served writes while
        this osd was behind or down."""
        from .osdmap import Incremental, OSDMap

        changed = False
        if msg.full is not None:
            m = OSDMap.decode(msg.full)
            if m.epoch > self.osdmap.epoch:
                if self.osdmap.epoch > 0 \
                        and m.epoch > self.osdmap.epoch + 1:
                    # full-map fallback across a gap: the intervals
                    # inside it cannot be reconstructed (the reference
                    # replays stored old maps; this build's mons ship
                    # contiguous incrementals, so this is the rare
                    # store-gap path) — past_intervals coverage is
                    # conservative-by-last-known here
                    self.ctx.log.info(
                        "osd", "osd.%d map jump %d -> %d: interval "
                        "history across the gap is approximate"
                        % (self.whoami, self.osdmap.epoch, m.epoch))
                # pool deletion is a TRANSITION event: on a real jump
                # (we had a nonzero epoch) drop PGs of pools gone from
                # the new map; a boot-time replay starting below the
                # pool's creation epoch must NOT drop loaded PGs
                if self.osdmap.epoch > 0:
                    self._drop_pgs_for_pools(
                        {pg.pool for pg in self.pgs}
                        - m.pools.keys())
                self.osdmap = m
                changed = True
                self._advance_pgs()
        for raw in msg.incrementals or []:
            inc = Incremental.decode(raw)
            if inc.epoch == self.osdmap.epoch + 1:
                self.osdmap.apply_incremental(inc)
                changed = True
                if inc.old_pools:
                    self._drop_pgs_for_pools(set(inc.old_pools))
                self._advance_pgs()
        up_here = (self.osdmap.is_up(self.whoami)
                   and self.osdmap.osd_addrs.get(self.whoami)
                   == self.msgr.addr)
        if not self.booted:
            if up_here:
                self.booted = True
                self.ctx.log.info("osd", "osd.%d booted" % self.whoami)
            else:
                self._send_boot()
        elif not up_here:
            # map says we are down but we are alive: protest and
            # re-boot (OSD "wrongly marked me down" flow)
            self.booted = False
            self._boot_sent_epoch = -1
            self._send_mons(MOSDAlive(osd=self.whoami,
                                      epoch=self.osdmap.epoch))
            self._send_boot()
        if not changed or self.osdmap.epoch == 0:
            return
        self.ctx.log.debug(
            "osd", "osd.%d at epoch %d" % (self.whoami,
                                           self.osdmap.epoch))
        waiting, self._waiting_for_map = self._waiting_for_map, []
        for conn, m in waiting:
            self._handle_op(conn, m)

    def _send_boot(self) -> None:
        epoch = self.osdmap.epoch if self.osdmap else 0
        if self._boot_sent_epoch >= 0 and epoch <= self._boot_sent_epoch:
            return  # already asked; wait for a newer epoch
        self._boot_sent_epoch = epoch
        self._send_mons(MOSDBoot(osd=self.whoami, addr=self.msgr.addr,
                                 epoch=epoch))

    def _split_pgs(self, pool_id: int, pool) -> None:
        """In-place PG split after a pg_num grow (PG::split_into /
        OSD::split_pgs condensed).  With pgp_num unchanged a child PG
        keeps its parent's placement (ceph_stable_mod folds the child
        ps back onto the parent's pps), so the split is purely local:
        every acting member deterministically moves each object whose
        hash now lands in a child into the child's collection, along
        with the log entries and missing rows naming it.  All members
        run the identical function on the same map epoch, so child
        logs/infos agree at the next peering without data movement.

        Also run with no recorded previous pg_num (post-restart): the
        sweep is idempotent — objects already in the right collection
        never move.  Clone hobjects ride the generic loop (identity =
        name+snap; a clone's name hashes with its head)."""
        for pgid in [p for p in list(self.pgs) if p.pool == pool_id]:
            pg = self.pgs[pgid]
            moves: dict[int, list] = {}
            for ho in self.store.collection_list(pg.cid):
                if ho.name == "__pgmeta__":
                    continue
                target = pool.raw_pg_to_pg(
                    self.osdmap.object_locator_to_pg(
                        ho.name, pool_id)).ps
                if target != pg.ps:
                    moves.setdefault(target, []).append(ho)
            if not moves:
                continue
            self.ctx.log.info(
                "osd", "osd.%d splitting pg %s: %d objects -> %s"
                % (self.whoami, pg.pgid,
                   sum(len(v) for v in moves.values()),
                   sorted(moves)))
            for child_ps, hos in sorted(moves.items()):
                cid = pg_t(pool_id, child_ps)
                child = self.pgs.get(cid)
                if child is None:
                    child = PG(self, pool_id, child_ps)
                    child.create_onstore()
                    self.pgs[cid] = child
                t = Transaction()
                moved = {ho.name for ho in hos}
                for ho in hos:
                    t.touch(child.cid, ho)
                    data = self.store.read(pg.cid, ho)
                    t.write(child.cid, ho, 0, len(data), data)
                    for k, v in self.store.getattrs(pg.cid,
                                                    ho).items():
                        t.setattr(child.cid, ho, k, v)
                    om = self.store.omap_get(pg.cid, ho)
                    if om:
                        t.omap_setkeys(child.cid, ho, om)
                    t.remove(pg.cid, ho)
                # the child inherits the parent's log entries for its
                # objects (delta recovery stays possible) and the
                # parent's version horizon, so every member's child
                # agrees at peering
                have = {e.version for e in child.log.entries}
                for e in pg.log.entries:
                    if e.oid in moved and e.version not in have:
                        child.log.append(e)
                        child.persist_log_entry(t, e)
                if pg.info.last_update > child.info.last_update:
                    child.info.last_update = pg.info.last_update
                for oid in list(pg.missing):
                    if oid in moved:
                        child.missing[oid] = pg.missing.pop(oid)
                for osd_id, pm in pg.peer_missing.items():
                    for oid in [o for o in pm if o in moved]:
                        child.peer_missing.setdefault(
                            osd_id, {})[oid] = pm.pop(oid)
                child.persist_meta(t)
                pg.persist_meta(t)
                self.store.apply_transaction(t)

    def _advance_pgs(self) -> None:
        """Recompute mappings; create/advance PGs (OSD::advance_map).
        Large maps route through the bulk device mapper instead of
        per-PG scalar calls (the ParallelPGMapper role,
        OSDMapMapping.h:18)."""
        m = self.osdmap
        # pg_num growth: split local PGs BEFORE mappings recompute so
        # freshly-created children already hold their objects.  An
        # unknown previous value (first map after boot/restart) runs
        # the idempotent sweep too — a split may have happened while
        # this osd was down.
        for pool_id, pool in m.pools.items():
            prev = self._pool_pg_num.get(pool_id)
            if (prev is None and self.pgs) or \
                    (prev is not None and pool.pg_num > prev):
                self._split_pgs(pool_id, pool)
            self._pool_pg_num[pool_id] = pool.pg_num
        for pool_id in list(self._pool_pg_num):
            if pool_id not in m.pools:
                del self._pool_pg_num[pool_id]
        mapping = None
        if sum(p.pg_num for p in m.pools.values()) >= 256:
            try:
                from ..parallel.mapping import OSDMapMapping

                mapping = OSDMapMapping(
                    m, chip=(self.device_chip.index
                             if self.device_chip is not None
                             else None))
            except Exception:
                mapping = None
        for pool_id, pool in m.pools.items():
            for ps in range(pool.pg_num):
                pgid = pg_t(pool_id, ps)
                if mapping is not None:
                    up, upp, acting, actingp = mapping.get(pgid)
                else:
                    up, upp, acting, actingp = \
                        m.pg_to_up_acting_osds(pgid)
                mine = self.whoami in acting
                pg = self.pgs.get(pgid)
                if pg is None:
                    if not mine:
                        continue
                    pg = PG(self, pool_id, ps)
                    pg.create_onstore()
                    self.pgs[pgid] = pg
                self._advance_pg(pg, up, upp, acting, actingp)

    def _drop_pgs_for_pools(self, pools: set[int]) -> None:
        for pgid in [p for p in self.pgs if p.pool in pools]:
            pg = self.pgs.pop(pgid)
            # a deleted pool answers nothing: retire tracked state so
            # parked/in-flight ops don't read as stuck forever
            for st in pg.in_flight.values():
                top = st.get("top")
                if top is not None:
                    top.finish("aborted_pool_deleted")
            for _conn, m in pg.waiting_for_active:
                self._op_finish(m, "dropped_pool_deleted")

    def _advance_pg(self, pg: PG, up, upp, acting, actingp) -> None:
        interval_changed = (acting != pg.acting or actingp != pg.primary)
        if interval_changed and pg.acting:
            # remember the data-holding set for pg_temp pinning
            pg.prev_acting = list(pg.acting)
        if interval_changed and pg.info.same_interval_since \
                and pg.acting:
            # close the ending interval into past_intervals
            # (PastIntervals::check_new_interval): it "maybe went rw"
            # iff it had a primary whose up_thru reached the interval
            # and enough acting members to meet min_size
            pool = self.osdmap.pools.get(pg.pool_id)
            members = [o for o in pg.acting if 0 <= o != ITEM_NONE]
            rw = (pg.primary >= 0 and pg.primary != ITEM_NONE
                  and len(members) >= (pool.min_size if pool else 1)
                  and (self.osdmap.get_up_thru(pg.primary)
                       >= pg.info.same_interval_since))
            pg.past_intervals.append({
                "first": pg.info.same_interval_since,
                "last": self.osdmap.epoch - 1,
                "up": list(pg.up), "acting": list(pg.acting),
                "primary": pg.primary, "rw": rw})
        pg.up, pg.acting, pg.primary = up, acting, actingp
        if not interval_changed:
            if pg.state in (STATE_ACTIVE, STATE_REPLICA):
                # ops can be parked by the min_size gate while acting
                # members are down; a peer rejoining without an
                # acting-set change (e.g. pg_temp pinning) triggers no
                # peering, so retry them on every map advance
                if pg.state == STATE_ACTIVE and pg.waiting_for_active \
                        and pg.is_primary():
                    self._requeue_waiters(pg)
                # the map may have added removed_snaps: start trimming
                self._maybe_snap_trim(pg)
            elif pg.state == STATE_PEERING and pg.is_primary():
                # same interval, new map: a blocked prior set may have
                # a member back up, or our up_thru bump may have landed
                if pg.peering_blocked:
                    self._start_peering(pg)
                elif pg.waiting_up_thru and \
                        self.osdmap.get_up_thru(self.whoami) \
                        >= pg.waiting_up_thru:
                    pg.waiting_up_thru = 0
                    self._finish_peering(pg)
                elif pg.waiting_up_thru:
                    self._request_up_thru(pg.waiting_up_thru)
                elif any(v is None and not self.osdmap.is_up(o)
                         for o, v in pg.waiting_for_peers.items()):
                    # a queried prior member died mid-round: recompute
                    # the prior set (it may now be blocked, or smaller)
                    self._start_peering(pg)
            return
        pg.info.same_interval_since = self.osdmap.epoch
        # repops aborted by the interval change will never be acked:
        # retire their tracked ops (the client re-targets and resends
        # on the same map change, so no reply is owed from here)
        for st in pg.in_flight.values():
            top = st.get("top")
            if top is not None:
                top.finish("aborted_interval_change")
        pg.in_flight.clear()
        if not pg.is_primary() and pg.waiting_for_active:
            # parked ops on a demoted primary would wait forever (only
            # a primary requeues); the client resends to the new
            # primary on this same map change — drop and retire them
            parked, pg.waiting_for_active = pg.waiting_for_active, []
            for _conn, m in parked:
                self._op_finish(m, "dropped_interval_change")
        # recovery targets that left the up/acting set die with the
        # interval: peering only refreshes entries for peers it
        # re-queries, so a departed osd's stale peer_missing would
        # otherwise read as "recovery outstanding" forever (wedging
        # active+clean) and re-kick recovery toward a ghost
        pg.peer_missing = {o: m for o, m in pg.peer_missing.items()
                           if o in pg.acting or o in pg.up}
        # registrations die with the interval; clients re-watch at the
        # new primary when they see the map change
        self.watches.pg_reset(pg.pool_id, pg.ps)
        pool = self.osdmap.pools.get(pg.pool_id)
        if pool is not None and pool.is_erasure():
            # a reshuffled acting set can leave this osd holding bytes
            # for a position it no longer has: mark them missing
            for oid, op in self.ec.scan_stale_shards(pg).items():
                pg.missing.setdefault(oid, op)
        # durable interval history: a restart mid-outage must still
        # know which past acting sets may hold newer writes
        t = Transaction()
        pg.persist_meta(t)
        self.store.apply_transaction(t)
        if pg.is_primary():
            self._start_peering(pg)
        else:
            pg.state = STATE_REPLICA

    # -- peering (primary) -------------------------------------------------

    def _build_prior(self, pg: PG) -> tuple[set[int], bool]:
        """PeeringState::build_prior: everyone who might hold writes —
        current acting peers plus live members of every past interval
        that may have gone rw.  Blocked (PG down) when some rw
        interval has NO live member at all (and we were not in it):
        its writes could exist only on the dead osds, so activating
        now could adopt stale authority."""
        prior = {o for o in pg.acting
                 if 0 <= o != self.whoami and o != ITEM_NONE}
        blocked = False
        for iv in pg.past_intervals:
            if not iv.get("rw"):
                continue
            members = [o for o in iv["acting"]
                       if 0 <= o != ITEM_NONE]
            live = [o for o in members
                    if o != self.whoami and self.osdmap.is_up(o)]
            prior.update(live)
            if members and not live and self.whoami not in members:
                blocked = True
        return prior, blocked

    def _request_up_thru(self, want: int) -> None:
        """Ask the mon to record our up_thru >= want (prepare_alive
        path); deduped per epoch so N PGs in one interval send once."""
        if getattr(self, "_up_thru_asked", (0, 0)) >= \
                (want, self.osdmap.epoch):
            return
        self._up_thru_asked = (want, self.osdmap.epoch)
        self._send_mons(MOSDAlive(osd=self.whoami,
                                  epoch=self.osdmap.epoch,
                                  want_up_thru=want))

    def _start_peering(self, pg: PG) -> None:
        pg.state = STATE_PEERING
        pg.peer_info.clear()
        pg.waiting_for_peers = {}
        pg.waiting_for_log = None
        pg.waiting_up_thru = 0
        prior, blocked = self._build_prior(pg)
        pg.peering_blocked = blocked
        if blocked:
            # PG down: every member of a maybe-rw interval is dead.
            # Hold peering until a map change brings one back
            # (PeeringState Down state)
            self.ctx.log.info(
                "osd", "pg %s down: prior rw interval has no live "
                "member" % pg.pgid)
            return
        peers = sorted(o for o in prior if self.osdmap.is_up(o)
                       or o in pg.acting)
        if not peers:
            self._finish_peering(pg)
            return
        epoch = self.osdmap.epoch
        pg.waiting_for_peers = {o: None for o in peers}
        for o in peers:
            self._send_osd(o, MOSDPGQuery(pool=pg.pool_id, ps=pg.ps,
                                          epoch=epoch, query="info",
                                          since=None))

    def _handle_pg_query(self, conn, msg: MOSDPGQuery) -> None:
        """Replica side.  query="info": peer state only (the GetInfo
        round never ships log entries).  query="log": entries newer
        than `since` (the bounded GetLog fetch, PeeringState GetLog ->
        MOSDPGLog)."""
        pg = self.pgs.get(pg_t(msg.pool, msg.ps))
        if pg is None:
            pg = PG(self, msg.pool, msg.ps)
            pg.create_onstore()
            self.pgs[pg_t(msg.pool, msg.ps)] = pg
        if msg.query == "log":
            since = tuple(msg.since) if msg.since else (0, 0)
            if since < pg.log.tail:
                # requester is behind our tail: entries cannot catch
                # it up — ship the live-object inventory so it can
                # backfill itself (reset + pull everything)
                payload = self._pack_log(pg, activate=False)
                payload["objects"] = [
                    h.name for h in
                    self.store.collection_list(pg.cid)
                    if h.name != "__pgmeta__"]
            else:
                payload = self._pack_log(pg, activate=False,
                                         since=since)
            payload["is_log_reply"] = True
        else:
            payload = self._pack_log(pg, activate=False,
                                     info_only=True)
        conn.send(MOSDPGLog(pool=msg.pool, ps=msg.ps,
                            epoch=msg.epoch, info=payload))

    def _pack_log(self, pg: PG, activate: bool,
                  since: tuple | None = None,
                  info_only: bool = False,
                  backfill: bool = False) -> dict:
        """Peering payload.  since bounds the entries to the delta a
        peer at that version needs (round-2 verdict: full logs on every
        round collapse at real log lengths); info_only ships none."""
        if info_only:
            entries = []
        elif since is not None:
            entries = [e for e in pg.log.entries if e.version > since]
        else:
            entries = pg.log.entries
        return {
            "activate": activate,
            "info": pg.info.to_wire(),
            "log": [e.to_wire() for e in entries],
            "log_tail": list(pg.log.tail),
            "since": (list(since) if since is not None else None),
            "backfill": backfill,
            # objects this osd knows it lacks (e.g. stale EC shards)
            "missing": {oid: op for oid, op in pg.missing.items()},
        }

    def _handle_pg_log(self, conn, msg: MOSDPGLog) -> None:
        pgid = pg_t(msg.pool, msg.ps)
        pg = self.pgs.get(pgid)
        if pg is None:
            return
        payload = msg.info
        if payload.get("activate"):
            self._activate_replica(conn, pg, payload)
            return
        sender = int(msg.src.split(".")[1])
        if payload.get("need_full"):
            # a replica's log diverged from the delta we sent: re-sync
            # with the full log.  When the replica shipped its log we
            # compute the divergence boundary and push ONLY the
            # affected objects (PGLog::merge_log); without it, the
            # conservative whole-log re-push
            if pg.is_primary() and pg.state == STATE_ACTIVE:
                from .pg import merge_divergent
                miss = pg.peer_missing.setdefault(sender, {})
                narrow = None
                peer_entries = [LogEntry.from_wire(w)
                                for w in payload.get("my_log") or []]
                if peer_entries:
                    narrow = merge_divergent(peer_entries,
                                             pg.log.entries)
                if narrow is not None:
                    miss.update(narrow)
                else:
                    for e in peer_entries:
                        miss.setdefault(e.oid, LogEntry.MODIFY)
                    for e in pg.log.entries:
                        miss.setdefault(e.oid, e.op)
                self._send_osd(sender, MOSDPGLog(
                    pool=pg.pool_id, ps=pg.ps,
                    epoch=self.osdmap.epoch,
                    info=self._pack_log(pg, activate=True)))
                self._kick_recovery(pg)
            return
        # primary collecting peering responses
        if pg.state != STATE_PEERING:
            return
        if payload.get("is_log_reply"):
            if getattr(pg, "waiting_for_log", None) != sender:
                return
            if self._merge_authoritative(pg, payload):
                pg.waiting_for_log = None
                self._after_log(pg)
            return
        if sender not in pg.waiting_for_peers:
            return
        pg.waiting_for_peers[sender] = payload
        if all(v is not None for v in pg.waiting_for_peers.values()):
            self._choose_authoritative(pg)

    def _choose_authoritative(self, pg: PG) -> None:
        """find_best_info: highest last_update wins.  When a peer is
        best, fetch only the entries past our own last_update (the
        GetLog bounded request) instead of having every info round
        carry whole logs."""
        best_osd = self.whoami
        best_lu = pg.info.last_update
        for osd, payload in pg.waiting_for_peers.items():
            lu = tuple(payload["info"]["last_update"])
            if lu > best_lu:
                best_lu, best_osd = lu, osd
        for osd, payload in pg.waiting_for_peers.items():
            pg.peer_info[osd] = PGInfo.from_wire(payload["info"])
        if best_osd != self.whoami and best_lu > pg.info.last_update:
            pg.waiting_for_log = best_osd
            pg.auth_osd = best_osd
            self._send_osd(best_osd, MOSDPGQuery(
                pool=pg.pool_id, ps=pg.ps, epoch=self.osdmap.epoch,
                query="log", since=list(pg.info.last_update)))
            return
        pg.auth_osd = self.whoami
        self._after_log(pg)

    def _merge_authoritative(self, pg: PG, payload: dict) -> bool:
        """PGLog::merge_log on the primary: append the authoritative
        delta when it chains onto our head.  A non-chaining delta
        means the histories diverged — re-fetch the FULL log once and
        resolve divergence by re-syncing every logged object
        (conservative divergent-entry resolution: pushes and pulls of
        authoritative copies converge the data either way).  Returns
        False while the full-log round trip is in flight."""
        entries = [LogEntry.from_wire(w) for w in payload["log"]]
        tail = tuple(payload["log_tail"])
        last_update = tuple(payload["info"]["last_update"])
        since = (tuple(payload["since"]) if payload.get("since")
                 else None)
        pool = self.osdmap.pools.get(pg.pool_id)
        mine = pg.info.last_update
        chains = (since == mine and tail <= mine
                  and (not entries or entries[0].prior_version == mine))
        t = Transaction()
        if since is not None and chains:
            # incremental: keep our prefix, append the delta
            for e in entries:
                if e.version > mine:
                    pg.missing[e.oid] = e.op
                    pg.log.append(e)
                    pg.persist_log_entry(t, e)
        elif payload.get("objects") is not None:
            # the auth log is trimmed past us: self-backfill — reset
            # our data objects and pull the authoritative inventory
            for h in self.store.collection_list(pg.cid):
                if h.name != "__pgmeta__":
                    t.remove(pg.cid, h)
            pg.missing = {o: LogEntry.MODIFY
                          for o in payload["objects"]}
            for e in entries:
                pg.missing.setdefault(e.oid, e.op)
            pg.replace_log(t, entries, tail)
        elif since is not None and since != (0, 0):
            # non-chaining delta: the partial entries are useless —
            # ask for the whole log (once; a (0,0) request's reply
            # lands in the branch below)
            self._send_osd(pg.waiting_for_log, MOSDPGQuery(
                pool=pg.pool_id, ps=pg.ps, epoch=self.osdmap.epoch,
                query="log", since=[0, 0]))
            return False
        else:
            # divergent histories, full log in hand: roll back only
            # the entries past the common boundary when the logs
            # share history (PGLog::merge_log); otherwise every oid in
            # either log gets re-synced
            from .pg import merge_divergent
            narrow = merge_divergent(pg.log.entries, entries)
            if narrow is not None:
                pg.missing.update(narrow)
            else:
                if pool is None or not pool.is_erasure():
                    for e in pg.log.entries:
                        if e.version > tail:
                            pg.missing[e.oid] = LogEntry.MODIFY
                for e in entries:
                    pg.missing[e.oid] = e.op
            pg.replace_log(t, entries, tail)
        if last_update > pg.info.last_update:
            pg.info.last_update = last_update
        pg.persist_meta(t)
        self.store.apply_transaction(t)
        return True

    def _after_log(self, pg: PG) -> None:
        """Authoritative log settled: derive each peer's missing set.
        A peer whose last_update predates our log tail cannot be
        caught up by log entries — it becomes a backfill target
        (whole-PG resync, PeeringState Backfilling)."""
        pg.backfill_targets = set()
        for osd, payload in pg.waiting_for_peers.items():
            info = pg.peer_info.get(osd)
            if info is None or payload is None:
                continue
            if osd not in pg.acting and osd not in pg.up:
                # prior-interval stray: its info (and possibly its
                # log) fed authority; it is not a recovery target
                continue
            missing = {}
            if info.last_update >= pg.log.tail:
                missing = pg.log.objects_since(info.last_update)
            else:
                pg.backfill_targets.add(osd)
                for h in self.store.collection_list(pg.cid):
                    if h.name != "__pgmeta__":
                        missing[h.name] = LogEntry.MODIFY
                for e in pg.log.entries:
                    missing.setdefault(e.oid, e.op)
            missing.update(payload.get("missing") or {})
            pg.peer_missing[osd] = missing
        self._finish_peering(pg)

    def _finish_peering(self, pg: PG) -> None:
        # up_thru gate (PeeringState::adjust_need_up_thru / WaitUpThru):
        # before activating, the map must record that we were primary-
        # capable through this interval's start — otherwise a LATER
        # peering round could not tell whether this interval went rw,
        # and a stale primary could silently adopt authority
        need = pg.info.same_interval_since
        if self.osdmap.get_up_thru(self.whoami) < need:
            pg.waiting_up_thru = need
            self._request_up_thru(need)
            return                      # resumes on the bumped map
        pg.state = STATE_ACTIVE
        pg.peering_blocked = False
        # activation settles all prior history: last_epoch_started
        # advances and past intervals are consumed
        pg.info.last_epoch_started = self.osdmap.epoch
        pg.past_intervals = []
        t = Transaction()
        pg.persist_meta(t)
        self.store.apply_transaction(t)
        self._maybe_request_pg_temp(pg)
        # up-but-not-acting members (we are serving under a pg_temp
        # pin): backfill them too, so the pin can be released once
        # they hold everything (PeeringState Backfilling with the
        # acting set pinned to the previous interval's members)
        extra = [o for o in pg.up
                 if 0 <= o != ITEM_NONE and o not in pg.acting
                 and o != self.whoami]
        for osd in extra:
            missing = {}
            for h in self.store.collection_list(pg.cid):
                if h.name != "__pgmeta__":
                    missing[h.name] = LogEntry.MODIFY
            for e in pg.log.entries:
                missing.setdefault(e.oid, e.op)
            pg.peer_missing[osd] = missing
        # activate replicas with their DELTA of the authoritative log
        # (backfill targets get the full log and a reset flag)
        for osd in list(pg.acting) + extra:
            if 0 <= osd != self.whoami and osd != ITEM_NONE:
                if osd in getattr(pg, "backfill_targets", set()) \
                        or osd in extra:
                    payload = self._pack_log(pg, activate=True,
                                             backfill=True)
                else:
                    info = pg.peer_info.get(osd)
                    since = (info.last_update if info is not None
                             else None)
                    payload = self._pack_log(pg, activate=True,
                                             since=since)
                self._send_osd(osd, MOSDPGLog(
                    pool=pg.pool_id, ps=pg.ps, epoch=self.osdmap.epoch,
                    info=payload))
        self.ctx.log.debug(
            "osd", "pg %s active on osd.%d acting=%s missing=%d"
            % (pg.pgid, self.whoami, pg.acting, len(pg.missing)))
        if pg.missing or any(pg.peer_missing.values()):
            # stat-worthy transition: this interval starts degraded /
            # misplaced — report NOW, not at the next periodic tick,
            # so the stats plane observes the rise even when recovery
            # drains it faster than the report cadence (the reference
            # sends MPGStats on pg stat changes for the same reason)
            self._mgr_report_stamp = 0.0
            self._maybe_send_mgr_report()
        self._kick_recovery(pg)
        self._maybe_snap_trim(pg)
        if not pg.missing:
            self._requeue_waiters(pg)

    def _maybe_request_pg_temp(self, pg: PG) -> None:
        """queue_want_pg_temp (PeeringState.cc): when the fresh acting
        set needs backfill but the previous interval's members are
        alive and sufficient, ask the monitor to pin acting to them so
        clients keep full-strength service during backfill
        (OSDMonitor::prepare_pgtemp commits it; cleared when backfill
        completes).  Replicated pools only — EC acting sets are
        positional and pinning them needs shard-aware ordering."""
        from ..msg.messages import MOSDPGTemp
        pool = self.osdmap.pools.get(pg.pool_id)
        if pool is None or pool.is_erasure():
            return
        pgid = pg_t(pg.pool_id, pg.ps)
        if self.osdmap.pg_temp.get(pgid):
            return                      # already pinned
        if not getattr(pg, "backfill_targets", None):
            return
        prev = [o for o in getattr(pg, "prev_acting", [])
                if 0 <= o != ITEM_NONE and self.osdmap.is_up(o)]
        if len(prev) < pool.min_size:
            return
        if set(prev) == set(pg.acting):
            return
        if getattr(pg, "_temp_req_epoch", -1) >= self.osdmap.epoch:
            return
        pg._temp_req_epoch = self.osdmap.epoch
        self._send_mons(MOSDPGTemp(
            epoch=self.osdmap.epoch,
            pgs=[[pg.pool_id, pg.ps, prev]]))

    def _maybe_clear_pg_temp(self, pg: PG) -> None:
        """Backfill complete: every up member holds everything —
        release the pg_temp pin so acting flips to the real mapping."""
        from ..msg.messages import MOSDPGTemp
        pgid = pg_t(pg.pool_id, pg.ps)
        if not self.osdmap.pg_temp.get(pgid) or not pg.is_primary():
            return
        for o in pg.up:
            if o < 0 or o == ITEM_NONE or o == self.whoami:
                continue
            if pg.peer_missing.get(o):
                return                  # still backfilling
        if getattr(pg, "_temp_clear_epoch", -1) >= self.osdmap.epoch:
            return
        pg._temp_clear_epoch = self.osdmap.epoch
        self._send_mons(MOSDPGTemp(
            epoch=self.osdmap.epoch,
            pgs=[[pg.pool_id, pg.ps, []]]))

    def _activate_replica(self, conn, pg: PG, payload: dict) -> None:
        """Replica activation: append the delta when it chains onto
        our log; on divergence ask the primary for a full re-sync
        (need_full), reporting our logged oids so it re-pushes them;
        on backfill reset the local objects first."""
        entries = [LogEntry.from_wire(w) for w in payload["log"]]
        since = (tuple(payload["since"]) if payload.get("since")
                 else None)
        tail = tuple(payload["log_tail"])
        last_update = tuple(payload["info"]["last_update"])
        t = Transaction()
        if payload.get("backfill"):
            for h in self.store.collection_list(pg.cid):
                if h.name != "__pgmeta__":
                    t.remove(pg.cid, h)
            pg.missing = {e.oid: e.op for e in entries}
            pg.replace_log(t, entries, tail)
            pg.info.last_update = last_update
        elif since is not None:
            mine = pg.info.last_update
            # the delta chains when the part BEYOND our head continues
            # exactly from it; entries at or below our head are a
            # shared prefix (a re-peering round built its delta from a
            # pre-activation info snapshot) and are skipped, not
            # grounds for a full resync.  A replica below the
            # primary's log tail has a gap no delta can cover.
            new = [e for e in entries if e.version > mine]
            chains = (since <= mine and tail <= mine
                      and (not new or new[0].prior_version == mine))
            if not chains:
                conn.send(MOSDPGLog(
                    pool=pg.pool_id, ps=pg.ps,
                    epoch=self.osdmap.epoch,
                    info={"need_full": True,
                          "my_log": [e.to_wire()
                                     for e in pg.log.entries]}))
                return
            for e in entries:
                if e.version > mine:
                    pg.missing[e.oid] = e.op
                    pg.log.append(e)
                    pg.persist_log_entry(t, e)
            if last_update > pg.info.last_update:
                pg.info.last_update = last_update
        else:
            # full log (divergence re-sync): adopt the authoritative
            # log, rolling back ONLY the divergent objects when the
            # logs share history (PGLog::merge_log); disjoint
            # histories keep the conservative whole-log resync
            from .pg import merge_divergent
            narrow = merge_divergent(pg.log.entries, entries)
            pool = self.osdmap.pools.get(pg.pool_id)
            if narrow is not None:
                pg.missing.update(narrow)
            else:
                if pool is None or not pool.is_erasure():
                    pg.missing = {}
                for e in entries:
                    pg.missing[e.oid] = e.op
            pg.replace_log(t, entries, tail)
            pg.info.last_update = last_update
        # activation consumes our interval history too: the primary's
        # authority covers it (peering heard us)
        pg.info.last_epoch_started = self.osdmap.epoch
        pg.past_intervals = []
        pg.persist_meta(t)
        self.store.apply_transaction(t)
        pg.state = STATE_REPLICA

    # -- recovery ----------------------------------------------------------

    def _kick_recovery(self, pg: PG) -> None:
        pool = self.osdmap.pools.get(pg.pool_id)
        if pool is not None and pool.is_erasure():
            self.msgr.spawn(self._ec_recover(pg))
            return
        self.msgr.spawn(self._replicated_recover(pg))

    def _span_recovery(self, pg: PG, t0: float, had: bool) -> None:
        """Record one recovery flow on the flight recorder (only
        flows that had work: the watchdog re-kicks idly)."""
        fr = getattr(self.ctx, "flight_recorder", None)
        if fr is not None and had:
            fr.span("recovery", t0, meta={"pgid": str(pg.pgid)})

    def _note_recovery_progress(self, pg: PG) -> None:
        """Drain the PG's recovery progress row: outstanding work is
        what the primary still lacks plus what its peers lack; zero
        outstanding finishes the bar."""
        outstanding = (len(pg.missing)
                       + sum(len(m)
                             for m in pg.peer_missing.values()))
        self.progress.drain("recovery/%s" % pg.pgid, outstanding)

    def _progress_rows(self) -> dict:
        """Report-time progress snapshot: refresh each primary's
        recovery drain first so a flow whose last push landed between
        reports still reaches 1.0 rather than stalling."""
        for pg in self.pgs.values():
            if pg.is_primary():
                self._note_recovery_progress(pg)
        return self.progress.rows()

    async def _replicated_recover(self, pg: PG) -> None:
        """Paced replicated recovery: pull/push in chunks, each chunk
        admitted through the mClock 'recovery' class so client I/O
        keeps its reservation during a recovery storm (the reference
        paces via osd_recovery_max_active + mClock op tags)."""
        from .scheduler import K_RECOVERY
        if getattr(pg, "_recovery_flow", False):
            return
        pg._recovery_flow = True
        had_work = bool(pg.missing
                        or any(pg.peer_missing.values()))
        if had_work:
            self.progress.start(
                "recovery", str(pg.pgid),
                total=len(pg.missing) + sum(
                    len(m) for m in pg.peer_missing.values()))
        t_rec0 = self.optracker.now()
        chunk = 16
        acting0 = list(pg.acting)
        try:
            if pg.missing:
                # pull what the primary lacks from a peer PROVEN to
                # have it: the authoritative log's owner first, else a
                # peer whose info reached the authoritative head (a
                # stale prior-interval stray also sits in peer_info —
                # pulling from it would adopt old data as recovered)
                src = None
                auth = getattr(pg, "auth_osd", self.whoami)
                if auth != self.whoami and self.osdmap.is_up(auth):
                    src = auth
                if src is None:
                    for osd, info in pg.peer_info.items():
                        if (not pg.peer_missing.get(osd)
                                and info.last_update
                                >= pg.info.last_update):
                            src = osd
                            break
                if src is None:
                    for osd in pg.acting:
                        if 0 <= osd != self.whoami and osd != ITEM_NONE:
                            src = osd
                            break
                if src is not None:
                    oids = sorted(pg.missing)
                    pg.recovering.update(oids)
                    for i in range(0, len(oids), chunk):
                        part = oids[i:i + chunk]
                        await self.sched.admit(
                            K_RECOVERY, cost=len(part),
                            key=(pg.pool_id, pg.ps))
                        if pg.acting != acting0 or self.stopping:
                            return      # interval changed: re-peer
                        self._send_osd(src, MOSDPGPush(
                            pool=pg.pool_id, ps=pg.ps,
                            epoch=self.osdmap.epoch,
                            pushes=[{"pull": True, "oids": part}]))
                return
            # push to replicas missing objects
            for osd, missing in list(pg.peer_missing.items()):
                if not missing:
                    continue
                items = sorted(missing.items())
                for i in range(0, len(items), chunk):
                    part = items[i:i + chunk]
                    await self.sched.admit(K_RECOVERY, cost=len(part),
                                           key=(pg.pool_id, pg.ps))
                    if pg.acting != acting0 or self.stopping:
                        return
                    pushes = [self._make_push(pg, oid, op)
                              for oid, op in part]
                    pg.stats.note_recovery(0, sum(
                        len(p.get("data") or b"") for p in pushes))
                    self._send_osd(osd, MOSDPGPush(
                        pool=pg.pool_id, ps=pg.ps,
                        epoch=self.osdmap.epoch, pushes=pushes))
        finally:
            pg._recovery_flow = False
            self._span_recovery(pg, t_rec0, had_work)
            if had_work:
                self._note_recovery_progress(pg)

    async def _ec_recover(self, pg: PG) -> None:
        """EC recovery: reconstruct (never copy) shards
        (ECBackend::continue_recovery_op).  The _recovery_flow guard
        keeps the heartbeat watchdog from stacking concurrent flows
        while mClock paces this one."""
        if getattr(pg, "_recovery_flow", False):
            return
        pg._recovery_flow = True
        had_work = bool(pg.missing
                        or any(pg.peer_missing.values()))
        if had_work:
            self.progress.start(
                "recovery", str(pg.pgid),
                total=len(pg.missing) + sum(
                    len(m) for m in pg.peer_missing.values()))
        t_rec0 = self.optracker.now()
        try:
            await self.ec.recover_primary_shards(pg)
            for osd_id, missing in list(pg.peer_missing.items()):
                if missing:
                    await self.ec.recover_peer_shards(pg, osd_id,
                                                      missing)
        finally:
            pg._recovery_flow = False
            self._span_recovery(pg, t_rec0, had_work)
            if had_work:
                self._note_recovery_progress(pg)
        if not pg.missing:
            self._requeue_waiters(pg)

    def _make_push(self, pg: PG, oid: str, op: str) -> dict:
        from . import snaps as snapmod
        ho = hobject_t(oid)
        if op == LogEntry.DELETE or not self.store.exists(pg.cid, ho):
            return {"oid": oid, "delete": True}
        push = {
            "oid": oid,
            "delete": False,
            "data": self.store.read(pg.cid, ho),
            "attrs": {k: v for k, v in
                      self.store.getattrs(pg.cid, ho).items()},
            "omap": self.store.omap_get(pg.cid, ho),
        }
        # snapshot clones travel with their head so a recovered
        # replica can serve snap reads (the reference recovers clones
        # as separate hobjects; whole-object pushes bundle them)
        ss = snapmod.load_snapset(self.store, pg.cid, ho)
        if ss and ss["clones"]:
            clones = []
            for c in ss["clones"]:
                cho = hobject_t(oid, snap=c)
                if not self.store.exists(pg.cid, cho):
                    continue
                clones.append({
                    "snap": c,
                    "data": self.store.read(pg.cid, cho),
                    "attrs": {k: v for k, v in
                              self.store.getattrs(pg.cid,
                                                  cho).items()},
                })
            if clones:
                push["clones"] = clones
        return push

    def _handle_pg_push(self, conn, msg: MOSDPGPush) -> None:
        pg = self.pgs.get(pg_t(msg.pool, msg.ps))
        if pg is None:
            return
        # pull request from the primary: respond with object pushes
        if msg.pushes and msg.pushes[0].get("pull"):
            oids = msg.pushes[0]["oids"]
            pushes = [self._make_push(pg, oid,
                                      pg.log.objects_since((0, 0)).get(
                                          oid, LogEntry.MODIFY))
                      for oid in oids]
            conn.send(MOSDPGPush(pool=msg.pool, ps=msg.ps,
                                 epoch=msg.epoch, pushes=pushes))
            return
        # real pushes: apply objects ("snap" targets a clone object —
        # EC clone-shard recovery)
        from ..store.objectstore import NOSNAP
        t = Transaction()
        done = []
        for push in msg.pushes:
            ho = hobject_t(push["oid"],
                           snap=push.get("snap", NOSNAP))
            if push.get("delete"):
                if self.store.exists(pg.cid, ho):
                    t.remove(pg.cid, ho)
            else:
                t.remove(pg.cid, ho) if self.store.exists(pg.cid, ho) \
                    else None
                t.touch(pg.cid, ho)
                t.write(pg.cid, ho, 0, len(push["data"]), push["data"])
                for k, v in (push.get("attrs") or {}).items():
                    t.setattr(pg.cid, ho, k, v)
                if push.get("omap"):
                    t.omap_setkeys(pg.cid, ho, push["omap"])
                for cl in push.get("clones") or ():
                    cho = hobject_t(push["oid"], snap=cl["snap"])
                    if self.store.exists(pg.cid, cho):
                        t.remove(pg.cid, cho)
                    t.touch(pg.cid, cho)
                    t.write(pg.cid, cho, 0, len(cl["data"]),
                            cl["data"])
                    for k, v in (cl.get("attrs") or {}).items():
                        t.setattr(pg.cid, cho, k, v)
            done.append(push["oid"])
            pg.missing.pop(push["oid"], None)
            pg.recovering.discard(push["oid"])
        pg.info.last_complete = pg.info.last_update
        pg.persist_meta(t)
        self.store.apply_transaction(t)
        if pg.is_primary():
            # primary pulled its own missing objects: recovery
            # progress counted here (peer pushes count on the reply)
            pg.stats.note_recovery(len(done), sum(
                len(p.get("data") or b"") for p in msg.pushes))
            self._note_recovery_progress(pg)
        conn.send(MOSDPGPushReply(pool=msg.pool, ps=msg.ps,
                                  epoch=msg.epoch, oids=done))
        if pg.is_primary() and not pg.missing:
            # primary finished pulling: now push to replicas + serve
            self._kick_recovery(pg)
            self._requeue_waiters(pg)

    def _handle_pg_push_reply(self, msg: MOSDPGPushReply) -> None:
        pg = self.pgs.get(pg_t(msg.pool, msg.ps))
        if pg is None or not pg.is_primary():
            return
        sender = int(msg.src.split(".")[1])
        pm = pg.peer_missing.get(sender)
        if pm:
            recovered = 0
            for oid in msg.oids:
                if pm.pop(oid, None) is not None:
                    recovered += 1
            pg.stats.note_recovery(recovered)
            self._note_recovery_progress(pg)
            # degraded-object writes park until their replicas are
            # whole again: re-gate them now
            if pg.waiting_for_active and pg.state == STATE_ACTIVE:
                self._requeue_waiters(pg)
        self._maybe_clear_pg_temp(pg)

    def _requeue_waiters(self, pg: PG) -> None:
        self._release_backoffs(pg)
        waiting, pg.waiting_for_active = pg.waiting_for_active, []
        for conn, msg in waiting:
            self._handle_op(conn, msg)

    # -- client backoff (PrimaryLogPG add_backoff / osd_backoff) -----------

    def _send_backoff(self, pg: PG, conn, oid: str | None = None) -> None:
        """Tell the client to stop re-sending ops for this PG (oid
        None) or one degraded object of it (the reference's
        hobject-ranged backoffs): the op is parked here and will be
        answered when the PG activates / the object recovers.  Without
        this, the Objecter's timeout-resend ramp would spam a peering /
        below-min-size PG with duplicates.  A PG-wide block supersedes
        object blocks, so none is sent while one is live."""
        if conn.peer_entity.startswith("osd"):
            return
        if (conn, None) in pg.backoffs or (conn, oid) in pg.backoffs:
            return
        self._backoff_id += 1
        pg.backoffs[(conn, oid)] = self._backoff_id
        conn.send(MOSDBackoff(pool=pg.pool_id, ps=pg.ps, op="block",
                              id=self._backoff_id, oid=oid,
                              epoch=self.osdmap.epoch))

    def _release_backoffs(self, pg: PG, oid: str | None = None) -> None:
        """Release every backoff (oid None) or just one object's."""
        if oid is None:
            backoffs, pg.backoffs = pg.backoffs, {}
        else:
            backoffs = {k: v for k, v in pg.backoffs.items()
                        if k[1] == oid}
            for k in backoffs:
                del pg.backoffs[k]
        for (conn, boid), bid in backoffs.items():
            if conn.is_open:
                conn.send(MOSDBackoff(pool=pg.pool_id, ps=pg.ps,
                                      op="unblock", id=bid, oid=boid,
                                      epoch=self.osdmap.epoch))

    # -- client ops --------------------------------------------------------

    def _handle_op(self, conn, msg: MOSDOp) -> None:
        self._op_event(msg, "reached_pg")
        if self.osdmap is None or msg.epoch > self.osdmap.epoch:
            self._op_event(msg, "waiting_for_map")
            self._waiting_for_map.append((conn, msg))
            return
        pool = self.osdmap.pools.get(msg.pool)
        if pool is None:
            conn.send(MOSDOpReply(tid=msg.tid, result=-2, outs=[],
                                  epoch=self.osdmap.epoch, version=0))
            self._op_finish(msg, "no_such_pool")
            return
        if msg.oid:
            # split retarget: after a pg_num grow the object may now
            # belong to a child PG the sender's older map cannot see —
            # drop, the client re-targets on its next map (Objecter
            # _scan_requests); executing here would strand the write
            # in the parent PG the readers no longer consult
            actual = pool.raw_pg_to_pg(
                self.osdmap.object_locator_to_pg(msg.oid, msg.pool)).ps
            if actual != msg.ps:
                self._op_finish(msg, "dropped_wrong_pg_after_split")
                return
        pgid = pg_t(msg.pool, msg.ps)
        pg = self.pgs.get(pgid)
        if pg is None or not pg.is_primary():
            # not mine: drop — the client resends on map change
            # (Objecter handle_osd_map -> _scan_requests)
            self._op_finish(msg, "dropped_not_primary")
            return
        dup = pg.lookup_reqid(msg.src, msg.tid)
        if dup is not None:
            # reqid dup detection: a timeout-triggered resend of an
            # already-committed (possibly non-idempotent) op is
            # answered from the journal, never re-executed
            conn.send(MOSDOpReply(
                tid=msg.tid, result=dup["result"], outs=dup["outs"],
                epoch=self.osdmap.epoch, version=dup["version"]))
            self.perf.inc("dup_ops")
            self._op_finish(msg, "dup_answered_from_journal")
            return
        if pg.state != STATE_ACTIVE:
            self._op_event(msg, "waiting_for_active")
            pg.waiting_for_active.append((conn, msg))
            self._send_backoff(pg, conn)
            return
        if pool.is_erasure():
            if not self._min_size_ok(pg, pool):
                self._op_event(msg, "waiting_for_min_size")
                pg.waiting_for_active.append((conn, msg))
                self._send_backoff(pg, conn)
                return
            self.msgr.spawn(self.ec.handle_op(pg, conn, msg))
            return
        writes = any(self._op_is_write(o) for o in msg.ops)
        if not self._min_size_ok(pg, pool):
            self._op_event(msg, "waiting_for_min_size")
            pg.waiting_for_active.append((conn, msg))
            self._send_backoff(pg, conn)
            return
        if any(o["op"] in ("watch", "unwatch", "notify")
               for o in msg.ops):
            self.msgr.spawn(self._handle_watch_ops(pg, conn, msg))
            return
        oid = msg.oid
        if oid in pg.missing:
            # object-scoped backoff (the reference's hobject-ranged
            # add_backoff for degraded objects): only ops on THIS
            # object pause client-side; the rest of the PG flows
            self._op_event(msg, "waiting_for_missing_object")
            pg.waiting_for_active.append((conn, msg))
            self._send_backoff(pg, conn, oid=oid)
            self._kick_recovery(pg)
            return
        if writes and any(oid in (pg.peer_missing.get(o) or {})
                          for o in pg.acting
                          if 0 <= o != self.whoami
                          and o != ITEM_NONE
                          and o not in getattr(pg, "backfill_targets",
                                               set())):
            # wait_for_degraded_object (PrimaryLogPG.cc): a write to
            # an object a log-recovering replica still lacks would
            # ship ops (truncate, partial write) it cannot apply —
            # recover it first, then requeue.  Backfill targets are
            # exempt (their peer_missing is the WHOLE collection; the
            # reference keeps the PG writable through backfill) — the
            # replica apply path tolerates their absent objects.
            self._op_event(msg, "waiting_for_degraded_object")
            pg.waiting_for_active.append((conn, msg))
            self._send_backoff(pg, conn, oid=oid)
            self._kick_recovery(pg)
            return
        if pool.compression_mode == "force" \
                and not pool.is_erasure():
            # compression pools: the compress/decompress CPU work is
            # paced through the device runtime's background class so
            # a compressed burst cannot starve client EC dispatches
            self.msgr.spawn(
                self._compression_paced(pg, conn, msg, writes))
            return
        if getattr(pool, "dedup_chunk_pool", -1) >= 0 \
                and not pool.is_erasure():
            # dedup base pools: chunk/fingerprint planning plus the
            # chunk-store I/O are async (internal objecter) and ride
            # the same background admission class as compression
            self.msgr.spawn(
                self.dedup.handle_op(pg, conn, msg, writes))
            return
        if writes:
            self._execute_write(pg, conn, msg)
        else:
            self._serve_read(pg, conn, msg)

    def _serve_read(self, pg: PG, conn, msg) -> None:
        outs, result = self._do_read_ops(
            pg, msg.oid, msg.ops, getattr(msg, "snapid", None),
            entity=msg.src)
        conn.send(MOSDOpReply(tid=msg.tid, result=result,
                              outs=outs, epoch=self.osdmap.epoch,
                              version=0))
        self.perf.inc("ops")
        pg.stats.note_read(sum(
            len(o.get("data") or b"") for o in outs
            if isinstance(o, dict)))
        self._op_finish(msg, "read_done")

    async def _compression_paced(self, pg: PG, conn, msg,
                                 writes: bool) -> None:
        """Pool-level compress/decompress rides the device runtime's
        BACKGROUND admission class (weight below recovery): a
        compressed-pool burst queues behind the data-path dispatch
        grants instead of interleaving freely with them, so client EC
        flushes keep their share of the chip.  A full admission queue
        degrades to unpaced execution — pacing must never fail or
        park the op itself.

        Pools whose algorithm is the device-native "tlz" additionally
        pre-plan their writefull compressions as device dispatches on
        this OSD's affinity chip (compress/tlz.compress_async) BEFORE
        the synchronous write executes — the expensive match phase
        leaves the event loop, and because the device and host paths
        emit byte-identical blobs, `_maybe_compress` consumes the
        pre-computed blob without any correctness coupling (any
        degradation inside compress_async already returned the host
        reference's bytes)."""
        from ..device.runtime import (DeviceBusy, DeviceRuntime,
                                      K_BACKGROUND)
        chip = (self.device_chip if self.device_chip is not None
                else DeviceRuntime.get().chip_for(self.whoami))
        cost = max(1.0, sum(len(op.get("data") or b"")
                            for op in msg.ops
                            if isinstance(op, dict)) / 65536.0)
        t0 = self.optracker.now()
        comp_pre: dict[int, bytes] | None = None
        pool = self.osdmap.pools.get(pg.pool_id)
        if writes and pool is not None \
                and pool.compression_algorithm == "tlz":
            from ..compress import tlz
            for i, op in enumerate(msg.ops):
                if not (isinstance(op, dict)
                        and op.get("op") == "writefull"):
                    continue
                data = op.get("data") or b""
                if len(data) < 128:
                    continue    # below _maybe_compress's floor
                try:
                    blob, path = await tlz.compress_async(
                        data, chip=chip.index, klass=K_BACKGROUND)
                except Exception:
                    continue    # host path inside _maybe_compress
                if comp_pre is None:
                    comp_pre = {}
                comp_pre[i] = blob
                self.perf.inc("comp_device_blobs"
                              if path == "device"
                              else "comp_host_blobs")
        granted = False
        try:
            await chip.queue.admit(K_BACKGROUND, cost)
            granted = True
            self.perf.inc("comp_paced_ops")
        except DeviceBusy:
            pass        # overloaded: run unpaced, never fail the op
        try:
            if writes:
                self._execute_write(pg, conn, msg,
                                    comp_pre=comp_pre)
            else:
                self._serve_read(pg, conn, msg)
        finally:
            if granted:
                chip.queue.release()
            fr = getattr(self.ctx, "flight_recorder", None)
            if fr is not None:
                fr.span("compression_paced", t0,
                        meta={"pgid": str(pg.pgid),
                              "paced": granted})

    async def _handle_watch_ops(self, pg: PG, conn, msg) -> None:
        """watch/unwatch/notify ops (PrimaryLogPG do_osd_ops
        CEPH_OSD_OP_WATCH / NOTIFY)."""
        outs = []
        result = 0
        for op in msg.ops:
            name = op["op"]
            if name == "watch":
                self.watches.watch(pg, msg.oid, conn)
                outs.append({})
            elif name == "unwatch":
                self.watches.unwatch(pg, msg.oid, conn)
                outs.append({})
            elif name == "notify":
                acked = await self.watches.notify(
                    pg, msg.oid, bytes(op.get("payload") or b""),
                    timeout=float(op.get("timeout", 5.0)))
                outs.append({"acked": acked})
            else:
                outs.append({"error": "bad op %s" % name})
                result = -22
        conn.send(MOSDOpReply(tid=msg.tid, result=result, outs=outs,
                              epoch=self.osdmap.epoch, version=0))
        self._op_finish(msg, "watch_done")

    def _min_size_ok(self, pg: PG, pool) -> bool:
        """min_size gating for ALL I/O (PeeringState is_active checks:
        the reference keeps a PG inactive, blocking reads and writes,
        while |acting| < pool.min_size).  EC additionally requires k
        live shards — acking a write persisted on fewer than k shards
        would make the object durable but unreadable."""
        live = sum(1 for o in pg.acting
                   if o >= 0 and self.osdmap.is_up(o))
        need = pool.min_size
        if pool.is_erasure():
            try:
                need = max(need,
                           self.ec.codec(pool).get_data_chunk_count())
            except Exception:
                pass  # unknown profile: handle_op will fail the op
        return live >= need

    def _op_is_write(self, o: dict) -> bool:
        """Write-path routing: builder ops by name; a cls call by its
        registered method flags (PrimaryLogPG's CEPH_OSD_OP_CALL
        flag check)."""
        from .cls import ClsError

        if o["op"] in _WRITE_OPS:
            return True
        if o["op"] == "call":
            try:
                return self.cls_handler.is_write(
                    o.get("cls", ""), o.get("method", ""))
            except ClsError:
                return False    # unknown: read path reports the error
        return False

    # -- pool compression (BlueStore blob-compression role over the
    # object layer; src/compressor consumers) --------------------------

    def _maybe_compress(self, pool, pg: PG, ho, data: bytes,
                        t: Transaction, cstate: dict,
                        blob: bytes | None = None) -> bytes:
        """Full-object writes on a compression pool store the
        compressed image when it saves enough (the reference's
        required-ratio gate); the algorithm + logical size ride
        xattrs so every consumer (reads, recovery pushes, scrub) sees
        a self-describing blob.  EC pools skip — stripe math needs
        the raw bytes.  ``cstate`` tracks per-txn staged comp state
        (ho -> algo | None): later ops in the SAME MOSDOp must see
        what earlier ops staged, not the committed attrs.  ``blob``
        is an optional pre-computed compression of exactly ``data``
        (the device-planned tlz path) — byte-identical to what the
        sync compressor would produce, so only the CPU cost differs."""
        from ..compress import OBJ_ALGO_ATTR, OBJ_SIZE_ATTR, create

        if pool is None or pool.compression_mode != "force" \
                or pool.is_erasure() or len(data) < 128:
            self._clear_comp_attrs(pg, ho, t, cstate)
            cstate[ho] = (None, data)
            return data
        if blob is None:
            blob = create(pool.compression_algorithm).compress(data)
        if len(blob) * 10 >= len(data) * 9:     # <10% saved: keep raw
            self._clear_comp_attrs(pg, ho, t, cstate)
            cstate[ho] = (None, data)
            return data
        t.setattr(pg.cid, ho, OBJ_ALGO_ATTR,
                  pool.compression_algorithm.encode())
        t.setattr(pg.cid, ho, OBJ_SIZE_ATTR, b"%d" % len(data))
        # keep the raw image beside the staged algo: a later op in
        # this txn cannot read the blob back (it is not applied yet)
        cstate[ho] = (pool.compression_algorithm, data)
        return blob

    def _clear_comp_attrs(self, pg: PG, ho, t: Transaction,
                          cstate: dict) -> None:
        from ..compress import OBJ_ALGO_ATTR, OBJ_SIZE_ATTR

        if self._comp_state(pg, ho, cstate)[0] is not None:
            t.rmattr(pg.cid, ho, OBJ_ALGO_ATTR)
            t.rmattr(pg.cid, ho, OBJ_SIZE_ATTR)
        cstate[ho] = None   # raw; content set by the caller's write

    def _comp_state(self, pg: PG, ho, cstate: dict | None = None
                    ) -> tuple[str | None, bytes | None]:
        """(algo, staged raw bytes) — txn-staged state wins over the
        committed attrs."""
        if cstate is not None and ho in cstate:
            st = cstate[ho]
            return (None, None) if st is None else st
        from ..compress import OBJ_ALGO_ATTR

        try:
            return (self.store.getattr(pg.cid, ho,
                                       OBJ_ALGO_ATTR).decode(), None)
        except NotFound:
            return (None, None)

    def _comp_algo(self, pg: PG, ho,
                   cstate: dict | None = None) -> str | None:
        return self._comp_state(pg, ho, cstate)[0]

    def _decompress_in_txn(self, pg: PG, ho, t: Transaction,
                           cstate: dict) -> None:
        """Partial mutations of a compressed object rewrite it raw
        first (staged in the same txn), so offset math stays exact —
        the GC/rewrite move BlueStore makes when a compressed blob is
        partially overwritten.  No-op if this txn already staged a
        raw image (cstate says None)."""
        algo, raw = self._comp_state(pg, ho, cstate)
        if algo is None:
            return
        from ..compress import OBJ_ALGO_ATTR, OBJ_SIZE_ATTR, create

        if raw is None:
            blob = self.store.read(pg.cid, ho)
            # a whiteout tombstone keeps its comp attrs but was
            # truncated to zero: its logical image is empty, not a
            # corrupt stream
            raw = create(algo).decompress(blob) if blob else b""
            if blob:
                self._check_comp_size(pg, ho, raw)
        t.truncate(pg.cid, ho, 0)
        t.write(pg.cid, ho, 0, len(raw), raw)
        t.rmattr(pg.cid, ho, OBJ_ALGO_ATTR)
        t.rmattr(pg.cid, ho, OBJ_SIZE_ATTR)
        # (None, raw): raw image staged WITH its content, so a later
        # op in this txn (e.g. a cls read) still sees logical bytes
        cstate[ho] = (None, raw)

    def _read_decompressed(self, pg: PG, ho, offset: int = 0,
                           length: int = -1) -> bytes:
        algo = self._comp_algo(pg, ho)
        if algo is None:
            return self.store.read(pg.cid, ho, offset, length)
        from ..compress import create

        raw = create(algo).decompress(self.store.read(pg.cid, ho))
        self._check_comp_size(pg, ho, raw)
        if length < 0:
            return raw[offset:]
        return raw[offset:offset + length]

    def _check_comp_size(self, pg: PG, ho, raw: bytes) -> None:
        """Decompress-side integrity: the stored `comp-size` attr and
        the decompressed length must agree, or the read fails with a
        CompressorError (EIO to the client) instead of silently
        serving truncated/padded data.  The rot is scrub-visible —
        deep scrub digests the stored blob AND the attrs, so a
        tampered blob or size attr diverges from the healthy replicas
        and repairs like any other inconsistency (the thrasher's
        `corrupt_compressed` arm proves the loop end to end)."""
        from ..compress import OBJ_SIZE_ATTR, CompressorError

        try:
            want = int(self.store.getattr(pg.cid, ho, OBJ_SIZE_ATTR))
        except (NotFound, ValueError):
            return      # no size attr staged (mid-txn states): skip
        if want != len(raw):
            self.perf.inc("comp_size_mismatches")
            raise CompressorError(
                "compressed object %s: comp-size attr %d disagrees"
                " with decompressed length %d" % (ho, want, len(raw)))

    def _stat_decompressed(self, pg: PG, ho) -> int:
        from ..compress import OBJ_SIZE_ATTR
        from ..dedup import OBJ_LOGICAL_ATTR

        try:
            # a manifested object's stored size is its manifest blob;
            # stat answers the logical (pre-dedup) size
            return int(self.store.getattr(pg.cid, ho,
                                          OBJ_LOGICAL_ATTR))
        except (NotFound, ValueError):
            pass
        try:
            return int(self.store.getattr(pg.cid, ho, OBJ_SIZE_ATTR))
        except NotFound:
            return self.store.stat(pg.cid, ho)

    # read-side op interpreter (do_osd_ops read branch)
    def _do_read_ops(self, pg: PG, oid: str, ops: list,
                     snapid: int | None = None, entity: str = ""):
        from ..store.objectstore import NOSNAP
        from . import snaps as snapmod
        if snapid not in (None, NOSNAP):
            # snapshot read: resolve to the covering clone or the
            # unmodified head (find_object_context)
            ho = snapmod.resolve_read_snap(self.store, pg, oid, snapid)
            if ho is None and any(o["op"] != "pgls" for o in ops):
                return ([{"error": "not found"}], -2)
        else:
            ho = hobject_t(oid)
            if oid and snapmod.is_whiteout(self.store, pg.cid, ho):
                ho = None
                if any(o["op"] != "pgls" for o in ops):
                    return ([{"error": "not found"}], -2)
        outs = []
        result = 0
        for op in ops:
            name = op["op"]
            try:
                if name == "read":
                    length = op.get("length", 0) or -1
                    data = self._read_decompressed(
                        pg, ho, op.get("offset", 0), length)
                    outs.append({"data": data})
                elif name == "stat":
                    outs.append({"size": self._stat_decompressed(
                        pg, ho)})
                elif name == "getxattr":
                    outs.append({"value": self.store.getattr(
                        pg.cid, ho, op["name"])})
                elif name == "omap-get":
                    outs.append({"kv": self.store.omap_get(pg.cid, ho)})
                elif name == "call":
                    from .cls import MethodContext

                    ctx = MethodContext(self.store, pg.cid, ho,
                                        None, entity)
                    code, out = self.cls_handler.call(
                        op.get("cls", ""), op.get("method", ""),
                        ctx, op.get("input") or {})
                    if code != 0:
                        outs.append(out)
                        result = code
                    else:
                        outs.append({"out": out})
                elif name == "pgls":
                    # PG object listing (the rados ls / pool
                    # enumeration primitive, PrimaryLogPG do_pg_op
                    # CEPH_OSD_OP_PGNLS); clones and whiteout heads
                    # are invisible to listing (PGNLS lists heads)
                    from ..store.objectstore import NOSNAP as _NS
                    names = sorted(
                        h.name for h in
                        self.store.collection_list(pg.cid)
                        if h.name != "__pgmeta__" and h.snap == _NS
                        and not snapmod.is_whiteout(self.store,
                                                    pg.cid, h))
                    outs.append({"names": names})
                else:
                    outs.append({"error": "bad op %s" % name})
                    result = -22
            except NotFound:
                outs.append({"error": "not found"})
                result = -2
            except Exception as e:
                from ..compress import CompressorError

                if not isinstance(e, CompressorError):
                    raise
                # corrupt blob / missing plugin: EIO, never a wedge
                outs.append({"error": str(e)})
                result = -5
        return outs, result

    def _execute_write(self, pg: PG, conn, msg: MOSDOp,
                       comp_pre: dict[int, bytes] | None = None,
                       dedup_pre: dict | None = None) -> None:
        """prepare_transaction + issue_repop (PrimaryLogPG.cc:8869,
        11394).  Snapshot bookkeeping (make_writeable) runs first so
        the clone ops ride the same replicated transaction.
        ``comp_pre`` maps op-list indices to device-planned
        compression blobs `_compression_paced` staged for writefull
        ops (byte-identical to the sync compressor's output).
        ``dedup_pre`` is the dedup plane's plan: ``manifest`` maps
        writefull op indices to a pre-built (manifest blob, logical
        size) — or None for an explicit raw store — and
        ``materialize`` carries the raw image of a manifested object
        about to be mutated in place."""
        from . import snaps as snapmod
        self._op_event(msg, "started_write")
        epoch = self.osdmap.epoch
        ver = pg.info.last_update[1] + 1
        version = (epoch, ver)
        ho = hobject_t(msg.oid)
        t = Transaction()
        outs, result = [], 0
        ss = snapmod.make_writeable(self.store, pg, ho,
                                    getattr(msg, "snapc", None), t)
        head_whiteout = snapmod.is_whiteout(self.store, pg.cid, ho)
        is_delete = False
        cstate: dict = {}   # per-txn staged compression state
        dmap = (dedup_pre or {}).get("manifest") or {}
        if dedup_pre and dedup_pre.get("materialize") is not None:
            from ..dedup import OBJ_LOGICAL_ATTR, OBJ_MANIFEST_ATTR
            raw0 = dedup_pre["materialize"]
            # a manifested object mutated in place: stage the
            # materialized raw image (and drop the manifest attrs)
            # ahead of the op list, so offset math sees logical bytes
            if self.store.exists(pg.cid, ho):
                t.truncate(pg.cid, ho, 0)
            else:
                t.touch(pg.cid, ho)
            t.write(pg.cid, ho, 0, len(raw0), raw0)
            t.rmattr(pg.cid, ho, OBJ_MANIFEST_ATTR)
            t.rmattr(pg.cid, ho, OBJ_LOGICAL_ATTR)
            cstate[ho] = (None, raw0)
        from ..compress import CompressorError
        for op_i, op in enumerate(msg.ops):
            name = op["op"]
            if name == "write":
                data = op["data"]
                off = op.get("offset", 0)
                if not self.store.exists(pg.cid, ho):
                    t.touch(pg.cid, ho)
                elif head_whiteout:
                    # resurrecting a whiteout head: clear the tombstone
                    t.setattr(pg.cid, ho, snapmod.WHITEOUT_ATTR, b"0")
                try:
                    self._decompress_in_txn(pg, ho, t, cstate)
                except CompressorError as e:
                    outs.append({"error": str(e)})
                    result = -5
                    continue
                t.write(pg.cid, ho, off, len(data), data)
                outs.append({})
            elif name == "writefull":
                data = op["data"]
                if self.store.exists(pg.cid, ho):
                    t.truncate(pg.cid, ho, 0)
                    if head_whiteout:
                        t.setattr(pg.cid, ho, snapmod.WHITEOUT_ATTR,
                                  b"0")
                else:
                    t.touch(pg.cid, ho)
                if op_i in dmap:
                    # dedup-planned writefull: store the manifest
                    # blob (or an explicit raw image when planning
                    # degraded) with the dedup attrs kept in step —
                    # dedup base pools are compression-free by mon
                    # validation, so the compression path is skipped
                    from ..dedup import (OBJ_LOGICAL_ATTR,
                                         OBJ_MANIFEST_ATTR)
                    ent = dmap[op_i]
                    if ent is not None:
                        blob, logical = ent
                        t.write(pg.cid, ho, 0, len(blob), blob)
                        t.setattr(pg.cid, ho, OBJ_MANIFEST_ATTR,
                                  b"1")
                        t.setattr(pg.cid, ho, OBJ_LOGICAL_ATTR,
                                  b"%d" % logical)
                    else:
                        t.write(pg.cid, ho, 0, len(data), data)
                        t.rmattr(pg.cid, ho, OBJ_MANIFEST_ATTR)
                        t.rmattr(pg.cid, ho, OBJ_LOGICAL_ATTR)
                    cstate[ho] = (None, data)
                    outs.append({})
                    continue
                pool0 = self.osdmap.pools.get(pg.pool_id)
                try:
                    stored = self._maybe_compress(
                        pool0, pg, ho, data, t, cstate,
                        blob=(comp_pre or {}).get(op_i))
                except CompressorError as e:
                    outs.append({"error": str(e)})
                    result = -5
                    continue
                t.write(pg.cid, ho, 0, len(stored), stored)
                outs.append({})
            elif name == "delete":
                if self.store.exists(pg.cid, ho) and not head_whiteout:
                    is_delete = snapmod.delete_head(self.store, pg,
                                                    ho, ss, t)
                    ss = None          # delete_head persisted it
                    outs.append({})
                else:
                    outs.append({"error": "not found"})
                    result = -2
            elif name == "truncate":
                try:
                    self._decompress_in_txn(pg, ho, t, cstate)
                except CompressorError as e:
                    outs.append({"error": str(e)})
                    result = -5
                    continue
                t.truncate(pg.cid, ho, op["length"])
                outs.append({})
            elif name == "setxattr":
                t.setattr(pg.cid, ho, op["name"], op["value"])
                outs.append({})
            elif name == "omap-rm":
                t.omap_rmkeys(pg.cid, ho,
                              [bytes(k) for k in op["keys"]])
                outs.append({})
            elif name == "omap-set":
                t.omap_setkeys(pg.cid, ho, op["kv"])
                outs.append({})
            elif name == "call":
                # cls method: reads committed state, stages writes
                # into this op's replicated transaction (atomic with
                # the rest of the op list)
                from .cls import MethodContext

                cctx = MethodContext(self.store, pg.cid, ho, t,
                                     msg.src, whiteout=head_whiteout,
                                     cstate=cstate)
                code, out = self.cls_handler.call(
                    op.get("cls", ""), op.get("method", ""),
                    cctx, op.get("input") or {})
                if code != 0:
                    outs.append(out)
                    result = code
                else:
                    if cctx._staged_remove and \
                            self.store.exists(pg.cid, ho) \
                            and not head_whiteout:
                        # snapshot-aware deletion, like the delete op
                        is_delete = snapmod.delete_head(
                            self.store, pg, ho, ss, t)
                        ss = None
                    outs.append({"out": out})
            elif name in _WRITE_OPS or name in ("read", "stat"):
                outs.append({"error": "mixed rw unsupported"})
                result = -22
            else:
                outs.append({"error": "bad op %s" % name})
                result = -22
        if result != 0:
            conn.send(MOSDOpReply(tid=msg.tid, result=result, outs=outs,
                                  epoch=epoch, version=0))
            self._op_finish(msg, "error_reply")
            return
        snapmod.persist_snapset(pg, ho, ss, t)
        entry = LogEntry(
            LogEntry.DELETE if is_delete else LogEntry.MODIFY,
            msg.oid, version, pg.info.last_update)
        pg.info.last_update = version
        pg.log.append(entry)
        pg.persist_log_entry(t, entry)
        pg.maybe_trim_log(t)   # rides the replicated txn to replicas
        pg.persist_meta(t)
        # reqid dup journal rides the same (replicated) transaction:
        # the mutation and its dup row land atomically everywhere, so
        # a resend after the reply was lost is answered, not re-run
        pg.record_reqid(t, msg.src, msg.tid, 0, outs, ver)
        wbytes = sum(len(op.get("data") or b"") for op in msg.ops
                     if isinstance(op, dict))
        self.note_op_size(wbytes)
        self._rep_tid += 1
        rep_tid = self._rep_tid
        waiting = set()
        txn_wire = denc.encode(t.to_wire())
        trace = getattr(msg, "trace", None)
        tenant = getattr(msg, "tenant", None)
        for osd in pg.acting:
            if osd < 0 or osd == self.whoami:
                continue
            waiting.add(osd)
            rep = MOSDRepOp(
                pool=pg.pool_id, ps=pg.ps, tid=rep_tid, txn=txn_wire,
                log_entry=entry.to_wire(), epoch=epoch,
                min_epoch=pg.info.same_interval_since,
                pg_trim_to=None)
            rep.trace = trace   # sub-op joins the client op's span
            rep.tenant = tenant
            self._send_osd(osd, rep)
        self.store.apply_transaction(t)
        if not waiting:
            conn.send(MOSDOpReply(tid=msg.tid, result=0, outs=outs,
                                  epoch=epoch, version=ver))
            self.perf.inc("ops")
            pg.stats.note_write(wbytes)
            self._op_finish(msg, "done_no_replicas")
            return
        self._op_event(msg, "sub_op_sent")
        pg.in_flight[rep_tid] = {
            "waiting": waiting, "conn": conn, "tid": msg.tid,
            "outs": outs, "version": ver, "bytes": wbytes,
            "top": getattr(msg, "_top", None),
            "t_sub": time.monotonic(),
        }

    def _handle_repop(self, conn, msg: MOSDRepOp) -> None:
        """Replica apply (ReplicatedBackend handle_message sub_op)."""
        self._op_event(msg, "started_apply")
        pgid = pg_t(msg.pool, msg.ps)
        pg = self.pgs.get(pgid)
        if pg is None:
            pg = PG(self, msg.pool, msg.ps)
            pg.create_onstore()
            self.pgs[pgid] = pg
        t = Transaction.from_wire(denc.decode(msg.txn))
        entry = LogEntry.from_wire(msg.log_entry)
        pg.log.append(entry)
        pg.info.last_update = entry.version
        # mirror the primary's trim policy so the in-memory log stays
        # in lockstep with the omap rows the replicated txn trims
        pg.maybe_trim_log(t)
        try:
            self.store.apply_transaction(t)
        except NotFound:
            # Tolerated ONLY while this replica is a known backfill /
            # recovery target for the object (pg.missing lists it):
            # the skipped ops converge via the push.  The pgmeta rows
            # later in the txn must still land, so apply op by op.
            # Anything else is real divergence and must surface.
            if not pg.missing:
                raise
            for op in t.ops:
                one = Transaction()
                one.ops.append(op)
                try:
                    self.store.apply_transaction(one)
                except NotFound:
                    ho = next((a for a in op
                               if isinstance(a, hobject_t)), None)
                    if ho is None or ho.name not in pg.missing:
                        raise
        conn.send(MOSDRepOpReply(pool=msg.pool, ps=msg.ps, tid=msg.tid,
                                 result=0, epoch=msg.epoch))
        self._op_finish(msg, "applied")

    def _handle_repop_reply(self, msg: MOSDRepOpReply) -> None:
        pg = self.pgs.get(pg_t(msg.pool, msg.ps))
        if pg is None:
            return
        st = pg.in_flight.get(msg.tid)
        if st is None:
            return
        sender = int(msg.src.split(".")[1])
        st["waiting"].discard(sender)
        top = st.get("top")
        if top is not None:
            top.mark_event("commit_rec_osd.%d" % sender)
        if not st["waiting"]:
            del pg.in_flight[msg.tid]
            t_sub = st.get("t_sub")
            if t_sub is not None:
                rtt = time.monotonic() - t_sub
                self.perf.hist_sample("op_subop_rtt", rtt)
                if top is not None and top.tenant is not None:
                    self.note_tenant_stage(top.tenant, "subop_rtt",
                                           rtt)
            if st["conn"] is not None:     # internal txns (snap trim)
                st["conn"].send(MOSDOpReply(
                    tid=st["tid"], result=0, outs=st["outs"],
                    epoch=self.osdmap.epoch, version=st["version"]))
                self.perf.inc("ops")
                pg.stats.note_write(st.get("bytes", 0))
            if top is not None:
                top.finish("done")

    # -- snapshot trim (PrimaryLogPG Trimming / SnapTrimEvent) -------------

    def _maybe_snap_trim(self, pg: PG) -> None:
        pool = self.osdmap.pools.get(pg.pool_id)
        if (pool is None or not pool.removed_snaps
                or not pg.is_primary() or pg.state != STATE_ACTIVE):
            return
        self.msgr.spawn(self._snap_trim(pg))

    def _load_purged(self, pg: PG) -> set[int]:
        from .pg import PGMETA_OID
        try:
            raw = self.store.omap_get(pg.cid, PGMETA_OID).get(
                b"purged_snaps")
        except Exception:
            return set()
        return set(denc.decode(raw)) if raw else set()

    async def _snap_trim(self, pg: PG) -> None:
        """Walk the SnapMapper rows for each removed-but-unpurged
        snap; per object, drop the snap from its clone (deleting the
        clone when its snap set empties) as a replicated, logged
        transaction — paced through the mClock 'snaptrim' class."""
        from . import snaps as snapmod
        from .pg import PGMETA_OID
        from .scheduler import K_SNAPTRIM
        if getattr(pg, "_trim_flow", False):
            return
        pg._trim_flow = True
        try:
            purged = self._load_purged(pg)
            pool = self.osdmap.pools.get(pg.pool_id)
            if pool is None:
                return
            for sid in [s for s in pool.removed_snaps
                        if s not in purged]:
                for oid in snapmod.list_snap_objects(self.store, pg,
                                                     sid):
                    await self.sched.admit(K_SNAPTRIM,
                                           key=(pg.pool_id, pg.ps))
                    if (not pg.is_primary()
                            or pg.state != STATE_ACTIVE
                            or self.stopping):
                        return
                    self._submit_trim(pg, oid, sid)
                purged.add(sid)
                t = Transaction()
                t.omap_setkeys(pg.cid, PGMETA_OID, {
                    b"purged_snaps": denc.encode(sorted(purged))})
                self.store.apply_transaction(t)
        finally:
            pg._trim_flow = False

    def _submit_trim(self, pg: PG, oid: str, sid: int) -> None:
        """One object's trim as a logged replicated transaction (the
        same wire path as a client write, no reply connection)."""
        from . import snaps as snapmod
        t = Transaction()
        snapmod.trim_object(self.store, pg, oid, sid, t)
        epoch = self.osdmap.epoch
        version = (epoch, pg.info.last_update[1] + 1)
        entry = LogEntry(LogEntry.MODIFY, oid, version,
                         pg.info.last_update)
        pg.info.last_update = version
        pg.log.append(entry)
        pool = self.osdmap.pools.get(pg.pool_id)
        if pool is not None and pool.is_erasure():
            # EC peers speak the EC sub-write channel; ship the BARE
            # trim txn (clone removal + snapset attr — identical on
            # every shard): handle_sub_write appends each shard's own
            # log/meta rows, matching submit_write's contract
            bare_wire = denc.encode(t.to_wire())
            self.ec._tid += 1
            for j, osd in enumerate(pg.acting):
                if osd < 0 or osd == self.whoami:
                    continue
                self._send_osd(osd, MOSDECSubOpWrite(
                    pool=pg.pool_id, ps=pg.ps, shard=j,
                    tid=self.ec._tid, txn=bare_wire,
                    log_entry=entry.to_wire(), epoch=epoch))
            pg.persist_log_entry(t, entry)
            pg.maybe_trim_log(t)
            pg.persist_meta(t)
            self.store.apply_transaction(t)
            return
        pg.persist_log_entry(t, entry)
        pg.maybe_trim_log(t)
        pg.persist_meta(t)
        txn_wire = denc.encode(t.to_wire())
        self._rep_tid += 1
        rep_tid = self._rep_tid
        waiting = set()
        for osd in pg.acting:
            if osd < 0 or osd == self.whoami:
                continue
            waiting.add(osd)
            self._send_osd(osd, MOSDRepOp(
                pool=pg.pool_id, ps=pg.ps, tid=rep_tid, txn=txn_wire,
                log_entry=entry.to_wire(), epoch=epoch,
                min_epoch=pg.info.same_interval_since,
                pg_trim_to=None))
        self.store.apply_transaction(t)
        if waiting:
            pg.in_flight[rep_tid] = {
                "waiting": waiting, "conn": None, "tid": 0,
                "outs": [], "version": version[1]}

    # -- heartbeats --------------------------------------------------------

    async def _heartbeat_loop(self) -> None:
        conf = self.ctx.conf
        while not self.stopping:
            await asyncio.sleep(conf["heartbeat_interval"])
            if self.osdmap is None or not self.booted:
                continue
            # recovery watchdog (OSD tick -> RecoveryPreemption /
            # queue_recovery): a push flow aborted by an interval
            # change or a dropped reply must not strand missing
            # objects — re-kick any primary PG with outstanding work
            # and re-check pg_temp release
            for pg in list(self.pgs.values()):
                if not pg.is_primary() or pg.state != STATE_ACTIVE:
                    continue
                if (pg.missing
                        or any(pg.peer_missing.get(o)
                               for o in pg.peer_missing)) \
                        and not getattr(pg, "_recovery_flow", False):
                    self._kick_recovery(pg)
                elif pg.waiting_for_active and not pg.missing:
                    # safety net against stuck parked ops: an active,
                    # whole PG with waiters means a requeue edge was
                    # lost (e.g. the push-reply that should have fired
                    # it raced an interval flip) — requeue now, gated
                    # on min_size so a still-degraded PG does not spin
                    pool = self.osdmap.pools.get(pg.pool_id)
                    if pool is not None and self._min_size_ok(pg,
                                                              pool):
                        self._requeue_waiters(pg)
                self._maybe_clear_pg_temp(pg)
            self._maybe_schedule_scrub()
            self._maybe_send_mgr_report()
            self._maybe_send_beacon()
            # event plane: re-flush unacked clog entries and pending
            # crash reports (delivery survives leader elections)
            self.clog.flush()
            self._maybe_ship_crashes()
            now = time.monotonic()
            grace = conf["heartbeat_grace"]
            # prune state for peers the map says are down, so a later
            # reboot starts with a fresh window instead of a stale
            # stamp that would instantly re-report it failed
            for osd in list(self.hb_last_rx):
                if osd >= self.osdmap.max_osd \
                        or not self.osdmap.is_up(osd):
                    del self.hb_last_rx[osd]
            # network plane housekeeping: the RTT tracker prunes by
            # the same rule, the messenger drops dead osd peers'
            # clock-offset and folded-wire entries (both tables would
            # otherwise grow forever across kill/revive cycles), the
            # wire ring takes a cumulative per-peer byte sample for
            # the chrome-trace counter tracks, and the messenger
            # resend/replay totals land in the perf counters
            alive = [osd for osd in range(self.osdmap.max_osd)
                     if self.osdmap.is_up(osd)]
            self.network.prune(alive)
            self.msgr.prune_peer_state("osd.%d" % o for o in alive)
            net_rows = self.msgr.net_dump()
            self.network.sample_wire(
                now, {k: v for k, v in net_rows.items()
                      if k.startswith("osd.")})
            self.perf.set("msgr_resends", sum(
                r["resends"] for r in net_rows.values()))
            self.perf.set("msgr_replays", sum(
                r["replays"] for r in net_rows.values()))
            self.perf.set("msgr_mark_downs", sum(
                r["mark_downs"] for r in net_rows.values()))
            for osd in range(self.osdmap.max_osd):
                if osd == self.whoami or not self.osdmap.is_up(osd):
                    continue
                addr = self.osdmap.osd_addrs.get(osd)
                if not addr:
                    continue
                self.msgr.send_to(addr, MOSDPing(
                    osd=self.whoami, op="ping", stamp=now,
                    epoch=self.osdmap.epoch),
                    entity_hint="osd.%d" % osd)
                last = self.hb_last_rx.get(osd)
                if last is None:
                    self.hb_last_rx[osd] = now
                elif now - last > grace:
                    self._send_mons(MOSDFailure(
                        target=osd, failed_for=now - last,
                        epoch=self.osdmap.epoch))

    # -- periodic scrub (the always-on integrity plane) --------------------

    def _maybe_schedule_scrub(self) -> None:
        """Drive scrubs on this primary's own schedule
        (PG::sched_scrub condensed): the PG most overdue against
        `osd_scrub_interval` / `osd_deep_scrub_interval` scrubs next,
        one at a time per daemon, paced through the mClock K_SCRUB
        class and the device runtime's background digest lanes.  Only
        clean, min_size-satisfied primary PGs are eligible — scrub
        compares copies, and a PG mid-recovery would read absent
        copies as rot."""
        if self._scrub_running or self.stopping or not self.booted:
            return
        conf = self.ctx.conf
        shallow = float(conf.get("osd_scrub_interval", 0) or 0)
        deep_iv = float(conf.get("osd_deep_scrub_interval", 0) or 0)
        if shallow <= 0 and deep_iv <= 0:
            return
        now = time.time()
        best = None             # (overdue-seconds, pg, deep)
        for pg in self.pgs.values():
            if not pg.is_primary() or pg.state != STATE_ACTIVE:
                continue
            if pg.missing or any(pg.peer_missing.get(o)
                                 for o in pg.peer_missing):
                continue
            if getattr(pg, "_scrub_cmd_running", False):
                continue
            pool = self.osdmap.pools.get(pg.pool_id)
            if pool is None or not self._min_size_ok(pg, pool):
                continue
            if deep_iv > 0 \
                    and now - pg.last_deep_scrub_stamp >= deep_iv:
                cand = (now - pg.last_deep_scrub_stamp - deep_iv,
                        pg, True)
            elif shallow > 0 \
                    and now - pg.last_scrub_stamp >= shallow:
                cand = (now - pg.last_scrub_stamp - shallow,
                        pg, False)
            else:
                continue
            if best is None or cand[0] > best[0]:
                best = cand
        if best is None:
            return
        self._scrub_running = True
        self.msgr.spawn(self._periodic_scrub(best[1], best[2]))

    async def _periodic_scrub(self, pg, deep: bool) -> None:
        """One scheduled scrub round.  recheck=True: an inconsistency
        only records if it persists across passes, so a client write
        racing the per-member map builds settles instead of raising
        PG_DAMAGED spuriously.  Failures are logged, never crash
        reports — an interval change or pool delete mid-scrub is
        routine, not a post-mortem."""
        fid = self.progress.start(
            "deep-scrub" if deep else "scrub", str(pg.pgid), total=1)
        try:
            res = await self.scrubber.scrub_pg(pg, deep=deep,
                                               recheck=True)
            if res["errors"]:
                self.ctx.log.info(
                    "osd", "osd.%d periodic %sscrub pg %s: %d "
                    "inconsistencies %s"
                    % (self.whoami, "deep-" if deep else "",
                       pg.pgid, res["errors"],
                       res["inconsistent"][:5]))
        except Exception as e:
            self.ctx.log.info(
                "osd", "osd.%d periodic scrub pg %s aborted: %r"
                % (self.whoami, pg.pgid, e))
        finally:
            self._scrub_running = False
            self.progress.finish(fid)

    def _maybe_send_beacon(self) -> None:
        """MOSDBeacon to the mons: liveness plus the slow-op count
        (in-flight ops past osd_op_complaint_time) and this OSD's
        chip state.  The monitor's HealthMonitor turns a nonzero
        cluster total into SLOW_OPS and clears it when a later beacon
        reports zero; device_fallback + device_chip feed the per-chip
        DEVICE_FALLBACK detail (only the OSDs bound to a lost chip
        report it — the rest of the mesh keeps serving on-device)."""
        from ..device.runtime import DeviceRuntime
        from ..msg.messages import MOSDBeacon
        slow = self.optracker.slow_in_flight()
        self.perf.set("slow_ops", len(slow))
        now = time.monotonic()
        if now - self._beacon_stamp < \
                self.ctx.conf["osd_beacon_report_interval"]:
            return
        self._beacon_stamp = now
        if slow:
            oldest = max(op.age for op in slow)
            self.ctx.log.info(
                "osd", "osd.%d has %d slow ops (oldest %.1fs): %s"
                % (self.whoami, len(slow), oldest,
                   slow[0].desc))
        chip = (self.device_chip
                if self.device_chip is not None
                else DeviceRuntime.get().chip_for(self.whoami))
        self._send_mons(MOSDBeacon(
            osd=self.whoami, epoch=self.osdmap.epoch,
            slow_ops=len(slow),
            # per-tenant slice of the slow count (tenant-less ops
            # fold under "") so the SLOW_OPS health detail can name
            # the worst tenant; legacy mons drop the unknown field
            slow_tenants=self.optracker.slow_tenants(),
            device_fallback=int(chip.fallback),
            device_chip=chip.index,
            # heartbeat RTT slice (worst peers + slow set) feeding
            # the mon's OSD_SLOW_PING_TIME edge; None until a peer
            # answers a stamped ping, so the beacon stays
            # byte-stable with legacy frames
            net=self.network.beacon_slice()))

    def _obj_logical_size(self, pg: PG, ho, is_ec: bool) -> int:
        """Logical object bytes: an EC shard records the full logical
        size in its SIZE_XATTR; replicated objects report the stored
        size (compression keeps the logical size in its own attr)."""
        if is_ec:
            from .ecbackend import SIZE_XATTR
            try:
                return int(self.store.getattr(pg.cid, ho, SIZE_XATTR))
            except (NotFound, ValueError):
                pass
        try:
            return self._stat_decompressed(pg, ho)
        except NotFound:
            return 0

    def _pg_stat(self, pg: PG) -> dict:
        """One primary PG's stat row (pg_stat_t condensed): object and
        byte counts from the store, degraded / misplaced / unfound
        tallies from the peering state, and the cumulative PGStats
        counters the mgr derives rates from.

        * degraded — object copies below the pool's target redundancy:
          acting-set holes (down members count num_objects whole) plus
          every missing entry on the primary or a live acting member.
        * misplaced — copies that exist safely but sit on the wrong
          OSD: outstanding entries for up-but-not-acting targets (the
          pg_temp-pinned backfill flow a pgp_num change drives).
        * unfound — missing objects no known source can provide."""
        from ..store.objectstore import NOSNAP as _NS
        pool = self.osdmap.pools.get(pg.pool_id)
        is_ec = pool is not None and pool.is_erasure()
        num_objects = 0
        num_bytes = 0
        for h in self.store.collection_list(pg.cid):
            if h.name == "__pgmeta__" or h.snap != _NS:
                continue
            num_objects += 1
            num_bytes += self._obj_logical_size(pg, h, is_ec)
        target = pool.size if pool is not None else len(pg.acting)
        live = [o for o in pg.acting
                if 0 <= o != ITEM_NONE and self.osdmap.is_up(o)]
        # misplaced vs degraded: outstanding copies for an acting
        # member are MISPLACED when a full prior-interval holder is
        # still up outside the acting set (remap/backfill — the data
        # exists, it just sits on the wrong osd); with no live
        # ex-member the redundancy is genuinely reduced -> DEGRADED
        prev_up = [o for o in getattr(pg, "prev_acting", [])
                   if 0 <= o != ITEM_NONE and o not in pg.acting
                   and self.osdmap.is_up(o)]
        missing_copies = len(pg.missing)
        misplaced = 0
        for o, pm in pg.peer_missing.items():
            if o in pg.acting:
                if o in live:
                    if prev_up:
                        misplaced += len(pm)
                    else:
                        missing_copies += len(pm)
            else:
                misplaced += len(pm)
        degraded = (num_objects * max(0, target - len(live))
                    + missing_copies)
        # unfound: a primary-missing object with no live peer claiming
        # a complete copy (conservative but cheap approximation of the
        # reference's might_have_unfound walk)
        unfound = 0
        if pg.missing:
            have_src = any(
                not pg.peer_missing.get(o)
                for o in pg.peer_info
                if o != self.whoami and self.osdmap.is_up(o))
            unfound = 0 if have_src else len(pg.missing)
        from .pg import STATE_INITIAL, STATE_PEERING
        names = {STATE_ACTIVE: "active", STATE_REPLICA: "replica",
                 STATE_PEERING: "peering", STATE_INITIAL: "creating"}
        return {
            "pgid": pg.pgid, "pool": pg.pool_id,
            "state": names.get(pg.state, "unknown"),
            "num_objects": num_objects, "num_bytes": num_bytes,
            "degraded": degraded, "misplaced": misplaced,
            "unfound": unfound,
            "log_size": len(pg.log.entries),
            # integrity plane: the residual inconsistency count and
            # the scrub stamps (pg_stat_t last_scrub_stamp) — the
            # mgr digest folds scrub_errors into OSD_SCRUB_ERRORS /
            # PG_DAMAGED health
            "scrub_errors": getattr(pg, "scrub_errors", 0),
            "last_scrub_stamp": getattr(pg, "last_scrub_stamp", 0.0),
            "last_deep_scrub_stamp": getattr(
                pg, "last_deep_scrub_stamp", 0.0),
            **pg.stats.to_wire(),
        }

    def _maybe_send_mgr_report(self) -> None:
        """MgrClient::send_report: ship perf counters, a PG state
        summary, AND the per-PG stat rows of every PG this osd is
        primary for (the MPGStats slice riding the report — the
        OSD::ms_handle->MgrClient pipeline the mgr folds into its
        PGMap)."""
        addr = getattr(self.osdmap, "mgr_addr", "")
        if not addr:
            return
        now = time.monotonic()
        if now - getattr(self, "_mgr_report_stamp", 0.0) < \
                self.ctx.conf.get("osd_mgr_report_interval", 2.0):
            return
        self._mgr_report_stamp = now
        from ..msg.messages import MMgrReport
        from .pg import STATE_INITIAL, STATE_PEERING
        names = {STATE_ACTIVE: "active", STATE_REPLICA: "replica",
                 STATE_PEERING: "peering", STATE_INITIAL: "creating"}
        states: dict[str, int] = {}
        num_objects = 0
        pg_stats: list[dict] = []
        for pg in self.pgs.values():
            st = names.get(pg.state, "unknown")
            states[st] = states.get(st, 0) + 1
            if pg.is_primary():
                if pg.missing or any(pg.peer_missing.get(o)
                                     for o in pg.peer_missing):
                    states["recovering"] = \
                        states.get("recovering", 0) + 1
                row = self._pg_stat(pg)
                pg_stats.append(row)
                num_objects += row["num_objects"]
        try:
            statfs = self.store.statfs()
        except Exception:
            statfs = None
        # per-chip utilization integrals: this OSD reports ITS
        # affinity chip's windowed busy/queue-wait/idle fractions —
        # the mgr digest folds one row per chip and `status` renders
        # the cluster's device-utilization line from them
        device_util = None
        if self.device_chip is not None:
            try:
                device_util = {"chip": self.device_chip.index,
                               **self.device_chip.utilization()}
            except Exception:
                device_util = None
        # telemetry fabric: ship the stat rows as ONE packed columnar
        # block (parallel typed arrays, pgids/states dictionary-
        # encoded) so the mgr's merge is a vectorized scatter, not a
        # row loop; conf-gated off -> legacy dict rows (mixed fleets
        # converge to the same digest)
        pg_stats_cols = None
        if pg_stats and self.ctx.conf.get("osd_stats_columnar", True):
            from ..msg.statblock import pack_stat_rows
            try:
                pg_stats_cols = pack_stat_rows(pg_stats)
                pg_stats = None
            except Exception:
                pg_stats_cols = None    # odd pgid: keep dict rows
        self.msgr.send_to(addr, MMgrReport(
            daemon="osd.%d" % self.whoami, epoch=self.osdmap.epoch,
            perf=self.ctx.perf.dump(), pg_states=states,
            num_pgs=len(self.pgs), num_objects=num_objects,
            pg_stats=pg_stats, pg_stats_cols=pg_stats_cols,
            osd_stats={"op_size_hist_bytes_pow2":
                       list(self.op_size_hist),
                       # raw-capacity axis for `df` + the exporter
                       "statfs": statfs,
                       # per-chip device utilization (flight-recorder
                       # plane: saturation visible cluster-wide)
                       "device_util": device_util,
                       # repair-traffic plane: per-codec recovery
                       # bytes (read from survivors / moved to
                       # rebuilt shards) — folded into the digest's
                       # repair_traffic section + codec-labeled
                       # exporter families
                       "repair": {c: dict(r) for c, r in
                                  self.ec.repair_traffic.items()},
                       # data-reduction plane: per-base-pool dedup
                       # counters — folded into the digest's
                       # dedup_pools section + pool-labeled exporter
                       # families
                       "dedup": self.dedup.stats_row(),
                       # tenant SLO plane: cumulative per-tenant
                       # stage histograms + good/bad op counters —
                       # the mgr SLO engine's burn-rate input
                       "tenants": {
                           t: {"stages": {s: list(h)
                                          for s, h in
                                          self.tenant_stages.get(
                                              t, {}).items()},
                               **self.tenant_ops.get(
                                   t, {"ops": 0, "errors": 0})}
                           for t in (set(self.tenant_stages)
                                     | set(self.tenant_ops))},
                       # clog emission counters
                       # (ceph_tpu_log_messages_total)
                       "log_messages": self.clog.counts_wire(),
                       # long-flow progress rows (recovery drains,
                       # scrub sweeps) — digest progress section +
                       # progress_start/finish events on the bus
                       "progress": self._progress_rows(),
                       # network plane: per-peer wire counters, wire
                       # rates over the report interval and the RTT
                       # rollup — digest net section, net.* history
                       # series, ceph_tpu_net_* exporter families
                       "net": self._net_stats_row()}),
            entity_hint="mgr")

    def _net_stats_row(self) -> dict:
        """osd_stats["net"]: this daemon's wire/RTT slice for the mgr
        digest.  Rates are computed here, over the report interval —
        the digest is instantaneous soft state and only the producer
        knows its own cadence.  Per-peer detail is cardinality-capped
        at the messenger (worst peers kept, tail folded into
        "other")."""
        now = time.monotonic()
        cap = max(1, int(self.ctx.conf.get("net_peer_max", 32)))
        rows = self.msgr.net_dump(cap=cap)
        tx = sum(r["tx_bytes"] for r in rows.values())
        rx = sum(r["rx_bytes"] for r in rows.values())
        resends = sum(r["resends"] for r in rows.values())
        tx_bps = rx_bps = resend_rate = 0.0
        prev = self._net_prev
        if prev is not None:
            dt = max(now - prev["t"], 1e-6)
            tx_bps = max(0.0, (tx - prev["tx"]) / dt)
            rx_bps = max(0.0, (rx - prev["rx"]) / dt)
            resend_rate = max(0.0, (resends - prev["resends"]) / dt)
        self._net_prev = {"t": now, "tx": tx, "rx": rx,
                          "resends": resends}
        return {
            "tx_bytes": tx, "rx_bytes": rx,
            "tx_Bps": round(tx_bps, 1), "rx_Bps": round(rx_bps, 1),
            "resends": resends,
            "replays": sum(r["replays"] for r in rows.values()),
            "mark_downs": sum(r["mark_downs"]
                              for r in rows.values()),
            "queue_depth": sum(r["queue_depth"]
                               for r in rows.values()),
            "resend_rate": round(resend_rate, 3),
            "peers": rows,
            "rtt": self.network.summary(),
            # per-peer 5s-window RTT (ms): the cluster RTT matrix row
            "rtt_peers": {str(p): round(
                pr.ewma.get("5s", 0.0) * 1000.0, 3)
                for p, pr in sorted(self.network.peers.items())},
        }

    def _handle_ping(self, conn, msg: MOSDPing) -> None:
        if msg.op == "ping":
            conn.send(MOSDPing(osd=self.whoami, op="reply",
                               stamp=msg.stamp,
                               epoch=self.osdmap.epoch
                               if self.osdmap else 0))
        else:
            now = time.monotonic()
            self.hb_last_rx[msg.osd] = now
            # the reply echoes our ping's send stamp: RTT = now -
            # stamp.  Legacy stampless frames echo None — the RTT
            # matrix stays partial instead of the daemon failing
            if msg.stamp is not None:
                try:
                    self.network.note_rtt(
                        msg.osd, now - float(msg.stamp), now)
                except (TypeError, ValueError):
                    pass

    # -- helpers -----------------------------------------------------------

    def _send_osd(self, osd: int, msg) -> None:
        addr = self.osdmap.osd_addrs.get(osd)
        if addr:
            self.msgr.send_to(addr, msg, entity_hint="osd.%d" % osd)


_WRITE_OPS = {"write", "writefull", "delete", "truncate", "setxattr",
              "omap-set", "omap-rm"}
