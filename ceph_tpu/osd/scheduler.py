"""mClock-style op scheduler with sharded queues.

Analog of the reference's ShardedOpWQ + OpScheduler stack
(src/osd/OSD.cc:2351,3528-3533; src/osd/scheduler/mClockScheduler.h:75
over the vendored dmclock library, src/dmclock/): every message-driven
unit of OSD work is tagged with a service class and drained from
per-shard queues by a dmClock arbiter, so background work (recovery,
scrub, snap trim) cannot starve client I/O and client bursts cannot
starve recovery below its reservation.

dmClock per class keeps three virtual tags (dmclock's RWL model):

  reservation tag  r += 1/(res_fraction * capacity)   — guaranteed rate
  proportional tag p += 1/weight                      — excess sharing
  limit tag        l += 1/(lim_fraction * capacity)   — hard ceiling

Schedule: any class whose reservation tag is in the past runs first
(by earliest r); otherwise the earliest proportional tag among classes
whose limit tag is in the past; otherwise sleep until the nearest tag
matures.  Tags are clamped to `now` when a class goes idle->busy so
an idle class cannot bank credit (the standard dmClock idle rule).

Shards: `osd_op_num_shards` independent queues, PG-affine (shard =
hash(pgid) % n), each drained by one asyncio worker — per-PG op order
is preserved per class, matching the reference's shard mapping.

Two entry points:
  enqueue(key, klass, fn)  — queue a work item (fn may be sync or
                             return an awaitable); used for message
                             dispatch (client ops, rep ops, EC subops).
  admit(klass, cost)       — awaitable admission ticket used by
                             long-running background flows (recovery
                             push loops, scrub chunks, snap trim) to
                             pace themselves through the same arbiter.
"""

from __future__ import annotations

import asyncio
import time

K_CLIENT = "client"
K_RECOVERY = "recovery"
K_SCRUB = "scrub"
K_SNAPTRIM = "snaptrim"

# (reservation fraction, weight, limit fraction) of osd capacity —
# mirrors the balanced mclock profile (mClockScheduler.cc profiles:
# client gets half the capacity reserved, background recovery a
# quarter, best-effort classes ride the excess)
DEFAULT_PROFILE = {
    K_CLIENT: (0.50, 4.0, 1.00),
    K_RECOVERY: (0.25, 2.0, 0.75),
    K_SCRUB: (0.05, 1.0, 0.50),
    K_SNAPTRIM: (0.05, 1.0, 0.50),
}

# Device dispatch-queue shares (ceph_tpu.device.runtime): the same
# client/recovery proportions as the mClock profile above, plus the
# bulk-mapping class — client EC flushes outrank recovery encodes,
# which outrank whole-pool remap passes, so a mapping storm cannot
# starve client writes of the accelerator.  The background class
# (scrub digest lanes, pool-compression pacing) sits below everything
# else: always-on integrity work rides the excess, never the
# reservation.
DEVICE_DISPATCH_WEIGHTS = {
    "client-ec": DEFAULT_PROFILE[K_CLIENT][1],      # 4.0
    "recovery-ec": DEFAULT_PROFILE[K_RECOVERY][1],  # 2.0
    "mapping": 1.0,
    "background": 0.5,
}

# per-tenant dmClock row defaults (fractions of osd capacity like the
# class profile): a tenant-stamped client op runs under its tenant's
# OWN (reservation, weight, limit) tag book nested in the client
# class, so a bully tenant is throttled at its limit tag while a
# victim's reservation keeps flowing — the dmclock d-parameter model
# extended to (class, tenant) keys.  Overridden per tenant via the
# `osd_mclock_tenant_qos` conf rows ("name:res:weight:limit,...").
TENANT_DEFAULT_PROFILE = (0.05, 1.0, 1.00)


def device_admission_weight(klass: str, tenant: str | None,
                            tenant_qos: dict[str, tuple] | None,
                            ) -> float:
    """Proportional admission weight of one op at the DEVICE layer
    (the dispatch stream's WFQ tags, device/stream.py): the class
    share from DEVICE_DISPATCH_WEIGHTS times, for tenant-stamped
    client-EC work, the tenant's dmClock weight column (its
    `osd_mclock_tenant_qos` row, default TENANT_DEFAULT_PROFILE).
    Reservation and limit stay host-side in the op scheduler — the
    device honors the proportional ordering, which is the column that
    decides who a contended accelerator serves next."""
    base = DEVICE_DISPATCH_WEIGHTS.get(klass, 1.0)
    if tenant is None or klass != "client-ec":
        return base
    row = (tenant_qos or {}).get(tenant)
    wgt = row[1] if row is not None else TENANT_DEFAULT_PROFILE[1]
    return base * max(float(wgt), 1e-9)


def parse_tenant_qos(spec: str) -> dict[str, tuple]:
    """Parse the `osd_mclock_tenant_qos` conf string:
    "bully:0.05:0.5:0.15,victim:0.30:4:1.0" ->
    {tenant: (res_frac, weight, lim_frac)}.  Malformed rows are
    skipped (a poison conf value must never sever the op path)."""
    out: dict[str, tuple] = {}
    for row in (spec or "").split(","):
        row = row.strip()
        if not row:
            continue
        parts = row.split(":")
        if len(parts) != 4:
            continue
        try:
            out[parts[0]] = (float(parts[1]), float(parts[2]),
                             float(parts[3]))
        except ValueError:
            continue
    return out


class _ClassQ:
    __slots__ = ("res", "wgt", "lim", "r_tag", "p_tag", "l_tag",
                 "items")

    def __init__(self, res_rate: float, weight: float,
                 lim_rate: float):
        self.res = max(res_rate, 1e-9)
        self.wgt = max(weight, 1e-9)
        self.lim = max(lim_rate, 1e-9)
        self.r_tag = 0.0
        self.p_tag = 0.0
        self.l_tag = 0.0
        self.items: list = []          # FIFO of (fn, cost, t_enq)


class _Shard:
    """Tag books are keyed by the base class name (str) or, for
    tenant-stamped client ops, by a ("client", tenant) tuple — each
    tenant gets its OWN dmClock RWL row nested inside the client
    class, created lazily on first sight from the tenant QoS rows."""

    def __init__(self, profile: dict, capacity: float):
        self.capacity = capacity
        self.classes: dict = {
            k: _ClassQ(res * capacity, wgt, lim * capacity)
            for k, (res, wgt, lim) in profile.items()}
        self.wake = asyncio.Event()
        self.size = 0

    def ensure(self, key, res_frac: float, wgt: float,
               lim_frac: float) -> None:
        """Create the (class, tenant) tag book on first sight."""
        if key not in self.classes:
            self.classes[key] = _ClassQ(res_frac * self.capacity,
                                        wgt,
                                        lim_frac * self.capacity)

    def push(self, klass, fn, cost: float) -> None:
        q = self.classes[klass]
        now = time.monotonic()
        if not q.items:
            # idle -> busy: no banked credit
            q.r_tag = max(q.r_tag, now)
            q.l_tag = max(q.l_tag, now)
            busy_p = [c.p_tag for c in self.classes.values() if c.items]
            q.p_tag = max(q.p_tag, min(busy_p) if busy_p else q.p_tag)
        q.items.append((fn, cost, now))
        self.size += 1
        self.wake.set()

    def _pick(self) -> tuple[str, float] | None:
        """(class, 0) to run now, or (None, delay) to sleep."""
        now = time.monotonic()
        busy = [(k, q) for k, q in self.classes.items() if q.items]
        if not busy:
            return None
        # 1. reservation phase (key= keeps mixed str/tuple book keys
        # out of the comparison when tags tie)
        ready = [(q.r_tag, k) for k, q in busy if q.r_tag <= now]
        if ready:
            return ("R", min(ready, key=lambda t: t[0])[1])
        # 2. proportional phase under limit
        under = [(q.p_tag, k) for k, q in busy if q.l_tag <= now]
        if under:
            return ("P", min(under, key=lambda t: t[0])[1])
        # 3. everything limited: sleep till the nearest tag matures
        horizon = min(min(q.r_tag for _, q in busy),
                      min(q.l_tag for _, q in busy))
        return ("S", max(horizon - now, 0.0005))

    def pop(self, klass, phase: str):
        """Returns (fn, queue_wait_seconds)."""
        q = self.classes[klass]
        fn, cost, t_enq = q.items.pop(0)
        self.size -= 1
        now = time.monotonic()
        if phase == "R":
            q.r_tag = max(q.r_tag, now) + cost / q.res
            # the proportional/limit books still advance: a
            # reservation-phase grant consumes budget everywhere
            q.p_tag += cost / q.wgt
            q.l_tag = max(q.l_tag, now) + cost / q.lim
        else:
            q.p_tag += cost / q.wgt
            q.l_tag = max(q.l_tag, now) + cost / q.lim
            q.r_tag = max(q.r_tag, now) + cost / q.res
        return fn, now - t_enq


class OpScheduler:
    """Sharded dmClock arbiter; one per OSD."""

    def __init__(self, ctx=None, num_shards: int | None = None,
                 capacity_iops: float | None = None,
                 profile: dict | None = None):
        conf = getattr(ctx, "conf", None)
        if num_shards is None:
            num_shards = int(conf["osd_op_num_shards"]) if conf else 4
        if capacity_iops is None:
            capacity_iops = (float(conf["osd_mclock_capacity_iops"])
                             if conf else 10000.0)
        self.profile = dict(profile or DEFAULT_PROFILE)
        self.capacity = capacity_iops
        self.ctx = ctx
        self.shards = [_Shard(self.profile, capacity_iops)
                       for _ in range(max(1, num_shards))]
        self._workers: list[asyncio.Task] = []
        self.running = False
        # perf visibility (base classes; tenant books fold into their
        # base class here and get their own tenant_dispatched counts)
        self.dispatched = {k: 0 for k in self.profile}
        self.tenant_dispatched: dict[str, int] = {}
        # per-class queue-wait books: klass -> [count, sum_seconds];
        # on_wait(klass, seconds, tenant) additionally fires per
        # dequeue so the OSD can feed its stage-latency histograms
        # (the queue-wait stage of the op timeline, per tenant)
        self.queue_wait = {k: [0, 0.0] for k in self.profile}
        self.on_wait = None
        # tenant QoS rows parsed from conf, cached per spec string
        self._tenant_qos_spec: str | None = None
        self._tenant_qos: dict[str, tuple] = {}

    # -- tenant QoS rows ---------------------------------------------------

    def tenant_profile(self, tenant: str) -> tuple:
        """(res_frac, weight, lim_frac) for one tenant: the
        `osd_mclock_tenant_qos` conf row when present, else the
        per-tenant defaults (`osd_mclock_tenant_*`).  Re-read per
        spec-string change so `config set` acts live."""
        conf = getattr(self.ctx, "conf", None)
        if conf is None:
            return TENANT_DEFAULT_PROFILE
        spec = str(conf.get("osd_mclock_tenant_qos", "") or "")
        if spec != self._tenant_qos_spec:
            self._tenant_qos_spec = spec
            self._tenant_qos = parse_tenant_qos(spec)
        row = self._tenant_qos.get(tenant)
        if row is not None:
            return row
        return (float(conf.get("osd_mclock_tenant_reservation",
                               TENANT_DEFAULT_PROFILE[0])),
                float(conf.get("osd_mclock_tenant_weight",
                               TENANT_DEFAULT_PROFILE[1])),
                float(conf.get("osd_mclock_tenant_limit",
                               TENANT_DEFAULT_PROFILE[2])))

    def _book_key(self, sh: _Shard, klass: str, tenant: str | None):
        """Resolve the tag-book key for one item, lazily creating the
        tenant's RWL row (tenant books nest only inside the client
        class — background classes are already cluster-internal)."""
        if tenant is None or klass != K_CLIENT:
            return klass
        key = (klass, tenant)
        if key not in sh.classes:
            res, wgt, lim = self.tenant_profile(tenant)
            sh.ensure(key, res, wgt, lim)
        return key

    # -- lifecycle ---------------------------------------------------------

    def start(self, spawn) -> None:
        """spawn: task factory (Messenger.spawn) so worker lifetimes
        track the daemon's."""
        if self.running:
            return
        self.running = True
        for sh in self.shards:
            self._workers.append(spawn(self._worker(sh)))

    def stop(self) -> None:
        self.running = False
        for sh in self.shards:
            sh.wake.set()

    async def _worker(self, sh: _Shard) -> None:
        while self.running:
            if sh.size == 0:
                sh.wake.clear()
                await sh.wake.wait()
                continue
            pick = sh._pick()
            if pick is None:
                continue
            phase, val = pick
            if phase == "S":
                try:
                    await asyncio.wait_for(sh.wake.wait(), timeout=val)
                    sh.wake.clear()
                except asyncio.TimeoutError:
                    pass
                continue
            fn, waited = sh.pop(val, phase)
            base, tenant = ((val[0], val[1])
                            if isinstance(val, tuple)
                            else (val, None))
            self.dispatched[base] = self.dispatched.get(base, 0) + 1
            if tenant is not None:
                self.tenant_dispatched[tenant] = \
                    self.tenant_dispatched.get(tenant, 0) + 1
            book = self.queue_wait[base]
            book[0] += 1
            book[1] += waited
            if self.on_wait is not None:
                try:
                    self.on_wait(base, waited, tenant)
                except Exception:
                    pass    # observability must never sink the worker
            try:
                r = fn()
                if asyncio.iscoroutine(r) or isinstance(r, asyncio.Future):
                    await r
            except Exception:       # worker must survive op failures
                import traceback
                traceback.print_exc()

    # -- entry points ------------------------------------------------------

    def shard_of(self, key) -> int:
        return hash(key) % len(self.shards)

    def enqueue(self, key, klass: str, fn, cost: float = 1.0,
                tenant: str | None = None) -> None:
        sh = self.shards[self.shard_of(key)]
        sh.push(self._book_key(sh, klass, tenant), fn, cost)

    async def admit(self, klass: str, cost: float = 1.0,
                    key=0, tenant: str | None = None) -> None:
        """Admission ticket for background flows: resolves when the
        arbiter grants `cost` units to `klass` (or to the tenant's
        own tag book when `tenant` is given)."""
        if not self.running:
            return
        loop = asyncio.get_event_loop()
        fut = loop.create_future()

        def grant():
            if not fut.done():
                fut.set_result(None)

        sh = self.shards[self.shard_of(key)]
        sh.push(self._book_key(sh, klass, tenant), grant, cost)
        await fut
