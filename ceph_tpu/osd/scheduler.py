"""mClock-style op scheduler with sharded queues.

Analog of the reference's ShardedOpWQ + OpScheduler stack
(src/osd/OSD.cc:2351,3528-3533; src/osd/scheduler/mClockScheduler.h:75
over the vendored dmclock library, src/dmclock/): every message-driven
unit of OSD work is tagged with a service class and drained from
per-shard queues by a dmClock arbiter, so background work (recovery,
scrub, snap trim) cannot starve client I/O and client bursts cannot
starve recovery below its reservation.

dmClock per class keeps three virtual tags (dmclock's RWL model):

  reservation tag  r += 1/(res_fraction * capacity)   — guaranteed rate
  proportional tag p += 1/weight                      — excess sharing
  limit tag        l += 1/(lim_fraction * capacity)   — hard ceiling

Schedule: any class whose reservation tag is in the past runs first
(by earliest r); otherwise the earliest proportional tag among classes
whose limit tag is in the past; otherwise sleep until the nearest tag
matures.  Tags are clamped to `now` when a class goes idle->busy so
an idle class cannot bank credit (the standard dmClock idle rule).

Shards: `osd_op_num_shards` independent queues, PG-affine (shard =
hash(pgid) % n), each drained by one asyncio worker — per-PG op order
is preserved per class, matching the reference's shard mapping.

Two entry points:
  enqueue(key, klass, fn)  — queue a work item (fn may be sync or
                             return an awaitable); used for message
                             dispatch (client ops, rep ops, EC subops).
  admit(klass, cost)       — awaitable admission ticket used by
                             long-running background flows (recovery
                             push loops, scrub chunks, snap trim) to
                             pace themselves through the same arbiter.
"""

from __future__ import annotations

import asyncio
import time

K_CLIENT = "client"
K_RECOVERY = "recovery"
K_SCRUB = "scrub"
K_SNAPTRIM = "snaptrim"

# (reservation fraction, weight, limit fraction) of osd capacity —
# mirrors the balanced mclock profile (mClockScheduler.cc profiles:
# client gets half the capacity reserved, background recovery a
# quarter, best-effort classes ride the excess)
DEFAULT_PROFILE = {
    K_CLIENT: (0.50, 4.0, 1.00),
    K_RECOVERY: (0.25, 2.0, 0.75),
    K_SCRUB: (0.05, 1.0, 0.50),
    K_SNAPTRIM: (0.05, 1.0, 0.50),
}

# Device dispatch-queue shares (ceph_tpu.device.runtime): the same
# client/recovery proportions as the mClock profile above, plus the
# bulk-mapping class — client EC flushes outrank recovery encodes,
# which outrank whole-pool remap passes, so a mapping storm cannot
# starve client writes of the accelerator.  The background class
# (scrub digest lanes, pool-compression pacing) sits below everything
# else: always-on integrity work rides the excess, never the
# reservation.
DEVICE_DISPATCH_WEIGHTS = {
    "client-ec": DEFAULT_PROFILE[K_CLIENT][1],      # 4.0
    "recovery-ec": DEFAULT_PROFILE[K_RECOVERY][1],  # 2.0
    "mapping": 1.0,
    "background": 0.5,
}


class _ClassQ:
    __slots__ = ("res", "wgt", "lim", "r_tag", "p_tag", "l_tag",
                 "items")

    def __init__(self, res_rate: float, weight: float,
                 lim_rate: float):
        self.res = max(res_rate, 1e-9)
        self.wgt = max(weight, 1e-9)
        self.lim = max(lim_rate, 1e-9)
        self.r_tag = 0.0
        self.p_tag = 0.0
        self.l_tag = 0.0
        self.items: list = []          # FIFO of (fn, cost, t_enq)


class _Shard:
    def __init__(self, profile: dict, capacity: float):
        self.classes = {
            k: _ClassQ(res * capacity, wgt, lim * capacity)
            for k, (res, wgt, lim) in profile.items()}
        self.wake = asyncio.Event()
        self.size = 0

    def push(self, klass: str, fn, cost: float) -> None:
        q = self.classes[klass]
        now = time.monotonic()
        if not q.items:
            # idle -> busy: no banked credit
            q.r_tag = max(q.r_tag, now)
            q.l_tag = max(q.l_tag, now)
            busy_p = [c.p_tag for c in self.classes.values() if c.items]
            q.p_tag = max(q.p_tag, min(busy_p) if busy_p else q.p_tag)
        q.items.append((fn, cost, now))
        self.size += 1
        self.wake.set()

    def _pick(self) -> tuple[str, float] | None:
        """(class, 0) to run now, or (None, delay) to sleep."""
        now = time.monotonic()
        busy = [(k, q) for k, q in self.classes.items() if q.items]
        if not busy:
            return None
        # 1. reservation phase
        ready = [(q.r_tag, k) for k, q in busy if q.r_tag <= now]
        if ready:
            return ("R", min(ready)[1])
        # 2. proportional phase under limit
        under = [(q.p_tag, k) for k, q in busy if q.l_tag <= now]
        if under:
            return ("P", min(under)[1])
        # 3. everything limited: sleep till the nearest tag matures
        horizon = min(min(q.r_tag for _, q in busy),
                      min(q.l_tag for _, q in busy))
        return ("S", max(horizon - now, 0.0005))

    def pop(self, klass: str, phase: str):
        """Returns (fn, queue_wait_seconds)."""
        q = self.classes[klass]
        fn, cost, t_enq = q.items.pop(0)
        self.size -= 1
        now = time.monotonic()
        if phase == "R":
            q.r_tag = max(q.r_tag, now) + cost / q.res
            # the proportional/limit books still advance: a
            # reservation-phase grant consumes budget everywhere
            q.p_tag += cost / q.wgt
            q.l_tag = max(q.l_tag, now) + cost / q.lim
        else:
            q.p_tag += cost / q.wgt
            q.l_tag = max(q.l_tag, now) + cost / q.lim
            q.r_tag = max(q.r_tag, now) + cost / q.res
        return fn, now - t_enq


class OpScheduler:
    """Sharded dmClock arbiter; one per OSD."""

    def __init__(self, ctx=None, num_shards: int | None = None,
                 capacity_iops: float | None = None,
                 profile: dict | None = None):
        conf = getattr(ctx, "conf", None)
        if num_shards is None:
            num_shards = int(conf["osd_op_num_shards"]) if conf else 4
        if capacity_iops is None:
            capacity_iops = (float(conf["osd_mclock_capacity_iops"])
                             if conf else 10000.0)
        self.profile = dict(profile or DEFAULT_PROFILE)
        self.capacity = capacity_iops
        self.shards = [_Shard(self.profile, capacity_iops)
                       for _ in range(max(1, num_shards))]
        self._workers: list[asyncio.Task] = []
        self.running = False
        # perf visibility
        self.dispatched = {k: 0 for k in self.profile}
        # per-class queue-wait books: klass -> [count, sum_seconds];
        # on_wait(klass, seconds) additionally fires per dequeue so the
        # OSD can feed its stage-latency histograms (the queue-wait
        # stage of the op timeline)
        self.queue_wait = {k: [0, 0.0] for k in self.profile}
        self.on_wait = None

    # -- lifecycle ---------------------------------------------------------

    def start(self, spawn) -> None:
        """spawn: task factory (Messenger.spawn) so worker lifetimes
        track the daemon's."""
        if self.running:
            return
        self.running = True
        for sh in self.shards:
            self._workers.append(spawn(self._worker(sh)))

    def stop(self) -> None:
        self.running = False
        for sh in self.shards:
            sh.wake.set()

    async def _worker(self, sh: _Shard) -> None:
        while self.running:
            if sh.size == 0:
                sh.wake.clear()
                await sh.wake.wait()
                continue
            pick = sh._pick()
            if pick is None:
                continue
            phase, val = pick
            if phase == "S":
                try:
                    await asyncio.wait_for(sh.wake.wait(), timeout=val)
                    sh.wake.clear()
                except asyncio.TimeoutError:
                    pass
                continue
            fn, waited = sh.pop(val, phase)
            self.dispatched[val] += 1
            book = self.queue_wait[val]
            book[0] += 1
            book[1] += waited
            if self.on_wait is not None:
                try:
                    self.on_wait(val, waited)
                except Exception:
                    pass    # observability must never sink the worker
            try:
                r = fn()
                if asyncio.iscoroutine(r) or isinstance(r, asyncio.Future):
                    await r
            except Exception:       # worker must survive op failures
                import traceback
                traceback.print_exc()

    # -- entry points ------------------------------------------------------

    def shard_of(self, key) -> int:
        return hash(key) % len(self.shards)

    def enqueue(self, key, klass: str, fn, cost: float = 1.0) -> None:
        self.shards[self.shard_of(key)].push(klass, fn, cost)

    async def admit(self, klass: str, cost: float = 1.0,
                    key=0) -> None:
        """Admission ticket for background flows: resolves when the
        arbiter grants `cost` units to `klass`."""
        if not self.running:
            return
        loop = asyncio.get_event_loop()
        fut = loop.create_future()

        def grant():
            if not fut.done():
                fut.set_result(None)

        self.shards[self.shard_of(key)].push(klass, grant, cost)
        await fut
