"""Daemon-side progress tracking for long-running background flows.

The reference's progress mgr module infers global recovery progress
from PGMap deltas; this build tracks it at the source instead: each
long flow (recovery drains, scrub sweeps, dedup ref-drains) registers
with its daemon's ProgressTracker, updates its done/total counts as it
runs, and the rows ride ``osd_stats["progress"]`` on the next
MMgrReport into the mgr digest — where `status` renders them as a
progress section and the mon leader diffs them into
progress_start/progress_finish events on the bus.

Fractions are clamped monotonic per flow: recovery totals can GROW
mid-drain (new peers reveal more missing objects), and a progress bar
that moves backwards reads as a bug, so `fraction` only ever rises —
`done`/`total` stay truthful for anyone doing arithmetic.  Finished
rows linger for LINGER_S so at least one report cycle ships the 1.0
row (the finish edge must reach the digest before the row vanishes).
"""

from __future__ import annotations

import time

# how long a finished flow's 1.0 row stays visible in rows()
LINGER_S = 10.0


class ProgressTracker:
    """One daemon's in-flight background flows, keyed by
    "<kind>/<key>" (e.g. "recovery/1.0s0", "scrub/2.3")."""

    def __init__(self):
        self._flows: dict[str, dict] = {}

    @staticmethod
    def _id(kind: str, key: str) -> str:
        return "%s/%s" % (kind, key)

    def start(self, kind: str, key: str, total: int) -> str:
        """Register (or restart) a flow; returns its id.  A restart
        of a finished flow (a second scrub of the same PG) begins a
        fresh bar; restarting a LIVE flow keeps its monotonic
        fraction (recovery re-kicked mid-drain is one drain)."""
        fid = self._id(kind, key)
        row = self._flows.get(fid)
        if row is None or row["finished"] is not None:
            self._flows[fid] = {
                "kind": kind, "key": key, "done": 0,
                "total": max(int(total), 0), "fraction": 0.0,
                "started": time.time(), "finished": None}
        else:
            row["total"] = max(row["total"], int(total))
        return fid

    def update(self, fid: str, done: int,
               total: int | None = None) -> None:
        row = self._flows.get(fid)
        if row is None or row["finished"] is not None:
            return
        if total is not None:
            row["total"] = max(int(total), 0)
        row["done"] = min(max(int(done), 0), row["total"])
        if row["total"] > 0:
            row["fraction"] = max(row["fraction"],
                                  row["done"] / row["total"])

    def drain(self, fid: str, outstanding: int) -> None:
        """Drain-shaped update: the flow knows how much work is LEFT
        (missing objects, queued refs), not how much is done.  Total
        grows to cover any newly-revealed work, done is derived, and
        outstanding hitting zero finishes the flow."""
        row = self._flows.get(fid)
        if row is None or row["finished"] is not None:
            return
        outstanding = max(int(outstanding), 0)
        if outstanding == 0:
            self.finish(fid)
            return
        row["total"] = max(row["total"], outstanding)
        self.update(fid, row["total"] - outstanding)

    def finish(self, fid: str) -> None:
        row = self._flows.get(fid)
        if row is None or row["finished"] is not None:
            return
        row["done"] = row["total"]
        row["fraction"] = 1.0
        row["finished"] = time.time()

    def rows(self, now: float | None = None) -> dict:
        """Report-time view: {flow id: row}; finished rows past the
        linger window prune here (the report loop is the only steady
        caller, so pruning needs no timer of its own)."""
        now = time.time() if now is None else now
        out: dict[str, dict] = {}
        for fid, row in list(self._flows.items()):
            fin = row["finished"]
            if fin is not None and now - fin > LINGER_S:
                del self._flows[fid]
                continue
            out[fid] = {"kind": row["kind"], "key": row["key"],
                        "done": row["done"], "total": row["total"],
                        "fraction": round(row["fraction"], 4)}
        return out
