"""Per-peer heartbeat RTT tracking — the OSD's network plane.

Reference analog: OSDService's ping-time tracking behind
``dump_osd_network`` (osd/OSD.cc) and the mon_warn_on_slow_ping_time
machinery feeding the OSD_SLOW_PING_TIME health check.

Every heartbeat reply echoes the ping's send stamp; the OSD feeds
``monotonic() - stamp`` here.  Per peer we keep last/min/max, a
time-decayed EWMA per window (5s/60s/15min), and a pow2-µs
histogram.  Legacy stampless pings simply never feed the tracker, so
mixed-version clusters converge with partial matrices instead of
failing.

A peer is "slow" when BOTH the 5s window average and the most recent
probe sit above the threshold: the window average makes the raise
robust to one spiky probe, the last-probe condition makes the clear
immediate once healthy pings resume (a pure EWMA would hold the
alert for many window constants after a lifted delay).
"""

from __future__ import annotations

import math
import time

# (name, tau seconds): the reference's 1min/5min ping windows plus a
# 5s window, because dev-paced clusters (FAST_CONF) live whole lives
# in under a minute
WINDOWS = (("5s", 5.0), ("60s", 60.0), ("15m", 900.0))

HIST_BUCKETS = 32


class _PeerRtt:
    __slots__ = ("last_s", "min_s", "max_s", "ewma", "hist",
                 "samples", "stamp")

    def __init__(self):
        self.last_s = 0.0
        self.min_s: float | None = None
        self.max_s = 0.0
        self.ewma: dict[str, float] = {}
        self.hist = [0] * HIST_BUCKETS
        self.samples = 0
        self.stamp = 0.0

    def note(self, rtt_s: float, now: float) -> None:
        self.last_s = rtt_s
        self.min_s = rtt_s if self.min_s is None \
            else min(self.min_s, rtt_s)
        self.max_s = max(self.max_s, rtt_s)
        dt = max(0.0, now - self.stamp) if self.samples else 0.0
        for name, tau in WINDOWS:
            cur = self.ewma.get(name)
            if cur is None:
                self.ewma[name] = rtt_s
            else:
                # time-decayed EWMA: irregular ping spacing (thrash
                # stalls, injected delays) must not change the
                # window's effective horizon
                alpha = max(1.0 - math.exp(-dt / tau), 1e-3)
                self.ewma[name] = cur + alpha * (rtt_s - cur)
        us = max(0, int(rtt_s * 1e6))
        self.hist[min(HIST_BUCKETS - 1, us.bit_length())] += 1
        self.samples += 1
        self.stamp = now


class OsdNetwork:
    """The daemon's view of its peers' ping health.

    Registers itself on the context (``ctx.osd_network``) so the
    admin socket's ``dump_osd_network`` builtin resolves it lazily —
    the same backref pattern as the op tracker and flight recorder.
    Also keeps a bounded ring of per-peer cumulative wire-byte
    samples (heartbeat-paced) that the chrome-trace exporter renders
    as per-peer throughput counter tracks.
    """

    WIRE_CAP = 512

    def __init__(self, ctx=None):
        self.ctx = ctx
        self.peers: dict[int, _PeerRtt] = {}
        self.wire_ring: list[dict] = []
        if ctx is not None:
            ctx.osd_network = self

    # -- configuration -----------------------------------------------------

    def slow_threshold_s(self) -> float:
        """Slow-ping bar: explicit conf when set, else 5% of the
        heartbeat grace — a peer eating that much of its grace budget
        in RTT is degraded long before it is declared dead."""
        ms = 0.0
        if self.ctx is not None:
            try:
                ms = float(self.ctx.conf["osd_slow_ping_time_ms"])
            except Exception:
                ms = 0.0
        if ms > 0:
            return ms / 1000.0
        grace = 6.0
        if self.ctx is not None:
            try:
                grace = float(self.ctx.conf["heartbeat_grace"])
            except Exception:
                grace = 6.0
        return grace * 0.05

    # -- ingest ------------------------------------------------------------

    def note_rtt(self, peer: int, rtt_s: float,
                 now: float | None = None) -> None:
        if rtt_s < 0:
            return
        if now is None:
            now = time.monotonic()
        pr = self.peers.get(peer)
        if pr is None:
            pr = self.peers[peer] = _PeerRtt()
        pr.note(rtt_s, now)

    def sample_wire(self, now: float, peer_rows: dict) -> None:
        """Record cumulative per-peer tx/rx byte counters (from
        ``Messenger.net_dump()``) into the bounded trace ring."""
        for peer, row in sorted(peer_rows.items()):
            self.wire_ring.append({
                "t": now, "peer": peer,
                "tx": int(row.get("tx_bytes", 0)),
                "rx": int(row.get("rx_bytes", 0))})
        drop = len(self.wire_ring) - self.WIRE_CAP
        if drop > 0:
            del self.wire_ring[:drop]

    def prune(self, alive) -> None:
        """Forget peers no longer up in the map (mirrors the
        heartbeat-state prune: a revived OSD starts a fresh row)."""
        alive = set(alive)
        for peer in list(self.peers):
            if peer not in alive:
                del self.peers[peer]

    # -- derived views -----------------------------------------------------

    def slow_peers(self) -> list[int]:
        thr = self.slow_threshold_s()
        return sorted(p for p, pr in self.peers.items()
                      if pr.ewma.get("5s", 0.0) > thr
                      and pr.last_s > thr)

    def beacon_slice(self, cap: int = 16) -> dict | None:
        """The bounded MOSDBeacon net slice: worst ``cap`` peers by
        5s-window RTT plus the slow set.  None while no peer has
        answered a stamped ping, so legacy beacons stay byte-stable.
        """
        if not self.peers:
            return None
        worst = sorted(
            self.peers,
            key=lambda p: -self.peers[p].ewma.get("5s", 0.0))
        rtt = {str(p):
               round(self.peers[p].ewma.get("5s", 0.0) * 1000.0, 3)
               for p in worst[:cap]}
        return {"rtt_ms": rtt, "slow": self.slow_peers()}

    def summary(self) -> dict:
        """Daemon-wide rollup for the mgr report / digest."""
        if not self.peers:
            return {"peers": 0, "rtt_avg_ms": 0.0, "rtt_max_ms": 0.0}
        avgs = [pr.ewma.get("5s", 0.0) for pr in self.peers.values()]
        return {
            "peers": len(self.peers),
            "rtt_avg_ms": round(sum(avgs) / len(avgs) * 1000.0, 3),
            "rtt_max_ms": round(max(avgs) * 1000.0, 3)}

    def dump(self) -> dict:
        """The ``dump_osd_network`` admin-socket payload."""
        now = time.monotonic()
        peers = {}
        for p, pr in sorted(self.peers.items()):
            peers["osd.%d" % p] = {
                "last_ms": round(pr.last_s * 1000.0, 3),
                "min_ms": round((pr.min_s or 0.0) * 1000.0, 3),
                "max_ms": round(pr.max_s * 1000.0, 3),
                "avg_ms": {name: round(
                    pr.ewma.get(name, 0.0) * 1000.0, 3)
                    for name, _tau in WINDOWS},
                "hist_us_pow2": list(pr.hist),
                "samples": pr.samples,
                "age_s": round(now - pr.stamp, 3),
            }
        return {
            "threshold_ms": round(self.slow_threshold_s() * 1000.0, 3),
            "peers": peers,
            "slow": ["osd.%d" % p for p in self.slow_peers()]}
