"""RADOS snapshot semantics: SnapSet, clone-on-write, snap reads,
SnapMapper bookkeeping and snap trim.

Analog of the reference's object-snapshot core:
  * SnapSet per head object (src/osd/osd_types.h SnapSet: seq, clones,
    clone_size, clone_snaps) stored here as a denc dict in the head's
    "snapset" attr;
  * PrimaryLogPG::make_writeable (src/osd/PrimaryLogPG.cc): first
    write under a newer SnapContext clones the head into an hobject
    with snap = snapc.seq before the mutation applies — the clone ops
    ride the SAME replicated Transaction as the write, so replicas
    materialise identical clones with no extra protocol;
  * find_object_context snap-read resolution (PrimaryLogPG.cc): a read
    at snapid resolves to the smallest clone covering it, or the head
    when the object is unchanged since the snap;
  * SnapMapper (src/osd/SnapMapper.cc): snap -> object index rows in
    the PG meta object's omap ("sna_" prefix), maintained inside the
    write transaction, consumed by the trimmer;
  * snap trim (PrimaryLogPG::Trimming + SnapTrimEvent): when the pool
    reports removed_snaps, the primary walks the SnapMapper rows for
    each removed snap, drops the snap from each clone (deleting clones
    whose snap set empties), and replicates the per-object updates as
    ordinary logged transactions, paced through the mClock 'snaptrim'
    class.

Head deletion with live clones leaves a whiteout head (zero-length,
"whiteout" attr) carrying the SnapSet — the snapdir object's role
(PrimaryLogPG.cc SNAPDIR) without a second object id.
"""

from __future__ import annotations

from ..store.objectstore import NOSNAP, Transaction, hobject_t
from ..utils import denc

SNAPSET_ATTR = "snapset"
WHITEOUT_ATTR = "whiteout"
SNA_PREFIX = b"sna_"


def new_snapset() -> dict:
    return {"seq": 0, "clones": [], "clone_size": {},
            "clone_snaps": {}}


def load_snapset(store, cid, ho: hobject_t) -> dict | None:
    try:
        raw = store.getattr(cid, ho, SNAPSET_ATTR)
    except Exception:
        return None
    if raw is None:
        return None
    ss = denc.decode(raw)
    ss["clone_size"] = {int(k): v
                        for k, v in ss["clone_size"].items()}
    ss["clone_snaps"] = {int(k): list(v)
                         for k, v in ss["clone_snaps"].items()}
    return ss


def snapset_bytes(ss: dict) -> bytes:
    return denc.encode({
        "seq": ss["seq"], "clones": list(ss["clones"]),
        "clone_size": {str(k): v for k, v in ss["clone_size"].items()},
        "clone_snaps": {str(k): list(v)
                        for k, v in ss["clone_snaps"].items()}})


def is_whiteout(store, cid, ho: hobject_t) -> bool:
    try:
        return store.getattr(cid, ho, WHITEOUT_ATTR) == b"1"
    except Exception:
        return False


def sna_key(snap: int, oid: str) -> bytes:
    return SNA_PREFIX + b"%016x_%s" % (snap, oid.encode())


def make_writeable(store, pg, ho: hobject_t, snapc,
                   t: Transaction) -> dict | None:
    """Clone-on-first-write: if the object exists and the write's
    SnapContext carries snaps newer than the SnapSet's seq, clone the
    head to snap=snapc.seq inside `t`, record the covered snaps, and
    index them in the SnapMapper rows.  Returns the (possibly new)
    SnapSet to be persisted by the caller's mutation, or None when no
    snapshot bookkeeping applies (no snapc ever seen)."""
    if not snapc:
        return None
    seq, snap_ids = int(snapc[0]), [int(s) for s in snapc[1]]
    ss = load_snapset(store, pg.cid, ho)
    exists = store.exists(pg.cid, ho) and not is_whiteout(
        store, pg.cid, ho)
    if ss is None:
        if not snap_ids:
            return None
        ss = new_snapset()
    newer = [s for s in snap_ids if s > ss["seq"]]
    if exists and newer and seq > ss["seq"]:
        cloneid = seq
        cho = hobject_t(ho.name, pool=ho.pool, nspace=ho.nspace,
                        key=ho.key, snap=cloneid)
        t.clone(pg.cid, ho, cho)
        size = store.stat(pg.cid, ho)
        ss["clones"].append(cloneid)
        ss["clones"].sort()
        ss["clone_size"][cloneid] = size
        ss["clone_snaps"][cloneid] = sorted(newer)
        for s in newer:
            t.omap_setkeys(pg.cid, _pgmeta(pg),
                           {sna_key(s, ho.name): b"1"})
    if seq > ss["seq"]:
        ss["seq"] = seq
    return ss


def persist_snapset(pg, ho: hobject_t, ss: dict | None,
                    t: Transaction) -> None:
    if ss is not None:
        t.setattr(pg.cid, ho, SNAPSET_ATTR, snapset_bytes(ss))


def resolve_read_snap(store, pg, oid: str, snapid: int
                      ) -> hobject_t | None:
    """find_object_context: map (oid, snapid) to the store object that
    serves the read, or None for ENOENT."""
    ho = hobject_t(oid)
    if snapid in (None, NOSNAP):
        if store.exists(pg.cid, ho) and not is_whiteout(
                store, pg.cid, ho):
            return ho
        return None
    ss = load_snapset(store, pg.cid, ho)
    c = choose_clone(ss, snapid)
    if c is None:
        return None
    if c != "head":
        return hobject_t(oid, snap=c)
    # head serves: object unchanged since that snap (or never snapped)
    if store.exists(pg.cid, ho) and not is_whiteout(
            store, pg.cid, ho):
        return ho
    return None


def choose_clone(ss: dict | None, snapid: int):
    """Pure find_object_context core (PrimaryLogPG.cc:12065-12090):
    head serves only when snapid is STRICTLY newer than snapset.seq;
    otherwise the first clone >= snapid serves if its snap list covers
    snapid; no covering clone at snapid <= seq means the object did
    not exist at that snap (ENOENT).  Returns "head", a clone id, or
    None."""
    if ss is None:
        return "head"                         # never written snapped
    if snapid > ss["seq"]:
        return "head"                         # unchanged since snap
    for c in ss["clones"]:                    # ascending
        if c >= snapid:
            snaps = ss["clone_snaps"].get(c, [c])
            if snapid in snaps or (snaps and
                                   min(snaps) <= snapid <= c):
                return c
            return None                       # gap: born later
    return None                               # born after the snap


def delete_head(store, pg, ho: hobject_t, ss: dict | None,
                t: Transaction) -> bool:
    """Head removal preserving clones: whiteout when clones remain
    (the snapdir role), plain remove otherwise.  Returns True when the
    object is fully gone (no whiteout left behind)."""
    if ss is not None and ss["clones"]:
        t.truncate(pg.cid, ho, 0)
        t.setattr(pg.cid, ho, WHITEOUT_ATTR, b"1")
        persist_snapset(pg, ho, ss, t)
        return False
    t.remove(pg.cid, ho)
    return True


def _pgmeta(pg):
    from .pg import PGMETA_OID
    return PGMETA_OID


def list_snap_objects(store, pg, snap: int) -> list[str]:
    """SnapMapper query: object names holding clones for `snap`."""
    prefix = SNA_PREFIX + b"%016x_" % snap
    try:
        rows = store.omap_get(pg.cid, _pgmeta(pg))
    except Exception:
        return []
    out = []
    for k in rows:
        if k.startswith(prefix):
            out.append(k[len(prefix):].decode())
    return sorted(out)


def trim_object(store, pg, oid: str, snap: int,
                t: Transaction) -> bool:
    """Drop `snap` from oid's clone that covers it; delete the clone
    when its snap set empties (PrimaryLogPG::trim_object).  Returns
    True if anything changed."""
    ho = hobject_t(oid)
    ss = load_snapset(store, pg.cid, ho)
    if ss is None:
        t.omap_rmkeys(pg.cid, _pgmeta(pg), [sna_key(snap, oid)])
        return False
    changed = False
    for c in list(ss["clones"]):
        snaps = ss["clone_snaps"].get(c, [])
        if snap in snaps:
            snaps.remove(snap)
            changed = True
            if not snaps:
                cho = hobject_t(oid, snap=c)
                if store.exists(pg.cid, cho):
                    t.remove(pg.cid, cho)
                ss["clones"].remove(c)
                ss["clone_size"].pop(c, None)
                ss["clone_snaps"].pop(c, None)
            else:
                ss["clone_snaps"][c] = snaps
            break
    t.omap_rmkeys(pg.cid, _pgmeta(pg), [sna_key(snap, oid)])
    if not changed:
        return False
    if not ss["clones"] and is_whiteout(store, pg.cid, ho):
        # last clone gone and head is a whiteout: drop the stub
        t.remove(pg.cid, ho)
    else:
        persist_snapset(pg, ho, ss, t)
    return True
