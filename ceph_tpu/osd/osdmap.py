"""OSDMap: the versioned cluster map and the PG->OSD mapping pipeline.

Re-derivation of src/osd/OSDMap.{h,cc} and pg_pool_t (src/osd/
osd_types.cc): epoch-versioned device states/weights plus an embedded
CrushMap, with the deterministic mapping pipeline every node computes
identically (OSDMap.cc:2879 _pg_to_up_acting_osds):

    raw_pg_to_pps (stable-mod + rjenkins pool mix, osd_types.cc:1815)
    -> crush do_rule            (host Mapper or vectorized DeviceMapper)
    -> _apply_upmap             (OSDMap.cc:2656)
    -> _raw_to_up_osds          (OSDMap.cc:2724)
    -> _pick_primary / _apply_primary_affinity (OSDMap.cc:2749)
    -> pg_temp / primary_temp   (OSDMap.cc:2804)

Incremental mutation follows the same new_* field pattern as
OSDMap::Incremental so monitors can publish deltas.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..models.crushmap import ITEM_NONE, CrushMap
from ..ops.crush.hashes import hash32_2, str_hash_rjenkins
from ..ops.crush.host import Mapper

CEPH_OSD_MAX_PRIMARY_AFFINITY = 0x10000
CEPH_OSD_DEFAULT_PRIMARY_AFFINITY = 0x10000
CEPH_OSD_IN = 0x10000
CEPH_OSD_OUT = 0

# osd_state bits
OSD_EXISTS = 1
OSD_UP = 2

POOL_TYPE_REPLICATED = 1
POOL_TYPE_ERASURE = 3

FLAG_HASHPSPOOL = 1


def calc_bits_of(t: int) -> int:
    b = 0
    while t:
        t >>= 1
        b += 1
    return b


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """Stable modulo: remaps only the necessary inputs when b grows
    toward the next power of two (include/ceph_hash-adjacent helper used
    by pg selection)."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


@dataclass(frozen=True)
class pg_t:
    """Raw placement-group id: (pool, ps)."""

    pool: int
    ps: int

    def __str__(self) -> str:
        return "%d.%x" % (self.pool, self.ps)


@dataclass
class PGPool:
    """pg_pool_t analog (the subset the mapping/data path needs)."""

    id: int
    name: str
    type: int = POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 32
    pgp_num: int = 0
    crush_rule: int = 0
    flags: int = FLAG_HASHPSPOOL
    erasure_code_profile: str = ""
    object_hash: str = "rjenkins"  # only rjenkins supported
    last_change: int = 0
    # snapshot state (pg_pool_t snap_seq/snaps/removed_snaps,
    # src/osd/osd_types.h): snap_seq is the newest snapid ever issued
    # for this pool (pool snaps AND selfmanaged share the space);
    # snaps maps pool-snapshot ids to names; removed_snaps lists
    # deleted snapids until every PG reports them purged
    snap_seq: int = 0
    snaps: dict = field(default_factory=dict)       # snapid -> name
    removed_snaps: list = field(default_factory=list)
    # pool-level compression (pg_pool_t compression_* options feeding
    # the BlueStore blob-compression role): mode "none" | "force"
    compression_mode: str = "none"
    compression_algorithm: str = "zlib"
    # data-reduction plane (pg_pool_t dedup_chunk_pool): writes to
    # this pool chunk/fingerprint/dedup into the named chunk pool;
    # -1 disables
    dedup_chunk_pool: int = -1

    def __post_init__(self):
        if not self.pgp_num:
            self.pgp_num = self.pg_num

    def snap_context(self) -> tuple[int, list[int]]:
        """Implicit pool-snap SnapContext: (seq, snapids desc) — what
        the Objecter attaches to writes when the app did not supply a
        selfmanaged snapc (Objecter::_op_submit pool snapc)."""
        live = sorted((s for s in self.snaps), reverse=True)
        return (self.snap_seq, live)

    @property
    def pg_num_mask(self) -> int:
        return (1 << calc_bits_of(self.pg_num - 1)) - 1

    @property
    def pgp_num_mask(self) -> int:
        return (1 << calc_bits_of(self.pgp_num - 1)) - 1

    def is_erasure(self) -> bool:
        return self.type == POOL_TYPE_ERASURE

    def can_shift_osds(self) -> bool:
        # replicated sets compact; erasure sets are positional
        return self.type == POOL_TYPE_REPLICATED

    def hash_key(self, key: str, nspace: str) -> int:
        """Object key -> 32-bit ps hash (osd_types.cc:1777-1794): the
        namespace, when present, is prefixed with a 0x1f separator."""
        if nspace:
            buf = nspace.encode() + b"\x1f" + key.encode()
        else:
            buf = key.encode()
        return str_hash_rjenkins(buf)

    def raw_pg_to_pg(self, pg: pg_t) -> pg_t:
        return pg_t(pg.pool, ceph_stable_mod(pg.ps, self.pg_num,
                                             self.pg_num_mask))

    def raw_pg_to_pps(self, pg: pg_t) -> int:
        """Placement seed (osd_types.cc:1815-1831)."""
        if self.flags & FLAG_HASHPSPOOL:
            return hash32_2(
                ceph_stable_mod(pg.ps, self.pgp_num, self.pgp_num_mask),
                pg.pool)
        return ceph_stable_mod(pg.ps, self.pgp_num,
                               self.pgp_num_mask) + pg.pool

    def to_dict(self) -> dict:
        return {
            "id": self.id, "name": self.name, "type": self.type,
            "size": self.size, "min_size": self.min_size,
            "pg_num": self.pg_num, "pgp_num": self.pgp_num,
            "crush_rule": self.crush_rule, "flags": self.flags,
            "erasure_code_profile": self.erasure_code_profile,
            "object_hash": self.object_hash,
            "last_change": self.last_change,
            "snap_seq": self.snap_seq,
            "snaps": {str(k): v for k, v in self.snaps.items()},
            "removed_snaps": list(self.removed_snaps),
            "compression_mode": self.compression_mode,
            "compression_algorithm": self.compression_algorithm,
            "dedup_chunk_pool": self.dedup_chunk_pool,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PGPool":
        # tolerate keys from NEWER writers (forward compat: an old
        # daemon reading a new map keeps what it understands)
        import dataclasses

        known = {f.name for f in dataclasses.fields(cls)}
        d = {k: v for k, v in d.items() if k in known}
        d["snaps"] = {int(k): v
                      for k, v in (d.get("snaps") or {}).items()}
        d.setdefault("snap_seq", 0)
        d.setdefault("removed_snaps", [])
        d.setdefault("compression_mode", "none")
        d.setdefault("compression_algorithm", "zlib")
        d.setdefault("dedup_chunk_pool", -1)
        return cls(**d)


class OSDMap:
    """The cluster map. All mutation goes through apply_incremental so
    every node's copy stays identical per epoch."""

    def __init__(self):
        self.epoch = 0
        self.fsid = ""
        self.max_osd = 0
        self.osd_state: list[int] = []
        self.osd_weight: list[int] = []      # 16.16 in/out weight
        self.osd_primary_affinity: list[int] | None = None
        self.osd_addrs: dict[int, str] = {}
        # latest epoch through which each osd was confirmed able to
        # serve as primary (OSDMap::get_up_thru): peering uses it to
        # decide whether a past interval could have gone read-write
        self.osd_up_thru: dict[int, int] = {}
        self.crush = CrushMap()
        self.pools: dict[int, PGPool] = {}
        self.pool_max = -1
        self.mgr_addr = ""          # active manager (MgrMap's role)
        self.pg_temp: dict[pg_t, list[int]] = {}
        self.primary_temp: dict[pg_t, int] = {}
        self.pg_upmap: dict[pg_t, list[int]] = {}
        self.pg_upmap_items: dict[pg_t, list[tuple[int, int]]] = {}
        self.pg_upmap_primaries: dict[pg_t, int] = {}
        self.blocklist: dict[str, float] = {}
        # name -> profile kv (OSDMap::erasure_code_profiles)
        self.erasure_code_profiles: dict[str, dict] = {}
        self._mapper: Mapper | None = None
        self._dmapper = None  # lazily-built DeviceMapper, same lifetime

    # -- device state ------------------------------------------------------

    def set_max_osd(self, n: int) -> None:
        while len(self.osd_state) < n:
            self.osd_state.append(0)
            self.osd_weight.append(CEPH_OSD_OUT)
        self.max_osd = n

    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and bool(
            self.osd_state[osd] & OSD_EXISTS)

    def is_up(self, osd: int) -> bool:
        return self.exists(osd) and bool(self.osd_state[osd] & OSD_UP)

    def is_down(self, osd: int) -> bool:
        return not self.is_up(osd)

    def is_in(self, osd: int) -> bool:
        return self.exists(osd) and self.osd_weight[osd] > 0

    def is_out(self, osd: int) -> bool:
        return not self.is_in(osd)

    def get_weight(self, osd: int) -> int:
        return self.osd_weight[osd]

    def get_up_thru(self, osd: int) -> int:
        return self.osd_up_thru.get(osd, 0)

    def primary_affinity(self, osd: int) -> int:
        if self.osd_primary_affinity is None:
            return CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
        return self.osd_primary_affinity[osd]

    def get_pg_pool(self, pool: int) -> PGPool | None:
        return self.pools.get(pool)

    def _crush_mapper(self) -> Mapper:
        if self._mapper is None:
            self._mapper = Mapper(self.crush)
        return self._mapper

    def device_mapper(self):
        """Shared vectorized mapper, flattened once per crush epoch
        (raises ValueError when the map is outside device scope)."""
        if self._dmapper is None:
            from ..ops.crush.device import DeviceMapper

            self._dmapper = DeviceMapper(self.crush)
        return self._dmapper

    # -- object -> pg ------------------------------------------------------

    def object_locator_to_pg(self, name: str, pool: int,
                             key: str = "", nspace: str = "") -> pg_t:
        p = self.pools[pool]
        ps = p.hash_key(key or name, nspace)
        return pg_t(pool, ps)

    # -- mapping pipeline --------------------------------------------------

    def _pg_to_raw_osds(self, pool: PGPool, pg: pg_t) -> tuple[list[int], int]:
        pps = pool.raw_pg_to_pps(pg)
        raw = self._crush_mapper().do_rule(
            pool.crush_rule, pps, pool.size, self.osd_weight)
        self._remove_nonexistent_osds(pool, raw)
        return raw, pps

    def _remove_nonexistent_osds(self, pool: PGPool,
                                 osds: list[int]) -> None:
        if pool.can_shift_osds():
            osds[:] = [o for o in osds if self.exists(o)]
        else:
            for i, o in enumerate(osds):
                if o != ITEM_NONE and not self.exists(o):
                    osds[i] = ITEM_NONE

    def _apply_upmap(self, pool: PGPool, raw_pg: pg_t,
                     raw: list[int]) -> None:
        pg = pool.raw_pg_to_pg(raw_pg)
        p = self.pg_upmap.get(pg)
        if p is not None:
            # any out target rejects the whole explicit mapping — and,
            # like OSDMap.cc:2666, skips items/primaries too
            if any(o != ITEM_NONE and 0 <= o < self.max_osd
                   and self.osd_weight[o] == 0 for o in p):
                return
            raw[:] = list(p)
        q = self.pg_upmap_items.get(pg)
        if q is not None:
            for osd_from, osd_to in q:
                exists = False
                pos = -1
                for i, o in enumerate(raw):
                    if o == osd_to:
                        exists = True
                        break
                    if (o == osd_from and pos < 0 and not (
                            osd_to != ITEM_NONE and 0 <= osd_to < self.max_osd
                            and self.osd_weight[osd_to] == 0)):
                        pos = i
                if not exists and pos >= 0:
                    raw[pos] = osd_to
        r = self.pg_upmap_primaries.get(pg)
        if r is not None:
            if (r != ITEM_NONE and 0 <= r < self.max_osd
                    and self.osd_weight[r] != 0):
                idx = 0
                for i in range(1, len(raw)):
                    if raw[i] == r:
                        idx = i
                        break
                if idx > 0:
                    raw[idx] = raw[0]
                    raw[0] = r

    def _raw_to_up_osds(self, pool: PGPool, raw: list[int]) -> list[int]:
        if pool.can_shift_osds():
            return [o for o in raw if self.exists(o) and self.is_up(o)]
        return [o if (self.exists(o) and self.is_up(o)) else ITEM_NONE
                for o in raw]

    @staticmethod
    def _pick_primary(osds: list[int]) -> int:
        for o in osds:
            if o != ITEM_NONE:
                return o
        return -1

    def _apply_primary_affinity(self, seed: int, pool: PGPool,
                                osds: list[int], primary: int) -> int:
        if self.osd_primary_affinity is None:
            return primary
        if not any(o != ITEM_NONE and
                   self.osd_primary_affinity[o] !=
                   CEPH_OSD_DEFAULT_PRIMARY_AFFINITY for o in osds):
            return primary
        pos = -1
        for i, o in enumerate(osds):
            if o == ITEM_NONE:
                continue
            a = self.osd_primary_affinity[o]
            if (a < CEPH_OSD_MAX_PRIMARY_AFFINITY
                    and (hash32_2(seed, o) >> 16) >= a):
                if pos < 0:
                    pos = i
            else:
                pos = i
                break
        if pos < 0:
            return primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            for i in range(pos, 0, -1):
                osds[i] = osds[i - 1]
            osds[0] = primary
        return primary

    def _get_temp_osds(self, pool: PGPool,
                       pg: pg_t) -> tuple[list[int], int]:
        pg = pool.raw_pg_to_pg(pg)
        temp = []
        for o in self.pg_temp.get(pg, []):
            if not self.exists(o) or self.is_down(o):
                if pool.can_shift_osds():
                    continue
                temp.append(ITEM_NONE)
            else:
                temp.append(o)
        temp_primary = self.primary_temp.get(pg, -1)
        if temp_primary == -1 and temp:
            for o in temp:
                if o != ITEM_NONE:
                    temp_primary = o
                    break
        return temp, temp_primary

    def pg_to_up_acting_osds(
        self, pg: pg_t,
    ) -> tuple[list[int], int, list[int], int]:
        """Returns (up, up_primary, acting, acting_primary) — the full
        OSDMap.cc:2879 composition."""
        pool = self.pools.get(pg.pool)
        if pool is None or pg.ps >= pool.pg_num:
            return [], -1, [], -1
        acting, acting_primary = self._get_temp_osds(pool, pg)
        raw, pps = self._pg_to_raw_osds(pool, pg)
        self._apply_upmap(pool, pg, raw)
        up = self._raw_to_up_osds(pool, raw)
        up_primary = self._pick_primary(up)
        up_primary = self._apply_primary_affinity(pps, pool, up, up_primary)
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    def pg_to_acting_osds(self, pg: pg_t) -> tuple[list[int], int]:
        _, _, acting, primary = self.pg_to_up_acting_osds(pg)
        return acting, primary

    @staticmethod
    def calc_pg_role(osd: int, acting: list[int]) -> int:
        for i, o in enumerate(acting):
            if o == osd:
                return i
        return -1

    # -- incremental mutation ---------------------------------------------

    def apply_incremental(self, inc: "Incremental") -> None:
        if inc.epoch != self.epoch + 1:
            raise ValueError("incremental epoch %d does not follow %d"
                             % (inc.epoch, self.epoch))
        self.epoch = inc.epoch
        if inc.new_max_osd >= 0:
            self.set_max_osd(inc.new_max_osd)
        if inc.new_mgr_addr is not None:
            self.mgr_addr = inc.new_mgr_addr
        for pid, pool in inc.new_pools.items():
            self.pools[pid] = pool
            self.pool_max = max(self.pool_max, pid)
        for pid in inc.old_pools:
            self.pools.pop(pid, None)
        for osd, st in inc.new_state.items():
            # xor semantics like the reference: toggles the given bits
            self.osd_state[osd] ^= st
        for osd, w in inc.new_weight.items():
            self.osd_weight[osd] = w
        for osd, aff in inc.new_primary_affinity.items():
            if self.osd_primary_affinity is None:
                self.osd_primary_affinity = (
                    [CEPH_OSD_DEFAULT_PRIMARY_AFFINITY] * self.max_osd)
            while len(self.osd_primary_affinity) < self.max_osd:
                self.osd_primary_affinity.append(
                    CEPH_OSD_DEFAULT_PRIMARY_AFFINITY)
            self.osd_primary_affinity[osd] = aff
        for osd, addr in inc.new_up_client.items():
            self.osd_state[osd] |= OSD_EXISTS | OSD_UP
            self.osd_addrs[osd] = addr
        for osd, thru in inc.new_up_thru.items():
            self.osd_up_thru[osd] = thru
        for pg, osds in inc.new_pg_temp.items():
            if osds:
                self.pg_temp[pg] = list(osds)
            else:
                self.pg_temp.pop(pg, None)
        for pg, p in inc.new_primary_temp.items():
            if p >= 0:
                self.primary_temp[pg] = p
            else:
                self.primary_temp.pop(pg, None)
        for pg, osds in inc.new_pg_upmap.items():
            if osds:
                self.pg_upmap[pg] = list(osds)
            else:
                self.pg_upmap.pop(pg, None)
        for pg in inc.old_pg_upmap:
            self.pg_upmap.pop(pg, None)
        for pg, items in inc.new_pg_upmap_items.items():
            if items:
                self.pg_upmap_items[pg] = [tuple(t) for t in items]
            else:
                self.pg_upmap_items.pop(pg, None)
        for pg in inc.old_pg_upmap_items:
            self.pg_upmap_items.pop(pg, None)
        for name, prof in inc.new_erasure_code_profiles.items():
            self.erasure_code_profiles[name] = dict(prof)
        for name in inc.old_erasure_code_profiles:
            self.erasure_code_profiles.pop(name, None)
        if inc.new_crush is not None:
            self.crush = inc.new_crush
            self._mapper = None
            self._dmapper = None

    def new_incremental(self) -> "Incremental":
        return Incremental(epoch=self.epoch + 1)

    # -- wire encoding (OSDMap::encode/decode analog) ----------------------

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "fsid": self.fsid,
            "max_osd": self.max_osd,
            "osd_state": list(self.osd_state),
            "osd_weight": list(self.osd_weight),
            "osd_primary_affinity": (
                list(self.osd_primary_affinity)
                if self.osd_primary_affinity is not None else None),
            "osd_addrs": {str(k): v for k, v in self.osd_addrs.items()},
            "osd_up_thru": {str(k): v
                            for k, v in self.osd_up_thru.items()},
            "crush": self.crush.to_dict(),
            "pools": {str(k): p.to_dict() for k, p in self.pools.items()},
            "pool_max": self.pool_max,
            "mgr_addr": self.mgr_addr,
            "pg_temp": _enc_pg_map(self.pg_temp),
            "primary_temp": _enc_pg_map(self.primary_temp),
            "pg_upmap": _enc_pg_map(self.pg_upmap),
            "pg_upmap_items": [
                [pg.pool, pg.ps, [list(t) for t in items]]
                for pg, items in self.pg_upmap_items.items()],
            "pg_upmap_primaries": _enc_pg_map(self.pg_upmap_primaries),
            "blocklist": dict(self.blocklist),
            "erasure_code_profiles": {
                k: dict(v)
                for k, v in self.erasure_code_profiles.items()},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "OSDMap":
        m = cls()
        m.epoch = d["epoch"]
        m.fsid = d["fsid"]
        m.max_osd = d["max_osd"]
        m.osd_state = list(d["osd_state"])
        m.osd_weight = list(d["osd_weight"])
        m.osd_primary_affinity = (
            list(d["osd_primary_affinity"])
            if d["osd_primary_affinity"] is not None else None)
        m.osd_addrs = {int(k): v for k, v in d["osd_addrs"].items()}
        m.osd_up_thru = {int(k): v
                         for k, v in d.get("osd_up_thru", {}).items()}
        m.crush = CrushMap.from_dict(d["crush"])
        m.pools = {int(k): PGPool.from_dict(p)
                   for k, p in d["pools"].items()}
        m.pool_max = d["pool_max"]
        m.mgr_addr = d.get("mgr_addr", "")
        m.pg_temp = _dec_pg_map(d["pg_temp"], list)
        m.primary_temp = _dec_pg_map(d["primary_temp"], int)
        m.pg_upmap = _dec_pg_map(d["pg_upmap"], list)
        m.pg_upmap_items = {
            pg_t(p, ps): [tuple(t) for t in items]
            for p, ps, items in d["pg_upmap_items"]}
        m.pg_upmap_primaries = _dec_pg_map(d["pg_upmap_primaries"], int)
        m.blocklist = dict(d["blocklist"])
        m.erasure_code_profiles = {
            k: dict(v)
            for k, v in d.get("erasure_code_profiles", {}).items()}
        return m

    # encoding version history (ENCODE_START discipline, encoding.h):
    #   1 — round-4 layout
    #   2 — +osd_up_thru, +pool compression fields (additive: compat
    #       stays 1, old decoders read their known keys)
    #   3 — +pool dedup_chunk_pool (additive, compat stays 1)
    STRUCT_V = 3
    STRUCT_COMPAT = 1

    def encode(self) -> bytes:
        from ..utils import denc

        return denc.encode_versioned(self.to_dict(), self.STRUCT_V,
                                     self.STRUCT_COMPAT)

    @classmethod
    def decode(cls, data: bytes) -> "OSDMap":
        from ..utils import denc

        if bytes(data[:1]) == b"V":
            _v, d = denc.decode_versioned(data, cls.STRUCT_V)
            return cls.from_dict(d)
        # legacy (pre-versioning) blob, e.g. an old store's full map
        return cls.from_dict(denc.decode(data))


def consume_map_payload(cur: "OSDMap", full: bytes | None,
                        incrementals: list | None
                        ) -> tuple["OSDMap", bool]:
    """Shared subscriber-side map consumption (Objecter::handle_osd_map
    / OSD::handle_osd_map): adopt a newer full map, then apply every
    contiguous incremental.  Returns (map, changed)."""
    changed = False
    if full is not None:
        m = OSDMap.decode(full)
        if m.epoch > cur.epoch:
            cur = m
            changed = True
    for raw in incrementals or []:
        inc = Incremental.decode(raw)
        if inc.epoch == cur.epoch + 1:
            cur.apply_incremental(inc)
            changed = True
    return cur, changed


def _enc_pg_map(d: dict) -> list:
    return [[pg.pool, pg.ps,
             list(v) if isinstance(v, (list, tuple)) else v]
            for pg, v in d.items()]


def _dec_pg_map(rows: list, vtype) -> dict:
    if vtype is list:
        return {pg_t(p, ps): list(v) for p, ps, v in rows}
    return {pg_t(p, ps): v for p, ps, v in rows}


@dataclass
class Incremental:
    """OSDMap::Incremental analog: a sparse delta to the next epoch."""

    epoch: int
    new_max_osd: int = -1
    new_mgr_addr: str | None = None
    new_pools: dict[int, PGPool] = field(default_factory=dict)
    old_pools: list[int] = field(default_factory=list)
    new_state: dict[int, int] = field(default_factory=dict)    # xor bits
    new_weight: dict[int, int] = field(default_factory=dict)
    new_primary_affinity: dict[int, int] = field(default_factory=dict)
    new_up_client: dict[int, str] = field(default_factory=dict)
    new_up_thru: dict[int, int] = field(default_factory=dict)
    new_pg_temp: dict[pg_t, list[int]] = field(default_factory=dict)
    new_primary_temp: dict[pg_t, int] = field(default_factory=dict)
    new_pg_upmap: dict[pg_t, list[int]] = field(default_factory=dict)
    old_pg_upmap: list[pg_t] = field(default_factory=list)
    new_pg_upmap_items: dict[pg_t, list[tuple[int, int]]] = (
        field(default_factory=dict))
    old_pg_upmap_items: list[pg_t] = field(default_factory=list)
    new_crush: CrushMap | None = None
    new_erasure_code_profiles: dict[str, dict] = field(
        default_factory=dict)
    old_erasure_code_profiles: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "new_max_osd": self.new_max_osd,
            "new_mgr_addr": self.new_mgr_addr,
            "new_pools": {str(k): p.to_dict()
                          for k, p in self.new_pools.items()},
            "old_pools": list(self.old_pools),
            "new_state": {str(k): v for k, v in self.new_state.items()},
            "new_weight": {str(k): v for k, v in self.new_weight.items()},
            "new_primary_affinity": {
                str(k): v for k, v in self.new_primary_affinity.items()},
            "new_up_client": {str(k): v
                              for k, v in self.new_up_client.items()},
            "new_up_thru": {str(k): v
                            for k, v in self.new_up_thru.items()},
            "new_pg_temp": _enc_pg_map(self.new_pg_temp),
            "new_primary_temp": _enc_pg_map(self.new_primary_temp),
            "new_pg_upmap": _enc_pg_map(self.new_pg_upmap),
            "old_pg_upmap": [[pg.pool, pg.ps] for pg in self.old_pg_upmap],
            "new_pg_upmap_items": [
                [pg.pool, pg.ps, [list(t) for t in items]]
                for pg, items in self.new_pg_upmap_items.items()],
            "old_pg_upmap_items": [[pg.pool, pg.ps]
                                   for pg in self.old_pg_upmap_items],
            "new_crush": (self.new_crush.to_dict()
                          if self.new_crush is not None else None),
            "new_erasure_code_profiles": {
                k: dict(v)
                for k, v in self.new_erasure_code_profiles.items()},
            "old_erasure_code_profiles": list(
                self.old_erasure_code_profiles),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Incremental":
        inc = cls(epoch=d["epoch"])
        inc.new_max_osd = d["new_max_osd"]
        inc.new_mgr_addr = d.get("new_mgr_addr")
        inc.new_pools = {int(k): PGPool.from_dict(p)
                         for k, p in d["new_pools"].items()}
        inc.old_pools = list(d["old_pools"])
        inc.new_state = {int(k): v for k, v in d["new_state"].items()}
        inc.new_weight = {int(k): v for k, v in d["new_weight"].items()}
        inc.new_primary_affinity = {
            int(k): v for k, v in d["new_primary_affinity"].items()}
        inc.new_up_client = {int(k): v
                             for k, v in d["new_up_client"].items()}
        inc.new_up_thru = {int(k): v
                           for k, v in d.get("new_up_thru", {}).items()}
        inc.new_pg_temp = _dec_pg_map(d["new_pg_temp"], list)
        inc.new_primary_temp = _dec_pg_map(d["new_primary_temp"], int)
        inc.new_pg_upmap = _dec_pg_map(d["new_pg_upmap"], list)
        inc.old_pg_upmap = [pg_t(p, ps) for p, ps in d["old_pg_upmap"]]
        inc.new_pg_upmap_items = {
            pg_t(p, ps): [tuple(t) for t in items]
            for p, ps, items in d["new_pg_upmap_items"]}
        inc.old_pg_upmap_items = [pg_t(p, ps)
                                  for p, ps in d["old_pg_upmap_items"]]
        inc.new_crush = (CrushMap.from_dict(d["new_crush"])
                         if d["new_crush"] is not None else None)
        inc.new_erasure_code_profiles = {
            k: dict(v)
            for k, v in d.get("new_erasure_code_profiles", {}).items()}
        inc.old_erasure_code_profiles = list(
            d.get("old_erasure_code_profiles", []))
        return inc

    STRUCT_V = 2        # 2: +new_up_thru (additive)
    STRUCT_COMPAT = 1

    def encode(self) -> bytes:
        from ..utils import denc

        return denc.encode_versioned(self.to_dict(), self.STRUCT_V,
                                     self.STRUCT_COMPAT)

    @classmethod
    def decode(cls, data: bytes) -> "Incremental":
        from ..utils import denc

        if bytes(data[:1]) == b"V":
            _v, d = denc.decode_versioned(data, cls.STRUCT_V)
            return cls.from_dict(d)
        return cls.from_dict(denc.decode(data))
