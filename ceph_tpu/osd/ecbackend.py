"""EC backend: erasure-coded PG I/O over positional shards.

Condensed analog of src/osd/ECBackend.cc + ECUtil.{h,cc}: an EC pool's
PG stores each object as k+m shards, one per acting-set position —
acting[j] holds shard j (shard_id_t).  The primary:

* write  — encodes the object payload through the ErasureCodeInterface
  plugin (ECUtil::encode -> encode_chunks), persists its own shard, and
  sends each remote shard its transaction via MOSDECSubOpWrite
  (ECBackend::submit_transaction -> handle_sub_write,
  ECBackend.cc:1539,945); partial-extent writes are read-modify-write
  through the reconstruct path (start_rmw, ECBackend.cc:1898).
* read   — fetches the minimum shard set first (local + enough remotes
  for k distinct shards) and widens to every member on shortfall
  (objects_read_and_reconstruct + minimum_to_decode,
  ECBackend.cc:2405).  Sourcing is by *stored* shard, not acting
  position: any k distinct shards decode, so a member whose bytes
  belong to a previous layout still serves as a reconstruction source —
  availability the reference keeps via pg_temp + backfill.
* recover— rebuilds exactly the TARGET's shard from k survivors and
  pushes it (continue_recovery_op, ECBackend.cc:591): unlike the
  replicated backend, a pushed EC object is the recipient's shard, not
  a copy of the pusher's.

Shard metadata xattrs (the role ECUtil::HashInfo plays):
  ec_size  — true (unpadded) object length;
  ec_shard — which shard index these bytes encode (the shard_id_t the
             reference bakes into hobject_t);
  ec_ver   — the pg-log version that produced the bytes, so readers
             never mix shards from different writes (a member that
             missed a write is simply not a source until recovered).

Ordering: a per-(pg, oid) refcounted asyncio lock serializes client
RMW cycles AND recovery of the same object, the way ECBackend's
pipeline ordering (waiting_state -> waiting_reads -> waiting_commit)
plus the recovery read lock do.
"""

from __future__ import annotations

import asyncio

from ..ec.plugin import ErasureCodePluginRegistry
from ..models.crushmap import ITEM_NONE
from ..msg.messages import (MOSDECSubOpRead, MOSDECSubOpReadReply,
                            MOSDECSubOpWrite, MOSDECSubOpWriteReply,
                            MOSDOpReply, MOSDPGPush)
from ..store.objectstore import NotFound, Transaction, hobject_t
from ..utils import denc
from .pg import PG, LogEntry

SIZE_XATTR = "ec_size"
SHARD_XATTR = "ec_shard"
VER_XATTR = "ec_ver"
HINFO_XATTR = "ec_hinfo"   # crc32 of every shard, comma-joined (the
                           # role ECUtil::HashInfo plays: deep scrub
                           # identifies a rotted shard by its crc)


def hinfo_bytes(shards: dict[int, bytes]) -> bytes:
    import zlib

    return b",".join(b"%d" % (zlib.crc32(shards[j]) & 0xFFFFFFFF)
                     for j in sorted(shards))


def _ver_bytes(version: tuple[int, int]) -> bytes:
    return b"%d.%d" % tuple(version)


def _parse_ver(raw: bytes) -> tuple[int, int]:
    a, b = raw.split(b".")
    return (int(a), int(b))


def derive_warmup_buckets(op_size_hist: list[int] | None, k: int,
                          w: int, top: int = 3) -> tuple | None:
    """Workload-aware device warmup for RAGGED streams: map the
    daemon's client write-size histogram (pow2 byte buckets —
    op_size_hist[i] counts writes of [2^i, 2^(i+1)) bytes) onto the
    bucket-ladder segment programs a k-chunk, w-bit codec's flushes
    will actually dispatch.  The batcher stages each flush TOTAL as a
    pow2 segment ladder (``DeviceRuntime.ragged_plan``), so the
    buckets worth warming are the ladder segments of each top item
    width (solo flushes) plus the segments of their combined total
    (the heterogeneous mixed flush a concurrent stream produces) —
    not each item's own pow2 ceiling.  Returns None when there is no
    history (caller falls back to the static default list)."""
    if not op_size_hist or not any(op_size_hist):
        return None
    from ..device.runtime import DeviceRuntime
    word_bytes = max(1, int(w) // 8)
    ranked = sorted(
        (i for i, n in enumerate(op_size_hist) if n > 0),
        key=lambda i: (-op_size_hist[i], i))[:top]
    words = []
    for i in ranked:
        payload = 1 << (i + 1)          # bucket upper bound, bytes
        words.append(-(-payload // (k * word_bytes)))   # ceil div
    buckets = set()
    for n in words + ([sum(words)] if len(words) > 1 else []):
        for _lo, seg in DeviceRuntime.ragged_plan(n):
            buckets.add(seg)
    return tuple(sorted(buckets))


class _OidLock:
    """Refcounted per-oid lock so the registry stays bounded."""

    __slots__ = ("lock", "refs")

    def __init__(self):
        self.lock = asyncio.Lock()
        self.refs = 0


class ECPGBackend:
    """Per-daemon EC I/O engine (shared across the daemon's EC PGs)."""

    def __init__(self, osd):
        self.osd = osd
        self._codecs: dict[str, object] = {}
        self._tid = 0
        # tid -> {"waiting": set, "event": Event, "buffers": dict,
        #         "errors": dict}
        self._reads: dict[int, dict] = {}
        self._writes: dict[int, dict] = {}
        self._locks: dict[tuple, _OidLock] = {}
        # telemetry: shard bytes fetched over the wire (RMW
        # amplification visibility; tests pin partial-write traffic)
        self.sub_read_bytes = 0
        # repair-traffic accounting (per codec plugin): survivor
        # bytes read through minimum_to_decode's minimal shard sets
        # vs rebuilt bytes pushed — shipped in MMgrReport osd_stats
        # and mirrored on the daemon's chip as chip-labeled series
        self.repair_traffic: dict[str, dict[str, int]] = {}
        # last degraded-read plan (tests assert fetched == minimal)
        self.last_read_plan: dict | None = None
        # last version-selection plan (tests assert the decode staged
        # exactly the minimum_to_decode-costed shard set)
        self.last_version_plan: dict | None = None

    # -- codec -------------------------------------------------------------

    def codec(self, pool):
        prof_name = pool.erasure_code_profile or "default"
        c = self._codecs.get(prof_name)
        if c is None:
            profile = dict(
                self.osd.osdmap.erasure_code_profiles.get(prof_name)
                or {"plugin": "jerasure", "k": "2", "m": "1",
                    "technique": "reed_sol_van"})
            plugin = profile.get("plugin", "jerasure")
            c = ErasureCodePluginRegistry.instance().factory(
                plugin, profile)
            self._codecs[prof_name] = c
            self._maybe_warmup(c)
        return c

    def _codec_name(self, pool) -> str:
        """The pool codec's plugin name (the repair-traffic label)."""
        prof = dict(self.osd.osdmap.erasure_code_profiles.get(
            pool.erasure_code_profile or "default") or {})
        return prof.get("plugin", "jerasure")

    def note_repair(self, codec_name: str, bytes_read: int,
                    bytes_moved: int, targeted: bool = True) -> None:
        """Account one shard repair: `bytes_read` survivor bytes
        sourced (the minimal-set fetch when `targeted`, the full
        k-wide read otherwise) and `bytes_moved` rebuilt bytes
        written/pushed.  Flows to the perf counters, the MMgrReport
        osd_stats.repair row, and the daemon's chip gauges."""
        row = self.repair_traffic.setdefault(
            codec_name, {"read": 0, "moved": 0, "objects": 0,
                         "targeted": 0, "full": 0})
        row["read"] += max(0, int(bytes_read))
        row["moved"] += max(0, int(bytes_moved))
        row["objects"] += 1
        row["targeted" if targeted else "full"] += 1
        try:
            self.osd.perf.inc("repair_bytes_read",
                              max(0, int(bytes_read)))
            self.osd.perf.inc("repair_bytes_moved",
                              max(0, int(bytes_moved)))
            self.osd.perf.inc("repair_targeted" if targeted
                              else "repair_full")
        except KeyError:
            pass            # shells/tests without the counter set
        chip = getattr(self.osd, "device_chip", None)
        if chip is not None:
            chip.note_repair(bytes_read, bytes_moved)

    def _maybe_warmup(self, codec) -> None:
        """First sight of a profile: pre-compile its common device
        buckets in the background (the runtime's boot warmup) so the
        first client flushes hit the compile cache instead of paying
        XLA latency inside the write path."""
        from ..device.runtime import DeviceRuntime
        from ..ec.batcher import device_offload_enabled
        try:
            if not int(self.osd.ctx.conf["device_warmup"]):
                return
        except (KeyError, TypeError, ValueError):
            pass
        families = getattr(codec, "device_families",
                           lambda: [])()
        if not families or not device_offload_enabled():
            return
        rt = DeviceRuntime.get()
        if rt.chip_available(self._chip()):
            # every program family the codec's flushes AND repairs
            # will dispatch (plain codecs: the coding matrix;
            # LRC: per-layer matrices + the local-group repair rows;
            # SHEC/CLAY: encode + single-failure decode shapes) —
            # so the first repair after boot doesn't eat a JIT
            # compile on the hot path.  Workload-aware buckets from
            # the daemon's op-size histogram when history exists;
            # the static default list otherwise — compiled on this
            # OSD's own chip (the one its flushes dispatch on).
            for matrix, w in families:
                derived = derive_warmup_buckets(
                    getattr(self.osd, "op_size_hist", None),
                    k=len(matrix[0]), w=w)
                if derived:
                    self.osd.msgr.spawn(
                        rt.warmup_ec(matrix, w, buckets=derived,
                                     chip=self._chip()))
                else:
                    self.osd.msgr.spawn(
                        rt.warmup_ec(matrix, w, chip=self._chip()))

    class _Locked:
        def __init__(self, backend, key):
            self.backend = backend
            self.key = key

        async def __aenter__(self):
            entry = self.backend._locks.get(self.key)
            if entry is None:
                entry = self.backend._locks[self.key] = _OidLock()
            entry.refs += 1
            self.entry = entry
            await entry.lock.acquire()

        async def __aexit__(self, *exc):
            self.entry.lock.release()
            self.entry.refs -= 1
            if self.entry.refs == 0 and \
                    self.backend._locks.get(self.key) is self.entry:
                del self.backend._locks[self.key]

    def oid_lock(self, pg: PG, oid: str) -> "_Locked":
        return self._Locked(self, (pg.pool_id, pg.ps, oid))

    # -- client op entry ---------------------------------------------------

    async def handle_op(self, pg: PG, conn, msg) -> None:
        """Primary-side execution of one client op list."""
        async with self.oid_lock(pg, msg.oid):
            # dup re-check under the oid lock: a resend that queued
            # behind the original acquires the lock after the first
            # execution journaled its reply
            dup = pg.lookup_reqid(msg.src, msg.tid)
            if dup is not None:
                conn.send(MOSDOpReply(
                    tid=msg.tid, result=dup["result"],
                    outs=dup["outs"], epoch=self.osd.osdmap.epoch,
                    version=dup["version"]))
                self.osd.perf.inc("dup_ops")
                self.osd._op_finish(msg, "dup_answered_from_journal")
                return
            try:
                await self._do_op(pg, conn, msg)
            except Exception as e:
                import traceback

                traceback.print_exc()
                conn.send(MOSDOpReply(
                    tid=msg.tid, result=-5, outs=[{"error": repr(e)}],
                    epoch=self.osd.osdmap.epoch, version=0))
            finally:
                # every exit retires the tracked op (idempotent): the
                # success paths already finished it with their stage
                self.osd._op_finish(msg, "ec_error_reply")

    async def _get_snapset(self, pg: PG, oid: str):
        """SnapSet from the local shard's attr, else any member's
        (identical on every shard)."""
        from . import snaps as snapmod
        ss = snapmod.load_snapset(self.osd.store, pg.cid,
                                  hobject_t(oid))
        if ss is not None:
            return ss
        raw = await self._fetch_xattr(pg, oid, snapmod.SNAPSET_ATTR)
        if raw is None:
            return None
        ss = denc.decode(raw)
        ss["clone_size"] = {int(k): v
                            for k, v in ss["clone_size"].items()}
        ss["clone_snaps"] = {int(k): list(v)
                             for k, v in ss["clone_snaps"].items()}
        return ss

    async def _head_state(self, pg: PG, oid: str):
        """(exists, whiteout) of the head object, judged from the
        local shard when present, else a peer's attrs."""
        from . import snaps as snapmod
        ho = hobject_t(oid)
        local = self._local_shard(pg, ho)
        if local is not None:
            return True, local[4].get(snapmod.WHITEOUT_ATTR) == b"1"
        raw = await self._fetch_xattr(pg, oid, SHARD_XATTR)
        if raw is None:
            return False, False
        wo = await self._fetch_xattr(pg, oid, snapmod.WHITEOUT_ATTR)
        return True, wo == b"1"

    async def _do_op(self, pg: PG, conn, msg) -> None:
        from ..store.objectstore import NOSNAP
        from . import snaps as snapmod
        writes = any(o["op"] in _EC_WRITE_OPS for o in msg.ops)
        epoch = self.osd.osdmap.epoch
        if not writes:
            outs, result = [], 0
            data = None
            fetched = False
            # snapshot read: resolve the serving clone up front
            read_snap = None
            snapid = getattr(msg, "snapid", None)
            if snapid not in (None, NOSNAP):
                ss = await self._get_snapset(pg, msg.oid)
                c = snapmod.choose_clone(ss, snapid)
                if c is None:
                    conn.send(MOSDOpReply(
                        tid=msg.tid, result=-2,
                        outs=[{"error": "not found"}],
                        epoch=epoch, version=0))
                    return
                if c != "head":
                    read_snap = c
            for op in msg.ops:
                name = op["op"]
                if name in ("read", "stat"):
                    if not fetched:
                        data, _v, rattrs = await self.read_object_attrs(
                            pg, msg.oid, snap=read_snap)
                        if (data is not None and read_snap is None
                                and (rattrs or {}).get(
                                    snapmod.WHITEOUT_ATTR) == b"1"):
                            data = None     # whiteout head: ENOENT
                        fetched = True
                    if data is None:
                        outs.append({"error": "not found"})
                        result = -2
                    elif name == "read":
                        off = op.get("offset", 0)
                        ln = op.get("length", 0)
                        outs.append({"data": data[off:off + ln]
                                     if ln else data[off:]})
                    else:
                        outs.append({"size": len(data)})
                elif name == "pgls":
                    from ..store.objectstore import NOSNAP as _NS
                    names = sorted(
                        h.name for h in
                        self.osd.store.collection_list(pg.cid)
                        if h.name != "__pgmeta__" and h.snap == _NS
                        and not snapmod.is_whiteout(self.osd.store,
                                                    pg.cid, h))
                    outs.append({"names": names})
                elif name == "getxattr":
                    val = await self._fetch_xattr(pg, msg.oid,
                                                  op["name"])
                    if val is None:
                        outs.append({"error": "not found"})
                        result = -2
                    else:
                        outs.append({"value": val})
                else:
                    outs.append({"error": "bad ec op %s" % name})
                    result = -22
            conn.send(MOSDOpReply(tid=msg.tid, result=result, outs=outs,
                                  epoch=epoch, version=0))
            self.osd.perf.inc("ops")
            pg.stats.note_read(sum(
                len(o.get("data") or b"") for o in outs
                if isinstance(o, dict)))
            self.osd._op_finish(msg, "ec_read_done")
            return

        # write path.  Pure in-place overwrites first try the
        # parity-delta RMW (bytes moved proportional to the touched
        # range, not the object — ECBackend start_rmw's role)
        self.osd._op_event(msg, "ec_write_started")
        wbytes = sum(len(o.get("data") or b"") for o in msg.ops
                     if isinstance(o, dict))
        self.osd.note_op_size(wbytes)
        if msg.ops and all(o["op"] == "write" for o in msg.ops):
            res = await self._try_delta_write(pg, msg)
            if res is not None:
                outs2, ok2 = res
                # the delta path journals the reqid inside the
                # replicated shard txns themselves (submit_write's
                # full-write path now does the same via `reqid`)
                conn.send(MOSDOpReply(
                    tid=msg.tid, result=0 if ok2 else -11,
                    outs=outs2, epoch=epoch,
                    version=pg.info.last_update[1]))
                self.osd.perf.inc("ops")
                if ok2:
                    pg.stats.note_write(wbytes)
                self.osd._op_finish(msg, "ec_delta_done")
                return
        # whole-object RMW fallback
        outs = []
        current: bytes | None = None
        loaded = False
        is_delete = False
        for op in msg.ops:
            name = op["op"]
            if name == "writefull":
                current = bytes(op["data"])
                loaded = True
                outs.append({})
            elif name == "write":
                off = op.get("offset", 0)
                if not loaded:
                    current, _ = await self.read_object(pg, msg.oid)
                    current = current or b""
                    loaded = True
                data = op["data"]
                if len(current) < off:
                    current = current + b"\0" * (off - len(current))
                current = current[:off] + data + \
                    current[off + len(data):]
                outs.append({})
            elif name == "truncate":
                if not loaded:
                    current, _ = await self.read_object(pg, msg.oid)
                    current = current or b""
                    loaded = True
                ln = op["length"]
                if len(current) < ln:
                    current = current + b"\0" * (ln - len(current))
                else:
                    current = current[:ln]
                outs.append({})
            elif name == "delete":
                # existence gate (mirrors the replicated path): a
                # delete of a never-written OR already-whiteouted
                # object must return -2, not append a spurious DELETE
                # log entry (a whiteout head reads back as b"", so the
                # probe alone cannot tell)
                h_exists, h_white = await self._head_state(pg, msg.oid)
                if not h_exists or h_white:
                    conn.send(MOSDOpReply(
                        tid=msg.tid, result=-2,
                        outs=[{"error": "not found"}],
                        epoch=epoch, version=0))
                    return
                is_delete = True
                current = None
                loaded = True
                outs.append({})
            elif name == "setxattr":
                outs.append({})  # applied with the shard transactions
            else:
                conn.send(MOSDOpReply(
                    tid=msg.tid, result=-22,
                    outs=[{"error": "bad ec op %s" % name}],
                    epoch=epoch, version=0))
                return
        if not is_delete and not loaded:
            # xattr-only mutation: rewrite the current payload
            current, _ = await self.read_object(pg, msg.oid)
            current = current or b""
        xattrs = {op["name"]: op["value"] for op in msg.ops
                  if op["op"] == "setxattr"}
        # snapshot bookkeeping (make_writeable on shards): first write
        # under a newer SnapContext clones every shard object inside
        # the same shard transactions
        clone_to, snapset_b, sna_snaps, whiteout = \
            await self._prepare_snapc(pg, msg, is_delete)
        ok = await self.submit_write(pg, msg.oid, current, is_delete,
                                     xattrs, clone_to=clone_to,
                                     snapset_b=snapset_b,
                                     sna_snaps=sna_snaps,
                                     whiteout=whiteout,
                                     top=getattr(msg, "_top", None),
                                     reqid=(msg.src, msg.tid, outs))
        ver = pg.info.last_update[1]
        conn.send(MOSDOpReply(tid=msg.tid, result=0 if ok else -11,
                              outs=outs, epoch=self.osd.osdmap.epoch,
                              version=ver))
        self.osd.perf.inc("ops")
        if ok:
            pg.stats.note_write(wbytes)
        self.osd._op_finish(msg, "ec_write_done")

    # -- write path --------------------------------------------------------

    def _chip(self) -> int | None:
        """This daemon's mesh-chip index (OSD->chip affinity): every
        EC dispatch from this backend lands on the OSD's own chip, so
        a chip loss degrades exactly this daemon to the host paths."""
        chip = getattr(self.osd, "device_chip", None)
        return chip.index if chip is not None else None

    def _on_dispatch_ticket(self, top):
        """Per-op device-dispatch attribution callback: the batcher
        delivers the DispatchTicket of the EXACT flush that carried
        this op's shards (closing the PR-2 gap where the stage
        histogram sampled the batcher's last flush time — wrong under
        heavy interleaving).  Host-fallback flushes deliver none."""
        def on_ticket(t):
            self.osd.perf.hist_sample("op_ec_device_dispatch",
                                      t.device_s)
            if top is not None:
                top.mark_event("device_dispatched")
                if getattr(t, "stream", False):
                    # the op's slot retired it independently of any
                    # co-resident slot (the continuous-dispatch path)
                    top.mark_event("device_stream_retired")
                top.note("device_ticket", t.dump())
                if top.tenant is not None:
                    self.osd.note_tenant_stage(
                        top.tenant, "device_dispatch", t.device_s)
        return on_ticket

    async def _encode_shards(self, pg: PG, data: bytes,
                             top=None,
                             klass: str | None = None
                             ) -> dict[int, bytes]:
        """Shard encode for the write path — the device-batched analog
        of ECTransaction::generate_transactions -> ECUtil::encode:
        concurrent writes across PGs aggregate into one TPU dispatch
        (ceph_tpu.ec.batcher routed through the device runtime).  The
        await spans the batch window PLUS the device flush, so its
        duration is the op's "EC batch wait" stage; the flush that
        actually carried the shards reports itself through the
        dispatch ticket as the "device dispatch" stage."""
        import time as _time
        codec = self.codec(self.osd.osdmap.pools[pg.pool_id])
        n = codec.get_chunk_count()
        tenant = top.tenant if top is not None else None
        if top is not None:
            top.mark_event("ec_encode_start")
        t0 = _time.monotonic()
        shards = await codec.encode_async(
            set(range(n)), data, klass=klass,
            on_ticket=self._on_dispatch_ticket(top),
            chip=self._chip(), tenant=tenant)
        dt = _time.monotonic() - t0
        self.osd.perf.hist_sample("op_ec_batch_wait", dt)
        if tenant is not None:
            self.osd.note_tenant_stage(tenant, "ec_batch_wait", dt)
        if top is not None:
            top.mark_event("ec_encoded")
        return shards

    def _shard_txn(self, pg: PG, ho: hobject_t, shard: bytes, j: int,
                   size: int, version, xattrs: dict | None,
                   hinfo: bytes | None = None) -> Transaction:
        t = Transaction()
        # touch+truncate(0)+write replaces any older (possibly longer)
        # shard without knowing remote existence
        t.touch(pg.cid, ho)
        t.truncate(pg.cid, ho, 0)
        t.write(pg.cid, ho, 0, len(shard), shard)
        t.setattr(pg.cid, ho, SIZE_XATTR, b"%d" % size)
        t.setattr(pg.cid, ho, SHARD_XATTR, b"%d" % j)
        t.setattr(pg.cid, ho, VER_XATTR, _ver_bytes(version))
        if hinfo is not None:
            t.setattr(pg.cid, ho, HINFO_XATTR, hinfo)
        for k, v in (xattrs or {}).items():
            t.setattr(pg.cid, ho, k, v)
        return t

    async def submit_write(self, pg: PG, oid: str,
                           data: bytes | None, is_delete: bool,
                           xattrs: dict | None = None,
                           clone_to: int | None = None,
                           snapset_b: bytes | None = None,
                           sna_snaps: list | None = None,
                           whiteout: bool = False,
                           top=None, reqid: tuple | None = None
                           ) -> bool:
        """Encode + distribute one object write; True when every live
        shard acked (ECBackend::try_reads_to_commit).

        Snapshot args: clone_to clones each member's shard object to
        hobject(oid, snap=clone_to) before the write applies;
        snapset_b is the updated SnapSet attr; sna_snaps index the new
        clone in the SnapMapper rows; whiteout turns a delete into a
        zero-length tombstone that keeps the SnapSet (clones alive).

        `reqid` = (src, tid, outs) journals the client's reply dup
        row inside EVERY shard transaction (the delta path's
        replicated-journal contract extended to full writes): after a
        primary loss, the promoted replica answers the client's
        resend from its own store instead of re-executing.  A < k
        commit forgets the pre-journaled row (the resend must
        re-execute)."""
        from . import snaps as snapmod
        from .pg import PGMETA_OID
        epoch = self.osd.osdmap.epoch
        version = (epoch, pg.info.last_update[1] + 1)
        entry = LogEntry(
            LogEntry.DELETE if is_delete else LogEntry.MODIFY,
            oid, version, pg.info.last_update)
        pg.info.last_update = version
        pg.log.append(entry)
        # this write supersedes any pending recovery of the object
        pg.missing.pop(oid, None)
        for pm in pg.peer_missing.values():
            pm.pop(oid, None)
        shards = (None if is_delete
                  else await self._encode_shards(pg, data, top=top))
        hinfo = None if shards is None else hinfo_bytes(shards)
        ho = hobject_t(oid)

        txns: dict[int, Transaction] = {}
        for j, osd_id in enumerate(pg.acting):
            if osd_id == ITEM_NONE or osd_id < 0:
                continue
            t = Transaction()
            if clone_to is not None:
                t.clone(pg.cid, ho, hobject_t(oid, snap=clone_to))
            if is_delete and whiteout:
                t.truncate(pg.cid, ho, 0)
                t.setattr(pg.cid, ho, snapmod.WHITEOUT_ATTR, b"1")
                t.setattr(pg.cid, ho, VER_XATTR, _ver_bytes(version))
            elif is_delete:
                t.remove(pg.cid, ho)
            else:
                t.append(self._shard_txn(pg, ho, shards[j], j,
                                         len(data), version, xattrs,
                                         hinfo))
                if snapset_b is not None:
                    t.setattr(pg.cid, ho, snapmod.WHITEOUT_ATTR, b"0")
            if snapset_b is not None and not (is_delete
                                              and not whiteout):
                t.setattr(pg.cid, ho, snapmod.SNAPSET_ATTR, snapset_b)
            for sn in (sna_snaps or ()):
                t.omap_setkeys(pg.cid, PGMETA_OID,
                               {snapmod.sna_key(sn, oid): b"1"})
            txns[j] = t
        if reqid is not None:
            src, tid, outs = reqid
            pg.record_reqid(list(txns.values()), src, tid, 0,
                            list(outs), version[1])
        ok = await self._commit_shard_txns(pg, oid, entry, txns,
                                           top=top)
        if reqid is not None and not ok:
            # < k shards acked: the resend must re-execute, not be
            # answered 0 from the pre-journaled row (mirrors the
            # delta path's forget-on-failed-commit contract)
            pg.forget_reqid(reqid[0], reqid[1])
        return ok

    async def _commit_shard_txns(self, pg: PG, oid: str, entry,
                                 txns: dict[int, "Transaction"],
                                 top=None) -> bool:
        """Distribute per-position shard transactions with the
        submit_write ack contract: local apply carries the log/meta
        rows, remotes ride MOSDECSubOpWrite, stragglers become
        peer_missing, success = >= k shards persisted."""
        epoch = self.osd.osdmap.epoch
        self._tid += 1
        tid = self._tid
        waiting: set[int] = set()
        down_skipped: set[int] = set()
        ev = asyncio.Event()
        st = {"waiting": waiting, "event": ev}
        self._writes[tid] = st
        for j, t in txns.items():
            osd_id = pg.acting[j]
            if osd_id == ITEM_NONE or osd_id < 0:
                continue
            if osd_id != self.osd.whoami \
                    and not self.osd.osdmap.is_up(osd_id):
                # a member the map already knows is down cannot ack:
                # mark it behind immediately instead of stalling the
                # client write on the sub-op timeout — but it still
                # counts as NOT applied for the >= k durability check
                pg.peer_missing.setdefault(osd_id, {})[oid] = entry.op
                down_skipped.add(osd_id)
                continue
            if osd_id == self.osd.whoami:
                entryt = Transaction()
                entryt.append(t)
                pg.persist_log_entry(entryt, entry)
                pg.maybe_trim_log(entryt)
                pg.persist_meta(entryt)
                self.osd.store.apply_transaction(entryt)
            else:
                waiting.add(osd_id)
                sub = MOSDECSubOpWrite(
                    pool=pg.pool_id, ps=pg.ps, shard=j, tid=tid,
                    txn=denc.encode(t.to_wire()),
                    log_entry=entry.to_wire(), epoch=epoch)
                # the sub-op joins the client op's cross-daemon span
                # (and its tenant rides along for shard-side books)
                sub.trace = top.trace if top is not None else None
                sub.tenant = top.tenant if top is not None else None
                self.osd._send_osd(osd_id, sub)
        if waiting:
            if top is not None:
                top.mark_event("ec_sub_write_sent")
            try:
                await asyncio.wait_for(
                    ev.wait(),
                    float(self.osd.ctx.conf["osd_ec_subop_timeout"]))
            except asyncio.TimeoutError:
                pass
            if top is not None:
                top.mark_event("ec_sub_write_acked"
                               if not st["waiting"]
                               else "ec_sub_write_timeout")
        self._writes.pop(tid, None)
        behind = set(st["waiting"]) | down_skipped
        if behind:
            for osd_id in st["waiting"]:
                pg.peer_missing.setdefault(osd_id, {})[oid] = entry.op
            codec = self.codec(self.osd.osdmap.pools[pg.pool_id])
            applied = sum(
                1 for j, osd_id in enumerate(pg.acting)
                if osd_id != ITEM_NONE and osd_id >= 0
                and osd_id not in behind)
            if applied >= codec.get_data_chunk_count():
                self.osd._kick_recovery(pg)
                return True
            return False
        return True

    async def _prepare_snapc(self, pg: PG, msg,
                             is_delete: bool = False):
        """Shared snapshot bookkeeping for both EC write paths:
        (clone_to, snapset_b, sna_snaps, whiteout)."""
        from . import snaps as snapmod
        clone_to = None
        snapset_b = None
        sna_snaps: list[int] = []
        whiteout = False
        snapc = getattr(msg, "snapc", None)
        if snapc:
            seq = int(snapc[0])
            snap_ids = [int(s) for s in snapc[1]]
            ss = await self._get_snapset(pg, msg.oid)
            head_exists, head_white = await self._head_state(pg,
                                                             msg.oid)
            if ss is None:
                ss = snapmod.new_snapset()
            newer = [s for s in snap_ids if s > ss["seq"]]
            if head_exists and not head_white and newer \
                    and seq > ss["seq"]:
                clone_to = seq
                try:
                    szb = await self._fetch_xattr(pg, msg.oid,
                                                  SIZE_XATTR)
                    size = int(szb or 0)
                except Exception:
                    size = 0
                ss["clones"].append(clone_to)
                ss["clones"].sort()
                ss["clone_size"][clone_to] = size
                ss["clone_snaps"][clone_to] = sorted(newer)
                sna_snaps = sorted(newer)
            if seq > ss["seq"]:
                ss["seq"] = seq
            if is_delete and ss["clones"]:
                whiteout = True
            snapset_b = snapmod.snapset_bytes(ss)
        return clone_to, snapset_b, sna_snaps, whiteout

    async def _try_delta_write(self, pg: PG, msg):
        """Chunk-aware partial overwrite: parity-delta RMW
        (ECBackend::start_rmw + ECUtil stripe math, ECBackend.cc:1898,
        ECUtil.h:25-66 — re-derived for the contiguous chunk layout
        using GF linearity).

        For an in-place overwrite of byte range [a,b) the only chunks
        whose bytes change are the touched data chunk columns and the
        SAME columns of every parity chunk:

            new_parity_i[x] = old_parity_i[x] XOR
                              sum_j gfmul(M[i][j], delta_j[x])

        so the network traffic is (1+m) ranged reads + (1+m) ranged
        writes proportional to the touched bytes — NOT the object
        size.  The GF products route through ``codec.delta_async`` —
        device-batched on this OSD's affinity chip, so concurrent
        partial writes across PGs/objects share one dispatch (numpy
        host path under DeviceBusy/poison, bit-identical) — and the
        reqid dup journal rides every shard txn so promoted replicas
        answer resends.  Untouched data shards get an attr-only
        version bump so readers never mix generations.  Shard crcs
        (hinfo) update incrementally via crc32 linearity:
        crc(new) = crc(old) ^ crc(delta0pad) ^ crc(zeros) — computed
        by the primary with no extra I/O.  Returns op outs, or None
        when ineligible (growth, degraded members, non-matrix codec,
        big spans), in which case the caller's whole-object RMW runs.
        The per-object oid_lock plays the ExtentCache role of
        serializing overlapping RMW cycles."""
        import zlib
        pool = self.osd.osdmap.pools[pg.pool_id]
        codec = self.codec(pool)
        matrix = getattr(codec, "matrix", None)
        if (not matrix or getattr(codec, "w", 0) not in (8, 16, 32)
                or codec.get_chunk_mapping()):
            return None
        # w=16/32: parity changes at word granularity (GF products
        # mix bits across the word), so column intervals align to the
        # word boundary below; the data-chunk writes themselves stay
        # byte-granular
        word = codec.w // 8
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        m = n - k
        if msg.oid in pg.missing or any(
                msg.oid in pm for pm in pg.peer_missing.values()):
            # a stale shard exists somewhere: the delta path cannot
            # detect it (it never reads untouched shards) and must not
            # re-stamp versions over old bytes — whole-object RMW
            # rewrites every shard and heals instead
            return None
        local = self._local_shard(pg, hobject_t(msg.oid))
        if local is None:
            return None                      # primary degraded: RMW
        _j, _buf, size, ver, lattrs = local
        from . import snaps as snapmod
        if lattrs.get(snapmod.WHITEOUT_ATTR) == b"1":
            return None
        hinfo_raw = lattrs.get(HINFO_XATTR)
        if hinfo_raw is None:
            return None
        old_crcs = [int(x) for x in hinfo_raw.split(b",")]
        if len(old_crcs) != n:
            return None
        writes = []
        total = 0
        for op in msg.ops:
            off = int(op.get("offset", 0))
            data = bytes(op["data"])
            if off < 0 or off + len(data) > size or not data:
                return None                  # growth/degenerate: RMW
            writes.append((off, data))
            total += len(data)
        if total * 4 > size:
            return None                      # big span: full RMW wins
        cs = codec.get_chunk_size(size)
        if cs % word:
            return None          # word-ragged chunk layout: full RMW
        # per-chunk parts: {j: [(c0, new_bytes), ...]} in column space
        per_chunk: dict[int, list] = {}
        for off, data in writes:
            pos = off
            while pos < off + len(data):
                j = pos // cs
                c0 = pos % cs
                take = min(cs - c0, off + len(data) - pos)
                per_chunk.setdefault(j, []).append(
                    (c0, data[pos - off:pos - off + take]))
                pos += take
        # merged column intervals (parity changes exactly there),
        # floored/ceiled to the codec's word boundary — a sub-word
        # overwrite dirties its whole containing parity word; a
        # boundary-crossing write yields ranges at OPPOSITE chunk ends
        # — they must stay separate reads, never one covering span
        raw_ivs = sorted(((c0 // word) * word,
                          min(cs, -(-(c0 + len(d)) // word) * word))
                         for parts in per_chunk.values()
                         for c0, d in parts)
        ivs: list[list[int]] = []
        for a, b in raw_ivs:
            if ivs and a <= ivs[-1][1]:
                ivs[-1][1] = max(ivs[-1][1], b)
            else:
                ivs.append([a, b])

        async def ranged(j, a, b):
            """Old shard bytes [a,b) of position j, or None."""
            osd_id = pg.acting[j] if j < len(pg.acting) else -1
            if osd_id < 0 or osd_id == ITEM_NONE:
                return None
            if osd_id == self.osd.whoami:
                loc = self._local_shard(pg, hobject_t(msg.oid))
                if loc is None or loc[0] != j or loc[3] != ver:
                    return None
                return loc[1][a:b]
            rows = (await self._sub_read(
                pg, msg.oid, [osd_id], off=a,
                length=b - a)).get(osd_id) or []
            if not rows:
                return None
            rj, buf, _sz, rver, _attrs = rows[0]
            if rj != j or tuple(rver) != ver or len(buf) < b - a:
                return None              # stale/short: full RMW
            return bytes(buf)

        # old bytes: per-part for touched data chunks, per-interval
        # for every parity chunk — ALL reads issued concurrently (one
        # latency round, not one RTT per shard/part)
        keys: list[tuple] = []
        coros = []
        for j, parts in per_chunk.items():
            for c0, d in parts:
                keys.append(("d", j, c0))
                coros.append(ranged(j, c0, c0 + len(d)))
        for i in range(k, n):
            for a, b in ivs:
                keys.append(("p", i, a))
                coros.append(ranged(i, a, b))
        results = await asyncio.gather(*coros)
        old_part: dict[tuple, bytes] = {}
        old_par: dict[tuple, bytes] = {}
        for (kind, x, y), ob in zip(keys, results):
            if ob is None:
                return None
            if kind == "d":
                old_part[(x, y)] = ob
            else:
                old_par[(x, y)] = ob
        # deltas + incremental crcs (crc32 linearity over GF(2))
        import numpy as _np
        zeros_cs_crc = zlib.crc32(bytes(cs)) & 0xFFFFFFFF
        new_crcs = list(old_crcs)
        delta_part: dict[tuple, bytes] = {}
        for j, parts in per_chunk.items():
            dpad = bytearray(cs)
            for c0, d in parts:
                ob = old_part[(j, c0)]
                delta = bytes(x ^ y for x, y in zip(ob, d))
                delta_part[(j, c0)] = delta
                dpad[c0:c0 + len(delta)] = delta
            new_crcs[j] = (old_crcs[j] ^ zlib.crc32(bytes(dpad))
                           ^ zeros_cs_crc) & 0xFFFFFFFF
        # parity deltas: one device-batched GF product per interval
        # (codec.delta_async — concurrent partial writes across
        # PGs/objects batch their coefficient-column products into one
        # dispatch on this OSD's chip, host numpy under
        # DeviceBusy/poison), intervals issued concurrently so they
        # share a flush; the op's ticket feeds op_ec_device_dispatch
        top = getattr(msg, "_top", None)

        def _iv_deltas(a: int, b: int) -> dict[int, bytes]:
            out: dict[int, bytes] = {}
            for j, parts in per_chunk.items():
                row = bytearray(b - a)
                touched = False
                for c0, d in parts:
                    if c0 >= b or c0 + len(d) <= a:
                        continue
                    dp = delta_part[(j, c0)]
                    row[c0 - a:c0 - a + len(dp)] = dp
                    touched = True
                if touched:
                    out[j] = bytes(row)
            return out

        pdeltas = await asyncio.gather(*[
            codec.delta_async(_iv_deltas(a, b),
                              on_ticket=self._on_dispatch_ticket(top),
                              chip=self._chip(),
                              tenant=(top.tenant if top is not None
                                      else None))
            for a, b in ivs])
        new_par: dict[tuple, bytes] = {}
        for i in range(m):
            dpad = bytearray(cs)
            for (a, b), pd in zip(ivs, pdeltas):
                acc = _np.frombuffer(pd[i], _np.uint8)
                ob = _np.frombuffer(old_par[(k + i, a)], _np.uint8)
                new_par[(k + i, a)] = (ob[:b - a] ^ acc).tobytes()
                dpad[a:b] = pd[i]
            new_crcs[k + i] = (old_crcs[k + i]
                               ^ zlib.crc32(bytes(dpad))
                               ^ zeros_cs_crc) & 0xFFFFFFFF
        # snapshot bookkeeping shares the write path's semantics
        clone_to, snapset_b, sna_snaps, _wo = \
            await self._prepare_snapc(pg, msg)
        epoch = self.osd.osdmap.epoch
        version = (epoch, pg.info.last_update[1] + 1)
        entry = LogEntry(LogEntry.MODIFY, msg.oid, version,
                         pg.info.last_update)
        pg.info.last_update = version
        pg.log.append(entry)
        ho = hobject_t(msg.oid)
        hinfo_b = b",".join(b"%d" % c for c in new_crcs)
        from . import snaps as _snapmod
        from .pg import PGMETA_OID
        txns: dict[int, Transaction] = {}
        for j in range(min(n, len(pg.acting))):
            t = Transaction()
            if clone_to is not None:
                t.clone(pg.cid, ho,
                        hobject_t(msg.oid, snap=clone_to))
            if j in per_chunk:
                for c0, d in per_chunk[j]:
                    t.write(pg.cid, ho, c0, len(d), bytes(d))
            elif j >= k:
                for a, b in ivs:
                    t.write(pg.cid, ho, a, b - a,
                            new_par[(j, a)])
            t.setattr(pg.cid, ho, VER_XATTR, _ver_bytes(version))
            t.setattr(pg.cid, ho, HINFO_XATTR, hinfo_b)
            if snapset_b is not None:
                t.setattr(pg.cid, ho, _snapmod.SNAPSET_ATTR,
                          snapset_b)
                t.setattr(pg.cid, ho, _snapmod.WHITEOUT_ATTR, b"0")
            for s in (sna_snaps or ()):
                t.omap_setkeys(pg.cid, PGMETA_OID,
                               {_snapmod.sna_key(s, msg.oid): b"1"})
            txns[j] = t
        outs = [{} for _ in msg.ops]
        # the reqid dup journal rides EVERY shard txn (replicated, not
        # primary-local like the full-write path's own-txn journal):
        # after a primary loss the promoted replica answers a client
        # resend from its own store
        pg.record_reqid(list(txns.values()), msg.src, msg.tid, 0,
                        outs, version[1])
        self.osd._op_event(msg, "ec_delta_rmw")
        ok = await self._commit_shard_txns(pg, msg.oid, entry, txns,
                                           top=top)
        if not ok:
            # < k shards acked: the resend must re-execute (an
            # in-place overwrite re-executes idempotently), not be
            # answered 0 from the pre-journaled row
            pg.forget_reqid(msg.src, msg.tid)
        # the log entry is appended either way: do NOT fall back to the
        # whole-object path after a commit attempt (same durability
        # contract as submit_write: ok = >= k shards persisted)
        return (outs, ok)

    def handle_sub_write(self, conn, msg: MOSDECSubOpWrite) -> None:
        """Shard side (ECBackend::handle_sub_write)."""
        from .osdmap import pg_t

        pgid = pg_t(msg.pool, msg.ps)
        pg = self.osd.pgs.get(pgid)
        if pg is None:
            pg = PG(self.osd, msg.pool, msg.ps)
            pg.create_onstore()
            self.osd.pgs[pgid] = pg
        t = Transaction.from_wire(denc.decode(msg.txn))
        entry = LogEntry.from_wire(msg.log_entry)
        pg.log.append(entry)
        pg.info.last_update = entry.version
        pg.missing.pop(entry.oid, None)  # the write heals the object
        pg.persist_log_entry(t, entry)
        pg.maybe_trim_log(t)
        pg.persist_meta(t)
        self.osd.store.apply_transaction(t)
        conn.send(MOSDECSubOpWriteReply(
            pool=msg.pool, ps=msg.ps, shard=msg.shard, tid=msg.tid,
            result=0, epoch=msg.epoch))
        self.osd._op_finish(msg, "ec_shard_applied")

    def handle_sub_write_reply(self, msg: MOSDECSubOpWriteReply) -> None:
        st = self._writes.get(msg.tid)
        if st is None:
            return
        sender = int(msg.src.split(".")[1])
        st["waiting"].discard(sender)
        if not st["waiting"]:
            st["event"].set()

    # -- read path ---------------------------------------------------------

    def _local_shard(self, pg: PG, ho: hobject_t):
        """(shard_index, bytes, size, version, attrs) of the local
        object, or None."""
        if not self.osd.store.exists(pg.cid, ho):
            return None
        try:
            attrs = self.osd.store.getattrs(pg.cid, ho)
            j = int(attrs[SHARD_XATTR])
            size = int(attrs[SIZE_XATTR])
            ver = _parse_ver(attrs[VER_XATTR])
            return (j, self.osd.store.read(pg.cid, ho), size, ver,
                    attrs)
        except (NotFound, KeyError, ValueError):
            return None

    async def read_object(self, pg: PG, oid: str, snap: int = None):
        """Reconstructing whole-object read; returns (data, version)
        or (None, None)."""
        data, ver, _attrs = await self.read_object_attrs(pg, oid,
                                                        snap=snap)
        return data, ver

    async def read_object_attrs(self, pg: PG, oid: str,
                                snap: int = None):
        """Reconstructing whole-object read; returns
        (data, version, attrs) or (None, None, None).  Fetches the
        minimum member set first and widens on shortfall; only shards
        stamped with the newest observed version are mixed (ec_ver);
        attrs come from any shard of the winning version (user xattrs
        are written identically to every shard)."""
        pool = self.osd.osdmap.pools[pg.pool_id]
        codec = self.codec(pool)
        k = codec.get_data_chunk_count()
        ho = (hobject_t(oid) if snap is None
              else hobject_t(oid, snap=snap))
        members = []
        for osd_id in pg.acting:
            if osd_id != ITEM_NONE and osd_id >= 0 \
                    and osd_id not in members \
                    and (osd_id == self.osd.whoami
                         or self.osd.osdmap.is_up(osd_id)):
                # map-down members cannot answer: querying them only
                # burns the sub-read timeout per object — degraded
                # reads and recovery go straight to live shards
                members.append(osd_id)
        # per-version shard pools: {ver: {j: (bytes, size)}}
        by_ver: dict[tuple, dict[int, tuple]] = {}
        attrs_by_ver: dict[tuple, dict] = {}
        local = self._local_shard(pg, ho) \
            if self.osd.whoami in members else None
        if local is not None:
            j, buf, size, ver, lattrs = local
            by_ver.setdefault(ver, {})[j] = (buf, size)
            attrs_by_ver.setdefault(ver, dict(lattrs))
        remote = [o for o in members if o != self.osd.whoami]
        # ask the minimum first — planned through the codec's
        # minimum_to_decode so locality-aware codecs (LRC local
        # groups, SHEC shingle windows) fetch only their minimal
        # shard set, not the first k members; shortfall still widens
        # to everyone.  Falls back to the k-members heuristic when
        # the plan fails (too few live members: widening handles it).
        mapping = codec.get_chunk_mapping()
        want_pos = ({mapping[i] for i in range(k)} if mapping
                    else set(range(k)))
        pos_member = {pos: osd_id
                      for pos, osd_id in enumerate(pg.acting)
                      if osd_id in members}
        local_pos = next((p for p, o in pos_member.items()
                          if o == self.osd.whoami), None)
        minimal_pos = None
        try:
            minimal_pos = set(codec.minimum_to_decode(
                want_pos, set(pos_member)))
        except Exception:
            pass
        if minimal_pos is not None:
            minimal_members = {pos_member[p] for p in minimal_pos}
            first = [o for o in remote if o in minimal_members]
        else:
            have = 1 if local is not None else 0
            first = remote[:max(0, k - have)]
        rest = [o for o in remote if o not in first]
        self.last_read_plan = {
            "minimal": minimal_pos,
            "local": local_pos,
            "queried": {p for p, o in pos_member.items()
                        if o in first},
            "widened": False,
        }
        for batch in ([first, rest] if first else [rest]):
            if not batch:
                continue
            if batch is rest:
                self.last_read_plan["widened"] = True
                self.last_read_plan["queried"] |= {
                    p for p, o in pos_member.items() if o in rest}
            for sender, rows in \
                    (await self._sub_read(pg, oid, batch,
                                          snap=snap)).items():
                for (j, buf, sz, verw, rattrs) in rows:
                    ver = tuple(verw)
                    by_ver.setdefault(ver, {}).setdefault(
                        j, (buf, sz))
                    if rattrs:
                        attrs_by_ver.setdefault(ver, dict(rattrs))
            best = self._best_version(codec, k, by_ver)
            if best is not None:
                ver, use_pos = best
                chunks = {j: b for j, (b, _s) in
                          by_ver[ver].items() if j in use_pos}
                size = next(iter(by_ver[ver].values()))[1]
                try:
                    data = await codec.decode_concat_async(
                        chunks, chip=self._chip())
                except (IOError, OSError):
                    continue  # widen to the remaining members
                return (data[:size], ver,
                        attrs_by_ver.get(ver, {}))
        return None, None, None

    def _best_version(self, codec, k, by_ver):
        """(version, decode shard set) for the newest version with a
        decodable shard set, else None.  Data positions come from the
        codec's chunk mapping — LRC-style layouts do NOT put data at
        0..k-1.

        Cost planning is `minimum_to_decode`-sized, not MDS-assumed:
        the old code fed EVERY gathered shard of the winning version
        to the decoder (the k-cost MDS assumption), which makes
        recovery-codec pools stage shards the plan never needed —
        SHEC decodes a shingle window, CLAY a sub-chunk plane subset,
        LRC a local group.  Now each candidate version's minimal plan
        is costed in sub-chunk units (a CLAY helper that ships
        d/q planes costs d/q of a shard, not 1), the newest decodable
        version still wins — serving an older version when a newer
        one is readable would be a stale read, so cost can never
        override recency — and the decode dispatch stages exactly the
        planned set.  Every candidate's cost lands in
        `last_version_plan` so tests and operators can audit what the
        cheaper plan saved."""
        mapping = codec.get_chunk_mapping()
        want = ({mapping[i] for i in range(k)} if mapping
                else set(range(k)))
        sub = max(1, codec.get_sub_chunk_count())
        candidates: dict = {}
        best = None
        for ver in sorted(by_ver, reverse=True):
            have = set(by_ver[ver])
            try:
                plan = dict(codec.minimum_to_decode(want, have))
            except Exception:
                continue
            use = set(plan) & have
            if not use:
                continue
            cost = sum(sum(cnt for _off, cnt in plan[p]) / sub
                       for p in use)
            candidates[ver] = {"shards": sorted(use),
                               "cost_chunks": round(cost, 4)}
            if best is None:
                best = (ver, use)
        self.last_version_plan = (
            None if best is None else
            {"version": best[0], "shards": sorted(best[1]),
             "cost_chunks": candidates[best[0]]["cost_chunks"],
             "candidates": candidates})
        return best

    async def _sub_read(self, pg: PG, oid: str,
                        members: list, snap: int = None,
                        off: int = 0, length: int = -1) -> dict:
        """One round of MOSDECSubOpRead to `members`; returns
        {sender: [(j, bytes, size, ver), ...]}.  snap targets a clone
        shard object; off/length select a shard byte range (-1 = the
        whole shard) — the ranged form is what makes partial-overwrite
        RMW traffic proportional to the touched extent."""
        self._tid += 1
        tid = self._tid
        ev = asyncio.Event()
        st = {"waiting": set(members), "event": ev, "buffers": {},
              "errors": {}}
        self._reads[tid] = st
        for osd_id in members:
            self.osd._send_osd(osd_id, MOSDECSubOpRead(
                pool=pg.pool_id, ps=pg.ps, shard=-1, tid=tid,
                reads=[[oid, length, snap, off]],
                epoch=self.osd.osdmap.epoch))
        try:
            await asyncio.wait_for(
                ev.wait(),
                float(self.osd.ctx.conf["osd_ec_subop_timeout"]))
        except asyncio.TimeoutError:
            pass
        self._reads.pop(tid, None)
        return st["buffers"]

    async def _fetch_xattr(self, pg: PG, oid: str,
                           name: str) -> bytes | None:
        """Client xattr read: local shard if present, else any member's
        shard attrs (xattrs are replicated to every shard)."""
        local = self._local_shard(pg, hobject_t(oid))
        if local is not None:
            return local[4].get(name)
        members = [o for o in pg.acting
                   if o != ITEM_NONE and 0 <= o != self.osd.whoami
                   and self.osd.osdmap.is_up(o)]
        for osd_id in members:
            rows = (await self._sub_read(pg, oid, [osd_id])) \
                .get(osd_id) or []
            if rows:
                attrs = rows[0][4] if len(rows[0]) > 4 else {}
                return attrs.get(name)
        return None

    def handle_sub_read(self, conn, msg: MOSDECSubOpRead) -> None:
        """Shard side (ECBackend::handle_sub_read): serves whatever
        shard index the stored bytes actually encode, with its version
        stamp and attrs."""
        from .osdmap import pg_t

        pg = self.osd.pgs.get(pg_t(msg.pool, msg.ps))
        buffers = []
        errors = []
        for row in msg.reads:
            oid = row[0]
            snap = row[2] if len(row) > 2 else None
            off = row[3] if len(row) > 3 else 0
            length = row[1] if len(row) > 1 else -1
            if pg is None:
                errors.append([oid, -2])
                continue
            ho = (hobject_t(oid) if snap is None
                  else hobject_t(oid, snap=snap))
            local = self._local_shard(pg, ho)
            if local is None:
                errors.append([oid, -2])
                continue
            j, buf, size, ver, attrs = local
            if length is not None and length >= 0:
                buf = buf[off:off + length]
            wire_attrs = {k: v for k, v in attrs.items()
                          if isinstance(k, str)}
            buffers.append([oid, j, buf, size, list(ver), wire_attrs])
        conn.send(MOSDECSubOpReadReply(
            pool=msg.pool, ps=msg.ps, shard=msg.shard, tid=msg.tid,
            buffers=buffers, errors=errors, epoch=msg.epoch))

    def handle_sub_read_reply(self, msg: MOSDECSubOpReadReply) -> None:
        st = self._reads.get(msg.tid)
        if st is None:
            return
        sender = int(msg.src.split(".")[1])
        rows = []
        for row in msg.buffers:
            oid, j, buf, sz, ver = row[0], row[1], row[2], row[3], \
                row[4]
            attrs = row[5] if len(row) > 5 else {}
            self.sub_read_bytes += len(buf)
            rows.append((j, buf, sz, ver, attrs))
        st["buffers"][sender] = rows
        for oid, err in msg.errors:
            st["errors"][sender] = err
        st["waiting"].discard(sender)
        if not st["waiting"]:
            st["event"].set()

    # -- recovery ----------------------------------------------------------

    def scan_stale_shards(self, pg: PG) -> dict[str, str]:
        """Objects whose stored bytes encode a different position than
        this osd now holds (after a remap reshuffled acting): they are
        effectively missing and must be reconstructed."""
        pos = None
        for j, o in enumerate(pg.acting):
            if o == self.osd.whoami:
                pos = j
                break
        if pos is None:
            return {}
        stale: dict[str, str] = {}
        from .pg import PGMETA_OID

        for ho in self.osd.store.collection_list(pg.cid):
            if ho.name == PGMETA_OID.name:
                continue
            local = self._local_shard(pg, ho)
            if local is None or local[0] != pos:
                stale[ho.name] = LogEntry.MODIFY
        return stale

    async def _reconstruct_shard(self, pg: PG, oid: str, j: int,
                                 klass: str, snap: int = None):
        """Rebuild ONLY position j's shard from the codec's minimal
        shard set (`minimum_to_decode({j}, survivors)`): LRC fetches
        the local group, SHEC the shingle window, CLAY only the
        repair planes (sub-chunk ranged reads), RS its k survivors —
        repair traffic proportional to the minimal set instead of a
        whole-object read + re-encode.  Returns
        (shard_bytes, size, ver, attrs, bytes_read), or None when the
        caller must fall back to the full read+re-encode path
        (version skew, stale layout, missing hinfo, unplannable
        loss).  The rebuilt shard is crc-checked against the
        survivors' hinfo vector before it is trusted."""
        import zlib
        pool = self.osd.osdmap.pools[pg.pool_id]
        codec = self.codec(pool)
        n = codec.get_chunk_count()
        avail = set()
        pos_member: dict[int, int] = {}
        for pos, osd_id in enumerate(pg.acting[:n]):
            if pos == j or osd_id == ITEM_NONE or osd_id < 0:
                continue
            if osd_id == self.osd.whoami \
                    or self.osd.osdmap.is_up(osd_id):
                avail.add(pos)
                pos_member[pos] = osd_id
        try:
            plan = dict(codec.minimum_to_decode({j}, avail))
        except Exception:
            return None
        if not plan or any(p not in pos_member for p in plan):
            return None
        sub = codec.get_sub_chunk_count()
        whole = [(0, sub)]
        partial = any(list(runs) != whole for runs in plan.values())
        ho = (hobject_t(oid) if snap is None
              else hobject_t(oid, snap=snap))

        async def fetch(pos: int, a: int = 0, ln: int = -1):
            """(bytes, size, ver, attrs) of shard `pos` [a, a+ln), or
            None."""
            member = pos_member[pos]
            if member == self.osd.whoami:
                loc = self._local_shard(pg, ho)
                if loc is None or loc[0] != pos:
                    return None
                buf = (loc[1] if ln < 0 else loc[1][a:a + ln])
                return bytes(buf), loc[2], loc[3], loc[4]
            rows = (await self._sub_read(
                pg, oid, [member], snap=snap, off=a,
                length=ln)).get(member) or []
            if not rows:
                return None
            rj, buf, sz, rver, rattrs = rows[0]
            if rj != pos:
                return None         # stale layout: full path heals
            return bytes(buf), sz, tuple(rver), (rattrs or {})

        if partial:
            # CLAY sub-chunk plan: learn the geometry from one
            # survivor's attrs (length-0 ranged read), then fetch
            # only each helper's repair planes
            pre = await fetch(sorted(plan)[0], 0, 0)
            if pre is None:
                return None
            _b, size, ver, attrs = pre
            cs = codec.get_chunk_size(size)
            if cs <= 0 or cs % sub:
                return None
            sc = cs // sub
            keys, coros = [], []
            for pos, runs in sorted(plan.items()):
                for off, cnt in runs:
                    keys.append(pos)
                    coros.append(fetch(pos, off * sc, cnt * sc))
            got = await asyncio.gather(*coros)
            helper: dict[int, list[bytes]] = {}
            nread = 0
            for pos, res in zip(keys, got):
                if res is None or res[2] != ver:
                    return None
                helper.setdefault(pos, []).append(res[0])
                nread += len(res[0])
            subchunks = {pos: b"".join(parts)
                         for pos, parts in helper.items()}
            expect = sum(cnt for runs in plan.values()
                         for _o, cnt in runs) * sc
            if sum(len(b) for b in subchunks.values()) != expect:
                return None
            repair = getattr(codec, "repair_async", None)
            if repair is None:
                return None
            shard = await repair(j, subchunks, klass=klass,
                                 chip=self._chip())
        else:
            got = await asyncio.gather(*[fetch(p)
                                         for p in sorted(plan)])
            chunks: dict[int, bytes] = {}
            size = ver = attrs = None
            nread = 0
            for pos, res in zip(sorted(plan), got):
                if res is None:
                    return None
                buf, sz, rver, rattrs = res
                if ver is None:
                    size, ver, attrs = sz, rver, dict(rattrs)
                elif rver != ver:
                    return None     # mixed generations: full path
                if rattrs.get(HINFO_XATTR) and \
                        not attrs.get(HINFO_XATTR):
                    attrs = dict(rattrs)
                chunks[pos] = buf
                nread += len(buf)
            lens = {len(c) for c in chunks.values()}
            if len(lens) != 1 or 0 in lens:
                return None
            decoded = await codec.decode_async(
                {j}, chunks, klass=klass, chip=self._chip())
            shard = decoded[j]
        hinfo_raw = (attrs or {}).get(HINFO_XATTR)
        if not hinfo_raw:
            return None
        try:
            crcs = [int(x) for x in hinfo_raw.split(b",")]
        except ValueError:
            return None
        if len(crcs) != n \
                or (zlib.crc32(shard) & 0xFFFFFFFF) != crcs[j]:
            return None             # untrusted rebuild: full path
        return shard, size, ver, attrs, nread

    def _push_attrs(self, attrs: dict, j: int, size: int,
                    ver) -> dict:
        """Survivor attrs re-stamped for the rebuilt shard (hinfo is
        already the full per-shard crc vector, identical on every
        member)."""
        out = dict(attrs)
        out[SIZE_XATTR] = b"%d" % size
        out[SHARD_XATTR] = b"%d" % j
        out[VER_XATTR] = _ver_bytes(ver)
        return out

    async def recover_peer_shards(self, pg: PG, osd_id: int,
                                  missing: dict) -> None:
        """Reconstruct each missing object's TARGET shard and push it
        (ECBackend::continue_recovery_op)."""
        j = None
        for pos, o in enumerate(pg.acting):
            if o == osd_id:
                j = pos
                break
        if j is None:
            return
        pool = self.osd.osdmap.pools[pg.pool_id]
        codec = self.codec(pool)
        pushes = []
        for oid, op in sorted(missing.items()):
            # per-object mClock admission: reconstruction yields to
            # client I/O (mClockScheduler background_recovery class)
            from .scheduler import K_RECOVERY
            await self.osd.sched.admit(K_RECOVERY,
                                       key=(pg.pool_id, pg.ps))
            async with self.oid_lock(pg, oid):
                if oid not in pg.peer_missing.get(osd_id, {}):
                    continue  # superseded by a newer write
                if op == LogEntry.DELETE:
                    pushes.append({"oid": oid, "delete": True})
                    continue
                n = codec.get_chunk_count()
                from ..device.runtime import K_RECOVERY_EC
                cname = self._codec_name(pool)
                # targeted repair first: rebuild ONLY the target's
                # shard from the codec's minimal shard set (LRC local
                # group / SHEC shingle window / CLAY repair planes /
                # RS k survivors), with the bytes it actually moved
                # accounted per codec
                rec = await self._reconstruct_shard(
                    pg, oid, j, K_RECOVERY_EC)
                if rec is not None:
                    shard, size, ver, rattrs, nread = rec
                    attrs = self._push_attrs(rattrs, j, size, ver)
                    pushes.append({"oid": oid, "delete": False,
                                   "data": shard, "attrs": attrs,
                                   "omap": {}})
                    self.note_repair(cname, nread, len(shard))
                else:
                    # full path: whole-object read + re-encode (also
                    # the version-skew / stale-layout healer)
                    read0 = self.sub_read_bytes
                    data, ver, rattrs = await self.read_object_attrs(
                        pg, oid)
                    if data is None:
                        pushes.append({"oid": oid, "delete": True})
                        continue
                    shards = await codec.encode_async(
                        set(range(n)), data, klass=K_RECOVERY_EC,
                        chip=self._chip())
                    # user xattrs: local shard first, else the attrs
                    # the surviving shards returned with the read
                    # replies (the primary's own shard may be missing
                    # too)
                    try:
                        attrs = dict(self.osd.store.getattrs(
                            pg.cid, hobject_t(oid)))
                    except NotFound:
                        attrs = dict(rattrs or {})
                    attrs[SIZE_XATTR] = b"%d" % len(data)
                    attrs[SHARD_XATTR] = b"%d" % j
                    attrs[VER_XATTR] = _ver_bytes(ver)
                    attrs[HINFO_XATTR] = hinfo_bytes(shards)
                    pushes.append({"oid": oid, "delete": False,
                                   "data": shards[j], "attrs": attrs,
                                   "omap": {}})
                    self.note_repair(
                        cname, self.sub_read_bytes - read0,
                        len(shards[j]), targeted=False)
                # clone shards travel too (snap reads after recovery)
                from . import snaps as snapmod
                ssraw = attrs.get(snapmod.SNAPSET_ATTR)
                if ssraw:
                    ss = denc.decode(ssraw)
                    for c in ss.get("clones", []):
                        crec = await self._reconstruct_shard(
                            pg, oid, j, K_RECOVERY_EC, snap=int(c))
                        if crec is not None:
                            cshard, csz, cver, cattrs, cread = crec
                            ca = self._push_attrs(cattrs, j, csz,
                                                  cver)
                            pushes.append({"oid": oid,
                                           "snap": int(c),
                                           "delete": False,
                                           "data": cshard,
                                           "attrs": ca, "omap": {}})
                            self.note_repair(cname, cread,
                                             len(cshard))
                            continue
                        cd, cver, cattrs = \
                            await self.read_object_attrs(
                                pg, oid, snap=int(c))
                        if cd is None:
                            continue
                        cshards = await codec.encode_async(
                            set(range(n)), cd, klass=K_RECOVERY_EC,
                            chip=self._chip())
                        ca = dict(cattrs or {})
                        ca[SIZE_XATTR] = b"%d" % len(cd)
                        ca[SHARD_XATTR] = b"%d" % j
                        ca[VER_XATTR] = _ver_bytes(cver)
                        ca[HINFO_XATTR] = hinfo_bytes(cshards)
                        pushes.append({"oid": oid, "snap": int(c),
                                       "delete": False,
                                       "data": cshards[j],
                                       "attrs": ca, "omap": {}})
        if pushes:
            pg.stats.note_recovery(0, sum(
                len(p.get("data") or b"") for p in pushes))
            self.osd._send_osd(osd_id, MOSDPGPush(
                pool=pg.pool_id, ps=pg.ps,
                epoch=self.osd.osdmap.epoch, pushes=pushes))

    async def recover_primary_shards(self, pg: PG) -> None:
        """Rebuild the primary's own missing shards from survivors."""
        j = None
        for pos, o in enumerate(pg.acting):
            if o == self.osd.whoami:
                j = pos
                break
        if j is None:
            return
        for oid, op in sorted(pg.missing.items()):
            from .scheduler import K_RECOVERY
            await self.osd.sched.admit(K_RECOVERY,
                                       key=(pg.pool_id, pg.ps))
            async with self.oid_lock(pg, oid):
                if oid not in pg.missing:
                    continue  # superseded by a newer write
                ho = hobject_t(oid)
                t = Transaction()
                if op == LogEntry.DELETE:
                    if self.osd.store.exists(pg.cid, ho):
                        t.remove(pg.cid, ho)
                else:
                    from ..device.runtime import K_RECOVERY_EC
                    pool = self.osd.osdmap.pools[pg.pool_id]
                    codec = self.codec(pool)
                    cname = self._codec_name(pool)
                    rec = await self._reconstruct_shard(
                        pg, oid, j, K_RECOVERY_EC)
                    if rec is not None:
                        shard, size, ver, rattrs, nread = rec
                        user = {ak: av for ak, av in rattrs.items()
                                if ak not in (SIZE_XATTR,
                                              SHARD_XATTR,
                                              VER_XATTR,
                                              HINFO_XATTR)}
                        t = self._shard_txn(
                            pg, ho, shard, j, size, ver, user,
                            rattrs.get(HINFO_XATTR))
                        self.note_repair(cname, nread, len(shard))
                    else:
                        read0 = self.sub_read_bytes
                        data, ver = await self.read_object(pg, oid)
                        if data is None:
                            pg.missing.pop(oid, None)
                            continue
                        n = codec.get_chunk_count()
                        shards = await codec.encode_async(
                            set(range(n)), data, klass=K_RECOVERY_EC,
                            chip=self._chip())
                        t = self._shard_txn(pg, ho, shards[j], j,
                                            len(data), ver, None,
                                            hinfo_bytes(shards))
                        self.note_repair(
                            cname, self.sub_read_bytes - read0,
                            len(shards[j]), targeted=False)
                pg.missing.pop(oid, None)
                pg.stats.note_recovery(1)
                pg.persist_meta(t)
                self.osd.store.apply_transaction(t)
                # rebuild local clone shards listed by the snapset
                from . import snaps as snapmod
                ss = snapmod.load_snapset(self.osd.store, pg.cid, ho)
                for c in (ss or {}).get("clones", []):
                    cho = hobject_t(oid, snap=int(c))
                    if self.osd.store.exists(pg.cid, cho):
                        continue
                    cd, cver = await self.read_object(pg, oid,
                                                      snap=int(c))
                    if cd is None:
                        continue
                    codec = self.codec(
                        self.osd.osdmap.pools[pg.pool_id])
                    n = codec.get_chunk_count()
                    from ..device.runtime import K_RECOVERY_EC
                    cshards = await codec.encode_async(
                        set(range(n)), cd, klass=K_RECOVERY_EC,
                    chip=self._chip())
                    ct = self._shard_txn(pg, cho, cshards[j], j,
                                         len(cd), cver, None,
                                         hinfo_bytes(cshards))
                    self.osd.store.apply_transaction(ct)


_EC_WRITE_OPS = {"write", "writefull", "delete", "truncate",
                 "setxattr"}
