"""PG scrub: chunked cross-replica/shard integrity checking + repair.

Condensed analog of src/osd/scrubber/ (scrub_machine.cc state flow,
scrub_backend.cc compare logic, PrimaryLogScrub): the primary walks the
PG's objects in chunks, asks every acting member for a scrub map of the
chunk (MOSDRepScrub -> MOSDRepScrubMap: per-object size/digest/attrs
digest — ScrubMap in osd_types.h), compares, and repairs from an
authoritative copy when asked (the `repair` flag of
do_scrub_operation).

* replicated pools — byte digests must match across replicas; the
  authoritative copy is the digest held by the majority with the
  primary breaking ties (scrub_backend.cc select_auth_object); repair
  pushes the authoritative bytes over the divergent replicas (and can
  heal the primary itself by fetching them first).
* EC pools — shards differ by construction, so integrity is checked at
  the stripe level: shallow scrub compares shard metadata
  (ec_ver/ec_size agreement); deep scrub checks every stored shard's
  byte digest against the majority-voted hinfo crc vector (and the
  hinfo attr itself against the vote — rotted integrity METADATA is
  as detectable as rotted bytes), falling back to a fetch-based
  decode vote for legacy objects; repair rewrites divergent shards
  from a re-encode of the clean ones, hinfo recomputed.

Always-on discipline (the integrity plane):

* digests are **device-offloaded**: `build_scrub_map` batches a whole
  chunk's object bytes + attr blobs into one crc32 dispatch on the
  daemon's affinity chip (ceph_tpu.device.digest, `background`
  admission class), with the `zlib.crc32` loop as the DeviceBusy /
  poisoned-chip fallback — bit-identical by construction.
* **stragglers are never conflated with absence**: a replica that
  misses the chunk deadline is retried once, then recorded in
  `result["unavailable"]` — its objects are excluded from comparison
  (not flagged absent), repair decisions that would need its vote are
  skipped for the chunk, and scrub stamps are not advanced (the round
  did not authoritatively cover the PG).
* **periodic scrubs confirm before flagging** (`recheck=True`): an
  inconsistency is only recorded if it persists across passes, so a
  client write racing the per-member map builds settles instead of
  raising PG_DAMAGED spuriously.
* every completed scrub updates `last_scrub_stamp` /
  `last_deep_scrub_stamp` and the PG's residual `scrub_errors` count,
  which ride the stat row into the mgr digest and the mon's
  OSD_SCRUB_ERRORS / PG_DAMAGED health checks — cleared only by a
  repair scrub that drains the residual to zero.
"""

from __future__ import annotations

import asyncio
import itertools
import zlib

from ..msg.messages import MOSDPGPush, MOSDRepScrub, MOSDRepScrubMap
from ..store.objectstore import NOSNAP, NotFound, Transaction, \
    hobject_t
from .pg import PG


def _skey(name: str, snap: int = NOSNAP) -> str:
    """Scrub-map key for one hobject: heads keep their (escaped)
    name, clones append "@@<snapid-hex>" — the scrubber walks the
    WHOLE snap set (scrub_backend.cc scrubs every hobject, clones
    included).  '@' in object names is escaped to '@a' so a client
    object literally named 'x@@2a' can never be conflated with the
    clone (x, 0x2a)."""
    esc = name.replace("@", "@a")
    return esc if snap == NOSNAP else "%s@@%x" % (esc, snap)


def _sobj(key: str) -> hobject_t:
    name, sep, s = key.rpartition("@@")
    if sep:
        try:
            return hobject_t(name.replace("@a", "@"), snap=int(s, 16))
        except ValueError:
            pass
    return hobject_t(key.replace("@a", "@"))


def _digest(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _attrs_blob(attrs: dict) -> bytes:
    return b"\0".join(b"%s=%s" % (k.encode(), v)
                      for k, v in sorted(attrs.items()))


def _attrs_digest(attrs: dict) -> int:
    return zlib.crc32(_attrs_blob(attrs)) & 0xFFFFFFFF


class Scrubber:
    """Per-daemon scrub engine (the primary drives its PGs)."""

    def __init__(self, osd):
        self.osd = osd
        self._tid = 0
        self._waiting: dict[int, dict] = {}

    # -- scrub maps ---------------------------------------------------------

    async def build_scrub_map(self, pg: PG, oids: list[str],
                              fetch: bool = False) -> dict:
        """{oid: {size, digest, attrs_digest, attrs[, data]}} for the
        local objects (ScrubMap::objects).  The whole chunk's digests
        (object bytes + attr blobs) dispatch as ONE device crc32
        batch on this daemon's affinity chip; any degradation lands
        on the host loop with identical values."""
        from ..device.digest import crc32_batch
        rows: list[tuple[str, bytes, dict]] = []
        bufs: list[bytes] = []
        for oid in oids:
            ho = _sobj(oid)
            try:
                data = self.osd.store.read(pg.cid, ho)
                attrs = dict(self.osd.store.getattrs(pg.cid, ho))
            except NotFound:
                continue
            rows.append((oid, data, attrs))
            bufs.append(data)
            bufs.append(_attrs_blob(attrs))
        if not rows:
            return {}
        chip = (self.osd.device_chip.index
                if self.osd.device_chip is not None else None)
        digs, path = await crc32_batch(bufs, chip=chip)
        try:
            self.osd.perf.inc("scrub_digest_device" if path == "device"
                              else "scrub_digest_host", len(bufs))
        except KeyError:
            pass        # bare Scrubber without the OSD counters
        out = {}
        for i, (oid, data, attrs) in enumerate(rows):
            entry = {
                "size": len(data),
                "digest": digs[2 * i],
                "attrs_digest": digs[2 * i + 1],
                "attrs": attrs,
            }
            if fetch:
                entry["data"] = data
            out[oid] = entry
        return out

    async def handle_rep_scrub(self, conn, msg: MOSDRepScrub) -> None:
        """Replica side: build and return the chunk's scrub map (or,
        in inventory mode, every hobject key we hold — the primary's
        stray sweep must see replica-only clones too).  Digesting
        rides this replica's own affinity chip."""
        from .osdmap import pg_t

        pg = self.osd.pgs.get(pg_t(msg.pool, msg.ps))
        if pg is None:
            objects = {}
        elif msg.inventory:
            objects = {_skey(h.name, h.snap): {}
                       for h in self.osd.store.collection_list(pg.cid)
                       if h.name != "__pgmeta__"}
        else:
            objects = await self.build_scrub_map(
                pg, msg.oids, fetch=bool(msg.fetch))
        conn.send(MOSDRepScrubMap(pool=msg.pool, ps=msg.ps,
                                  tid=msg.tid, objects=objects))

    def handle_rep_scrub_map(self, msg: MOSDRepScrubMap) -> None:
        st = self._waiting.get(msg.tid)
        if st is None:
            return
        try:
            osd_id = int(msg.src.split(".", 1)[1])
        except (ValueError, IndexError):
            return
        st["maps"][osd_id] = msg.objects
        st["waiting"].discard(osd_id)
        if not st["waiting"]:
            st["event"].set()

    async def _gather_maps(self, pg: PG, oids: list[str],
                           fetch: bool = False,
                           members=None,
                           inventory: bool = False
                           ) -> tuple[dict, set[int]]:
        """Scrub maps from the acting members (self included).
        Returns (maps, unavailable): a member that misses the chunk
        deadline is retried ONCE (the request frame may simply have
        been lost), then recorded in `unavailable` — callers must
        treat its objects as UNKNOWN, never absent, and skip
        authority/repair decisions that would need its vote."""
        targets0 = members if members is not None else pg.acting
        maps = {}
        if members is None or self.osd.whoami in targets0:
            if inventory:
                maps[self.osd.whoami] = {
                    _skey(h.name, h.snap): {}
                    for h in self.osd.store.collection_list(pg.cid)
                    if h.name != "__pgmeta__"}
            else:
                maps[self.osd.whoami] = await self.build_scrub_map(
                    pg, oids, fetch=fetch)
        self._tid += 1
        tid = self._tid
        waiting: set[int] = set()
        ev = asyncio.Event()
        self._waiting[tid] = {"maps": maps, "waiting": waiting,
                              "event": ev}
        targets = members if members is not None else pg.acting

        def send(osd_id: int) -> None:
            addr = self.osd.osdmap.osd_addrs.get(osd_id)
            if addr:
                self.osd.msgr.send_to(addr, MOSDRepScrub(
                    pool=pg.pool_id, ps=pg.ps, tid=tid, oids=oids,
                    fetch=fetch, inventory=inventory),
                    entity_hint="osd.%d" % osd_id)

        for osd_id in targets:
            if osd_id < 0 or osd_id == self.osd.whoami:
                continue
            if not self.osd.osdmap.is_up(osd_id):
                continue
            if not self.osd.osdmap.osd_addrs.get(osd_id):
                continue
            waiting.add(osd_id)
            send(osd_id)
        timeout = float(self.osd.ctx.conf.get(
            "osd_scrub_chunk_timeout", 5.0))
        if waiting:
            for attempt in range(2):
                try:
                    await asyncio.wait_for(ev.wait(), timeout)
                    break
                except asyncio.TimeoutError:
                    if attempt == 0:
                        # retry once: the request (or the reply) may
                        # have been a lost frame, not a dead member
                        for osd_id in sorted(waiting):
                            send(osd_id)
        unavailable = set(waiting)
        self._waiting.pop(tid, None)
        return maps, unavailable

    # -- scrub driver -------------------------------------------------------

    async def scrub_pg(self, pg: PG, deep: bool = False,
                       repair: bool = False,
                       chunk: int = 25,
                       recheck: bool = False,
                       only: set | None = None) -> dict:
        """Primary-side scrub of one PG; returns
        {"errors": n, "inconsistent": [oid...], "repaired": n,
         "residual": unrepaired error count, "unavailable": [osd...]}.

        `recheck=True` (periodic / oracle scrubs) confirms every
        inconsistency across a second pass before recording it, so a
        client write racing the per-member map builds settles instead
        of flagging.  On completion the PG's scrub stamps and residual
        `scrub_errors` update (and persist), and a changed residual
        forces an immediate mgr report so the OSD_SCRUB_ERRORS /
        PG_DAMAGED health edges flow now, not at the next tick.

        `only` narrows the round to hobjects whose BASE NAME is in
        the set (heads and their clones ride together) — the
        surgical-repair path: rewrite exactly the known-bad objects
        without racing unrelated in-flight writes."""
        fr = getattr(self.osd.ctx, "flight_recorder", None)
        t_span0 = fr.now() if fr is not None else 0.0
        result = await self._scrub_once(pg, deep, repair, chunk,
                                        only=only)
        if recheck and result["errors"] and not repair:
            prev = set(result["inconsistent"])
            for _ in range(2):
                if not prev:
                    break
                await asyncio.sleep(0.1)
                again = await self._scrub_once(pg, deep, False, chunk,
                                               only=only)
                cur = set(again["inconsistent"]) & prev
                result = again
                if cur == prev:
                    break               # stable across passes: real
                prev = cur
            result["inconsistent"] = sorted(prev)
            result["errors"] = len(prev)
            result["residual"] = len(prev)
        if result.get("ran"):
            self._note_scrub_done(pg, deep, result,
                                  partial=only is not None)
        if fr is not None and result.get("ran"):
            # background-work span beside the ops it competed with
            fr.span("deep_scrub" if deep else "scrub", t_span0,
                    meta={"pgid": str(pg.pgid),
                          "errors": result.get("errors", 0),
                          "repaired": result.get("repaired", 0)})
        return result

    def _note_scrub_done(self, pg: PG, deep: bool, result: dict,
                         partial: bool = False) -> None:
        """Completed-scrub bookkeeping: perf counters, stamps (only
        when every member answered — a partial round did not
        authoritatively cover the PG), the residual error count the
        stats plane ships, and the immediate report on an edge."""
        import time as _t
        osd = self.osd
        try:
            osd.perf.inc("deep_scrubs" if deep else "scrubs")
            if result["errors"]:
                osd.perf.inc("scrub_errors_found", result["errors"])
            if result["repaired"]:
                osd.perf.inc("scrub_repaired", result["repaired"])
        except KeyError:
            pass
        prev_err = getattr(pg, "scrub_errors", 0)
        pg.scrub_errors = int(result.get("residual",
                                         result["errors"]))
        if not result.get("unavailable") and not partial:
            # an `only`-narrowed round (surgical repair) or one with
            # a straggler did not cover the PG: stamps stay put
            now = _t.time()
            pg.last_scrub_stamp = now
            if deep:
                pg.last_deep_scrub_stamp = now
        t = Transaction()
        pg.persist_scrub(t)
        osd.store.apply_transaction(t)
        if pg.scrub_errors != prev_err:
            if pg.scrub_errors:
                osd.clog.warn(
                    "pg %s %sscrub found %d inconsistencies: %s"
                    % (pg.pgid, "deep-" if deep else "",
                       pg.scrub_errors,
                       result["inconsistent"][:5]))
            else:
                osd.clog.info(
                    "pg %s repaired: scrub errors drained to zero"
                    % pg.pgid)
            # the health edge must flow through OSD -> mgr -> mon now
            osd._mgr_report_stamp = 0.0
            osd._maybe_send_mgr_report()

    async def _scrub_once(self, pg: PG, deep: bool, repair: bool,
                          chunk: int, only: set | None = None
                          ) -> dict:
        pool = self.osd.osdmap.pools.get(pg.pool_id)
        result = {"errors": 0, "inconsistent": [], "repaired": 0,
                  "residual": 0, "unavailable": []}
        if pool is None or not pg.is_primary():
            return result
        result["ran"] = True
        unavailable: set[int] = set()
        # hobject inventory from EVERY member: replica-only strays
        # (e.g. a clone a lost trim left behind) must be scrubbed too
        keys = {_skey(h.name, h.snap) for h in
                self.osd.store.collection_list(pg.cid)
                if h.name != "__pgmeta__"}
        inv, un = await self._gather_maps(pg, [], inventory=True)
        unavailable |= un
        for mm in inv.values():
            keys.update(mm)
        keys.update(_skey(e.oid) for e in pg.log.entries)
        if only is not None:
            keys = {k for k in keys if _sobj(k).name in only}
        oids = sorted(keys)
        presence: dict[str, set[int]] = {}
        # head snapset votes across members: the orphan sweep must
        # not trust a single (possibly rotted) copy
        ss_votes: dict[str, dict[bytes, int]] = {}
        for i in range(0, len(oids), chunk):
            batch = oids[i:i + chunk]
            # each chunk passes the mClock 'scrub' class so scrubbing
            # yields to client I/O and recovery under load
            from .scheduler import K_SCRUB
            await self.osd.sched.admit(K_SCRUB, cost=len(batch),
                                       key=(pg.pool_id, pg.ps))
            maps, un = await self._gather_maps(pg, batch)
            unavailable |= un
            # a straggler's vote is missing: flag among responders,
            # but never repair on an incomplete quorum
            can_repair = repair and not un
            from .snaps import SNAPSET_ATTR
            for osd_id, mm in maps.items():
                for k, row in mm.items():
                    presence.setdefault(k, set()).add(osd_id)
                    if "@@" not in k:
                        raw = row["attrs"].get(SNAPSET_ATTR)
                        if raw:
                            v = ss_votes.setdefault(k, {})
                            v[bytes(raw)] = v.get(bytes(raw), 0) + 1
            if pool.is_erasure():
                await self._compare_ec(pg, pool, batch, maps, deep,
                                       can_repair, result)
            else:
                await self._compare_replicated(pg, batch, maps,
                                              can_repair, result)
        await self._validate_snapsets(pg, presence, ss_votes,
                                      repair and not unavailable,
                                      result,
                                      complete=not unavailable)
        result["unavailable"] = sorted(unavailable)
        return result

    async def _validate_snapsets(self, pg: PG, presence, ss_votes,
                                 repair, result,
                                 complete: bool = True) -> None:
        """Snap-set consistency (scrub_backend.cc + SnapMapper roles):
        every clone a head's snapset lists must exist on some member
        (a listed-but-absent clone is unrecoverable data loss, flagged
        only), and every on-disk clone must be claimed by its head's
        snapset (orphans are flagged and, on repair, removed
        everywhere — the reference's snap-mapper repair).  Each head's
        snapset is the MAJORITY copy across members, so one rotted
        replica cannot drive a cluster-wide clone deletion.  With an
        unavailable member (`complete=False`) the sweep is skipped
        entirely: a straggler's unseen clones and snapset votes must
        never read as absence."""
        from ..utils import denc

        if not complete:
            return
        snapsets: dict[str, dict] = {}
        for name, votes in ss_votes.items():
            for raw, _n in sorted(votes.items(),
                                  key=lambda kv: -kv[1]):
                try:
                    snapsets[name] = denc.decode(raw)
                    break
                except Exception:
                    continue
            else:
                result["errors"] += 1
                result["residual"] += 1
                result["inconsistent"].append(name)
        for name, ss in snapsets.items():
            for snap in ss.get("clones", []):
                key = _skey(name, int(snap))
                if key not in presence:
                    result["errors"] += 1
                    result["residual"] += 1
                    result["inconsistent"].append(key)
                    self.osd.ctx.log.info(
                        "osd", "scrub %d.%x %s: clone listed in "
                        "snapset but absent on every member"
                        % (pg.pool_id, pg.ps, key))
        orphans = []
        for key, members in presence.items():
            ho = _sobj(key)
            if ho.snap == NOSNAP:
                continue
            ss = snapsets.get(ho.name)
            if ss is None or int(ho.snap) not in [
                    int(c) for c in ss.get("clones", [])]:
                orphans.append((key, ho, sorted(members)))
        for key, ho, members in orphans:
            result["errors"] += 1
            result["inconsistent"].append(key)
            self.osd.ctx.log.info(
                "osd", "scrub %d.%x %s: orphan clone (no snapset "
                "claims it) on %s" % (pg.pool_id, pg.ps, key, members))
            if not repair:
                result["residual"] += 1
                continue
            for osd_id in members:
                if osd_id == self.osd.whoami:
                    t = Transaction()
                    t.remove(pg.cid, ho)
                    self.osd.store.apply_transaction(t)
                else:
                    self.osd._send_osd(osd_id, MOSDPGPush(
                        pool=pg.pool_id, ps=pg.ps,
                        epoch=self.osd.osdmap.epoch,
                        pushes=[{"oid": ho.name, "snap": ho.snap,
                                 "delete": True}]))
            result["repaired"] += 1

    # -- replicated compare -------------------------------------------------

    async def _compare_replicated(self, pg: PG, oids, maps, repair,
                                  result) -> None:
        live = [o for o in pg.acting if o >= 0 and o in maps]
        for oid in oids:
            present = {o: maps[o][oid] for o in live
                       if oid in maps[o]}
            if not present:
                continue
            digests: dict[tuple, list[int]] = {}
            for o, r in present.items():
                digests.setdefault(
                    (r["size"], r["digest"], r["attrs_digest"]),
                    []).append(o)
            # content-addressed chunk objects (the dedup chunk
            # store) carry their truth in the oid — crc32 and size.
            # Candidate auth copies must MATCH the address, so a
            # majority of rotted replicas can never outvote one
            # healthy copy, and unanimous rot is still detected
            from ..dedup import parse_chunk_oid
            named = parse_chunk_oid(oid)
            keys = list(digests)
            if named is not None:
                good = [k for k in keys
                        if k[1] == named[0] and k[0] == named[1]]
                if not good:
                    # every copy disagrees with its own address:
                    # nothing to repair from — unrepairable residual
                    result["errors"] += len(present)
                    result["inconsistent"].append(oid)
                    result["residual"] += len(present)
                    self.osd.ctx.log.info(
                        "osd", "scrub %d.%x %s: all copies diverge"
                        " from the chunk address"
                        % (pg.pool_id, pg.ps, oid))
                    continue
                keys = good
            if len(digests) == 1 and len(present) == len(live) \
                    and (named is None or len(keys) == len(digests)):
                continue
            # authoritative = the majority digest, primary tiebreak
            auth_key = max(
                keys,
                key=lambda k: (len(digests[k]),
                               self.osd.whoami in digests[k]))
            bad = [o for o in live if o not in digests[auth_key]]
            result["errors"] += len(bad)
            result["inconsistent"].append(oid)
            self.osd.ctx.log.info(
                "osd", "scrub %d.%x %s: inconsistent on %s"
                % (pg.pool_id, pg.ps, oid, bad))
            if not repair:
                result["residual"] += len(bad)
                continue
            auth_osd = (self.osd.whoami
                        if self.osd.whoami in digests[auth_key]
                        else digests[auth_key][0])
            data = await self._auth_bytes(pg, oid, auth_osd)
            if data is None:
                result["residual"] += len(bad)
                continue
            attrs = present[auth_osd]["attrs"]
            repaired = 0
            ho = _sobj(oid)
            for osd_id in bad:
                if osd_id == self.osd.whoami:
                    t = Transaction()
                    t.write(pg.cid, ho, 0, len(data), data)
                    t.truncate(pg.cid, ho, len(data))
                    # attrs replace wholesale: a divergent EXTRA
                    # attr must not survive the repair (setattrs
                    # merges)
                    t.rmattrs(pg.cid, ho)
                    t.setattrs(pg.cid, ho, dict(attrs))
                    self.osd.store.apply_transaction(t)
                    repaired += 1
                else:
                    self.osd._send_osd(osd_id, MOSDPGPush(
                        pool=pg.pool_id, ps=pg.ps,
                        epoch=self.osd.osdmap.epoch,
                        pushes=[{"oid": ho.name, "snap": ho.snap,
                                 "delete": False, "data": data,
                                 "attrs": dict(attrs), "omap": {}}]))
                    repaired += 1
            result["repaired"] += repaired
            result["residual"] += max(0, len(bad) - repaired)

    async def _auth_bytes(self, pg: PG, oid: str,
                          auth_osd: int) -> bytes | None:
        if auth_osd == self.osd.whoami:
            try:
                return self.osd.store.read(pg.cid, _sobj(oid))
            except NotFound:
                return None
        maps, _un = await self._gather_maps(pg, [oid], fetch=True,
                                            members=[auth_osd])
        row = maps.get(auth_osd, {}).get(oid)
        return None if row is None else bytes(row["data"])

    # -- EC compare ---------------------------------------------------------

    @staticmethod
    def _majority_hinfo(rows: dict
                        ) -> tuple[list[int] | None, bytes | None]:
        """(crc vector, raw blob) most shards agree on, or
        (None, None) — legacy or unparseable hinfo (corrupted
        metadata must degrade to the fetch-based vote, not crash the
        scrub)."""
        votes: dict[bytes, int] = {}
        for r in rows.values():
            hv = r["attrs"].get("ec_hinfo")
            if hv:
                votes[bytes(hv)] = votes.get(bytes(hv), 0) + 1
        for hv, _n in sorted(votes.items(), key=lambda kv: -kv[1]):
            try:
                return [int(x) for x in hv.split(b",")], hv
            except ValueError:
                continue
        return None, None

    async def _compare_ec(self, pg: PG, pool, oids, maps, deep,
                          repair, result) -> None:
        from .ecbackend import SIZE_XATTR, VER_XATTR

        codec = self.osd.ec.codec(pool)
        live = [o for o in pg.acting if o >= 0 and o in maps]
        pos_of = {o: j for j, o in enumerate(pg.acting)}
        for oid in oids:
            present = {o: maps[o][oid] for o in live
                       if oid in maps[o]}
            if not present:
                continue
            # authoritative metadata = the (ver, size) group most
            # shards carry (newest version breaks ties)
            groups: dict[tuple, list[int]] = {}
            for o, r in present.items():
                key = (r["attrs"].get(VER_XATTR),
                       r["attrs"].get(SIZE_XATTR))
                groups.setdefault(key, []).append(o)
            auth_key = max(groups,
                           key=lambda k: (len(groups[k]), k[0] or b""))
            auth = {o: present[o] for o in groups[auth_key]}
            meta_bad = [o for o in present if o not in auth]
            # byte rot among the metadata-consistent shards: compare
            # each shard's shallow crc against the voted hinfo vector
            # (no byte fetch needed); a shard whose own hinfo ATTR
            # disagrees with the vote is rotted integrity metadata and
            # flags the same way; legacy objects without hinfo go
            # through the fetch-based decode vote
            byte_bad: list[int] = []
            crcs = voted_raw = None
            if deep:
                crcs, voted_raw = self._majority_hinfo(auth)
            legacy = deep and crcs is None
            if deep and crcs is not None:
                for o, r in auth.items():
                    j = pos_of.get(o)
                    if j is not None and j < len(crcs) \
                            and r["digest"] != crcs[j]:
                        byte_bad.append(o)
                    else:
                        hv = r["attrs"].get("ec_hinfo")
                        if hv is not None \
                                and bytes(hv) != voted_raw:
                            byte_bad.append(o)
            if legacy:
                byte_bad = await self._legacy_byte_vote(
                    pg, codec, oid, auth, pos_of)
            if not meta_bad and not byte_bad:
                continue
            bad = sorted(set(meta_bad) | set(byte_bad))
            result["errors"] += len(meta_bad) + len(byte_bad)
            result["inconsistent"].append(oid)
            self.osd.ctx.log.info(
                "osd", "scrub %d.%x %s: EC inconsistency "
                "(meta=%s shards=%s)"
                % (pg.pool_id, pg.ps, oid, meta_bad,
                   sorted(byte_bad)))
            if repair:
                fixed = await self._repair_ec(
                    pg, codec, oid, auth, pos_of, bad)
                result["repaired"] += fixed
                result["residual"] += max(0, len(bad) - fixed)
            else:
                result["residual"] += len(meta_bad) + len(byte_bad)

    async def _legacy_byte_vote(self, pg: PG, codec, oid: str, auth,
                                pos_of) -> list[int]:
        """No hinfo: fetch the shard bytes and vote decode subsets —
        each decode reproduces its inputs, so the re-encode agreeing
        with the most stored shards wins (sound for m >= 2)."""
        shards = await self._fetch_shards(pg, oid, list(auth), pos_of)
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        by_j = {j: buf for _o, (j, buf) in shards.items()}
        if len(by_j) < k:
            return []
        best = None
        for subset in itertools.combinations(sorted(by_j), k):
            try:
                cand = codec.encode(
                    set(range(n)),
                    codec.decode_concat(
                        {j: by_j[j] for j in subset}))
            except Exception:
                continue
            agree = sum(1 for j, buf in by_j.items()
                        if cand.get(j, b"") == buf)
            if best is None or agree > best[0]:
                best = (agree, cand)
            if agree == len(by_j):
                break
        if best is None:
            return []
        expect = best[1]
        return [o for o, (j, buf) in shards.items()
                if j in expect and expect[j] != buf]

    async def _fetch_shards(self, pg: PG, oid: str, members,
                            pos_of) -> dict:
        """{osd: (shard_index, bytes)} for the given members."""
        maps, _un = await self._gather_maps(pg, [oid], fetch=True,
                                            members=members)
        out = {}
        for osd_id, m in maps.items():
            row = m.get(oid)
            if row is None:
                continue
            j = pos_of.get(osd_id)
            if j is not None:
                out[osd_id] = (j, bytes(row["data"]))
        return out

    async def _repair_ec(self, pg: PG, codec, oid: str, auth,
                         pos_of, bad: list[int]) -> int:
        """Rebuild every divergent shard (metadata, bytes, or hinfo)
        from a decode of the clean authoritative shards and rewrite
        it with the authoritative attrs — its own shard index
        substituted and the hinfo crc vector RECOMPUTED from the
        re-encode, so a rotted hinfo attr never survives the repair
        (nor propagates from a corrupted auth member)."""
        from .ecbackend import HINFO_XATTR, hinfo_bytes

        good = [o for o in auth if o not in bad]
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        if len(good) < k:
            return 0
        shards = await self._fetch_shards(pg, oid, good, pos_of)
        chunks = {j: buf for _o, (j, buf) in shards.items()}
        try:
            expect = codec.encode(set(range(n)),
                                  codec.decode_concat(chunks))
        except (IOError, ValueError):
            return 0
        auth_attrs = dict(next(iter(auth.values()))["attrs"])
        if auth_attrs.get(HINFO_XATTR) is not None:
            auth_attrs[HINFO_XATTR] = hinfo_bytes(expect)
        repaired = 0
        for osd_id in bad:
            j = pos_of.get(osd_id)
            if j is None or j not in expect:
                continue
            attrs = dict(auth_attrs)
            attrs["ec_shard"] = b"%d" % j
            ho = _sobj(oid)
            if osd_id == self.osd.whoami:
                t = Transaction()
                t.write(pg.cid, ho, 0, len(expect[j]), expect[j])
                t.truncate(pg.cid, ho, len(expect[j]))
                t.rmattrs(pg.cid, ho)
                t.setattrs(pg.cid, ho, attrs)
                self.osd.store.apply_transaction(t)
            else:
                self.osd._send_osd(osd_id, MOSDPGPush(
                    pool=pg.pool_id, ps=pg.ps,
                    epoch=self.osd.osdmap.epoch,
                    pushes=[{"oid": ho.name, "snap": ho.snap,
                             "delete": False, "data": expect[j],
                             "attrs": attrs, "omap": {}}]))
            repaired += 1
        return repaired
