"""PG scrub: chunked cross-replica/shard integrity checking + repair.

Condensed analog of src/osd/scrubber/ (scrub_machine.cc state flow,
scrub_backend.cc compare logic, PrimaryLogScrub): the primary walks the
PG's objects in chunks, asks every acting member for a scrub map of the
chunk (MOSDRepScrub -> MOSDRepScrubMap: per-object size/digest/attrs
digest — ScrubMap in osd_types.h), compares, and repairs from an
authoritative copy when asked (the `repair` flag of
do_scrub_operation).

* replicated pools — byte digests must match across replicas; the
  authoritative copy is the digest held by the majority with the
  primary breaking ties (scrub_backend.cc select_auth_object); repair
  pushes the authoritative bytes over the divergent replicas (and can
  heal the primary itself by fetching them first).
* EC pools — shards differ by construction, so integrity is checked at
  the stripe level: shallow scrub compares shard metadata
  (ec_ver/ec_size agreement); deep scrub fetches every stored shard,
  searches for a decode of k shards whose re-encode agrees with the
  most stored shards (the role hinfo_t crcs play in ECBackend's
  scrub), and flags the disagreeing shards; repair rewrites them from
  the consistent re-encode.
"""

from __future__ import annotations

import asyncio
import itertools
import zlib

from ..msg.messages import MOSDPGPush, MOSDRepScrub, MOSDRepScrubMap
from ..store.objectstore import NotFound, Transaction, hobject_t
from .pg import PG


def _digest(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _attrs_digest(attrs: dict) -> int:
    blob = b"\0".join(b"%s=%s" % (k.encode(), v)
                      for k, v in sorted(attrs.items()))
    return zlib.crc32(blob) & 0xFFFFFFFF


class Scrubber:
    """Per-daemon scrub engine (the primary drives its PGs)."""

    def __init__(self, osd):
        self.osd = osd
        self._tid = 0
        self._waiting: dict[int, dict] = {}

    # -- scrub maps ---------------------------------------------------------

    def build_scrub_map(self, pg: PG, oids: list[str],
                        fetch: bool = False) -> dict:
        """{oid: {size, digest, attrs_digest, attrs[, data]}} for the
        local objects (ScrubMap::objects)."""
        out = {}
        for oid in oids:
            ho = hobject_t(oid)
            try:
                data = self.osd.store.read(pg.cid, ho)
                attrs = dict(self.osd.store.getattrs(pg.cid, ho))
            except NotFound:
                continue
            entry = {
                "size": len(data),
                "digest": _digest(data),
                "attrs_digest": _attrs_digest(attrs),
                "attrs": attrs,
            }
            if fetch:
                entry["data"] = data
            out[oid] = entry
        return out

    def handle_rep_scrub(self, conn, msg: MOSDRepScrub) -> None:
        """Replica side: build and return the chunk's scrub map."""
        from .osdmap import pg_t

        pg = self.osd.pgs.get(pg_t(msg.pool, msg.ps))
        objects = {} if pg is None else self.build_scrub_map(
            pg, msg.oids, fetch=bool(msg.fetch))
        conn.send(MOSDRepScrubMap(pool=msg.pool, ps=msg.ps,
                                  tid=msg.tid, objects=objects))

    def handle_rep_scrub_map(self, msg: MOSDRepScrubMap) -> None:
        st = self._waiting.get(msg.tid)
        if st is None:
            return
        try:
            osd_id = int(msg.src.split(".", 1)[1])
        except (ValueError, IndexError):
            return
        st["maps"][osd_id] = msg.objects
        st["waiting"].discard(osd_id)
        if not st["waiting"]:
            st["event"].set()

    async def _gather_maps(self, pg: PG, oids: list[str],
                           fetch: bool = False,
                           members=None) -> dict:
        """Scrub maps from the acting members (self included)."""
        maps = {self.osd.whoami:
                self.build_scrub_map(pg, oids, fetch=fetch)}
        self._tid += 1
        tid = self._tid
        waiting: set[int] = set()
        ev = asyncio.Event()
        self._waiting[tid] = {"maps": maps, "waiting": waiting,
                              "event": ev}
        targets = members if members is not None else pg.acting
        for osd_id in targets:
            if osd_id < 0 or osd_id == self.osd.whoami:
                continue
            if not self.osd.osdmap.is_up(osd_id):
                continue
            addr = self.osd.osdmap.osd_addrs.get(osd_id)
            if not addr:
                continue
            waiting.add(osd_id)
            self.osd.msgr.send_to(addr, MOSDRepScrub(
                pool=pg.pool_id, ps=pg.ps, tid=tid, oids=oids,
                fetch=fetch), entity_hint="osd.%d" % osd_id)
        if waiting:
            try:
                await asyncio.wait_for(ev.wait(), 5.0)
            except asyncio.TimeoutError:
                pass
        self._waiting.pop(tid, None)
        return maps

    # -- scrub driver -------------------------------------------------------

    async def scrub_pg(self, pg: PG, deep: bool = False,
                       repair: bool = False,
                       chunk: int = 25) -> dict:
        """Primary-side scrub of one PG; returns
        {"errors": n, "inconsistent": [oid...], "repaired": n}."""
        pool = self.osd.osdmap.pools.get(pg.pool_id)
        result = {"errors": 0, "inconsistent": [], "repaired": 0}
        if pool is None or not pg.is_primary():
            return result
        oids = sorted({h.name for h in
                       self.osd.store.collection_list(pg.cid)})
        for e in pg.log.entries:      # replica-only objects
            if e.oid not in oids:
                oids.append(e.oid)
        for i in range(0, len(oids), chunk):
            batch = oids[i:i + chunk]
            maps = await self._gather_maps(pg, batch)
            if pool.is_erasure():
                await self._compare_ec(pg, pool, batch, maps, deep,
                                       repair, result)
            else:
                await self._compare_replicated(pg, batch, maps,
                                              repair, result)
        return result

    # -- replicated compare -------------------------------------------------

    async def _compare_replicated(self, pg: PG, oids, maps, repair,
                                  result) -> None:
        live = [o for o in pg.acting if o >= 0 and o in maps]
        for oid in oids:
            present = {o: maps[o][oid] for o in live
                       if oid in maps[o]}
            if not present:
                continue
            digests: dict[tuple, list[int]] = {}
            for o, r in present.items():
                digests.setdefault(
                    (r["size"], r["digest"]), []).append(o)
            if len(digests) == 1 and len(present) == len(live):
                continue
            # authoritative = the majority digest, primary tiebreak
            auth_key = max(
                digests,
                key=lambda k: (len(digests[k]),
                               self.osd.whoami in digests[k]))
            bad = [o for o in live if o not in digests[auth_key]]
            result["errors"] += len(bad)
            result["inconsistent"].append(oid)
            self.osd.ctx.log.info(
                "osd", "scrub %d.%x %s: inconsistent on %s"
                % (pg.pool_id, pg.ps, oid, bad))
            if not repair:
                continue
            auth_osd = (self.osd.whoami
                        if self.osd.whoami in digests[auth_key]
                        else digests[auth_key][0])
            data = await self._auth_bytes(pg, oid, auth_osd)
            if data is None:
                continue
            attrs = present[auth_osd]["attrs"]
            repaired = 0
            for osd_id in bad:
                if osd_id == self.osd.whoami:
                    t = Transaction()
                    ho = hobject_t(oid)
                    t.write(pg.cid, ho, 0, len(data), data)
                    t.truncate(pg.cid, ho, len(data))
                    t.setattrs(pg.cid, ho, dict(attrs))
                    self.osd.store.apply_transaction(t)
                    repaired += 1
                else:
                    self.osd._send_osd(osd_id, MOSDPGPush(
                        pool=pg.pool_id, ps=pg.ps,
                        epoch=self.osd.osdmap.epoch,
                        pushes=[{"oid": oid, "delete": False,
                                 "data": data,
                                 "attrs": dict(attrs), "omap": {}}]))
                    repaired += 1
            result["repaired"] += repaired

    async def _auth_bytes(self, pg: PG, oid: str,
                          auth_osd: int) -> bytes | None:
        if auth_osd == self.osd.whoami:
            try:
                return self.osd.store.read(pg.cid, hobject_t(oid))
            except NotFound:
                return None
        maps = await self._gather_maps(pg, [oid], fetch=True,
                                       members=[auth_osd])
        row = maps.get(auth_osd, {}).get(oid)
        return None if row is None else bytes(row["data"])

    # -- EC compare ---------------------------------------------------------

    async def _compare_ec(self, pg: PG, pool, oids, maps, deep,
                          repair, result) -> None:
        from .ecbackend import SIZE_XATTR, VER_XATTR

        codec = self.osd.ec.codec(pool)
        live = [o for o in pg.acting if o >= 0 and o in maps]
        for oid in oids:
            present = {o: maps[o][oid] for o in live
                       if oid in maps[o]}
            if not present:
                continue
            vers = {r["attrs"].get(VER_XATTR)
                    for r in present.values()}
            sizes = {r["attrs"].get(SIZE_XATTR)
                     for r in present.values()}
            meta_bad = len(vers) > 1 or len(sizes) > 1
            byte_bad: dict[int, bytes] = {}
            if deep and not meta_bad:
                byte_bad = await self._deep_verify_ec(
                    pg, codec, oid, present)
            if not meta_bad and not byte_bad:
                continue
            result["errors"] += int(meta_bad) + len(byte_bad)
            result["inconsistent"].append(oid)
            self.osd.ctx.log.info(
                "osd", "scrub %d.%x %s: EC inconsistency "
                "(meta=%s shards=%s)"
                % (pg.pool_id, pg.ps, oid, meta_bad,
                   sorted(byte_bad)))
            if repair and byte_bad:
                result["repaired"] += self._repair_ec(
                    pg, oid, present, byte_bad)

    async def _deep_verify_ec(self, pg: PG, codec, oid: str,
                              present: dict) -> dict[int, bytes]:
        """{bad_osd: expected_shard_bytes}: every shard carries the
        crc vector of ALL shards (ec_hinfo, written at encode time —
        ECUtil::HashInfo's role); the majority vector identifies
        rotted shards exactly, even with a single parity (where a
        decode-subset vote cannot — each decode reproduces its own
        inputs).  Objects without hinfo fall back to the subset vote
        (sound for m >= 2)."""
        maps = await self._gather_maps(pg, [oid], fetch=True,
                                       members=list(present))
        shards: dict[int, tuple[int, bytes, dict]] = {}
        for osd_id, m in maps.items():
            row = m.get(oid)
            if row is None:
                continue
            try:
                j = int(row["attrs"].get("ec_shard"))
            except (TypeError, ValueError):
                continue
            shards[osd_id] = (j, bytes(row["data"]), row["attrs"])
        by_j: dict[int, tuple[int, bytes]] = {}
        for osd_id, (j, buf, _a) in shards.items():
            by_j.setdefault(j, (osd_id, buf))
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        if len(by_j) < k:
            return {}
        # majority hinfo vector
        votes: dict[bytes, int] = {}
        for _o, (_j, _b, attrs) in shards.items():
            hv = attrs.get("ec_hinfo")
            if hv:
                votes[bytes(hv)] = votes.get(bytes(hv), 0) + 1
        expect = None
        if votes:
            hv = max(votes, key=votes.get)
            crcs = [int(x) for x in hv.split(b",")]
            bad_j = [j for j, (_o, buf) in by_j.items()
                     if j < len(crcs) and _digest(buf) != crcs[j]]
            # a rotted-shorter shard keeps its prefix crc-mismatched
            # too, so the crc test covers truncation as well
            good = {j: by_j[j][1] for j in by_j if j not in bad_j}
            if not bad_j:
                return {}
            if len(good) >= k:
                try:
                    expect = codec.encode(
                        set(range(n)), codec.decode_concat(good))
                except (IOError, ValueError):
                    expect = None
        if expect is None:
            # legacy objects: decode-subset vote
            best = None
            for subset in itertools.combinations(sorted(by_j), k):
                chunks = {j: by_j[j][1] for j in subset}
                try:
                    cand = codec.encode(
                        set(range(n)),
                        codec.decode_concat(chunks))
                except Exception:
                    continue
                agree = sum(1 for j, (_o, buf) in by_j.items()
                            if cand.get(j, b"") == buf)
                if best is None or agree > best[0]:
                    best = (agree, cand)
                if agree == len(by_j):
                    break
            if best is None:
                return {}
            expect = best[1]
        bad = {}
        for osd_id, (j, buf, _a) in shards.items():
            if j in expect and expect[j] != buf:
                bad[osd_id] = expect[j]
        return bad

    def _repair_ec(self, pg: PG, oid: str, present: dict,
                   bad: dict[int, bytes]) -> int:
        repaired = 0
        for osd_id, expected in bad.items():
            attrs = dict(present[osd_id]["attrs"])
            if osd_id == self.osd.whoami:
                t = Transaction()
                ho = hobject_t(oid)
                t.write(pg.cid, ho, 0, len(expected), expected)
                t.truncate(pg.cid, ho, len(expected))
                t.setattrs(pg.cid, ho, attrs)
                self.osd.store.apply_transaction(t)
            else:
                self.osd._send_osd(osd_id, MOSDPGPush(
                    pool=pg.pool_id, ps=pg.ps,
                    epoch=self.osd.osdmap.epoch,
                    pushes=[{"oid": oid, "delete": False,
                             "data": expected, "attrs": attrs,
                             "omap": {}}]))
            repaired += 1
        return repaired
