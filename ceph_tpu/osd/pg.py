"""PG: placement group state, log, and peering for the replicated path.

Condensed re-derivation of the reference's per-PG machinery:

* PGLog (src/osd/PGLog.h): an ordered list of versioned entries
  (eversion = (epoch, ver)) used for delta recovery — a peer whose
  last_update is inside our log tail recovers only the objects named
  by the newer entries; one that diverged or fell behind the tail gets
  backfill (full object set).
* PeeringState (src/osd/PeeringState.h:587): the full boost::statechart
  is collapsed to the GetInfo -> GetLog -> Active path a fresh primary
  walks: query every acting peer's info+log (MOSDPGQuery/MOSDPGLog),
  pick the authoritative log (highest last_update — the reference's
  find_best_info), merge it, compute per-peer missing sets, activate,
  then recover by pushing whole objects (MOSDPGPush) — the
  log-based-recovery flow of doc/dev/osd_internals/log_based_pg.rst.
* Op execution (PrimaryLogPG::do_osd_ops, PrimaryLogPG.cc:5969): the
  opcode interpreter over the object store, here a name-keyed dict of
  handlers producing one ObjectStore Transaction per client op.

Durability: the log + info persist in the pgmeta object's omap
(coll_t pgmeta, like PG::prepare_write_info) within the same
transaction as the data mutation, so a restarted OSD replays exact
state.
"""

from __future__ import annotations

import time

from ..store.objectstore import Transaction, coll_t, hobject_t
from ..utils import denc

PGMETA_OID = hobject_t("__pgmeta__")


def ev_key(ev: tuple[int, int]) -> bytes:
    return b"%010d.%010d" % tuple(ev)


class LogEntry:
    """One pg-log record (pg_log_entry_t)."""

    __slots__ = ("op", "oid", "version", "prior_version")

    MODIFY = "modify"
    DELETE = "delete"

    def __init__(self, op: str, oid: str, version: tuple[int, int],
                 prior_version: tuple[int, int]):
        self.op = op
        self.oid = oid
        self.version = tuple(version)
        self.prior_version = tuple(prior_version)

    def to_wire(self) -> list:
        return [self.op, self.oid, list(self.version),
                list(self.prior_version)]

    @classmethod
    def from_wire(cls, w: list) -> "LogEntry":
        return cls(w[0], w[1], (w[2][0], w[2][1]), (w[3][0], w[3][1]))


class PGLog:
    """Bounded, ordered op log (src/osd/PGLog.h)."""

    def __init__(self):
        self.entries: list[LogEntry] = []
        self.tail: tuple[int, int] = (0, 0)  # versions <= tail trimmed

    @property
    def head(self) -> tuple[int, int]:
        return self.entries[-1].version if self.entries else self.tail

    def append(self, e: LogEntry) -> None:
        self.entries.append(e)

    def trim(self, to: tuple[int, int]) -> list[LogEntry]:
        """Drop entries <= to; returns them for omap cleanup."""
        dropped = [e for e in self.entries if e.version <= to]
        if dropped:
            self.entries = [e for e in self.entries if e.version > to]
            self.tail = max(self.tail, dropped[-1].version)
        return dropped

    def objects_since(self, since: tuple[int, int]) -> dict[str, str]:
        """oid -> final op for entries newer than `since` (the missing
        set a peer at `since` must recover)."""
        out: dict[str, str] = {}
        for e in self.entries:
            if e.version > since:
                out[e.oid] = e.op
        return out


class PGInfo:
    """pg_info_t subset: identity + log bounds."""

    def __init__(self, pool: int, ps: int):
        self.pool = pool
        self.ps = ps
        self.last_update: tuple[int, int] = (0, 0)
        self.last_complete: tuple[int, int] = (0, 0)
        self.log_tail: tuple[int, int] = (0, 0)
        self.same_interval_since = 0
        # epoch of the last completed activation: past intervals
        # older than this are settled history (pg_info_t
        # last_epoch_started, PeeringState.h:587 neighborhood)
        self.last_epoch_started = 0

    def to_wire(self) -> dict:
        return {"pool": self.pool, "ps": self.ps,
                "last_update": list(self.last_update),
                "last_complete": list(self.last_complete),
                "log_tail": list(self.log_tail),
                "same_interval_since": self.same_interval_since,
                "last_epoch_started": self.last_epoch_started}

    @classmethod
    def from_wire(cls, d: dict) -> "PGInfo":
        info = cls(d["pool"], d["ps"])
        info.last_update = tuple(d["last_update"])
        info.last_complete = tuple(d["last_complete"])
        info.log_tail = tuple(d["log_tail"])
        info.same_interval_since = d["same_interval_since"]
        info.last_epoch_started = d.get("last_epoch_started", 0)
        return info


class PGStats:
    """Cumulative per-PG I/O + recovery counters (pg_stat_t's counter
    slice, object_stat_sum_t role): the primary accumulates these on
    its op/recovery paths and ships them in the periodic mgr report
    (the MPGStats flow); the mgr's PGMap derives rates from deltas
    between two reports.  Counters are NOT persisted — a restarted or
    newly promoted primary restarts from zero, and the rate derivation
    clamps the resulting negative delta to 0 (exactly the reference's
    reported-epoch reset behavior)."""

    COUNTERS = ("read_ops", "read_bytes", "write_ops", "write_bytes",
                "recovery_ops", "recovery_bytes")

    __slots__ = COUNTERS

    def __init__(self):
        for c in self.COUNTERS:
            setattr(self, c, 0)

    def note_read(self, nbytes: int) -> None:
        self.read_ops += 1
        self.read_bytes += int(nbytes)

    def note_write(self, nbytes: int) -> None:
        self.write_ops += 1
        self.write_bytes += int(nbytes)

    def note_recovery(self, nobjects: int, nbytes: int = 0) -> None:
        self.recovery_ops += int(nobjects)
        self.recovery_bytes += int(nbytes)

    def to_wire(self) -> dict:
        return {c: getattr(self, c) for c in self.COUNTERS}


# PG lifecycle states (PeeringState.h state names, flattened)
STATE_INITIAL = "initial"
STATE_PEERING = "peering"
STATE_ACTIVE = "active"
STATE_REPLICA = "replica"  # ReplicaActive / Stray


class PG:
    """One placement group on one OSD."""

    def __init__(self, osd, pool_id: int, ps: int):
        self.osd = osd                      # owning daemon
        self.pool_id = pool_id
        self.ps = ps
        self.cid = coll_t.pg(pool_id, ps)
        self.info = PGInfo(pool_id, ps)
        self.log = PGLog()
        self.state = STATE_INITIAL
        self.up: list[int] = []
        self.acting: list[int] = []
        self.primary = -1
        self.missing: dict[str, str] = {}       # oid -> op to recover
        self.peer_missing: dict[int, dict[str, str]] = {}
        self.peer_info: dict[int, PGInfo] = {}
        self.waiting_for_active: list = []      # queued ops
        self.waiting_for_peers: dict[int, dict] = {}   # peering round
        self.recovering: set[str] = set()
        self.in_flight: dict[int, dict] = {}    # repop tid -> state
        # PastIntervals (src/osd/osd_types.h PastIntervals): one
        # record per acting-set interval since last_epoch_started:
        # {"first", "last", "up", "acting", "primary", "rw"} where
        # "rw" = the interval could have served writes (its primary's
        # up_thru reached the interval, enough acting members).
        # Cleared on activation; peering must hear from (or rule out)
        # every rw interval before claiming authority.
        self.past_intervals: list[dict] = []
        self.peering_blocked = False   # a prior rw interval has no
        #                                live member: cannot activate
        self.waiting_up_thru = 0       # epoch our up_thru must reach
        # conn -> backoff id: clients told to stop resending at this
        # PG (MOSDBackoff); released when parked ops requeue
        self.backoffs: dict = {}
        # reqid dup-detection journal (PrimaryLogPG osd_reqid_t dedup,
        # PGLog pg_log_dup_t role): (client entity, tid) -> the reply
        # already sent, so a timeout-triggered RESEND of a
        # non-idempotent op (append-style cls methods) is answered
        # from the journal instead of re-executed.  Bounded FIFO,
        # persisted in the pgmeta omap within the same transaction as
        # the write it journals.
        self.reqid_journal: dict[tuple[str, int], dict] = {}
        self.reqid_order: list[tuple[str, int]] = []
        # cumulative client-I/O + recovery counters this primary
        # accumulated (PGStats above); reported to the mgr
        self.stats = PGStats()
        # integrity plane (pg_stat_t last_scrub_stamp/
        # last_deep_scrub_stamp + the inconsistent-object residual):
        # stamps seed to creation time so a fresh cluster does not
        # storm itself with due-immediately scrubs; the periodic
        # scheduler (osd_scrub_interval / osd_deep_scrub_interval)
        # advances them, scrub_errors is the residual count the stat
        # row ships into OSD_SCRUB_ERRORS / PG_DAMAGED health —
        # cleared only by a repair scrub draining it to zero
        self.last_scrub_stamp = time.time()
        self.last_deep_scrub_stamp = self.last_scrub_stamp
        self.scrub_errors = 0

    # -- identity ----------------------------------------------------------

    @property
    def pgid(self) -> str:
        return "%d.%x" % (self.pool_id, self.ps)

    def is_primary(self) -> bool:
        return self.primary == self.osd.whoami

    # -- durable state -----------------------------------------------------

    def persist_meta(self, t: Transaction) -> None:
        t.omap_setkeys(self.cid, PGMETA_OID, {
            b"info": denc.encode(self.info.to_wire()),
            b"past_intervals": denc.encode(self.past_intervals),
        })

    def persist_log_entry(self, t: Transaction, e: LogEntry) -> None:
        t.omap_setkeys(self.cid, PGMETA_OID, {
            b"log." + ev_key(e.version): denc.encode(e.to_wire()),
        })

    def persist_scrub(self, t: Transaction) -> None:
        """Scrub stamps + residual error count, durable so a restart
        neither re-scrubs immediately nor forgets an unrepaired
        inconsistency."""
        t.omap_setkeys(self.cid, PGMETA_OID, {
            b"scrub": denc.encode([self.last_scrub_stamp,
                                   self.last_deep_scrub_stamp,
                                   self.scrub_errors]),
        })

    # -- reqid dup journal -------------------------------------------------

    @staticmethod
    def _reqid_row(src: str, tid: int) -> bytes:
        return b"dup.%s.%d" % (src.encode(), int(tid))

    def record_reqid(self, t, src: str, tid,
                     result: int, outs: list, version: int) -> None:
        """Journal one completed client write's reply, riding the same
        transaction as the write itself (atomic: a replayed store
        never has the mutation without its dup row or vice versa).

        `t` is one Transaction or a collection of them: the EC delta
        path passes EVERY per-position shard transaction, so the dup
        row replicates to each member and a promoted replica answers
        a post-primary-loss resend from its own store."""
        if not src or tid is None:
            return
        ts = (list(t) if isinstance(t, (list, tuple)) else [t])
        key = (src, int(tid))
        entry = {"result": int(result), "outs": list(outs or []),
                 "version": int(version)}
        if key not in self.reqid_journal:
            self.reqid_order.append(key)
        self.reqid_journal[key] = entry
        for txn in ts:
            txn.omap_setkeys(self.cid, PGMETA_OID,
                             {self._reqid_row(*key):
                              denc.encode(entry)})
        cap = int(self.osd.ctx.conf.get("osd_pg_log_dups_tracked",
                                        128))
        while len(self.reqid_order) > cap:
            old = self.reqid_order.pop(0)
            self.reqid_journal.pop(old, None)
            for txn in ts:
                txn.omap_rmkeys(self.cid, PGMETA_OID,
                                [self._reqid_row(*old)])

    def forget_reqid(self, src: str, tid) -> None:
        """Drop a pre-journaled reply after a FAILED commit (< k
        shards acked): the resend must re-execute, not be answered 0.
        Local store row included; replicated copies on members that
        did apply are harmless — re-execution of the same (src,tid)
        write converges to the same bytes."""
        if not src or tid is None:
            return
        key = (src, int(tid))
        if self.reqid_journal.pop(key, None) is None:
            return
        try:
            self.reqid_order.remove(key)
        except ValueError:
            pass
        t = Transaction()
        t.omap_rmkeys(self.cid, PGMETA_OID, [self._reqid_row(*key)])
        self.osd.store.apply_transaction(t)

    def lookup_reqid(self, src: str, tid) -> dict | None:
        if not src or tid is None:
            return None
        key = (src, int(tid))
        entry = self.reqid_journal.get(key)
        if entry is None:
            # replicated dup rows (the EC delta path journals inside
            # the shard transactions): a replica promoted to primary
            # serves the dup from its own store WITHOUT a reload
            try:
                raw = self.osd.store.omap_get_values(
                    self.cid, PGMETA_OID,
                    [self._reqid_row(*key)]).get(
                        self._reqid_row(*key))
            except Exception:
                raw = None
            if raw:
                entry = dict(denc.decode(raw))
                self.reqid_journal[key] = entry
                self.reqid_order.append(key)
        return entry

    def maybe_trim_log(self, t: Transaction) -> None:
        """Bound the log after appending a WRITE entry (never call
        from the bulk merge/adopt persist loops — trimming under an
        iteration over pg.log.entries would re-persist dropped rows).
        Peers that fall behind the trimmed tail are backfilled.  On
        replicas the same policy keeps the in-memory log in lockstep
        with the primary's replicated omap trims."""
        limit = self.osd.ctx.conf["osd_max_pg_log_entries"]
        if len(self.log.entries) <= limit:
            return
        keep = self.log.entries[-(limit // 2):]
        cut = keep[0].version
        for d in self.log.trim((cut[0], cut[1] - 1)):
            t.omap_rmkeys(self.cid, PGMETA_OID,
                          [b"log." + ev_key(d.version)])
        self.info.log_tail = self.log.tail

    def replace_log(self, t: Transaction, entries, tail) -> None:
        """Wholesale log replacement (full adoption / backfill):
        removes EVERY persisted log row first — leftover rows from the
        replaced history would resurrect dead entries on the next
        load()."""
        try:
            old = self.osd.store.omap_get(self.cid, PGMETA_OID)
            stale = [k for k in old if k.startswith(b"log.")]
            if stale:
                t.omap_rmkeys(self.cid, PGMETA_OID, stale)
        except Exception:
            pass
        self.log.entries = list(entries)
        self.log.tail = tuple(tail)
        self.info.log_tail = self.log.tail
        for e in self.log.entries:
            self.persist_log_entry(t, e)

    def load(self) -> bool:
        """Restore info+log from the pgmeta omap; False if absent."""
        store = self.osd.store
        try:
            data = store.omap_get(self.cid, PGMETA_OID)
        except Exception:
            return False
        if b"info" not in data:
            return False
        self.info = PGInfo.from_wire(denc.decode(data[b"info"]))
        if b"past_intervals" in data:
            self.past_intervals = [
                dict(iv) for iv in
                denc.decode(data[b"past_intervals"])]
        if b"scrub" in data:
            try:
                ss, ds, errs = denc.decode(data[b"scrub"])
                self.last_scrub_stamp = float(ss)
                self.last_deep_scrub_stamp = float(ds)
                self.scrub_errors = int(errs)
            except (ValueError, TypeError):
                pass
        entries = []
        for k, v in sorted(data.items()):
            if k.startswith(b"log."):
                entries.append(LogEntry.from_wire(denc.decode(v)))
            elif k.startswith(b"dup."):
                try:
                    src, tid_s = k[4:].rsplit(b".", 1)
                    key = (src.decode(), int(tid_s))
                except (ValueError, UnicodeDecodeError):
                    continue
                self.reqid_journal[key] = dict(denc.decode(v))
                self.reqid_order.append(key)
        self.log.entries = entries
        self.log.tail = self.info.log_tail
        return True

    def create_onstore(self) -> None:
        """Idempotent: a collection can already exist on disk from a
        previous tenure whose pgmeta never became loadable (load()
        returned False) — re-adopt it rather than failing."""
        t = Transaction()
        if not self.osd.store.collection_exists(self.cid):
            t.create_collection(self.cid)
        t.touch(self.cid, PGMETA_OID)
        self.persist_meta(t)
        self.osd.store.apply_transaction(t)


def merge_divergent(my_entries, auth_entries):
    """PGLog::merge_log / _merge_divergent_entries core: given this
    node's log and the authoritative log, find the newest COMMON entry
    (same version and object) and compute exactly the objects whose
    state can differ beyond it:

      * authoritative entries after the common point — the authority
        changed them; we need its copies;
      * our own entries after the common point (the divergent ones —
        writes nobody else acked) — they must be ROLLED BACK to the
        authority's state (push of its copy, or deletion when the
        authority never had the object).

    Returns {oid: op} of that narrow set, or None when the logs share
    no entry at all (disjoint histories — the caller falls back to the
    conservative whole-log resync, e.g. when the divergence predates
    the authoritative log's tail)."""
    auth_keys = {(tuple(e.version), e.oid) for e in auth_entries}
    common = None
    for e in reversed(my_entries):
        if (tuple(e.version), e.oid) in auth_keys:
            common = tuple(e.version)
            break
    if common is None:
        return None
    missing: dict[str, str] = {}
    for e in auth_entries:
        if tuple(e.version) > common:
            missing[e.oid] = e.op
    for e in my_entries:
        if tuple(e.version) > common:
            # divergent entry: rollback — authoritative copy wins
            missing.setdefault(e.oid, LogEntry.MODIFY)
    return missing
