"""cls_rgw: bucket-index primitives for the S3 gateway.

Analog of src/cls/rgw/cls_rgw.cc (the in-OSD bucket index RGW relies
on): the index object's omap maps object key -> entry meta, and every
mutation is one atomic in-OSD method, so concurrent PUTs/DELETEs and
listings see a consistent index.
"""

from __future__ import annotations

from ...utils import denc
from . import EEXIST, EINVAL, ENOENT, RD, WR, ClsError, MethodContext


def bucket_init(ctx: MethodContext, inp: dict) -> dict:
    """Exclusive index creation (-EEXIST when the bucket exists)."""
    if ctx.exists():
        raise ClsError(EEXIST, "bucket exists")
    ctx.create()
    ctx.omap_set({})
    return {}


def index_put(ctx: MethodContext, inp: dict) -> dict:
    key = inp.get("key", "")
    meta = inp.get("meta")
    if not key or meta is None:
        raise ClsError(EINVAL, "bad index_put args")
    if not ctx.exists():
        raise ClsError(ENOENT, "no such bucket")
    ctx.omap_set({key.encode(): denc.encode(dict(meta))})
    return {}


def index_rm(ctx: MethodContext, inp: dict) -> dict:
    key = inp.get("key", "")
    if not ctx.exists():
        raise ClsError(ENOENT, "no such bucket")
    kb = key.encode()
    if not ctx.omap_get_vals([kb]):
        raise ClsError(ENOENT, "no such key")
    ctx.omap_rm([kb])
    return {}


def index_get(ctx: MethodContext, inp: dict) -> dict:
    """Point lookup — O(1) against the omap, where index_list would
    materialize and sort the whole bucket."""
    if not ctx.exists():
        raise ClsError(ENOENT, "no such bucket")
    key = inp.get("key", "")
    kb = key.encode()
    v = ctx.omap_get_vals([kb]).get(kb)
    if v is None:
        raise ClsError(ENOENT, "no such key")
    e = denc.decode(v)
    e["key"] = key
    return {"entry": e}


def index_list(ctx: MethodContext, inp: dict) -> dict:
    """Ordered listing with marker/prefix/max (the ListBucket
    pagination contract)."""
    if not ctx.exists():
        raise ClsError(ENOENT, "no such bucket")
    marker = inp.get("marker", "")
    prefix = inp.get("prefix", "")
    maxn = int(inp.get("max", 1000))
    out = []
    truncated = False
    for k, v in sorted(ctx.omap_get().items()):
        key = bytes(k).decode()
        if marker and key <= marker:
            continue
        if prefix and not key.startswith(prefix):
            continue
        if len(out) >= maxn:
            truncated = True
            break
        e = denc.decode(v)
        e["key"] = key
        out.append(e)
    return {"entries": out, "truncated": truncated}


def index_stat(ctx: MethodContext, inp: dict) -> dict:
    if not ctx.exists():
        raise ClsError(ENOENT, "no such bucket")
    entries = ctx.omap_get()
    total = 0
    for v in entries.values():
        try:
            total += int(denc.decode(v).get("size", 0))
        except Exception:
            pass
    return {"count": len(entries), "bytes": total}


def register(h) -> None:
    h.register_class("rgw", {
        "bucket_init": (WR, bucket_init),
        "index_put": (WR, index_put),
        "index_rm": (WR, index_rm),
        "index_get": (RD, index_get),
        "index_list": (RD, index_list),
        "index_stat": (RD, index_stat),
    })
