"""cls_rbd: image header methods (src/cls/rbd/cls_rbd.cc subset).

RBD-lite's header mutations move in-OSD: create-exclusive, size
changes, and snapshot-table edits each become one atomic method, so
two clients racing image create / snap create cannot interleave
(the races the reference built cls_rbd to close).  The attr layout is
the one services/rbd.py already wrote, so pre-cls images decode
unchanged.
"""

from __future__ import annotations

from ...utils import denc
from . import (EBUSY, EEXIST, EINVAL, ENOENT, RD, WR, ClsError,
               MethodContext)

SIZE_XATTR = "rbd.size"
LAYOUT_XATTR = "rbd.layout"
SNAPS_XATTR = "rbd.snaps"
PARENT_XATTR = "rbd.parent"     # denc {"image","snapid","overlap"}
_CHILD_PREFIX = b"child."       # omap child.<snapid>.<name> on parent


def create(ctx: MethodContext, inp: dict) -> dict:
    """Initialize a header object exactly once (-EEXIST on a second
    create, checked in-OSD so a raced create cannot clobber)."""
    if ctx.getxattr(SIZE_XATTR) is not None:
        raise ClsError(EEXIST, "image exists")
    size = int(inp.get("size", 0))
    layout = inp.get("layout", b"")
    if size < 0 or not layout:
        raise ClsError(EINVAL, "bad create args")
    ctx.write_full(b"")
    ctx.setxattr(SIZE_XATTR, b"%d" % size)
    ctx.setxattr(LAYOUT_XATTR, bytes(layout))
    ctx.setxattr(SNAPS_XATTR, denc.encode({}))
    return {}


def get_metadata(ctx: MethodContext, inp: dict) -> dict:
    size = ctx.getxattr(SIZE_XATTR)
    if size is None:
        raise ClsError(ENOENT, "no image header")
    layout = ctx.getxattr(LAYOUT_XATTR) or b""
    snaps_blob = ctx.getxattr(SNAPS_XATTR)
    snaps = denc.decode(snaps_blob) if snaps_blob else {}
    out = {"size": int(size), "layout": layout, "snaps": snaps}
    parent = ctx.getxattr(PARENT_XATTR)
    if parent:
        out["parent"] = denc.decode(parent)
    return out


def set_size(ctx: MethodContext, inp: dict) -> dict:
    if ctx.getxattr(SIZE_XATTR) is None:
        raise ClsError(ENOENT, "no image header")
    size = int(inp.get("size", -1))
    if size < 0:
        raise ClsError(EINVAL, "bad size")
    ctx.setxattr(SIZE_XATTR, b"%d" % size)
    return {}


def snap_add(ctx: MethodContext, inp: dict) -> dict:
    name = inp.get("name", "")
    snapid = int(inp.get("snapid", 0))
    size = int(inp.get("size", 0))
    if not name or snapid <= 0:
        raise ClsError(EINVAL, "bad snap args")
    blob = ctx.getxattr(SNAPS_XATTR)
    if blob is None:
        raise ClsError(ENOENT, "no image header")
    snaps = denc.decode(blob)
    if name in snaps:
        raise ClsError(EEXIST, "snap exists")
    snaps[name] = {"id": snapid, "size": size}
    ctx.setxattr(SNAPS_XATTR, denc.encode(snaps))
    return {}


def snap_remove(ctx: MethodContext, inp: dict) -> dict:
    name = inp.get("name", "")
    blob = ctx.getxattr(SNAPS_XATTR)
    snaps = denc.decode(blob) if blob else {}
    if name not in snaps:
        raise ClsError(ENOENT, "no such snap")
    # a snapshot with clone children cannot be removed (the
    # protect/unprotect gate of cls_rbd, collapsed to its purpose)
    pref = _CHILD_PREFIX + (b"%d." % int(snaps[name]["id"]))
    for k in ctx.omap_get():
        if bytes(k).startswith(pref):
            raise ClsError(EBUSY, "snap has clone children")
    removed = snaps.pop(name)
    ctx.setxattr(SNAPS_XATTR, denc.encode(snaps))
    return {"id": removed["id"]}


def set_parent(ctx: MethodContext, inp: dict) -> dict:
    """Mark a CLONE's header with its parent linkage."""
    if ctx.getxattr(SIZE_XATTR) is None:
        raise ClsError(ENOENT, "no image header")
    image = inp.get("image", "")
    snapid = int(inp.get("snapid", 0))
    overlap = int(inp.get("overlap", -1))
    if not image or snapid <= 0 or overlap < 0:
        raise ClsError(EINVAL, "bad parent args")
    if ctx.getxattr(PARENT_XATTR) is not None:
        raise ClsError(EEXIST, "parent already set")
    ctx.setxattr(PARENT_XATTR, denc.encode(
        {"image": image, "snapid": snapid, "overlap": overlap}))
    return {}


def remove_parent(ctx: MethodContext, inp: dict) -> dict:
    """Flatten completion: the clone stands alone."""
    if ctx.getxattr(PARENT_XATTR) is None:
        raise ClsError(ENOENT, "no parent")
    ctx.rmxattr(PARENT_XATTR)
    return {}


def child_add(ctx: MethodContext, inp: dict) -> dict:
    """Register a clone on its PARENT's header (cls_rbd children)."""
    snapid = int(inp.get("snapid", 0))
    name = inp.get("name", "")
    if snapid <= 0 or not name:
        raise ClsError(EINVAL, "bad child args")
    ctx.omap_set({_CHILD_PREFIX + b"%d.%s" % (snapid, name.encode()):
                  b"1"})
    return {}


def child_rm(ctx: MethodContext, inp: dict) -> dict:
    snapid = int(inp.get("snapid", 0))
    name = inp.get("name", "")
    key = _CHILD_PREFIX + b"%d.%s" % (snapid, name.encode())
    if not ctx.omap_get_vals([key]):
        raise ClsError(ENOENT, "no such child")
    ctx.omap_rm([key])
    return {}


def children(ctx: MethodContext, inp: dict) -> dict:
    out = []
    for k in ctx.omap_get():
        kb = bytes(k)
        if kb.startswith(_CHILD_PREFIX):
            snap_s, _sep, name = \
                kb[len(_CHILD_PREFIX):].partition(b".")
            out.append({"snapid": int(snap_s),
                        "name": name.decode()})
    return {"children": out}


def dir_add(ctx: MethodContext, inp: dict) -> dict:
    """rbd_directory registration (-EEXIST when taken, atomically)."""
    name = inp.get("name", "")
    if not name:
        raise ClsError(EINVAL, "bad name")
    if ctx.omap_get_vals([name.encode()]):
        raise ClsError(EEXIST, "name taken")
    ctx.omap_set({name.encode(): b"1"})
    return {}


def dir_remove(ctx: MethodContext, inp: dict) -> dict:
    name = inp.get("name", "")
    if not ctx.omap_get_vals([name.encode()]):
        raise ClsError(ENOENT, "no such image")
    ctx.omap_rm([name.encode()])
    return {}


def register(h) -> None:
    h.register_class("rbd", {
        "create": (WR, create),
        "get_metadata": (RD, get_metadata),
        "set_size": (WR, set_size),
        "snap_add": (WR, snap_add),
        "snap_remove": (WR, snap_remove),
        "dir_add": (WR, dir_add),
        "dir_remove": (WR, dir_remove),
        "set_parent": (WR, set_parent),
        "remove_parent": (WR, remove_parent),
        "child_add": (WR, child_add),
        "child_rm": (WR, child_rm),
        "children": (RD, children),
    })
