"""cls_fsmeta: atomic filesystem-metadata primitives for CephFS-lite.

The in-OSD mutations the MDS journal + CDir locking guarantee in the
reference (src/mds/CDir.cc commit, InoTable.cc alloc), collapsed to
three methods on the metadata objects:

    alloc_ino      — atomic inode-number allocation on the inotable
    link           — dentry insert, optionally exclusive (-EEXIST
                     inside the OSD: racing creates cannot both win)
    update_dentry  — read-modify-write of dentry fields (size/mtime
                     cap flush) without clobbering concurrent renames
"""

from __future__ import annotations

from ...utils import denc
from . import EEXIST, EINVAL, ENOENT, WR, ClsError, MethodContext


def alloc_ino(ctx: MethodContext, inp: dict) -> dict:
    cur = ctx.omap_get_vals([b"next_ino"]).get(b"next_ino")
    if cur is None:
        raise ClsError(ENOENT, "no inotable (mkfs first)")
    ino = int(cur)
    ctx.omap_set({b"next_ino": b"%d" % (ino + 1)})
    return {"ino": ino}


def link(ctx: MethodContext, inp: dict) -> dict:
    name = inp.get("name", "")
    blob = inp.get("dentry")
    if not name or blob is None:
        raise ClsError(EINVAL, "bad link args")
    if ctx.getxattr("sealed"):
        # rmdir sealed this dirfrag atomically: nothing may be
        # created inside a directory that is mid-removal
        raise ClsError(ENOENT, "directory removed")
    key = name.encode()
    if inp.get("exclusive", True) and ctx.omap_get_vals([key]):
        raise ClsError(EEXIST, "dentry exists")
    ctx.create()
    ctx.omap_set({key: bytes(blob)})
    return {}


def update_dentry(ctx: MethodContext, inp: dict) -> dict:
    """Size/mtime flush.  The caller's inode must still own the
    dentry — a rename + re-create of the old name must not let a
    stale handle stamp the NEW file's metadata."""
    name = inp.get("name", "")
    key = name.encode()
    cur = ctx.omap_get_vals([key]).get(key)
    if cur is None:
        raise ClsError(ENOENT, "no such dentry")
    d = denc.decode(cur)
    want_ino = inp.get("ino")
    if want_ino is not None and int(d.get("ino", -1)) != int(want_ino):
        raise ClsError(ENOENT, "dentry re-owned (stale handle)")
    d.update(dict(inp.get("set") or {}))
    ctx.omap_set({key: denc.encode(d)})
    return {}


ENOTEMPTY = -39


def seal_empty(ctx: MethodContext, inp: dict) -> dict:
    """Atomic empty-check + tombstone for rmdir: succeeds only when
    the dirfrag has no dentries, and from then on link() refuses —
    closing the check-then-remove race."""
    if not ctx.exists():
        raise ClsError(ENOENT, "no such dirfrag")
    if ctx.omap_get():
        raise ClsError(ENOTEMPTY, "directory not empty")
    ctx.setxattr("sealed", b"1")
    return {}


def register(h) -> None:
    h.register_class("fsmeta", {
        "alloc_ino": (WR, alloc_ino),
        "link": (WR, link),
        "update_dentry": (WR, update_dentry),
        "seal_empty": (WR, seal_empty),
    })
