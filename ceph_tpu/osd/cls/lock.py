"""cls_lock: advisory object locks executed inside the OSD.

Analog of src/cls/lock/cls_lock.cc: lock state lives in an object
xattr (``lock.<name>``), and every transition is one atomic in-OSD
method — two clients racing ``lock`` cannot both win, because the
methods serialize through the primary's op pipeline.

State blob (denc dict):
    {"type": "exclusive"|"shared", "tag": str,
     "lockers": [{"locker": entity, "cookie": str, "desc": str}]}

Methods (matching cls_lock's surface):
    lock(name, type, cookie, tag, desc, renew=False)  [WR]
    unlock(name, cookie)                              [WR]
    break_lock(name, locker, cookie)                  [WR]
    get_info(name)                                    [RD]
"""

from __future__ import annotations

import time

from ...utils import denc
from . import (EBUSY, EEXIST, EINVAL, ENOENT, RD, WR, ClsError,
               MethodContext)

LOCK_XATTR = "lock."

EXCLUSIVE = "exclusive"
SHARED = "shared"


def _load(ctx: MethodContext, name: str) -> dict | None:
    blob = ctx.getxattr(LOCK_XATTR + name)
    return denc.decode(blob) if blob else None


def _store(ctx: MethodContext, name: str, st: dict | None) -> None:
    if st is None or not st["lockers"]:
        ctx.rmxattr(LOCK_XATTR + name)
    else:
        ctx.setxattr(LOCK_XATTR + name, denc.encode(st))


def lock(ctx: MethodContext, inp: dict) -> dict:
    name = inp.get("name", "")
    ltype = inp.get("type", EXCLUSIVE)
    cookie = inp.get("cookie", "")
    tag = inp.get("tag", "")
    desc = inp.get("desc", "")
    renew = bool(inp.get("renew", False))
    if not name or ltype not in (EXCLUSIVE, SHARED):
        raise ClsError(EINVAL, "bad lock args")
    st = _load(ctx, name)
    # stamp = primary-side clock at (re)acquire/renew: liveness
    # watchers (e.g. MDS standby takeover) read it from get_info to
    # detect a holder that stopped renewing (the lock_duration role)
    me = {"locker": ctx.entity, "cookie": cookie, "desc": desc,
          "stamp": time.time()}
    if st is None:
        ctx.create()
        _store(ctx, name, {"type": ltype, "tag": tag, "lockers": [me]})
        return {}
    mine = [l for l in st["lockers"]
            if l["locker"] == ctx.entity and l["cookie"] == cookie]
    if mine:
        if not renew:
            # already held by us: cls_lock returns -EEXIST unless the
            # caller asked to renew
            raise ClsError(EEXIST, "already locked by caller")
        mine[0]["stamp"] = time.time()
        _store(ctx, name, st)
        return {}
    if st["type"] == EXCLUSIVE or ltype == EXCLUSIVE:
        if st["lockers"]:
            raise ClsError(EBUSY, "held by %s"
                           % st["lockers"][0]["locker"])
    if st.get("tag", "") != tag and st["lockers"]:
        raise ClsError(EBUSY, "tag mismatch")
    st["type"] = ltype
    st["tag"] = tag
    st["lockers"].append(me)
    _store(ctx, name, st)
    return {}


def unlock(ctx: MethodContext, inp: dict) -> dict:
    name = inp.get("name", "")
    cookie = inp.get("cookie", "")
    st = _load(ctx, name)
    if st is None:
        raise ClsError(ENOENT, "no such lock")
    keep = [l for l in st["lockers"]
            if not (l["locker"] == ctx.entity
                    and l["cookie"] == cookie)]
    if len(keep) == len(st["lockers"]):
        raise ClsError(ENOENT, "not the holder")
    st["lockers"] = keep
    _store(ctx, name, st)
    return {}


def break_lock(ctx: MethodContext, inp: dict) -> dict:
    """Forcible removal of another entity's lock (admin path)."""
    name = inp.get("name", "")
    locker = inp.get("locker", "")
    cookie = inp.get("cookie", "")
    st = _load(ctx, name)
    if st is None:
        raise ClsError(ENOENT, "no such lock")
    keep = [l for l in st["lockers"]
            if not (l["locker"] == locker and l["cookie"] == cookie)]
    if len(keep) == len(st["lockers"]):
        raise ClsError(ENOENT, "no such locker")
    st["lockers"] = keep
    _store(ctx, name, st)
    return {}


def get_info(ctx: MethodContext, inp: dict) -> dict:
    st = _load(ctx, inp.get("name", ""))
    if st is None:
        return {"lockers": [], "type": "", "tag": ""}
    return {"lockers": st["lockers"], "type": st["type"],
            "tag": st.get("tag", "")}


def register(h) -> None:
    h.register_class("lock", {
        "lock": (WR, lock),
        "unlock": (WR, unlock),
        "break_lock": (WR, break_lock),
        "get_info": (RD, get_info),
    })
