"""cls: in-OSD object classes (the RADOS compute extension tier).

Analog of src/osd/ClassHandler.cc:148 (dlopen + method dispatch) and
src/cls/ (the class library): services push small atomic read-modify-
write methods INTO the OSD instead of racing GETs and SETs from the
client.  A client issues ``{"op": "call", "cls": c, "method": m,
"input": {...}}`` through the normal opcode interpreter; the method
runs on the primary against the object, reads committed state, and
stages its writes into the SAME replicated transaction as the rest of
the client op — so a cls call is atomic and ordered exactly like any
other mutation.

Differences from the reference, on purpose:

* classes are Python modules registered at import (no dlopen); the
  registry shape (class -> method -> handler+flags) matches
  ClassHandler::ClassData::register_method;
* methods declare RD or WR exactly as cls_register_cxx_method does,
  and a WR method arriving on the read path is refused (-1 EPERM),
  mirroring the reference's flag check in PrimaryLogPG::do_osd_ops;
* method results are (retcode, dict) rather than bufferlists — the
  wire layer is denc dicts everywhere in this framework.

Built-in classes (the set RBD-lite + tests lean on): ``lock``
(src/cls/lock), ``refcount`` (src/cls/refcount), ``rbd`` header
methods (src/cls/rbd subset).
"""

from __future__ import annotations

from ...store.objectstore import NotFound, Transaction, coll_t, \
    hobject_t

RD = 1
WR = 2

# errno-style results used by methods (matching the reference's use)
EPERM = -1
ENOENT = -2
EIO = -5
EACCES = -13
EEXIST = -17
EINVAL = -22
EBUSY = -16
EOPNOTSUPP = -95


class ClsError(Exception):
    """Raised by a method to abort the call with an errno result."""

    def __init__(self, code: int, msg: str = ""):
        super().__init__(msg or str(code))
        self.code = code


class MethodContext:
    """cls_method_context_t analog: the handle a method uses to read
    its object and stage writes.

    Reads see COMMITTED object state (the state at the head of this
    client op); writes are staged into the op's transaction and become
    visible with the op's atomic commit.  ``entity`` is the calling
    client's name (the reference's entity_name_t from the op context),
    which lock-style classes use as locker identity."""

    def __init__(self, store, cid: coll_t, oid: hobject_t,
                 txn: Transaction | None, entity: str,
                 whiteout: bool = False,
                 cstate: dict | None = None):
        self.store = store
        self.cid = cid
        self.oid = oid
        self.txn = txn              # None on the read path
        self.entity = entity
        self._staged_remove = False
        # snapshot-deleted head: the object is logically ABSENT even
        # though a tombstone with stale xattrs sits on disk.  Reads
        # behave as not-found; the first write resurrects it clean.
        self._whiteout = whiteout
        # pool-compressed image (comp-alg xattr): reads decompress,
        # the first data write rewrites raw (mirrors the daemon's
        # _decompress_in_txn), so class methods always see logical
        # bytes, never the physical blob.  ``cstate`` is the daemon's
        # per-txn compression state — an earlier op in the SAME
        # MOSDOp may have staged a compressed or raw image this
        # method must honor.
        self._cstate = cstate if cstate is not None else {}
        self._staged_raw: bytes | None = None

    def _comp_state(self) -> tuple[str | None, bytes | None, bool]:
        """(algo, staged image, staged?) — the txn's staged state wins
        over committed attrs.  staged image may be the RAW bytes of a
        this-txn decompression/writefull (algo None) or the raw
        source of a staged compressed blob (algo set)."""
        if self.oid in self._cstate:
            st = self._cstate[self.oid]
            if st is None:
                return (None, None, True)
            return (st[0], st[1], True)
        from ...compress import OBJ_ALGO_ATTR

        raw = None if self._whiteout else self.getxattr(OBJ_ALGO_ATTR)
        return (raw.decode() if raw else None, None, False)

    def _logical_bytes(self) -> bytes | None:
        """The logical image when the object is compressed or was
        rewritten earlier in this txn; None = committed raw state is
        authoritative."""
        algo, staged, in_txn = self._comp_state()
        if staged is not None:
            return staged
        if algo is None:
            return self._staged_raw if in_txn else None
        if in_txn:
            # staged compressed without content: cannot happen (the
            # daemon always records the raw beside a staged algo),
            # but fail safe as "empty"
            return b""
        from ...compress import CompressorError, create

        blob = self.store.read(self.cid, self.oid)
        try:
            return create(algo).decompress(blob) if blob else b""
        except CompressorError as e:
            raise ClsError(EIO, str(e)) from None

    def _decompress_for_write(self) -> None:
        algo, _staged, _in_txn = self._comp_state()
        if algo is None:
            return
        from ...compress import OBJ_ALGO_ATTR, OBJ_SIZE_ATTR

        raw = self._logical_bytes() or b""
        t = self._w()
        t.truncate(self.cid, self.oid, 0)
        t.write(self.cid, self.oid, 0, len(raw), raw)
        t.rmattr(self.cid, self.oid, OBJ_ALGO_ATTR)
        t.rmattr(self.cid, self.oid, OBJ_SIZE_ATTR)
        self._cstate[self.oid] = (None, raw)
        self._staged_raw = raw

    # -- reads (cls_cxx_read / getxattr / map_get_* ) ----------------------

    def exists(self) -> bool:
        return (not self._whiteout
                and self.store.exists(self.cid, self.oid))

    def stat(self) -> int:
        if self._whiteout:
            raise ClsError(ENOENT, "object absent")
        algo, staged, in_txn = self._comp_state()
        if staged is not None:
            return len(staged)
        if algo is not None and not in_txn:
            # committed-compressed: the logical size is one xattr
            # away — no need to decompress the whole blob
            from ...compress import OBJ_SIZE_ATTR

            raw = self.getxattr(OBJ_SIZE_ATTR)
            if raw:
                return int(raw)
        try:
            raw_img = self._logical_bytes()
            if raw_img is not None:
                return len(raw_img)
            return self.store.stat(self.cid, self.oid)
        except NotFound:
            raise ClsError(ENOENT, "object absent") from None

    def read(self, offset: int = 0, length: int = -1) -> bytes:
        if self._whiteout:
            raise ClsError(ENOENT, "object absent")
        try:
            raw = self._logical_bytes()
            if raw is None:
                return self.store.read(self.cid, self.oid, offset,
                                       length)
            if length < 0:
                return raw[offset:]
            return raw[offset:offset + length]
        except NotFound:
            raise ClsError(ENOENT, "object absent") from None

    def getxattr(self, name: str) -> bytes | None:
        if self._whiteout:
            return None
        try:
            return self.store.getattr(self.cid, self.oid, name)
        except NotFound:
            return None

    def getxattrs(self) -> dict:
        if self._whiteout:
            return {}
        try:
            return self.store.getattrs(self.cid, self.oid)
        except NotFound:
            return {}

    def omap_get(self) -> dict:
        if self._whiteout:
            return {}
        try:
            return self.store.omap_get(self.cid, self.oid)
        except NotFound:
            return {}

    def omap_get_vals(self, keys) -> dict:
        if self._whiteout:
            return {}
        try:
            return self.store.omap_get_values(self.cid, self.oid, keys)
        except NotFound:
            return {}

    # -- writes (cls_cxx_write / setxattr / map_set_vals / remove) ---------

    def _w(self) -> Transaction:
        if self.txn is None:
            raise ClsError(EPERM, "write method on read path")
        return self.txn

    def create(self) -> None:
        if self._whiteout:
            # resurrect the tombstone clean: stale non-snapshot
            # xattrs and omap must not leak into the new incarnation
            # (the snapset attr survives — the clones are still live)
            t = self._w()
            keep = ("snapset",)
            try:
                stale = [n for n in
                         self.store.getattrs(self.cid, self.oid)
                         if n not in keep]
            except NotFound:
                stale = []
            for n in stale:
                t.rmattr(self.cid, self.oid, n)
            t.omap_clear(self.cid, self.oid)
            t.setattr(self.cid, self.oid, "whiteout", b"0")
            self._whiteout = False
            return
        if not self.exists():
            self._w().touch(self.cid, self.oid)

    def write(self, offset: int, data: bytes) -> None:
        self.create()
        self._decompress_for_write()
        self._w().write(self.cid, self.oid, offset, len(data), data)

    def write_full(self, data: bytes) -> None:
        self.create()
        self._decompress_for_write()
        if self.store.exists(self.cid, self.oid):
            self._w().truncate(self.cid, self.oid, 0)
        self._w().write(self.cid, self.oid, 0, len(data), data)

    def setxattr(self, name: str, val: bytes) -> None:
        self.create()
        self._w().setattr(self.cid, self.oid, name, val)

    def rmxattr(self, name: str) -> None:
        self._w().rmattr(self.cid, self.oid, name)

    def omap_set(self, kv: dict) -> None:
        self.create()
        self._w().omap_setkeys(self.cid, self.oid, kv)

    def omap_rm(self, keys) -> None:
        self._w().omap_rmkeys(self.cid, self.oid, keys)

    def truncate(self, length: int) -> None:
        self._decompress_for_write()
        self._w().truncate(self.cid, self.oid, length)

    def remove(self) -> None:
        """Request object deletion.  NOT staged directly: the write
        interpreter performs it through the snapshot-aware delete path
        (snaps.delete_head) after the method returns, so a cls
        self-delete of a snapshotted head leaves the whiteout and
        keeps its clones readable, exactly like the 'delete' op."""
        self._w()               # write-path check only
        self._staged_remove = True


class ClassHandler:
    """class/method registry (ClassHandler::ClassData)."""

    def __init__(self):
        self._classes: dict[str, dict[str, tuple[int, object]]] = {}

    def register(self, cls: str, method: str, flags: int, fn) -> None:
        self._classes.setdefault(cls, {})[method] = (flags, fn)

    def register_class(self, cls: str, methods: dict) -> None:
        for m, (flags, fn) in methods.items():
            self.register(cls, m, flags, fn)

    def lookup(self, cls: str, method: str):
        """Returns (flags, fn) or raises ClsError like the reference's
        -EOPNOTSUPP for unknown class / method."""
        c = self._classes.get(cls)
        if c is None:
            raise ClsError(EOPNOTSUPP, "no class %r" % cls)
        m = c.get(method)
        if m is None:
            raise ClsError(EOPNOTSUPP,
                           "no method %s.%s" % (cls, method))
        return m

    def is_write(self, cls: str, method: str) -> bool:
        flags, _fn = self.lookup(cls, method)
        return bool(flags & WR)

    def call(self, cls: str, method: str, ctx: MethodContext,
             inp: dict) -> tuple[int, dict]:
        try:
            flags, fn = self.lookup(cls, method)
            if (flags & WR) and ctx.txn is None:
                raise ClsError(EPERM,
                               "%s.%s requires the write path"
                               % (cls, method))
            out = fn(ctx, dict(inp or {}))
            return 0, (out or {})
        except ClsError as e:
            return e.code, {"error": str(e)}
        except Exception as e:
            # a buggy method (bad input types, corrupt blob) must
            # fail the op, never wedge it: the reference converts
            # method exceptions to -EIO the same way
            return EIO, {"error": "%s.%s: %s" % (cls, method, e)}


def default_handler() -> ClassHandler:
    """The built-in class set, loaded per OSD (the role of the
    OSD's ClassHandler + the cls .so directory)."""
    from . import fsmeta, lock, rbd, refcount, rgw

    h = ClassHandler()
    lock.register(h)
    refcount.register(h)
    rbd.register(h)
    fsmeta.register(h)
    rgw.register(h)
    return h
