"""cls_refcount: tag-set reference counting with self-delete.

Analog of src/cls/refcount/cls_refcount.cc (the machinery RGW uses to
share one RADOS object among logical copies): refs are a set of tags
in an xattr; ``put`` on the last tag removes the object inside the
same atomic method — no client round-trip can race it.

An object with no refcount attr holds one implicit wildcard ref
(the reference's cls_refcount_put behavior): the first ``put``
removes it regardless of tag.
"""

from __future__ import annotations

from ...utils import denc
from . import EINVAL, ENOENT, RD, WR, ClsError, MethodContext

REF_XATTR = "refcount"


def _load(ctx: MethodContext) -> list | None:
    blob = ctx.getxattr(REF_XATTR)
    return list(denc.decode(blob)) if blob else None


def get(ctx: MethodContext, inp: dict) -> dict:
    tag = inp.get("tag", "")
    if not tag:
        raise ClsError(EINVAL, "empty tag")
    refs = _load(ctx) or []
    if tag not in refs:
        refs.append(tag)
    ctx.setxattr(REF_XATTR, denc.encode(refs))
    return {}


def put(ctx: MethodContext, inp: dict) -> dict:
    tag = inp.get("tag", "")
    if not tag:
        raise ClsError(EINVAL, "empty tag")
    if not ctx.exists():
        raise ClsError(ENOENT, "object absent")
    refs = _load(ctx)
    if refs is None:
        # implicit single wildcard ref
        ctx.remove()
        return {"removed": True}
    if tag not in refs:
        raise ClsError(ENOENT, "no such tag")
    refs.remove(tag)
    if refs:
        ctx.setxattr(REF_XATTR, denc.encode(refs))
        return {"removed": False}
    ctx.remove()
    return {"removed": True}


def set_refs(ctx: MethodContext, inp: dict) -> dict:
    refs = list(inp.get("refs", []))
    if not refs:
        raise ClsError(EINVAL, "empty ref list")
    ctx.setxattr(REF_XATTR, denc.encode(refs))
    return {}


def read(ctx: MethodContext, inp: dict) -> dict:
    return {"refs": _load(ctx) or []}


def register(h) -> None:
    h.register_class("refcount", {
        "get": (WR, get),
        "put": (WR, put),
        "set": (WR, set_refs),
        "read": (RD, read),
    })
