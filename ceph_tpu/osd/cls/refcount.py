"""cls_refcount: tag-set reference counting with self-delete.

Analog of src/cls/refcount/cls_refcount.cc (the machinery RGW uses to
share one RADOS object among logical copies): refs are a set of tags
in an xattr; ``put`` on the last tag removes the object inside the
same atomic method — no client round-trip can race it.

An object with no refcount attr holds one implicit wildcard ref
(the reference's cls_refcount_put behavior): the first ``put``
removes it regardless of tag.  The wildcard applies only to
PRE-EXISTING objects: ``get`` on an absent object CREATES it holding
exactly [tag] (the cls_cas chunk_create_or_get_ref shape the dedup
plane's ref-or-store path depends on), never the wildcard.

Refs are canonical — duplicate tags are collapsed on every mutation,
so one logical ref can never require two ``put``s and the last
``put`` always reaches the self-delete.
"""

from __future__ import annotations

from ...utils import denc
from . import EINVAL, ENOENT, RD, WR, ClsError, MethodContext

REF_XATTR = "refcount"


def _canon(refs) -> list:
    """Order-preserving dedupe: the canonical form every mutation
    stores (a raw duplicated tag would survive one ``put`` and leak
    the object forever)."""
    seen: set = set()
    out: list = []
    for t in refs:
        if t not in seen:
            seen.add(t)
            out.append(t)
    return out


def _load(ctx: MethodContext) -> list | None:
    blob = ctx.getxattr(REF_XATTR)
    return list(denc.decode(blob)) if blob else None


def get(ctx: MethodContext, inp: dict) -> dict:
    """Take (or re-take — idempotent) one tag ref.  Absent objects
    are CREATED holding exactly [tag] (cls_cas's
    chunk_create_or_get_ref shape).  Returns the object's COMMITTED
    size so a ref-or-store caller can decide "already stored?" in the
    same atomic method: size 0 means this get created (or raced the
    creation of) an empty chunk the caller must now write.
    ``created`` is true only for the one call that brought the object
    into existence — racing ref-or-store callers use it to decide who
    accounts the chunk as stored (all size-0 holders still write the
    identical content-addressed image)."""
    tag = inp.get("tag", "")
    if not tag:
        raise ClsError(EINVAL, "empty tag")
    created = not ctx.exists()
    size = 0 if created else ctx.stat()
    refs = _canon(_load(ctx) or [])
    if tag not in refs:
        refs.append(tag)
    ctx.setxattr(REF_XATTR, denc.encode(refs))
    return {"size": size, "created": created}


def put(ctx: MethodContext, inp: dict) -> dict:
    tag = inp.get("tag", "")
    if not tag:
        raise ClsError(EINVAL, "empty tag")
    if not ctx.exists():
        raise ClsError(ENOENT, "object absent")
    refs = _load(ctx)
    if refs is None:
        # implicit single wildcard ref
        ctx.remove()
        return {"removed": True}
    refs = _canon(refs)     # heal any legacy duplicated-tag list
    if tag not in refs:
        raise ClsError(ENOENT, "no such tag")
    refs.remove(tag)
    if refs:
        ctx.setxattr(REF_XATTR, denc.encode(refs))
        return {"removed": False}
    ctx.remove()
    return {"removed": True}


def set_refs(ctx: MethodContext, inp: dict) -> dict:
    refs = _canon(inp.get("refs", []))
    if not refs:
        raise ClsError(EINVAL, "empty ref list")
    ctx.setxattr(REF_XATTR, denc.encode(refs))
    return {}


def read(ctx: MethodContext, inp: dict) -> dict:
    return {"refs": _canon(_load(ctx) or [])}


def register(h) -> None:
    h.register_class("refcount", {
        "get": (WR, get),
        "put": (WR, put),
        "set": (WR, set_refs),
        "read": (RD, read),
    })
