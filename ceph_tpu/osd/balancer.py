"""Upmap balancer: calc_pg_upmaps.

Condensed analog of OSDMap::calc_pg_upmaps (src/osd/OSDMap.cc:5159) —
the flagship consumer of bulk mapping (the mgr balancer module drives
it): compute every PG's up set through the device bulk mapper, measure
per-OSD deviation from the weight-proportional target, and emit
pg_upmap_items exceptions that move PGs from overfull to underfull
OSDs until the deviation is within max_deviation or no further
progress is possible.

Placement correctness mirrors the reference's candidate validation
(try_pg_upmap + _choose_type_stack cleaning, CrushWrapper.h:1529):

* a move must not put two up-set members into the same failure domain
  (the rule's chooseleaf type), validated against the crush tree;
* the remap target must be up+in and absent from the PG's up set;
* item rewrites are computed against the RAW (pre-upmap) mapping: an
  existing (X -> over) exception is rewritten to (X -> under), never
  stacked as (over -> under) — the raw set does not contain `over`,
  so a stacked item would be a no-op and removing the old one would
  silently bounce the PG back (OSDMap::calc_pg_upmaps does the same
  raw-vs-up bookkeeping).
"""

from __future__ import annotations

from ..models.crushmap import (CHOOSE_FIRSTN, CHOOSE_INDEP,
                               CHOOSELEAF_FIRSTN, CHOOSELEAF_INDEP,
                               ITEM_NONE)
from .osdmap import Incremental, OSDMap, pg_t


def _failure_domains(osdmap: OSDMap, ruleno: int) -> dict[int, int] | None:
    """osd -> failure-domain bucket id for the rule's chooseleaf type,
    or None when the rule spreads over devices directly (type 0) or
    has no single choose step (validation then only blocks duplicate
    OSDs, like the reference's type-0 stack)."""
    rule = osdmap.crush.rules.get(ruleno)
    if rule is None:
        return None
    want_type = None
    for op, arg1, arg2 in rule.steps:
        if op in (CHOOSELEAF_FIRSTN, CHOOSELEAF_INDEP,
                  CHOOSE_FIRSTN, CHOOSE_INDEP):
            if want_type is not None:
                return None          # multi-step: no single domain
            want_type = arg2
    if not want_type:
        return None
    domains: dict[int, int] = {}

    def walk(bid: int, domain: int | None) -> None:
        b = osdmap.crush.buckets.get(bid)
        if b is None:
            return
        d = bid if b.type == want_type else domain
        for child in b.items:
            if child < 0:
                walk(child, d)
            elif d is not None:
                domains[child] = d

    children = {c for b in osdmap.crush.buckets.values()
                for c in b.items if c < 0}
    for bid in osdmap.crush.buckets:
        if bid not in children:
            walk(bid, None)
    return domains


def _apply_items(osdmap: OSDMap, raw: list[int],
                 items: list[tuple[int, int]]) -> list[int]:
    """Mirror of OSDMap._apply_upmap's pg_upmap_items pass: an item
    applies only when its target is absent from the row, its source
    present, and the target not weighted out."""
    row = list(raw)
    for osd_from, osd_to in items or ():
        if osd_to in row:
            continue
        if (osd_to != ITEM_NONE and 0 <= osd_to < osdmap.max_osd
                and osdmap.osd_weight[osd_to] == 0):
            continue
        for i, o in enumerate(row):
            if o == osd_from:
                row[i] = osd_to
                break
    return row


def _effective_up(osdmap: OSDMap, raw: list[int],
                  items: list[tuple[int, int]]) -> list[int]:
    row = _apply_items(osdmap, raw, items)
    return [o for o in row
            if o != ITEM_NONE and osdmap.exists(o) and osdmap.is_up(o)]


def _pool_raw(osdmap: OSDMap, pool) -> list[list[int]]:
    """Pre-upmap raw rows (down OSDs included, like
    _pg_to_raw_osds) for every PG, via the device bulk mapper's
    MapState when in scope."""
    import numpy as np

    try:
        from .osdmap import FLAG_HASHPSPOOL, OSD_EXISTS, OSD_UP

        dm = osdmap.device_mapper()
        state = np.asarray(osdmap.osd_state, dtype=np.int32)
        st = dm.map_pool_state(
            pool.crush_rule, pool.size, pool.pg_num, pool.pgp_num,
            pool.pgp_num_mask, pool.id,
            bool(pool.flags & FLAG_HASHPSPOOL), osdmap.osd_weight,
            (state & OSD_EXISTS) != 0, (state & OSD_UP) != 0, None,
            pool.can_shift_osds())
        raw_np = np.array(st.raw[:pool.pg_num])
        return [[o for o in row if o != ITEM_NONE]
                for row in raw_np.tolist()]
    except ValueError:
        # outside device scope (non-straw2, multi-choose): scalar path
        rows = []
        for ps in range(pool.pg_num):
            pg = pg_t(pool.id, ps)
            raw, _pps = osdmap._pg_to_raw_osds(pool, pg)
            rows.append([o for o in raw if o != ITEM_NONE])
        return rows


def calc_pg_upmaps(osdmap: OSDMap, inc: Incremental,
                   max_deviation: float = 1.0,
                   max_iterations: int = 100,
                   pools: list[int] | None = None) -> int:
    """Fill inc.new_pg_upmap_items / old_pg_upmap_items; returns the
    number of changes (OSDMap.cc:5159 contract)."""
    pool_ids = sorted(pools if pools is not None else osdmap.pools)
    pool_ids = [p for p in pool_ids if p in osdmap.pools]
    if not pool_ids:
        return 0

    pg_raw: dict[pg_t, list[int]] = {}
    pg_up: dict[pg_t, list[int]] = {}
    pinned: dict[pg_t, list[int]] = {}
    pg_domains: dict[int, dict[int, int] | None] = {}
    for pid in pool_ids:
        pool = osdmap.pools[pid]
        raw_rows = _pool_raw(osdmap, pool)
        pg_domains[pid] = _failure_domains(osdmap, pool.crush_rule)
        for ps in range(pool.pg_num):
            pg = pg_t(pid, ps)
            if pg in osdmap.pg_upmap:
                # explicit pg_upmap pins override items entirely
                # (OSDMap._apply_upmap); count their real placement
                # but never try to move them
                up, _, _, _ = osdmap.pg_to_up_acting_osds(pg)
                pinned[pg] = up
                continue
            pg_raw[pg] = raw_rows[ps]
            pg_up[pg] = _effective_up(
                osdmap, raw_rows[ps],
                osdmap.pg_upmap_items.get(pg, []))

    # weight-proportional target over up+in osds
    weights = {o: osdmap.osd_weight[o] / 0x10000
               for o in range(osdmap.max_osd)
               if osdmap.is_up(o) and osdmap.is_in(o)}
    total_w = sum(weights.values())
    if total_w <= 0:
        return 0
    total_placements = (sum(len(up) for up in pg_up.values())
                        + sum(len(up) for up in pinned.values()))
    target = {o: total_placements * w / total_w
              for o, w in weights.items()}

    counts = {o: 0 for o in weights}
    for up in pg_up.values():
        for o in up:
            if o in counts:
                counts[o] += 1
    for up in pinned.values():
        for o in up:
            if o in counts:
                counts[o] += 1

    existing = {pg: items for pg, items in osdmap.pg_upmap_items.items()
                if pg.pool in set(pool_ids)}
    # retire no-op entries up front (source left the raw set or the
    # item no longer applies) — the reference's clean_pg_upmaps pass
    new_items: dict[pg_t, list[tuple[int, int]]] = {}
    for pg, items in existing.items():
        if pg in pinned:
            new_items[pg] = list(items)   # masked by pg_upmap: keep
            continue
        raw = pg_raw.get(pg, [])
        row = list(raw)
        kept = []
        for f, t in items:
            if f in row and t not in row:
                row = [t if o == f else o for o in row]
                kept.append((f, t))
        new_items[pg] = kept

    def row_valid(pg: pg_t, row: list[int]) -> bool:
        if len(set(row)) != len(row):
            return False
        domains = pg_domains.get(pg.pool)
        if domains is None:
            return True
        doms = [domains.get(o) for o in row]
        return None not in doms and len(set(doms)) == len(doms)

    changes = 0
    for _ in range(max_iterations):
        deviations = {o: counts[o] - target[o] for o in counts}
        over = max(deviations, key=lambda o: deviations[o])
        if deviations[over] <= max_deviation:
            break
        under_sorted = sorted(deviations, key=lambda o: deviations[o])
        moved = False
        for pg, up in pg_up.items():
            if over not in up:
                continue
            raw = pg_raw[pg]
            for under in under_sorted:
                if deviations[under] >= -0.0001:
                    break  # nobody meaningfully underfull
                if under in up:
                    continue
                # rewrite against the RAW mapping: if `over` is a raw
                # member, add (over, under); else an existing item
                # (X -> over) must exist — rewrite it to (X -> under),
                # never stack (over -> under) no-ops
                items = [t for t in new_items.get(pg, [])
                         if t[1] != over]
                if over in raw:
                    items = [t for t in items if t[0] != over]
                    items.append((over, under))
                else:
                    src = next((f for f, t in new_items.get(pg, [])
                                if t == over), None)
                    if src is None or src not in raw:
                        continue
                    items = [t for t in items if t[0] != src]
                    items.append((src, under))
                # the REAL effect of the new item list (replayed via
                # _apply_upmap semantics over the raw row) is what
                # must be validated and accounted — dropping an item
                # can silently restore its source, so the old up row
                # is not a reliable base
                new_row = _effective_up(osdmap, raw, items)
                if over in new_row or not row_valid(pg, new_row):
                    continue
                if sum(1 for o in new_row if o == under) != 1:
                    continue
                new_items[pg] = items
                for o in up:
                    if o in counts:
                        counts[o] -= 1
                for o in new_row:
                    if o in counts:
                        counts[o] += 1
                pg_up[pg] = new_row
                changes += 1
                moved = True
                break
            if moved:
                break
        if not moved:
            break

    for pg, items in new_items.items():
        if items != existing.get(pg, []):
            if items:
                inc.new_pg_upmap_items[pg] = items
            elif pg in existing:
                inc.old_pg_upmap_items.append(pg)
    for pg in existing:
        if pg not in new_items:
            inc.old_pg_upmap_items.append(pg)
    return changes
