"""Upmap balancer: calc_pg_upmaps.

Condensed analog of OSDMap::calc_pg_upmaps (src/osd/OSDMap.cc:5159) —
the flagship consumer of bulk mapping (the mgr balancer module drives
it): compute every PG's up set through the device bulk mapper, measure
per-OSD deviation from the weight-proportional target, and emit
pg_upmap_items exceptions that move PGs from overfull to underfull
OSDs until the deviation is within max_deviation or no further
progress is possible.

Placement correctness mirrors the reference's candidate validation
(try_pg_upmap + _choose_type_stack cleaning, CrushWrapper.h:1529):

* a move must not put two up-set members into the same failure domain
  (the rule's chooseleaf type), validated against the crush tree;
* the remap target must be up+in and absent from the PG's up set;
* item rewrites are computed against the RAW (pre-upmap) mapping: an
  existing (X -> over) exception is rewritten to (X -> under), never
  stacked as (over -> under) — the raw set does not contain `over`,
  so a stacked item would be a no-op and removing the old one would
  silently bounce the PG back (OSDMap::calc_pg_upmaps does the same
  raw-vs-up bookkeeping).
"""

from __future__ import annotations

from ..models.crushmap import (CHOOSE_FIRSTN, CHOOSE_INDEP,
                               CHOOSELEAF_FIRSTN, CHOOSELEAF_INDEP,
                               ITEM_NONE)
from .osdmap import Incremental, OSDMap, pg_t


def _failure_domains(osdmap: OSDMap, ruleno: int) -> dict[int, int] | None:
    """osd -> failure-domain bucket id for the rule's chooseleaf type,
    or None when the rule spreads over devices directly (type 0) or
    has no single choose step (validation then only blocks duplicate
    OSDs, like the reference's type-0 stack)."""
    rule = osdmap.crush.rules.get(ruleno)
    if rule is None:
        return None
    want_type = None
    for op, arg1, arg2 in rule.steps:
        if op in (CHOOSELEAF_FIRSTN, CHOOSELEAF_INDEP,
                  CHOOSE_FIRSTN, CHOOSE_INDEP):
            if want_type is not None:
                return None          # multi-step: no single domain
            want_type = arg2
    if not want_type:
        return None
    domains: dict[int, int] = {}

    def walk(bid: int, domain: int | None) -> None:
        b = osdmap.crush.buckets.get(bid)
        if b is None:
            return
        d = bid if b.type == want_type else domain
        for child in b.items:
            if child < 0:
                walk(child, d)
            elif d is not None:
                domains[child] = d

    children = {c for b in osdmap.crush.buckets.values()
                for c in b.items if c < 0}
    for bid in osdmap.crush.buckets:
        if bid not in children:
            walk(bid, None)
    return domains


def _apply_items(osdmap: OSDMap, raw: list[int],
                 items: list[tuple[int, int]]) -> list[int]:
    """Mirror of OSDMap._apply_upmap's pg_upmap_items pass: an item
    applies only when its target is absent from the row, its source
    present, and the target not weighted out."""
    row = list(raw)
    for osd_from, osd_to in items or ():
        if osd_to in row:
            continue
        if (osd_to != ITEM_NONE and 0 <= osd_to < osdmap.max_osd
                and osdmap.osd_weight[osd_to] == 0):
            continue
        for i, o in enumerate(row):
            if o == osd_from:
                row[i] = osd_to
                break
    return row


def _effective_up(osdmap: OSDMap, raw: list[int],
                  items: list[tuple[int, int]]) -> list[int]:
    row = _apply_items(osdmap, raw, items)
    return [o for o in row
            if o != ITEM_NONE and osdmap.exists(o) and osdmap.is_up(o)]


def _pool_raw(osdmap: OSDMap, pool) -> list[list[int]]:
    """Pre-upmap raw rows (down OSDs included, like
    _pg_to_raw_osds) for every PG, via the device bulk mapper's
    MapState when in scope."""
    import numpy as np

    try:
        from .osdmap import FLAG_HASHPSPOOL, OSD_EXISTS, OSD_UP

        dm = osdmap.device_mapper()
        state = np.asarray(osdmap.osd_state, dtype=np.int32)
        st = dm.map_pool_state(
            pool.crush_rule, pool.size, pool.pg_num, pool.pgp_num,
            pool.pgp_num_mask, pool.id,
            bool(pool.flags & FLAG_HASHPSPOOL), osdmap.osd_weight,
            (state & OSD_EXISTS) != 0, (state & OSD_UP) != 0, None,
            pool.can_shift_osds())
        raw_np = np.array(st.raw[:pool.pg_num])
        return [[o for o in row if o != ITEM_NONE]
                for row in raw_np.tolist()]
    except ValueError:
        # outside device scope (non-straw2, multi-choose): scalar path
        rows = []
        for ps in range(pool.pg_num):
            pg = pg_t(pool.id, ps)
            raw, _pps = osdmap._pg_to_raw_osds(pool, pg)
            rows.append([o for o in raw if o != ITEM_NONE])
        return rows


class BalancerState:
    """The shared prologue of both optimizers (sequential
    calc_pg_upmaps and the batched scale-plane scorer): raw and
    effective-up rows per PG, pg_upmap-pinned placements, per-pool
    failure domains, the cleaned existing-items table, and the
    weight-proportional target/deviation accounting."""

    __slots__ = ("osdmap", "pool_ids", "pg_raw", "pg_up", "pinned",
                 "pg_domains", "existing", "new_items", "weights",
                 "target", "counts")

    def __init__(self, osdmap: OSDMap, pools: list[int] | None):
        self.osdmap = osdmap
        pool_ids = sorted(pools if pools is not None
                          else osdmap.pools)
        self.pool_ids = [p for p in pool_ids if p in osdmap.pools]
        self.pg_raw: dict[pg_t, list[int]] = {}
        self.pg_up: dict[pg_t, list[int]] = {}
        self.pinned: dict[pg_t, list[int]] = {}
        self.pg_domains: dict[int, dict[int, int] | None] = {}
        for pid in self.pool_ids:
            pool = osdmap.pools[pid]
            raw_rows = _pool_raw(osdmap, pool)
            self.pg_domains[pid] = _failure_domains(osdmap,
                                                    pool.crush_rule)
            for ps in range(pool.pg_num):
                pg = pg_t(pid, ps)
                if pg in osdmap.pg_upmap:
                    # explicit pg_upmap pins override items entirely
                    # (OSDMap._apply_upmap); count their real
                    # placement but never try to move them
                    up, _, _, _ = osdmap.pg_to_up_acting_osds(pg)
                    self.pinned[pg] = up
                    continue
                self.pg_raw[pg] = raw_rows[ps]
                self.pg_up[pg] = _effective_up(
                    osdmap, raw_rows[ps],
                    osdmap.pg_upmap_items.get(pg, []))

        # weight-proportional target over up+in osds
        self.weights = {o: osdmap.osd_weight[o] / 0x10000
                        for o in range(osdmap.max_osd)
                        if osdmap.is_up(o) and osdmap.is_in(o)}
        total_w = sum(self.weights.values())
        total_placements = (
            sum(len(up) for up in self.pg_up.values())
            + sum(len(up) for up in self.pinned.values()))
        self.target = ({o: total_placements * w / total_w
                        for o, w in self.weights.items()}
                       if total_w > 0 else {})
        self.counts = {o: 0 for o in self.weights}
        for ups in (self.pg_up, self.pinned):
            for up in ups.values():
                for o in up:
                    if o in self.counts:
                        self.counts[o] += 1

        self.existing = {pg: items
                         for pg, items in osdmap.pg_upmap_items.items()
                         if pg.pool in set(self.pool_ids)}
        # retire no-op entries up front (source left the raw set or
        # the item no longer applies) — the reference's
        # clean_pg_upmaps pass
        self.new_items: dict[pg_t, list[tuple[int, int]]] = {}
        for pg, items in self.existing.items():
            if pg in self.pinned:
                self.new_items[pg] = list(items)  # pg_upmap mask: keep
                continue
            raw = self.pg_raw.get(pg, [])
            row = list(raw)
            kept = []
            for f, t in items:
                if f in row and t not in row:
                    row = [t if o == f else o for o in row]
                    kept.append((f, t))
            self.new_items[pg] = kept

    def row_valid(self, pg: pg_t, row: list[int]) -> bool:
        if len(set(row)) != len(row):
            return False
        domains = self.pg_domains.get(pg.pool)
        if domains is None:
            return True
        doms = [domains.get(o) for o in row]
        return None not in doms and len(set(doms)) == len(doms)

    def try_move(self, pg: pg_t, over: int,
                 under: int) -> list[int] | None:
        """Attempt the move `over` -> `under` for one PG through the
        EXACT reference validity rules (raw-vs-up item rewrite,
        _apply_upmap replay, failure-domain validation).  On success
        the state (items, up row, counts) is updated and the new
        effective up row returned; None = invalid, state untouched.
        Both optimizers commit moves ONLY through here, so their
        emitted items are identical in effect by construction."""
        up = self.pg_up.get(pg)
        if up is None or over not in up or under in up:
            return None
        raw = self.pg_raw[pg]
        # rewrite against the RAW mapping: if `over` is a raw member,
        # add (over, under); else an existing item (X -> over) must
        # exist — rewrite it to (X -> under), never stack
        # (over -> under) no-ops
        items = [t for t in self.new_items.get(pg, [])
                 if t[1] != over]
        if over in raw:
            items = [t for t in items if t[0] != over]
            items.append((over, under))
        else:
            src = next((f for f, t in self.new_items.get(pg, [])
                        if t == over), None)
            if src is None or src not in raw:
                return None
            items = [t for t in items if t[0] != src]
            items.append((src, under))
        # the REAL effect of the new item list (replayed via
        # _apply_upmap semantics over the raw row) is what must be
        # validated and accounted — dropping an item can silently
        # restore its source, so the old up row is not a reliable base
        new_row = _effective_up(self.osdmap, raw, items)
        if over in new_row or not self.row_valid(pg, new_row):
            return None
        if sum(1 for o in new_row if o == under) != 1:
            return None
        self.new_items[pg] = items
        for o in up:
            if o in self.counts:
                self.counts[o] -= 1
        for o in new_row:
            if o in self.counts:
                self.counts[o] += 1
        self.pg_up[pg] = new_row
        return new_row

    def fill_incremental(self, inc: Incremental) -> None:
        for pg, items in self.new_items.items():
            if items != self.existing.get(pg, []):
                if items:
                    inc.new_pg_upmap_items[pg] = items
                elif pg in self.existing:
                    inc.old_pg_upmap_items.append(pg)
        for pg in self.existing:
            if pg not in self.new_items:
                inc.old_pg_upmap_items.append(pg)


def calc_pg_upmaps(osdmap: OSDMap, inc: Incremental,
                   max_deviation: float = 1.0,
                   max_iterations: int = 100,
                   pools: list[int] | None = None) -> int:
    """Fill inc.new_pg_upmap_items / old_pg_upmap_items; returns the
    number of changes (OSDMap.cc:5159 contract)."""
    st = BalancerState(osdmap, pools)
    if not st.pool_ids or not st.target:
        return 0

    changes = 0
    for _ in range(max_iterations):
        deviations = {o: st.counts[o] - st.target[o]
                      for o in st.counts}
        over = max(deviations, key=lambda o: deviations[o])
        if deviations[over] <= max_deviation:
            break
        under_sorted = sorted(deviations, key=lambda o: deviations[o])
        moved = False
        for pg, up in st.pg_up.items():
            if over not in up:
                continue
            for under in under_sorted:
                if deviations[under] >= -0.0001:
                    break  # nobody meaningfully underfull
                if under in up:
                    continue
                if st.try_move(pg, over, under) is None:
                    continue
                changes += 1
                moved = True
                break
            if moved:
                break
        if not moved:
            break

    st.fill_incremental(inc)
    return changes
