"""Upmap balancer: calc_pg_upmaps.

Condensed analog of OSDMap::calc_pg_upmaps (src/osd/OSDMap.cc:5159) —
the flagship consumer of bulk mapping (the mgr balancer module drives
it): compute every PG's up set, measure per-OSD deviation from the
weight-proportional target, and emit pg_upmap_items exceptions that
move PGs from overfull to underfull OSDs until the deviation is within
max_deviation or no further progress is possible.

Placement correctness is preserved the way the reference's
try_pg_upmap path does: a remap target must not already appear in the
PG's up set (no duplicate OSDs), must be up+in, and existing upmap
exceptions for a PG are replaced, not stacked.
"""

from __future__ import annotations

from .osdmap import Incremental, OSDMap, pg_t


def calc_pg_upmaps(osdmap: OSDMap, inc: Incremental,
                   max_deviation: float = 1.0,
                   max_iterations: int = 100,
                   pools: list[int] | None = None) -> int:
    """Fill inc.new_pg_upmap_items / old_pg_upmap_items; returns the
    number of changes (OSDMap.cc:5159 contract)."""
    pool_ids = sorted(pools if pools is not None else osdmap.pools)
    pool_ids = [p for p in pool_ids if p in osdmap.pools]
    if not pool_ids:
        return 0

    # current mapping + per-osd load
    pg_up: dict[pg_t, list[int]] = {}
    for pid in pool_ids:
        pool = osdmap.pools[pid]
        for ps in range(pool.pg_num):
            pg = pg_t(pid, ps)
            up, _, _, _ = osdmap.pg_to_up_acting_osds(pg)
            pg_up[pg] = up

    # weight-proportional target over up+in osds
    weights = {o: osdmap.osd_weight[o] / 0x10000
               for o in range(osdmap.max_osd)
               if osdmap.is_up(o) and osdmap.is_in(o)}
    total_w = sum(weights.values())
    if total_w <= 0:
        return 0
    total_placements = sum(len(up) for up in pg_up.values())
    target = {o: total_placements * w / total_w
              for o, w in weights.items()}

    counts = {o: 0 for o in weights}
    for up in pg_up.values():
        for o in up:
            if o in counts:
                counts[o] += 1

    # existing exceptions for these pools are re-derived from scratch
    existing = {pg: items for pg, items in osdmap.pg_upmap_items.items()
                if pg.pool in set(pool_ids)}
    new_items: dict[pg_t, list[tuple[int, int]]] = {
        pg: list(items) for pg, items in existing.items()}

    changes = 0
    for _ in range(max_iterations):
        deviations = {o: counts[o] - target[o] for o in counts}
        over = max(deviations, key=lambda o: deviations[o])
        if deviations[over] <= max_deviation:
            break
        under_sorted = sorted(deviations, key=lambda o: deviations[o])
        moved = False
        for pg, up in pg_up.items():
            if over not in up:
                continue
            for under in under_sorted:
                if deviations[under] >= -0.0001:
                    break  # nobody meaningfully underfull
                if under in up:
                    continue
                # move pg's replica from `over` to `under`
                items = [t for t in new_items.get(pg, [])
                         if t[0] != over and t[1] != over]
                items.append((over, under))
                new_items[pg] = items
                pg_up[pg] = [under if o == over else o for o in up]
                counts[over] -= 1
                counts[under] += 1
                changes += 1
                moved = True
                break
            if moved:
                break
        if not moved:
            break

    for pg, items in new_items.items():
        if items != existing.get(pg, []):
            if items:
                inc.new_pg_upmap_items[pg] = items
            elif pg in existing:
                inc.old_pg_upmap_items.append(pg)
    for pg in existing:
        if pg not in new_items:
            inc.old_pg_upmap_items.append(pg)
    return changes
