"""Monitor leader election: classic, disallow, and connectivity
strategies.

Analog of src/mon/Elector.h + ElectionLogic.cc:

* CLASSIC — the lowest-ranked monitor that can reach a majority wins.
* DISALLOW — classic, but ranks named in mon_disallowed_leaders never
  lead (they defer, never propose; ElectionLogic handle "disallowed").
* CONNECTIVITY — candidates are ranked by how well the QUORUM can
  reach them (ElectionLogic.cc:332 propose_connectivity_handler):
  every monitor keeps a decaying per-peer connectivity score
  (ConnectionTracker role), reports are GOSSIPED inside election
  messages with per-reporter versions, and a voter defers to the
  proposer whose aggregate score (mean of all reporters' views) is
  higher — rank only breaks near-ties.  Scores persist in the mon
  store (Elector.h:278 persist_connectivity_scores) so a restarted
  monitor remembers who was flaky.

Epochs are odd while electing and even when stable
(ElectionLogic::bump_epoch semantics); every PROPOSE carries the
proposer's epoch so stale rounds are ignored, DEFER (ack) goes to the
best candidate seen this round, and a proposer declares VICTORY once
a majority (including itself) has deferred.  Losing contact with the
leader (or a victory timeout) restarts the election with a bumped
epoch.
"""

from __future__ import annotations

import asyncio

ELECTING = "electing"
LEADER = "leader"
PEON = "peon"

PROPOSE = "propose"
DEFER = "defer"
VICTORY = "victory"

CLASSIC = "classic"
DISALLOW = "disallow"
CONNECTIVITY = "connectivity"

_SCORES_KEY = b"elector:scores"


class ConnectionTracker:
    """Decaying per-peer connectivity scores with gossip merge
    (src/mon/ConnectionTracker.h).

    Each monitor owns ONE report: {peer rank: score in [0,1]} plus a
    version; election traffic carries every report a node has seen,
    and receivers keep the freshest per reporter.  A candidate's
    aggregate score is the mean of all reporters' views of it, so a
    monitor that half the cluster cannot reach scores low everywhere
    once gossip spreads."""

    DECAY = 0.5         # per-tick multiplier for unseen peers
    FLOOR = 0.001

    def __init__(self, rank: int, store=None, n_ranks: int = 0):
        self.rank = rank
        self.store = store
        self.reports: dict[int, dict] = {}
        self._seen: set[int] = set()     # peers heard from this tick
        self._ticks = 0                  # boot grace (see tick())
        self._load()
        mine = self.reports.setdefault(
            rank, {"v": 0, "scores": {}})
        mine["scores"][rank] = 1.0
        # seed EVERY monmap rank so tick() decays peers that go
        # silent without a transport reset (a blackholed peer must
        # not keep its perfect score just because lost() never fired)
        for r in range(n_ranks):
            mine["scores"].setdefault(r, 1.0)

    # -- observation --------------------------------------------------------

    def saw(self, rank: int) -> None:
        """A message arrived from this peer: it is reachable now."""
        if rank == self.rank:
            return
        self._seen.add(rank)
        mine = self.reports[self.rank]
        cur = mine["scores"].get(rank, 1.0)
        if cur != 1.0:
            # gradual recovery (halfway per receipt): a peer dropping
            # half its traffic oscillates well below 1.0 instead of
            # snapping healthy on every delivered message — that gap
            # is what lets the strategy demote FLAKY monitors, not
            # just fully-partitioned ones
            mine["scores"][rank] = min(1.0, cur * 0.5 + 0.5)
            mine["v"] += 1
            self._persist()

    def lost(self, rank: int) -> None:
        """Transport to the peer reset: degrade immediately."""
        mine = self.reports[self.rank]
        cur = mine["scores"].get(rank, 1.0)
        mine["scores"][rank] = max(self.FLOOR, cur * self.DECAY)
        mine["v"] += 1
        self._persist()

    def tick(self) -> None:
        """Decay every peer not heard from since the last tick, then
        persist (the reference decays on a halflife; one multiplier
        per tick is the same shape).  The first few ticks are a BOOT
        GRACE: monitors start staggered, and decaying peers that
        simply have not finished booting makes every monitor's view
        diverge at once — contradictory candidate preferences then
        churn the very first election for many rounds."""
        self._ticks += 1
        if self._ticks <= 5:
            self._seen.clear()
            return
        mine = self.reports[self.rank]
        changed = False
        for r, s in list(mine["scores"].items()):
            if r == self.rank or r in self._seen:
                continue
            ns = max(self.FLOOR, s * self.DECAY)
            if ns != s:
                mine["scores"][r] = ns
                changed = True
        self._seen.clear()
        if changed:
            mine["v"] += 1
            self._persist()

    # -- gossip -------------------------------------------------------------

    def wire(self) -> dict:
        return {str(r): {"v": rep["v"],
                         "scores": {str(p): s
                                    for p, s in rep["scores"].items()}}
                for r, rep in self.reports.items()}

    def merge(self, wire: dict | None) -> None:
        for r_s, rep in (wire or {}).items():
            r = int(r_s)
            if r == self.rank:
                continue            # nobody overwrites MY report
            cur = self.reports.get(r)
            if cur is None or rep["v"] > cur["v"]:
                self.reports[r] = {
                    "v": rep["v"],
                    "scores": {int(p): float(s)
                               for p, s in rep["scores"].items()}}

    def aggregate(self, rank: int) -> float:
        """Mean of every reporter's view of ``rank`` (the candidate's
        cluster-wide reachability)."""
        views = [rep["scores"][rank]
                 for rep in self.reports.values()
                 if rank in rep["scores"]]
        return sum(views) / len(views) if views else 1.0

    # -- persistence --------------------------------------------------------

    def _persist(self) -> None:
        if self.store is None:
            return
        from ..utils import denc

        tx = self.store.get_transaction()
        tx.set(_SCORES_KEY, denc.encode(self.wire()))
        self.store.submit_transaction(tx, sync=False)

    def _load(self) -> None:
        if self.store is None:
            return
        from ..utils import denc

        raw = self.store.get(_SCORES_KEY)
        if raw is None:
            return
        try:
            for r_s, rep in denc.decode(raw).items():
                self.reports[int(r_s)] = {
                    "v": rep["v"],
                    "scores": {int(p): float(s)
                               for p, s in rep["scores"].items()}}
        except Exception:
            self.reports = {}


class Elector:
    def __init__(self, mon, timeout: float = 2.0,
                 strategy: str = CLASSIC,
                 disallowed: set[int] | None = None):
        self.mon = mon                  # Monitor: rank, peers, send
        self.timeout = timeout
        self.strategy = strategy
        self.disallowed = set(disallowed or ())
        # the tracker persists (and is even consulted) only under the
        # connectivity strategy — classic clusters pay no per-message
        # KV writes or gossip bytes for scores they never read
        self.tracker = ConnectionTracker(
            mon.rank,
            getattr(mon, "store", None)
            if strategy == CONNECTIVITY else None,
            n_ranks=len(getattr(mon, "monmap", [])))
        self.stopped = False
        self.epoch = 1
        self.state = ELECTING
        self.leader: int | None = None
        self.quorum: set[int] = set()
        self.deferred_to: int | None = None
        self._defers: set[int] = set()
        self._timer: asyncio.TimerHandle | None = None

    # -- helpers -----------------------------------------------------------

    def _majority(self) -> int:
        return len(self.mon.monmap) // 2 + 1

    def _allowed(self, rank: int) -> bool:
        return rank not in self.disallowed

    def _prefer(self, a: int, b: int) -> bool:
        """True when candidate ``a`` should lead over ``b``.  Classic
        and disallow rank by id; connectivity ranks by aggregate
        reachability, id breaking near-ties.  The margin must damp
        boot-time score jitter (two monitors with diverging views
        each preferring themselves would livelock a round) yet SCALE
        with cluster size: the aggregate is a mean over n reporters,
        so one fully-partitioned link moves it by ~1/n — a fixed
        margin would mask real partitions in larger quorums.  0.5/n
        sits halfway between jitter and a single dead link."""
        if self.strategy == CONNECTIVITY:
            sa, sb = (self.tracker.aggregate(a),
                      self.tracker.aggregate(b))
            margin = 0.5 / max(2, len(self.mon.monmap))
            if abs(sa - sb) > margin:
                return sa > sb
        return a < b

    def _bump(self, to_epoch: int | None = None, electing=True) -> None:
        e = max(self.epoch + 1, to_epoch or 0)
        if electing and e % 2 == 0:
            e += 1
        if not electing and e % 2 == 1:
            e += 1
        self.epoch = e

    def _arm_timer(self) -> None:
        self._cancel_timer()
        loop = asyncio.get_event_loop()
        self._timer = loop.call_later(self.timeout, self._on_timeout)

    def _cancel_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    # -- rounds ------------------------------------------------------------

    def stop(self) -> None:
        """Shutdown: a dead monitor must not keep proposing (a zombie
        lowest-rank proposer would collect defers it can never see and
        livelock the survivors)."""
        self.stopped = True
        self._cancel_timer()

    def note_newer_reign(self, epoch: int) -> None:
        """A PAXOS message arrived stamped with an election epoch
        newer than any election we took part in: a regime change
        happened while we were partitioned away (our LEADER/PEON
        state is stale — a healed ex-leader would otherwise sit in a
        split brain forever, serving stale reads and never publishing
        newer maps to its subscribers).  Adopt the newer epoch and
        force a fresh election so leadership reconverges (the
        reference's Monitor epoch-mismatch -> bump_epoch path)."""
        if self.stopped or epoch <= self.epoch:
            return
        self.mon.ctx.log.info(
            "mon", "%s: saw reign epoch %d > ours %d (healed "
            "partition?): re-electing" % (self.mon.name, epoch,
                                          self.epoch))
        self._bump(to_epoch=epoch, electing=True)
        self.state = ELECTING
        self.leader = None
        self.quorum = set()
        self.deferred_to = None
        self._defers = set()
        self.mon.on_lose(-1, self.epoch)
        self.start_election()

    def note_leader_alive(self) -> None:
        """Peon liveness watchdog: each lease receipt re-arms a timer;
        if leases stop (a wedged-but-connected leader that never
        triggers peer_lost), the timeout forces a new election."""
        if self.state == PEON and not self.stopped:
            self._cancel_timer()
            loop = asyncio.get_event_loop()
            self._timer = loop.call_later(3 * self.timeout,
                                          self._on_timeout)

    def start_election(self) -> None:
        if self.stopped:
            return
        if not self._allowed(self.mon.rank):
            # a disallowed monitor never proposes itself — and it
            # must NOT bump its epoch while waiting (nobody would see
            # the bump, so a few timeouts would race it permanently
            # ahead of the cluster and its DEFERs/VICTORYs would all
            # be dropped as epoch mismatches).  It waits at its
            # current epoch for an allowed candidate's PROPOSE.
            self.state = ELECTING
            self.leader = None
            self.quorum = set()
            self.deferred_to = None
            self._defers = set()
            self._arm_timer()
            return
        self._bump(electing=True)
        self.state = ELECTING
        self.leader = None
        self.quorum = set()
        self.deferred_to = self.mon.rank
        self._defers = {self.mon.rank}
        self.mon.ctx.log.debug(
            "mon", "%s election epoch %d: proposing"
            % (self.mon.name, self.epoch))
        self.mon.send_election(PROPOSE, self.epoch)
        self._arm_timer()
        self._maybe_win()

    def _on_timeout(self) -> None:
        if self.stopped:
            return
        if self.state == ELECTING:
            self.start_election()
        elif self.state == PEON and self.leader is not None:
            # leader lease lapsed: force a new round
            self.start_election()

    def _maybe_win(self) -> None:
        if (self.state == ELECTING
                and self.deferred_to == self.mon.rank
                and len(self._defers) >= self._majority()):
            self._declare_victory()

    def _declare_victory(self) -> None:
        self._bump(electing=False)
        self.state = LEADER
        self.leader = self.mon.rank
        self.quorum = set(self._defers)
        self._cancel_timer()
        self.mon.ctx.log.info(
            "mon", "%s won election epoch %d quorum %s"
            % (self.mon.name, self.epoch, sorted(self.quorum)))
        self.mon.send_election(VICTORY, self.epoch,
                               quorum=sorted(self.quorum))
        self.mon.on_win(self.epoch, self.quorum)

    # -- message handlers ---------------------------------------------------

    def handle(self, src_rank: int, op: str, epoch: int,
               quorum=None, scores=None) -> None:
        self.tracker.saw(src_rank)
        self.tracker.merge(scores)
        if op == "ping":
            return      # liveness probe: tracker.saw above is enough
        if op == PROPOSE:
            if epoch < self.epoch and self.state != ELECTING:
                # stale proposer (e.g. rejoining): poke it to catch up
                # by starting a fresh round it will see
                self.start_election()
                return
            if epoch > self.epoch:
                # a fresh round supersedes any stale defer state —
                # keeping it would suppress re-proposing and block
                # defers to better proposers at the new epoch
                self.epoch = epoch if epoch % 2 else epoch + 1
                self.state = ELECTING
                self.deferred_to = None
                self._defers = set()
            if self.state != ELECTING:
                return
            me = self.mon.rank
            i_can_lead = self._allowed(me)
            src_better = (not i_can_lead and self._allowed(src_rank)
                          ) or (self._allowed(src_rank)
                                and self._prefer(src_rank, me))
            if src_better:
                # defer to the better candidate — unless we already
                # acked someone at least as good this round
                if self.deferred_to is None \
                        or src_rank == self.deferred_to \
                        or self._prefer(src_rank, self.deferred_to):
                    self.deferred_to = src_rank
                    self.mon.send_election(DEFER, self.epoch,
                                           to_rank=src_rank)
                    self._arm_timer()
            else:
                # we are the better candidate: (re)propose ourselves —
                # but only if we have not already deferred this round
                # (ElectionLogic ignores worse proposals after acking
                # a better one — revoking the defer could hand two
                # proposers disjoint majorities in the same epoch)
                if self.deferred_to is None and i_can_lead:
                    self.deferred_to = me
                    self._defers = {me}
                    self.mon.send_election(PROPOSE, self.epoch)
                    self._arm_timer()
        elif op == DEFER:
            if epoch != self.epoch or self.state != ELECTING:
                return
            if self.deferred_to == self.mon.rank:
                self._defers.add(src_rank)
                self._maybe_win()
        elif op == VICTORY:
            if epoch < self.epoch:
                return
            self.epoch = epoch
            self.state = PEON
            self.leader = src_rank
            self.quorum = set(quorum or [])
            self._cancel_timer()
            self.mon.ctx.log.info(
                "mon", "%s: mon.%d leads epoch %d"
                % (self.mon.name, src_rank, epoch))
            self.note_leader_alive()
            self.mon.on_lose(src_rank, self.epoch)

    def peer_lost(self, rank: int) -> None:
        """A quorum member became unreachable: re-elect if it matters
        (the leader died, or we are the leader and lost majority)."""
        self.tracker.lost(rank)
        if self.state == PEON and rank == self.leader:
            self.start_election()
        elif self.state == LEADER and rank in self.quorum:
            self.quorum.discard(rank)
            if len(self.quorum) < self._majority():
                self.start_election()
