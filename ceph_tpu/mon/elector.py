"""Monitor leader election (classic strategy).

Analog of src/mon/Elector.h + ElectionLogic.cc's CLASSIC mode: the
lowest-ranked monitor that can reach a majority wins.  Epochs are odd
while electing and even when stable (ElectionLogic::bump_epoch
semantics); every PROPOSE carries the proposer's epoch so stale rounds
are ignored, DEFER (ack) goes to the lowest-ranked proposer seen this
round, and a proposer declares VICTORY once a majority (including
itself) has deferred.  Losing contact with the leader (or a victory
timeout) restarts the election with a bumped epoch.
"""

from __future__ import annotations

import asyncio

ELECTING = "electing"
LEADER = "leader"
PEON = "peon"

PROPOSE = "propose"
DEFER = "defer"
VICTORY = "victory"


class Elector:
    def __init__(self, mon, timeout: float = 2.0):
        self.mon = mon                  # Monitor: rank, peers, send
        self.timeout = timeout
        self.stopped = False
        self.epoch = 1
        self.state = ELECTING
        self.leader: int | None = None
        self.quorum: set[int] = set()
        self.deferred_to: int | None = None
        self._defers: set[int] = set()
        self._timer: asyncio.TimerHandle | None = None

    # -- helpers -----------------------------------------------------------

    def _majority(self) -> int:
        return len(self.mon.monmap) // 2 + 1

    def _bump(self, to_epoch: int | None = None, electing=True) -> None:
        e = max(self.epoch + 1, to_epoch or 0)
        if electing and e % 2 == 0:
            e += 1
        if not electing and e % 2 == 1:
            e += 1
        self.epoch = e

    def _arm_timer(self) -> None:
        self._cancel_timer()
        loop = asyncio.get_event_loop()
        self._timer = loop.call_later(self.timeout, self._on_timeout)

    def _cancel_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    # -- rounds ------------------------------------------------------------

    def stop(self) -> None:
        """Shutdown: a dead monitor must not keep proposing (a zombie
        lowest-rank proposer would collect defers it can never see and
        livelock the survivors)."""
        self.stopped = True
        self._cancel_timer()

    def note_leader_alive(self) -> None:
        """Peon liveness watchdog: each lease receipt re-arms a timer;
        if leases stop (a wedged-but-connected leader that never
        triggers peer_lost), the timeout forces a new election."""
        if self.state == PEON and not self.stopped:
            self._cancel_timer()
            loop = asyncio.get_event_loop()
            self._timer = loop.call_later(3 * self.timeout,
                                          self._on_timeout)

    def start_election(self) -> None:
        if self.stopped:
            return
        self._bump(electing=True)
        self.state = ELECTING
        self.leader = None
        self.quorum = set()
        self.deferred_to = self.mon.rank
        self._defers = {self.mon.rank}
        self.mon.ctx.log.debug(
            "mon", "%s election epoch %d: proposing"
            % (self.mon.name, self.epoch))
        self.mon.send_election(PROPOSE, self.epoch)
        self._arm_timer()
        self._maybe_win()

    def _on_timeout(self) -> None:
        if self.stopped:
            return
        if self.state == ELECTING:
            self.start_election()
        elif self.state == PEON and self.leader is not None:
            # leader lease lapsed: force a new round
            self.start_election()

    def _maybe_win(self) -> None:
        if (self.state == ELECTING
                and self.deferred_to == self.mon.rank
                and len(self._defers) >= self._majority()):
            self._declare_victory()

    def _declare_victory(self) -> None:
        self._bump(electing=False)
        self.state = LEADER
        self.leader = self.mon.rank
        self.quorum = set(self._defers)
        self._cancel_timer()
        self.mon.ctx.log.info(
            "mon", "%s won election epoch %d quorum %s"
            % (self.mon.name, self.epoch, sorted(self.quorum)))
        self.mon.send_election(VICTORY, self.epoch,
                               quorum=sorted(self.quorum))
        self.mon.on_win(self.epoch, self.quorum)

    # -- message handlers ---------------------------------------------------

    def handle(self, src_rank: int, op: str, epoch: int,
               quorum=None) -> None:
        if op == PROPOSE:
            if epoch < self.epoch and self.state != ELECTING:
                # stale proposer (e.g. rejoining): poke it to catch up
                # by starting a fresh round it will see
                self.start_election()
                return
            if epoch > self.epoch:
                # a fresh round supersedes any stale defer state —
                # keeping it would suppress re-proposing and block
                # defers to higher-ranked proposers at the new epoch
                self.epoch = epoch if epoch % 2 else epoch + 1
                self.state = ELECTING
                self.deferred_to = None
                self._defers = set()
            if self.state != ELECTING:
                return
            if src_rank < self.mon.rank:
                # defer to the better-ranked proposer
                if self.deferred_to is None \
                        or src_rank <= self.deferred_to:
                    self.deferred_to = src_rank
                    self.mon.send_election(DEFER, self.epoch,
                                           to_rank=src_rank)
                    self._arm_timer()
            else:
                # outrank them: (re)propose ourselves — but only if we
                # have not already deferred this round (deferred_to is
                # either None, our own rank, or a better rank we acked;
                # ElectionLogic ignores worse-ranked proposals after
                # acking a better one — revoking the defer could hand
                # two proposers disjoint majorities in the same epoch)
                if self.deferred_to is None:
                    self.deferred_to = self.mon.rank
                    self._defers = {self.mon.rank}
                    self.mon.send_election(PROPOSE, self.epoch)
                    self._arm_timer()
        elif op == DEFER:
            if epoch != self.epoch or self.state != ELECTING:
                return
            if self.deferred_to == self.mon.rank:
                self._defers.add(src_rank)
                self._maybe_win()
        elif op == VICTORY:
            if epoch < self.epoch:
                return
            self.epoch = epoch
            self.state = PEON
            self.leader = src_rank
            self.quorum = set(quorum or [])
            self._cancel_timer()
            self.mon.ctx.log.info(
                "mon", "%s: mon.%d leads epoch %d"
                % (self.mon.name, src_rank, epoch))
            self.note_leader_alive()
            self.mon.on_lose(src_rank, self.epoch)

    def peer_lost(self, rank: int) -> None:
        """A quorum member became unreachable: re-elect if it matters
        (the leader died, or we are the leader and lost majority)."""
        if self.state == PEON and rank == self.leader:
            self.start_election()
        elif self.state == LEADER and rank in self.quorum:
            self.quorum.discard(rank)
            if len(self.quorum) < self._majority():
                self.start_election()
