"""Monitor: the cluster control plane (map authority).

Analog of src/mon/Monitor.cc + OSDMonitor.cc as one asyncio daemon:
the authoritative OSDMap evolves only through Incrementals committed
via the Paxos log (PaxosService::propose_pending pattern), and every
committed epoch is pushed to subscribers (clients and OSDs follow maps,
never each other).

Implemented service logic (OSDMonitor):
* boot      — MOSDBoot marks the osd EXISTS|UP at its addr and adds it
              to the default CRUSH root (OSDMonitor::preprocess_boot).
* failure   — MOSDFailure reports gated by reporter count + grace
              (OSDMonitor::check_failure, mon/OSDMonitor.cc:3171),
              then the osd is marked down in a new epoch.
* auto-out  — down for mon_osd_down_out_interval -> weight 0
              (OSDMonitor::tick, "will mark out" flow).
* pools     — create/rm/set replicated and erasure pools; erasure
              profiles live in the map (OSDMap::erasure_code_profiles).
* commands  — MMonCommand dict protocol ("osd pool create", "status",
              "osd out/in/down", "osd dump" ...), the mon CLI surface.

Map persistence: every commit stores the Incremental in the paxos log
and the full map at osdmap:full:<epoch> (OSDMonitor's full/inc dual
storage), so a restarted monitor resumes at its last epoch.
"""

from __future__ import annotations

import asyncio
import time

from ..models.crushmap import (CHOOSE_FIRSTN, CHOOSE_INDEP, EMIT, STRAW2,
                               TAKE, CrushMap)
from ..msg import Messenger
from ..msg.messenger import ms_compress_from_conf
from ..msg.messages import (MMonCommand, MMonCommandAck, MMonElection,
                            MMonGetMap, MMonPaxos, MMonSubscribe,
                            MOSDAlive, MOSDBoot, MOSDFailure,
                            MOSDMapMsg, MOSDOp)
from ..osd.osdmap import (CEPH_OSD_OUT, OSD_EXISTS, OSD_UP,
                          POOL_TYPE_ERASURE, POOL_TYPE_REPLICATED,
                          Incremental, OSDMap, PGPool)
from ..store.kv import KeyValueDB, MemKV
from ..utils import denc
from ..utils.context import Context
from .elector import LEADER, Elector
from .paxos import MultiPaxos, Paxos

DEFAULT_EC_PROFILE = {"plugin": "jerasure", "k": "2", "m": "1",
                      "technique": "reed_sol_van"}


class FailureReport:
    __slots__ = ("first", "last", "failed_for")

    def __init__(self, now: float, failed_for: float):
        self.first = now
        self.last = now
        self.failed_for = failed_for


class Monitor:
    """One monitor daemon.  monmap is the fixed list of
    (name, "host:port") pairs defining ranks (MonMap.h: rank = index);
    a single-entry (or omitted) monmap runs the synchronous
    quorum-of-one paxos, a larger one runs the full
    collect/begin/accept/commit/lease exchange with elections."""

    def __init__(self, ctx: Context | None = None, name: str = "mon.0",
                 store: KeyValueDB | None = None, fsid: str = "tpu",
                 monmap: list[tuple[str, str]] | None = None):
        self.ctx = ctx or Context("mon")
        self.name = name
        self.fsid = fsid
        self.store = store or MemKV()
        self.store.open()
        self.monmap = monmap or [(name, "")]
        self.rank = next((i for i, (n, _a) in enumerate(self.monmap)
                          if n == name), 0)
        self.paxos = Paxos(self.store, rank=self.rank)
        self.multi = len(self.monmap) > 1
        if self.multi:
            strategy = self.ctx.conf["mon_election_strategy"]
            disallowed = self._parse_disallowed(
                self.ctx.conf["mon_disallowed_leaders"])
            if strategy == "classic" and disallowed:
                # classic ignores the disallow list (reference
                # behavior; the option documents its scope) — honor
                # that rather than silently barring leaders
                self.ctx.log.info(
                    "mon", "mon_disallowed_leaders ignored under the"
                    " classic election strategy")
                disallowed = set()
            self.elector = Elector(self, strategy=strategy,
                                   disallowed=disallowed)
        else:
            self.elector = None
        self.mpaxos = (MultiPaxos(self, self.paxos) if self.multi
                       else None)
        self._proposal_wake = asyncio.Event() if self.multi else None
        self._proposal_waiters: list = []
        self._last_proposal = None
        from ..msg.auth import AuthContext
        self.msgr = Messenger(
            name, auth=AuthContext.from_conf(self.ctx.conf),
            compress=ms_compress_from_conf(self.ctx.conf))
        self.msgr.add_dispatcher(self)
        self.osdmap = OSDMap()
        self.osdmap.fsid = fsid
        self.pending_inc: Incremental | None = None
        # conn -> epoch already sent (subscription state)
        self.subscribers: dict = {}
        # proposal batch window state (scale plane): boot storms and
        # clog appends fold into one proposal per window instead of
        # one commit (+ full-map encode) per message
        self._batch_flush_scheduled = False
        # crush membership caches: committed root items (invalidated
        # when the crush object changes) + the pending map's additions
        self._crush_set: set[int] = set()
        self._crush_set_src = None
        self._pending_crush_set: set[int] = set()
        # map-publication traffic counters (the late-joiner test and
        # `bench --scale` publication-cost figure)
        self.full_maps_sent = 0
        self.inc_epochs_sent = 0
        # target osd -> reporter osd -> FailureReport
        self.failure_info: dict[int, dict[int, FailureReport]] = {}
        self.down_pending_out: dict[int, float] = {}
        # osd -> (slow_op_count, monotonic stamp) from MOSDBeacons:
        # derived soft state every mon keeps; the LEADER additionally
        # commits transitions into the health service's paxos state so
        # a freshly elected leader reports SLOW_OPS / DEVICE_FALLBACK
        # immediately instead of waiting one beacon round (PR-2 gap)
        self.osd_slow_ops: dict[int, tuple[int, float]] = {}
        # osd -> ({tenant: slow count}, monotonic stamp): the
        # per-tenant slice of the slow counts (SLOW_OPS detail names
        # the worst tenant from it)
        self.osd_slow_tenants: dict[int, tuple[dict, float]] = {}
        # osd -> (device_fallback flag, monotonic stamp)
        self.osd_device_fallback: dict[int, tuple[int, float]] = {}
        # osd -> (beacon net slice {"rtt_ms": {peer: ms},
        # "slow": [peers]}, monotonic stamp): the heartbeat RTT view
        # behind OSD_SLOW_PING_TIME and `net status`; the leader
        # commits pair-list transitions into the health svc state
        self.osd_net: dict[int, tuple[dict, float]] = {}
        # latest PGMap digest from the mgr (MMonMgrDigest): soft state
        # every mon keeps (broadcast like beacons); feeds status/df/
        # pool-stats and the PG_DEGRADED / PG_AVAILABILITY checks; the
        # leader commits raise/clear edges into the health svc state
        self.mgr_digest: dict | None = None
        self.mgr_digest_stamp = 0.0
        # mon-side op tracking (MMonCommand requests)
        from ..trace import LogClient, OpTracker
        self.optracker = OpTracker(self.ctx, name)
        # the mon's own cluster-log handle: boot/mark-down/auto-out
        # and health-edge events ride the same seq/ack/resend path as
        # every other daemon's clog (a peon forwards to the leader)
        self.clog = LogClient(self.ctx, name,
                              send_fn=self._clog_send)
        # who -> conn that last delivered its MLog / MCrashReport:
        # the ack route back once the paxos commit applies here
        self._log_ack_routes: dict = {}
        self._crash_ack_routes: dict = {}
        self._tick_task = None
        # PaxosService quintet (ConfigMonitor/AuthMonitor/
        # HealthMonitor/LogMonitor/CrashMonitor analogs): their
        # mutations ride the same paxos stream as map changes via
        # pending_svc
        from .services import (AuthMonitor, ConfigMonitor,
                               CrashMonitor, EventMonitor,
                               HealthMonitor, LogMonitor)

        self.config_mon = ConfigMonitor(self)
        self.auth_mon = AuthMonitor(self)
        self.health_mon = HealthMonitor(self)
        self.log_mon = LogMonitor(self)
        self.crash_mon = CrashMonitor(self)
        self.event_mon = EventMonitor(self)
        self.pending_svc: dict[str, list] = {}
        # event-bus subscribers: conn -> last seq sent (each mon
        # serves ITS subscribers from the replicated event log)
        self.event_subs: dict = {}
        # leader-side progress-row memory: digest key -> last
        # fraction, the edge detector behind progress_start/finish
        # events (soft state — a new leader re-announces in-flight
        # flows, which a cursor dedups by seq, not by content)
        self._progress_seen: dict = {}
        # mon-side history rings: every mon folds each arriving mgr
        # digest into its own store and serves `perf history` locally
        # — no mon<->mgr query protocol, survives leader elections,
        # and a dead mgr leaves explicit bucket gaps
        from ..mgr.history import HistoryStore
        self.history = HistoryStore(self.ctx)
        # service state loads BEFORE _load(): crash recovery replays
        # a pending blob through the same apply path, which rewrites
        # the persisted service images — replaying onto empty dicts
        # would erase everything but the replayed ops
        self.config_mon.load()
        self.auth_mon.load()
        self.log_mon.load()
        self.health_mon.load()
        self.crash_mon.load()
        self.event_mon.load()
        self._load()

    def _parse_disallowed(self, raw: str) -> set[int]:
        """mon_disallowed_leaders accepts ranks or monitor names;
        unknown tokens are ignored with a warning (a typo must not
        stop the daemon), but barring EVERY rank is a configuration
        that can never form a quorum and is rejected outright."""
        out: set[int] = set()
        names = {n: i for i, (n, _a) in enumerate(self.monmap)}
        for tok in (raw or "").split(","):
            tok = tok.strip()
            if not tok:
                continue
            if tok in names:
                out.add(names[tok])
            else:
                try:
                    out.add(int(tok))
                except ValueError:
                    self.ctx.log.info(
                        "mon", "ignoring unknown disallowed leader"
                        " %r" % tok)
        if out >= set(range(len(self.monmap))):
            raise ValueError(
                "mon_disallowed_leaders bars every rank: no quorum"
                " could ever form")
        return out

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        raw = self.store.get(b"osdmap:last_epoch")
        if raw is not None:
            epoch = denc.decode(raw)
            full = self.store.get(b"osdmap:full:%016d" % epoch)
            if full is not None:
                self.osdmap = OSDMap.decode(full)
        # a crash between paxos commit and map apply leaves a committed
        # blob the map never reflected: recover() replays it through
        # the same apply+persist path as a live commit.  Quorum-of-one
        # only: in a multi-mon cluster a locally-pending value may
        # never have been chosen — it must go through leader_collect's
        # OP_LAST exchange, not be self-committed.
        self.paxos.on_commit.append(self._on_paxos_commit)
        if not self.multi:
            self.paxos.recover()

    def _on_paxos_commit(self, version: int, blob: bytes) -> None:
        payload = denc.decode(blob)
        svc = payload.get("svc") or {}
        if svc:
            # service mutations apply on EVERY monitor (leader, peons,
            # recovery replay) in one KV transaction
            tx = self.store.get_transaction()
            if svc.get("config"):
                self.config_mon.apply(svc["config"], tx)
            if svc.get("auth"):
                self.auth_mon.apply(svc["auth"], tx)
            if svc.get("log"):
                self.log_mon.apply(svc["log"], tx)
            if svc.get("health"):
                self.health_mon.apply(svc["health"], tx)
            if svc.get("crash"):
                self.crash_mon.apply(svc["crash"], tx)
            if svc.get("events"):
                self.event_mon.apply(svc["events"], tx)
            self.store.submit_transaction(tx)
            # committed events fan out from EVERY mon to its own
            # watch-events subscribers (seqs are identical cluster-
            # wide, so a client that re-subscribes elsewhere after an
            # election resumes its cursor without gaps or dups)
            if svc.get("events"):
                self._push_events()
            if svc.get("config"):
                self.config_mon.push_all()
            # committed = durable on a quorum: ack clog entries and
            # crash reports back to their senders (every mon applies
            # the commit; whichever holds the sender's conn acks)
            if svc.get("log"):
                self._ack_log_commit(svc["log"])
            if svc.get("crash"):
                self._ack_crash_commit(svc["crash"])
        inc_d = payload.get("osdmap_inc")
        if inc_d is None:
            return
        inc = Incremental.from_dict(inc_d)
        if inc.epoch != self.osdmap.epoch + 1:
            return  # already reflected in the stored full map
        self.osdmap.apply_incremental(inc)
        self._store_map(inc)
        self._publish()   # peons push replicated epochs to their subs

    def _store_map(self, inc: Incremental) -> None:
        tx = self.store.get_transaction()
        tx.set(b"osdmap:inc:%016d" % inc.epoch, inc.encode())
        tx.set(b"osdmap:full:%016d" % self.osdmap.epoch,
               self.osdmap.encode())
        tx.set(b"osdmap:last_epoch", denc.encode(self.osdmap.epoch))
        self.store.submit_transaction(tx)

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1",
                    port: int = 0) -> str:
        if self.multi:
            maddr = self.monmap[self.rank][1]
            host, p = maddr.rsplit(":", 1)
            port = int(p)
        addr = await self.msgr.bind(host, port)
        self._tick_task = self.msgr.spawn(self._tick_loop())
        if self.multi:
            self.msgr.spawn(self._proposal_loop())
            self.elector.start_election()
        self.ctx.log.info("mon", "%s serving at %s epoch %d"
                          % (self.name, addr, self.osdmap.epoch))
        return addr

    async def shutdown(self) -> None:
        if self.elector is not None:
            self.elector.stop()
        await self.msgr.shutdown()
        self.store.close()

    @property
    def addr(self) -> str:
        return self.msgr.addr

    # -- quorum plumbing (election + paxos transport) ----------------------

    def is_leader(self) -> bool:
        return (not self.multi) or self.elector.state == LEADER

    def quorum_ranks(self) -> list[int]:
        return list(range(len(self.monmap)))

    def _rank_addr(self, rank: int) -> str:
        return self.monmap[rank][1]

    def send_election(self, op: str, epoch: int, to_rank=None,
                      quorum=None) -> None:
        from .elector import CONNECTIVITY

        scores = (self.elector.tracker.wire()
                  if self.elector.strategy == CONNECTIVITY else None)
        msg = MMonElection(op=op, epoch=epoch, rank=self.rank,
                           quorum=quorum, scores=scores)
        targets = ([to_rank] if to_rank is not None else
                   [r for r in self.quorum_ranks() if r != self.rank])
        for r in targets:
            self.msgr.send_to(self._rank_addr(r), msg,
                              entity_hint="mon.%d" % r)

    def send_paxos(self, rank: int, op: str, **fields) -> None:
        epoch = self.elector.epoch if self.elector is not None else 0
        self.msgr.send_to(
            self._rank_addr(rank),
            MMonPaxos(op=op, rank=self.rank, epoch=epoch, **fields),
            entity_hint="mon.%d" % rank)

    def request_catchup(self, rank: int) -> None:
        self.send_paxos(rank, "catchup",
                        last_committed=self.paxos.last_committed)

    def on_win(self, epoch: int, quorum: set[int]) -> None:
        async def lead():
            try:
                await self.mpaxos.leader_collect(reign_epoch=epoch)
            except (IOError, asyncio.TimeoutError) as e:
                self.mpaxos.active = False
                if "reign superseded" in str(e):
                    # a newer election already ran while this reign's
                    # collect waited: its winner recovers; another
                    # election here would only churn
                    return
                self.ctx.log.info("mon", "%s collect failed: %s"
                                  % (self.name, e))
                self.elector.start_election()
                return
            self._publish()
            self._proposal_wake.set()

        self.msgr.spawn(lead())

    def on_lose(self, leader: int, epoch: int) -> None:
        self.mpaxos.active = False

    def readable(self) -> bool:
        """Consistent reads require leadership or a live lease
        (Paxos.h lease semantics) — a partitioned minority refuses."""
        if not self.multi:
            return True
        if self.is_leader():
            return self.mpaxos.active
        return self.mpaxos.lease_valid()

    # -- pending incremental / commit -------------------------------------

    def _pending(self) -> Incremental:
        if self.pending_inc is None:
            self.pending_inc = self.osdmap.new_incremental()
        return self.pending_inc

    def queue_svc_op(self, svc: str, op: tuple) -> None:
        """Stage a service mutation (config/auth/log) for the next
        paxos round (PaxosService pending analog).  Rides the batch
        window: a boot storm's clog appends fold into the same few
        commits as the boots themselves."""
        self.pending_svc.setdefault(svc, []).append(list(op))
        self._propose_soon()

    def _propose_soon(self) -> None:
        """Commit the pending state — now, or after the configured
        batch window (mon_propose_batch_window) so storm-prone
        fire-and-forget mutations (MOSDBoot floods at shell-cluster
        scale) fold into a handful of epochs instead of paying one
        paxos commit + full-map encode each.  Multi-mon mode already
        serializes through the proposal loop (its in-flight round IS
        the batch window); commands keep calling _propose_pending
        directly, so their synchronous-ack contract is unchanged."""
        window = float(self.ctx.conf.get("mon_propose_batch_window",
                                         0.0) or 0.0)
        if window <= 0 or self.multi:
            self._propose_pending()
            return
        if self._batch_flush_scheduled:
            return
        self._batch_flush_scheduled = True

        async def flush() -> None:
            try:
                await asyncio.sleep(window)
            finally:
                self._batch_flush_scheduled = False
            self._propose_pending()

        self.msgr.spawn(flush())

    def _take_svc(self) -> dict:
        svc, self.pending_svc = self.pending_svc, {}
        return svc

    def _propose_pending(self) -> None:
        """PaxosService::propose_pending: commit the pending Incremental
        and/or service ops through paxos, apply, persist, publish.
        Multi-mon: wake the serialized proposal loop (a second mutation
        arriving while a round is in flight folds into the next
        pending proposal)."""
        if self.multi:
            if self.pending_inc is not None or self.pending_svc:
                fut = asyncio.get_event_loop().create_future()
                self._proposal_waiters.append(fut)
                self._last_proposal = fut
                self._proposal_wake.set()
            return
        inc = self.pending_inc
        svc = self._take_svc()
        if inc is None and not svc:
            return
        self.pending_inc = None
        payload: dict = {}
        if inc is not None:
            payload["osdmap_inc"] = inc.to_dict()
        if svc:
            payload["svc"] = svc
        # the on_commit hook applies the payload to the map/services
        # and persists (same path live and during crash recovery)
        self.paxos.propose(denc.encode(payload))
        self.ctx.log.debug("mon", "committed epoch %d"
                           % self.osdmap.epoch)
        if inc is not None:
            self._publish()

    async def _proposal_loop(self) -> None:
        """Leader-side serialized proposer: one paxos round in flight;
        the pending Incremental is re-stamped against the current map
        just before encoding (mutations that landed during the
        previous round fold into one epoch)."""
        while True:
            await self._proposal_wake.wait()
            self._proposal_wake.clear()
            if self.pending_inc is None and not self.pending_svc:
                continue
            if not (self.is_leader() and self.mpaxos.active):
                continue    # re-woken after the next election win
            inc = self.pending_inc
            waiters = self._proposal_waiters
            self.pending_inc = None
            self._proposal_waiters = []
            payload: dict = {}
            if inc is not None:
                inc.epoch = self.osdmap.epoch + 1
                payload["osdmap_inc"] = inc.to_dict()
            svc = self._take_svc()
            if svc:
                payload["svc"] = svc
            blob = denc.encode(payload)
            try:
                await self.mpaxos.propose(blob)
            except (IOError, asyncio.TimeoutError) as e:
                self.ctx.log.info("mon", "%s proposal failed: %s"
                                  % (self.name, e))
                for w in waiters:
                    if not w.done():
                        w.set_exception(IOError("no quorum"))
                self.elector.start_election()
                continue
            for w in waiters:
                if not w.done():
                    w.set_result(None)
            self.ctx.log.debug("mon", "committed epoch %d"
                               % self.osdmap.epoch)
            self._publish()

    def _publish(self) -> None:
        """Push incrementals to every subscriber past its known epoch.
        The store reads are memoized per distinct `have` — at shell-
        cluster scale most of the fleet sits at the same epoch, so one
        commit's fan-out does O(distinct epochs) store walks, not
        O(subscribers)."""
        memo: dict[int, list[bytes]] = {}
        for conn, have in list(self.subscribers.items()):
            if not conn.is_open:
                del self.subscribers[conn]
                continue
            if have >= self.osdmap.epoch:
                continue
            incs = memo.get(have)
            if incs is None:
                incs = memo[have] = self._collect_incs(have)
            conn.send(MOSDMapMsg(fsid=self.fsid, full=None,
                                 incrementals=incs))
            self.inc_epochs_sent += len(incs)
            self.subscribers[conn] = self.osdmap.epoch

    def _collect_incs(self, have: int) -> list[bytes]:
        out = []
        for e in range(have + 1, self.osdmap.epoch + 1):
            raw = self.store.get(b"osdmap:inc:%016d" % e)
            if raw is None:
                return []  # gap: caller falls back to full map
            out.append(raw)
        return out

    # -- event bus (EventMonitor fan-out) ----------------------------------

    def emit_event(self, etype: str, message: str,
                   data: dict | None = None) -> None:
        """Stage one cluster event for the paxos-committed event log
        (leader-only; EventMonitor.emit guards).  The single funnel
        every emission site — health edges, boots, mark-downs,
        progress transitions — goes through."""
        self.event_mon.emit(etype, message, data=data)

    def _push_events(self) -> None:
        """Incremental fan-out after an events commit: each
        subscriber gets exactly the committed rows past its cursor."""
        from ..msg.messages import MMonEvents
        for conn, have in list(self.event_subs.items()):
            if not conn.is_open:
                del self.event_subs[conn]
                continue
            rows = self.event_mon.after(have)
            if not rows:
                continue
            conn.send(MMonEvents(events=rows,
                                 last_seq=self.event_mon.last_seq))
            self.event_subs[conn] = int(rows[-1]["seq"])

    def _diff_progress(self, progress: dict) -> None:
        """Leader-side edge detector over the digest's progress rows:
        a new key emits progress_start, reaching 1.0 (or vanishing
        short of it — daemon died, rows pruned) emits
        progress_finish.  Exactly one finish per flow: a row that
        lingers at 1.0 until the osd prunes it stays silent."""
        seen = self._progress_seen
        for key, row in progress.items():
            frac = float(row.get("fraction") or 0.0)
            prev = seen.get(key)
            if prev is None:
                self.emit_event(
                    "progress_start", "%s %s started"
                    % (row.get("kind"), key),
                    data={"key": key, "kind": row.get("kind")})
                seen[key] = frac
                if frac >= 1.0:
                    # the flow ran start-to-finish between two
                    # digests: the bar never showed partial progress,
                    # but the start/finish pair still must
                    self.emit_event(
                        "progress_finish", "%s %s complete"
                        % (row.get("kind"), key),
                        data={"key": key, "kind": row.get("kind"),
                              "fraction": 1.0})
            elif prev < 1.0 and frac >= 1.0:
                self.emit_event(
                    "progress_finish", "%s %s complete"
                    % (row.get("kind"), key),
                    data={"key": key, "kind": row.get("kind"),
                          "fraction": 1.0})
                seen[key] = frac
            else:
                seen[key] = max(prev, frac)
        for key in [k for k in seen if k not in progress]:
            if seen[key] < 1.0:
                self.emit_event(
                    "progress_finish", "%s ended at %d%%"
                    % (key, int(seen[key] * 100)),
                    data={"key": key,
                          "fraction": round(seen[key], 4)})
            del seen[key]

    def _send_map(self, conn, have: int = -1) -> None:
        if 0 <= have < self.osdmap.epoch:
            # bounded incremental catch-up: a subscriber a few epochs
            # behind gets the contiguous delta, but one N epochs back
            # (a late joiner against a long history) gets ONE full
            # map — shipping the whole incremental history would cost
            # O(history) wire per fresh subscriber at scale
            cap = int(self.ctx.conf.get("mon_map_catchup_max", 64))
            if self.osdmap.epoch - have <= cap:
                incs = self._collect_incs(have)
                if incs:
                    conn.send(MOSDMapMsg(fsid=self.fsid, full=None,
                                         incrementals=incs))
                    self.inc_epochs_sent += len(incs)
                    return
        conn.send(MOSDMapMsg(fsid=self.fsid, full=self.osdmap.encode(),
                             incrementals=[]))
        self.full_maps_sent += 1

    # -- dispatch ----------------------------------------------------------

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MMonElection):
            if self.elector is not None:
                self.elector.handle(msg.rank, msg.op, msg.epoch,
                                    msg.quorum,
                                    getattr(msg, "scores", None))
            return True
        if isinstance(msg, MMonPaxos):
            if self.elector is not None:
                self.elector.tracker.saw(msg.rank)
            if self.mpaxos is not None:
                self.mpaxos.handle(msg.rank, msg.op, {
                    f: getattr(msg, f)
                    for f in ("pn", "version", "blob",
                              "last_committed", "first_committed",
                              "lease_until", "uncommitted", "epoch",
                              "accepted_pn")})
            return True
        from ..msg.messages import (MCrashReport, MLog, MLogAck,
                                    MMonMgrDigest, MMonWatchEvents,
                                    MOSDBeacon, MOSDPGTemp)
        if isinstance(msg, MMonWatchEvents):
            # watch-events subscription (subscribe AND cursor renewal
            # both land here): record the client's cursor and serve
            # any committed backlog past it immediately
            self.event_subs[conn] = int(msg.start or 0)
            rows = self.event_mon.after(int(msg.start or 0))
            if rows:
                from ..msg.messages import MMonEvents
                conn.send(MMonEvents(
                    events=rows, last_seq=self.event_mon.last_seq))
                self.event_subs[conn] = int(rows[-1]["seq"])
            return True
        if isinstance(msg, MLog):
            self._handle_log(conn, msg.entries or [])
            return True
        if isinstance(msg, MLogAck):
            # ack for entries this (peon) mon forwarded to the leader
            self.clog.handle_ack(msg.who, int(msg.last or 0),
                                 inc=getattr(msg, "inc", None))
            return True
        if isinstance(msg, MCrashReport):
            self._handle_crash_report(conn, msg.reports or [])
            return True
        if isinstance(msg, MMonMgrDigest):
            self.mgr_digest = msg.digest or {}
            self.mgr_digest_stamp = time.monotonic()
            # EVERY mon folds the digest into its local history rings
            # (wall clock keys the buckets — a dead mgr leaves a hole,
            # and whichever mon serves `perf history` has the data)
            self.history.ingest(time.time(), self.mgr_digest)
            if self.is_leader() and \
                    (not self.multi or self.mpaxos.active):
                totals = self.mgr_digest.get("totals") or {}
                self.health_mon.maybe_commit_digest(
                    int(totals.get("degraded") or 0),
                    int(self.mgr_digest.get("inactive_pgs") or 0),
                    scrub_errors=int(
                        totals.get("scrub_errors") or 0),
                    damaged_pgs=int(
                        self.mgr_digest.get("inconsistent_pgs")
                        or 0))
                # tenant SLO edges: commit the violating-tenant sets
                # so SLO_LATENCY/SLO_BURN survive a leader change
                slo = self.mgr_digest.get("slo") or {}
                self.health_mon.maybe_commit_slo(
                    [t for t, v in slo.items()
                     if v.get("latency_violation")],
                    [t for t, v in slo.items()
                     if v.get("burn_alert")])
                # history-plane anomaly edges: commit the shifted
                # series names so PERF_ANOMALY survives elections
                self.health_mon.maybe_commit_anomaly(
                    self.mgr_digest.get("anomalies") or {})
                # progress-row edges -> progress_start/finish events
                self._diff_progress(
                    self.mgr_digest.get("progress") or {})
            return True
        if isinstance(msg, MOSDBeacon):
            # beacons are derived soft state: EVERY mon records them,
            # so whichever mon leads next already holds the picture —
            # and the current LEADER commits transitions into the
            # health service's replicated state, so even a mon that
            # never saw a beacon (fresh boot, healed partition)
            # reports the warnings immediately on election
            now = time.monotonic()
            slow = int(msg.slow_ops or 0)
            # device-fallback state is chip-encoded: 0 = on-device,
            # 1+chip = that mesh chip lost (the health detail names
            # it; an old beacon without the field reads as chip 0)
            flb = int(msg.device_fallback or 0)
            if flb:
                flb = 1 + int(getattr(msg, "device_chip", 0) or 0)
            self.osd_slow_ops[msg.osd] = (slow, now)
            # per-tenant slice (SLOW_OPS worst-tenant detail); soft
            # state only — the committed count covers fresh leaders
            self.osd_slow_tenants[msg.osd] = (
                dict(getattr(msg, "slow_tenants", None) or {}), now)
            self.osd_device_fallback[msg.osd] = (flb, now)
            # heartbeat-RTT slice (the network plane): soft state on
            # every mon; the leader commits slow-pair transitions so
            # OSD_SLOW_PING_TIME survives elections.  Legacy beacons
            # carry no net field and simply leave the matrix sparse.
            self.osd_net[msg.osd] = (
                dict(getattr(msg, "net", None) or {}), now)
            if self.is_leader() and \
                    (not self.multi or self.mpaxos.active):
                self.health_mon.maybe_commit(msg.osd, slow, flb)
                self.health_mon.maybe_commit_slow_ping(
                    self._slow_ping_pairs(now))
            return True
        if isinstance(msg, (MOSDBoot, MOSDFailure, MOSDAlive,
                            MOSDPGTemp)) \
                and self.multi and not self.is_leader():
            return True   # OSDs broadcast to every mon; leader acts
        if isinstance(msg, MOSDPGTemp):
            self._handle_pg_temp(msg)
            return True
        if isinstance(msg, MMonGetMap):
            self._send_map(conn, msg.have)
        elif isinstance(msg, MMonSubscribe):
            have = msg.start - 1
            if have < self.osdmap.epoch or have <= 0:
                # behind us (or a fresh session, which must get SOME
                # map back — connect() proves the link by it even on
                # an epoch-0 cluster)
                self._send_map(conn, have)
                self.subscribers[conn] = self.osdmap.epoch
            else:
                # renewal from a subscriber at (or past) our epoch:
                # nothing to send — record ITS epoch so publication
                # resumes from there once we catch up (a lagging
                # ex-partitioned mon must not replay stale epochs)
                self.subscribers[conn] = have
            # centralized config rides the subscription (MConfig on
            # session open, ConfigMonitor::check_sub)
            self.config_mon.push(conn, conn.peer_entity or "client")
        elif isinstance(msg, MOSDBoot):
            self._handle_boot(conn, msg)
        elif isinstance(msg, MOSDFailure):
            self._handle_failure(conn, msg)
        elif isinstance(msg, MOSDAlive):
            self.failure_info.pop(msg.osd, None)
            self._handle_alive_up_thru(msg)
        elif isinstance(msg, MMonCommand):
            self._handle_command(conn, msg)
        else:
            return False
        return True

    def ms_handle_reset(self, conn) -> None:
        self.subscribers.pop(conn, None)
        self.event_subs.pop(conn, None)
        if self.multi and conn.peer_entity.startswith("mon."):
            try:
                rank = int(conn.peer_entity.split(".", 1)[1])
            except ValueError:
                return
            if rank != self.rank:
                self.elector.peer_lost(rank)

    # -- cluster log + crash telemetry (LogClient -> LogMonitor /
    # MCrashReport -> CrashMonitor pipelines) ------------------------------

    def _clog_send(self, msg) -> None:
        """The mon's OWN clog route: the leader commits locally; a
        peon forwards to the leader over the mon-mon link (entries
        stay pending in the LogClient and the tick re-flush retries
        until a leader is known and acks)."""
        if self.is_leader() and (not self.multi
                                 or self.mpaxos.active):
            self._handle_log(None, msg.entries or [])
            return
        leader = (self.elector.leader
                  if self.elector is not None else None)
        if leader is not None and leader != self.rank:
            self.msgr.send_to(self._rank_addr(leader), msg,
                              entity_hint="mon.%d" % leader)

    def _handle_log(self, conn, entries: list) -> None:
        """One daemon's MLog batch: every mon records the ack route;
        only the active leader queues unseen entries through paxos
        (dedup against both the committed last_seq and the not-yet-
        proposed pending queue, so a re-flush racing its own proposal
        stacks nothing)."""
        def key(e) -> tuple[int, int]:
            # dedup key: (boot incarnation, seq) — a wiped-and-reborn
            # daemon's fresh incarnation re-keys its restarted seqs
            return (int(e.get("inc") or 0), int(e.get("seq") or 0))

        by_who: dict[str, list] = {}
        for e in entries:
            who = e.get("who")
            if who:
                by_who.setdefault(who, []).append(e)
        leading = self.is_leader() and (not self.multi
                                        or self.mpaxos.active)
        for who, batch in by_who.items():
            if conn is not None:
                self._log_ack_routes[who] = conn
            committed = self.log_mon.committed_floor(who)
            top = max(key(e) for e in batch)
            if committed >= top:
                # resend raced (or outlived) its ack: re-ack now
                self._send_log_ack(who, committed[1],
                                   inc=committed[0])
                continue
            if not leading:
                continue
            pend = max((key(op[1])
                        for op in self.pending_svc.get("log", [])
                        if op[0] == "append"
                        and op[1].get("who") == who),
                       default=(0, 0))
            base = max(committed, pend)
            for e in sorted(batch, key=key):
                if key(e) > base:
                    self.queue_svc_op("log", ("append", dict(e)))
                    # daemon-originated ERR/WRN entries mirror onto
                    # the event bus (the fresh-entry queue point is
                    # the natural resend dedup).  Mon-self lines stay
                    # off it — their transitions already ride as
                    # dedicated health_edge / osd_* / progress types.
                    if (e.get("level") in ("ERR", "WRN")
                            and who != self.name):
                        self.emit_event(
                            "clog", str(e.get("message", "")),
                            data={"who": who,
                                  "level": e.get("level")})

    def _ack_log_commit(self, ops: list) -> None:
        tops: dict[str, tuple[int, int]] = {}
        for op in ops:
            if op[0] == "append":
                who = op[1].get("who")
                seq = int(op[1].get("seq") or 0)
                inc = int(op[1].get("inc") or 0)
                if who and seq:
                    tops[who] = max(tops.get(who, (0, 0)),
                                    (inc, seq))
        for who, (inc, seq) in tops.items():
            self._send_log_ack(who, seq, inc=inc)

    def _send_log_ack(self, who: str, last: int,
                      inc: int = 0) -> None:
        from ..msg.messages import MLogAck
        if who == self.name:
            self.clog.handle_ack(who, last, inc=inc)
            return
        conn = self._log_ack_routes.get(who)
        if conn is not None and conn.is_open:
            conn.send(MLogAck(who=who, last=last, inc=inc))

    def _handle_crash_report(self, conn, reports: list) -> None:
        """Pending crash reports from a rebooted daemon: ack ids the
        committed table already holds (the resend path), and — on the
        leader — commit unseen ones plus the cluster-log event that
        makes the crash operator-visible in `log last`."""
        from ..msg.messages import MCrashReportAck
        known: list[str] = []
        fresh: list[dict] = []
        pend = {op[1].get("crash_id")
                for op in self.pending_svc.get("crash", [])
                if op[0] == "add"}
        for r in reports:
            cid = r.get("crash_id")
            if not cid:
                continue
            if conn is not None:
                self._crash_ack_routes[cid] = conn
            if cid in self.crash_mon.reports:
                known.append(cid)
            elif cid not in pend:
                fresh.append(r)
        if known and conn is not None and conn.is_open:
            conn.send(MCrashReportAck(crash_ids=known))
        if not (self.is_leader()
                and (not self.multi or self.mpaxos.active)):
            return
        for r in fresh:
            self.queue_svc_op("crash", ("add", dict(r)))
            self.log_mon.append(
                "WRN", "daemon %s crashed: %s: %s (crash id %s)"
                % (r.get("entity"), r.get("exc_type"),
                   r.get("exc_msg"), r.get("crash_id")))
        if fresh:
            # commit-time retention sweep rides the same proposal
            self.crash_mon.maybe_prune()

    def _ack_crash_commit(self, ops: list) -> None:
        from ..msg.messages import MCrashReportAck
        by_conn: dict = {}
        for op in ops:
            if op[0] != "add":
                continue
            cid = op[1].get("crash_id")
            conn = self._crash_ack_routes.pop(cid, None)
            if conn is not None and conn.is_open:
                by_conn.setdefault(id(conn), (conn, []))[1].append(cid)
        for conn, cids in by_conn.values():
            conn.send(MCrashReportAck(crash_ids=cids))

    def _handle_pg_temp(self, msg) -> None:
        """OSDMonitor::prepare_pgtemp: commit requested pg_temp
        mappings (a primary pinning the previous acting set while
        backfill runs) and clears (backfill done)."""
        from ..osd.osdmap import pg_t
        changed = False
        for pool, ps, want in (msg.pgs or []):
            pgid = pg_t(int(pool), int(ps))
            want = [int(o) for o in (want or [])]
            cur = self.osdmap.pg_temp.get(pgid, [])
            pend = (self.pending_inc.new_pg_temp.get(pgid)
                    if self.pending_inc is not None else None)
            now = pend if pend is not None else cur
            if list(now) == want:
                continue
            self._pending().new_pg_temp[pgid] = want
            changed = True
        if changed:
            self._propose_pending()

    # -- boot --------------------------------------------------------------

    def _handle_boot(self, conn, msg: MOSDBoot) -> None:
        osd, addr = msg.osd, msg.addr
        if (osd < self.osdmap.max_osd and self.osdmap.is_up(osd)
                and self.osdmap.osd_addrs.get(osd) == addr):
            return  # already up at that addr (preprocess_boot dup)
        inc = self._pending()
        if osd >= self.osdmap.max_osd and osd >= inc.new_max_osd:
            inc.new_max_osd = osd + 1
        known = osd < self.osdmap.max_osd
        cur_state = self.osdmap.osd_state[osd] if known else 0
        inc.new_up_client[osd] = addr
        if not (cur_state & OSD_EXISTS) or not known \
                or self.osdmap.is_out(osd):
            inc.new_weight[osd] = 0x10000
        self._ensure_in_crush(osd)
        self.failure_info.pop(osd, None)
        self.down_pending_out.pop(osd, None)
        # batched (mon_propose_batch_window): a boot STORM folds into
        # a handful of epochs instead of one commit each
        self._propose_soon()
        self.ctx.log.info("mon", "osd.%d booted at %s (epoch %d)"
                          % (osd, addr, self.osdmap.epoch))
        self.log_mon.append("INF", "osd.%d boot (epoch %d)"
                            % (osd, self.osdmap.epoch))
        self.emit_event("osd_boot", "osd.%d booted at %s"
                        % (osd, addr), data={"osd": osd})

    def _cmd_pg_scrub(self, prefix: str, cmd: dict) -> dict:
        """`ceph pg scrub|deep-scrub|repair <pgid>` (OSDMonitor
        forwards the request to the PG's primary; the scrub itself
        runs asynchronously there).  pgid = "<pool>.<ps-hex>"."""
        from ..msg.messages import MOSDScrub
        from ..osd.osdmap import pg_t

        pgid_s = str(cmd.get("pgid", ""))
        try:
            pool_s, ps_s = pgid_s.split(".", 1)
            pgid = pg_t(int(pool_s), int(ps_s, 16))
        except ValueError:
            raise ValueError("bad pgid %r (want <pool>.<ps-hex>)"
                             % pgid_s) from None
        if pgid.pool not in self.osdmap.pools:
            raise ValueError("no pool %d" % pgid.pool)
        _up, _upp, _acting, primary = \
            self.osdmap.pg_to_up_acting_osds(pgid)
        if primary < 0 or not self.osdmap.is_up(primary):
            raise ValueError("pg %s has no live primary" % pgid_s)
        addr = self.osdmap.osd_addrs.get(primary)
        self.msgr.send_to(addr, MOSDScrub(
            pool=pgid.pool, ps=pgid.ps,
            deep=prefix in ("pg deep-scrub", "pg repair"),
            repair=prefix == "pg repair"),
            entity_hint="osd.%d" % primary)
        return {"scheduled": True, "primary": primary}

    def _handle_alive_up_thru(self, msg) -> None:
        """OSDMonitor::prepare_alive: record that the osd was alive
        and primary-capable through the requested epoch.  Peering
        logic later uses up_thru >= interval_start as the witness
        that the interval could have served writes."""
        want = getattr(msg, "want_up_thru", None)
        if not want:
            return
        osd = msg.osd
        if not (osd < self.osdmap.max_osd and self.osdmap.is_up(osd)):
            return
        cur = self.osdmap.get_up_thru(osd)
        inc = self._pending()
        pend = inc.new_up_thru.get(osd, 0)
        if want > max(cur, pend):
            inc.new_up_thru[osd] = want
            self._propose_pending()

    def _crush_osds(self) -> set[int]:
        """Committed crush root membership as a set (cached per crush
        object — the per-boot `osd in root.items` list walk is O(n)
        and a 10k-osd boot storm would pay it n times)."""
        crush = self.osdmap.crush
        if self._crush_set_src is not crush:
            self._crush_set = set(self._crush_members(crush))
            self._crush_set_src = crush
        return self._crush_set

    def _ensure_in_crush(self, osd: int) -> None:
        """Make sure `osd` is in the (pending or committed) crush
        map.  The first addition of a proposal window builds the
        pending map once; later boots in the SAME window append to it
        in place — never O(n) rebuilds per boot."""
        inc = self._pending()
        if inc.new_crush is not None:
            if osd in self._pending_crush_set:
                return
            self._crush_append_osd(inc.new_crush, osd)
            self._pending_crush_set.add(osd)
            return
        if osd in self._crush_osds():
            return
        inc.new_crush = self._crush_with(osd)
        self._pending_crush_set = set(self._crush_members(
            inc.new_crush))

    @staticmethod
    def _crush_members(crush: CrushMap) -> list[int]:
        return [o for b in crush.buckets.values()
                for o in b.items if o >= 0]

    def _osds_per_host(self) -> int:
        return int(self.ctx.conf.get("mon_crush_osds_per_host", 0)
                   or 0)

    def _crush_append_osd(self, crush: CrushMap, osd: int) -> None:
        """In-place append to the PENDING crush map (O(1)-ish per
        boot): flat maps grow the root, host-grouped maps grow (or
        create) the osd's host bucket and roll its weight up to the
        root."""
        per_host = self._osds_per_host()
        root = crush.buckets.get(-1)
        if per_host <= 0:
            root.items.append(osd)
            root.item_weights.append(0x10000)
            root.weight += 0x10000
            return
        hid = -(2 + osd // per_host)
        hb = crush.buckets.get(hid)
        if hb is None:
            hb = crush.add_bucket(STRAW2, 1, [osd], [0x10000],
                                  id=hid,
                                  name="host-%d" % (osd // per_host))
            root.items.append(hid)
            root.item_weights.append(hb.weight)
        else:
            hb.items.append(osd)
            hb.item_weights.append(0x10000)
            hb.weight += 0x10000
            root.item_weights[root.items.index(hid)] += 0x10000
        root.weight += 0x10000

    def _crush_with(self, osd: int) -> CrushMap:
        """Default map rebuild.  Flat shape (the vstart dev-cluster
        default): one straw2 root holding every known osd, choose
        over devices.  With `mon_crush_osds_per_host` > 0 (the scale
        plane's shape): osds grouped into straw2 host buckets under
        the root, chooseleaf over hosts — real failure domains, and
        each placement draw hashes O(hosts + per_host) items instead
        of O(osds)."""
        known = set()
        known.update(self._crush_osds())
        pending = self.pending_inc
        if pending is not None:
            known.update(pending.new_up_client)
        known.add(osd)
        items = sorted(known)
        per_host = self._osds_per_host()
        crush = CrushMap()
        if per_host > 0:
            from ..models.crushmap import (CHOOSELEAF_FIRSTN,
                                           CHOOSELEAF_INDEP)
            hosts: dict[int, list[int]] = {}
            for o in items:
                hosts.setdefault(o // per_host, []).append(o)
            host_ids = []
            for h, its in sorted(hosts.items()):
                b = crush.add_bucket(STRAW2, 1, its,
                                     [0x10000] * len(its),
                                     id=-(2 + h), name="host-%d" % h)
                host_ids.append(b.id)
            crush.add_bucket(STRAW2, 2, host_ids,
                             [crush.buckets[h].weight
                              for h in host_ids], id=-1)
            crush.add_rule([(TAKE, -1, 0),
                            (CHOOSELEAF_FIRSTN, 0, 1), (EMIT, 0, 0)],
                           id=0, name="replicated_rule")
            crush.add_rule([(TAKE, -1, 0),
                            (CHOOSELEAF_INDEP, 0, 1), (EMIT, 0, 0)],
                           id=1, name="erasure_rule")
            return crush
        crush.add_bucket(STRAW2, 1, items, [0x10000] * len(items),
                         id=-1)
        crush.add_rule([(TAKE, -1, 0), (CHOOSE_FIRSTN, 0, 0),
                        (EMIT, 0, 0)], id=0, name="replicated_rule")
        crush.add_rule([(TAKE, -1, 0), (CHOOSE_INDEP, 0, 0),
                        (EMIT, 0, 0)], id=1, name="erasure_rule")
        return crush

    # -- failure detection (OSDMonitor.cc:3171 check_failure) --------------

    def _handle_failure(self, conn, msg: MOSDFailure) -> None:
        target = msg.target
        reporter = int(msg.src.split(".", 1)[1]) if "." in msg.src else -1
        if (target >= self.osdmap.max_osd
                or not self.osdmap.is_up(target)):
            return
        now = time.monotonic()
        reports = self.failure_info.setdefault(target, {})
        rep = reports.get(reporter)
        if rep is None:
            reports[reporter] = FailureReport(now, msg.failed_for)
        else:
            rep.last = now
            rep.failed_for = max(rep.failed_for, msg.failed_for)
        self._check_failure(target)

    def _check_failure(self, target: int) -> None:
        reports = self.failure_info.get(target, {})
        min_reporters = self.ctx.conf["mon_osd_min_down_reporters"]
        grace = self.ctx.conf["heartbeat_grace"]
        if len(reports) < min_reporters:
            return
        if max(r.failed_for for r in reports.values()) < grace:
            return
        self.ctx.log.info("mon", "marking osd.%d down (%d reporters)"
                          % (target, len(reports)))
        self.log_mon.append("WRN", "osd.%d marked down (%d reporters)"
                            % (target, len(reports)))
        self.emit_event("osd_down", "osd.%d marked down (%d "
                        "reporters)" % (target, len(reports)),
                        data={"osd": target})
        inc = self._pending()
        inc.new_state[target] = OSD_UP  # xor clears UP
        del self.failure_info[target]
        self.down_pending_out[target] = time.monotonic()
        self._propose_pending()

    async def _tick_loop(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            self._tick()

    def _tick(self) -> None:
        """Auto-out down osds after the down-out interval; decay +
        persist connectivity scores and probe peer liveness."""
        if self.elector is not None:
            from .elector import CONNECTIVITY

            self.elector.tracker.tick()
            if self.elector.strategy == CONNECTIVITY:
                # all-pairs liveness probes: the reference's Elector
                # pings keep scores meaningful between elections
                # (steady-state paxos is a leader-centred star)
                self.send_election("ping", self.elector.epoch)
        # re-flush unacked clog entries: a leader election or dropped
        # frame between emit and commit loses nothing
        self.clog.flush()
        # crash-table retention: the leader queues committed rm ops
        # for archived reports past mon_crash_retention
        if self.is_leader() and (not self.multi
                                 or self.mpaxos.active):
            self.crash_mon.maybe_prune()
        now = time.monotonic()
        interval = self.ctx.conf["mon_osd_down_out_interval"]
        changed = False
        for osd, down_at in list(self.down_pending_out.items()):
            if self.osdmap.is_up(osd):
                del self.down_pending_out[osd]
                continue
            if now - down_at >= interval and self.osdmap.is_in(osd):
                self._pending().new_weight[osd] = CEPH_OSD_OUT
                del self.down_pending_out[osd]
                changed = True
                self.ctx.log.info("mon", "marking osd.%d out" % osd)
                self.log_mon.append("WRN", "osd.%d auto-out" % osd)
                self.emit_event("osd_out", "osd.%d auto-out" % osd,
                                data={"osd": osd})
        if changed:
            self._propose_pending()

    # -- commands ----------------------------------------------------------

    def _handle_command(self, conn, msg: MMonCommand) -> None:
        cmd = msg.cmd or {}
        prefix = cmd.get("prefix", "")
        top = self.optracker.create(
            "mon_command(%s from %s)" % (prefix, msg.src),
            trace=getattr(msg, "trace", None))
        if self.multi and not self.is_leader():
            # peons redirect to the leader (the reference forwards;
            # redirect keeps the routing stateless).  -EHOSTDOWN tells
            # the client to retry elsewhere; a live lease could serve
            # pure reads, but commands are rare enough to centralise.
            leader = self.elector.leader
            out = {"leader": (self._rank_addr(leader)
                              if leader is not None else None)}
            conn.send(MMonCommandAck(tid=msg.tid, result=-112,
                                     out=out))
            top.finish("redirected")
            return
        if self.multi and not self.mpaxos.active:
            conn.send(MMonCommandAck(tid=msg.tid, result=-112,
                                     out={"leader": None}))
            top.finish("redirected_inactive")
            return
        if self.multi:
            # mutating commands must ack only after the paxos commit
            # lands (the single-mon path commits synchronously)
            self.msgr.spawn(self._command_async(conn, msg, prefix,
                                                cmd, top))
            return
        try:
            out = self._run_command(prefix, cmd)
            conn.send(MMonCommandAck(tid=msg.tid, result=0, out=out))
            top.finish("done")
        except Exception as e:
            conn.send(MMonCommandAck(tid=msg.tid, result=-22,
                                     out={"error": str(e)}))
            top.finish("error")

    async def _command_async(self, conn, msg, prefix, cmd,
                             top=None) -> None:
        try:
            self._last_proposal = None
            out = self._run_command(prefix, cmd)
            fut = self._last_proposal
            self._last_proposal = None
            if fut is not None:
                if top is not None:
                    top.mark_event("proposal_queued")
                await asyncio.wait_for(fut, 15.0)
            conn.send(MMonCommandAck(tid=msg.tid, result=0, out=out))
            if top is not None:
                top.finish("done")
        except (IOError, asyncio.TimeoutError):
            # quorum lost mid-round: the proposal MAY still commit
            # under a later reign, so a retryable redirect would make
            # clients re-run possibly-committed (non-idempotent)
            # commands — report ETIMEDOUT and let the caller decide
            conn.send(MMonCommandAck(
                tid=msg.tid, result=-110,
                out={"error": "proposal timed out; may have "
                              "committed"}))
            if top is not None:
                top.finish("proposal_timeout")
        except Exception as e:
            conn.send(MMonCommandAck(tid=msg.tid, result=-22,
                                     out={"error": str(e)}))
            if top is not None:
                top.finish("error")

    def _run_command(self, prefix: str, cmd: dict) -> dict:
        # service command surfaces (ConfigMonitor/AuthMonitor/
        # HealthMonitor/LogMonitor/CrashMonitor/EventMonitor)
        for svc in (self.config_mon, self.auth_mon, self.health_mon,
                    self.log_mon, self.crash_mon, self.event_mon):
            out = svc.command(prefix, cmd)
            if out is not None:
                return out
        if prefix == "perf history":
            # read-only history query against THIS mon's rings (the
            # digest broadcast feeds every mon identically modulo
            # arrival time); no series -> the retained inventory
            series = cmd.get("series")
            if not series:
                return {"series": [[s, lb] for s, lb
                                   in self.history.series_names()],
                        "stats": self.history.stats()}
            return self.history.query(
                str(series), label=cmd.get("label"),
                window=float(cmd.get("window") or 600.0))
        if prefix == "net status":
            # read-only network surface (like `perf history`, not
            # audited): heartbeat RTT matrix from beacon soft state
            # plus per-daemon wire rates from the digest
            return self._cmd_net_status()
        if prefix in _AUDIT_PREFIXES:
            # command provenance on the audit channel (the reference
            # mon's audit clog): only state-mutating prefixes — an
            # audit entry per status poll would burn a paxos round
            # each
            self.log_mon.append(
                "INF", "cmd: %s %s" % (prefix, {
                    k: v for k, v in cmd.items() if k != "prefix"}),
                channel="audit")
        if prefix == "osd pool create":
            return self._cmd_pool_create(cmd)
        if prefix == "osd pool rm":
            name = cmd["pool"]
            pid = self._pool_id(name)
            inc = self._pending()
            inc.old_pools.append(pid)
            self._propose_pending()
            self.log_mon.append("INF", "pool '%s' (id %d) removed"
                                % (name, pid))
            return {}
        if prefix == "osd pool set":
            return self._cmd_pool_set(cmd)
        if prefix == "osd erasure-code-profile set":
            inc = self._pending()
            inc.new_erasure_code_profiles[cmd["name"]] = dict(
                cmd.get("profile", {}))
            self._propose_pending()
            return {}
        if prefix == "osd out":
            inc = self._pending()
            inc.new_weight[int(cmd["id"])] = CEPH_OSD_OUT
            self._propose_pending()
            return {}
        if prefix == "osd in":
            inc = self._pending()
            inc.new_weight[int(cmd["id"])] = 0x10000
            self._propose_pending()
            return {}
        if prefix == "osd down":
            osd = int(cmd["id"])
            if self.osdmap.is_up(osd):
                inc = self._pending()
                inc.new_state[osd] = OSD_UP
                self.down_pending_out[osd] = time.monotonic()
                self._propose_pending()
            return {}
        if prefix == "mgr register":
            # MgrMonitor's role: record the active manager's address
            # in the map so daemons know where to send MMgrReports
            inc = self._pending()
            inc.new_mgr_addr = str(cmd["addr"])
            self._propose_pending()
            return {}
        if prefix == "osd pg-upmap-items":
            # the balancer's apply channel (OSDMonitor pg-upmap-items)
            from ..osd.osdmap import pg_t as _pg_t
            pgid = _pg_t(int(cmd["pool"]), int(cmd["ps"]))
            items = [(int(a), int(b)) for a, b in cmd["mappings"]]
            inc = self._pending()
            inc.new_pg_upmap_items[pgid] = items
            self._propose_pending()
            return {}
        if prefix == "osd rm-pg-upmap-items":
            from ..osd.osdmap import pg_t as _pg_t
            pgid = _pg_t(int(cmd["pool"]), int(cmd["ps"]))
            inc = self._pending()
            inc.new_pg_upmap_items[pgid] = []
            self._propose_pending()
            return {}
        if prefix == "osd pool mksnap":
            return self._cmd_pool_mksnap(cmd)
        if prefix == "osd pool rmsnap":
            return self._cmd_pool_rmsnap(cmd)
        if prefix == "osd snap create":
            return self._cmd_selfmanaged_snap_create(cmd)
        if prefix == "osd snap rm":
            return self._cmd_selfmanaged_snap_rm(cmd)
        if prefix in ("pg scrub", "pg deep-scrub", "pg repair"):
            return self._cmd_pg_scrub(prefix, cmd)
        if prefix == "status":
            return self._cmd_status()
        if prefix == "df":
            return self._cmd_df()
        if prefix == "osd pool stats":
            return self._cmd_pool_stats(cmd)
        if prefix == "osd dump":
            return self.osdmap.to_dict()
        raise ValueError("unknown command %r" % prefix)

    # -- cluster stats surfaces (PGMap digest consumers) -------------------

    def _digest_fresh(self) -> dict | None:
        """The mgr's PGMap digest when recent enough to serve (stale
        digests — mgr dead, never registered — surface as absent
        sections, never as frozen numbers)."""
        if self.mgr_digest is None:
            return None
        ttl = self.health_mon.SOFT_TTL
        if time.monotonic() - self.mgr_digest_stamp > ttl:
            return None
        return self.mgr_digest

    def _slow_ping_pairs(self, now: float | None = None) -> list:
        """Sorted "osd.A-osd.B" pair names any FRESH beacon net
        slice flags slow — the OSD_SLOW_PING_TIME commit value (the
        leader calls this per beacon; edges-only dedup in the health
        monitor keeps steady state free of paxos rounds)."""
        if now is None:
            now = time.monotonic()
        ttl = self.health_mon.SOFT_TTL
        pairs: set[str] = set()
        for osd, (nrow, stamp) in self.osd_net.items():
            if now - stamp >= ttl:
                continue
            for peer in (nrow or {}).get("slow") or []:
                try:
                    p = int(peer)
                except (TypeError, ValueError):
                    continue
                pairs.add("osd.%d-osd.%d"
                          % (min(osd, p), max(osd, p)))
        return sorted(pairs)

    def _cmd_net_status(self) -> dict:
        """`net status` (the `rados netstat` backend): the cluster
        heartbeat RTT matrix from beacon soft state plus per-daemon
        wire rates from the mgr digest — read-only, served from THIS
        mon's view like `perf history`."""
        now = time.monotonic()
        ttl = self.health_mon.SOFT_TTL
        matrix: dict[str, dict] = {}
        for osd, (nrow, stamp) in sorted(self.osd_net.items()):
            if now - stamp >= ttl:
                continue
            row: dict[str, float] = {}
            for peer, ms in ((nrow or {}).get("rtt_ms")
                             or {}).items():
                try:
                    row["osd.%d" % int(peer)] = round(
                        float(ms), 3)
                except (TypeError, ValueError):
                    continue
            matrix["osd.%d" % osd] = row
        dig = self._digest_fresh()
        net = (dig.get("net") or {}) if dig else {}
        daemons = {
            str(d): {
                "tx_Bps": float(row.get("tx_Bps") or 0.0),
                "rx_Bps": float(row.get("rx_Bps") or 0.0),
                "resends": int(row.get("resends") or 0),
                "replays": int(row.get("replays") or 0),
                "queue_depth": int(row.get("queue_depth") or 0),
                "resend_rate": float(
                    row.get("resend_rate") or 0.0),
                "rtt_avg_ms": float(row.get("rtt_avg_ms") or 0.0),
                "rtt_max_ms": float(row.get("rtt_max_ms") or 0.0),
            } for d, row in sorted(net.items())}
        return {"rtt_ms": matrix,
                "slow_pairs": self._slow_ping_pairs(now),
                "reporting": len(matrix),
                "daemons": daemons,
                "daemons_available": dig is not None}

    def _cmd_status(self) -> dict:
        """`ceph -s`: mon/osd summary plus the PGMap data/io sections
        the digest carries (pg states, object+byte totals, client IO
        and recovery rates)."""
        up = sum(1 for o in range(self.osdmap.max_osd)
                 if self.osdmap.is_up(o))
        inn = sum(1 for o in range(self.osdmap.max_osd)
                  if self.osdmap.is_in(o))
        out = {"epoch": self.osdmap.epoch, "fsid": self.fsid,
               "num_osds": self.osdmap.max_osd, "num_up_osds": up,
               "num_in_osds": inn,
               "pools": sorted(self.osdmap.pools)}
        health = self.health_mon.command("health", {})
        out["health"] = health["status"]
        out["checks"] = sorted(health["checks"])
        dig = self._digest_fresh()
        if dig is None:
            # a digest-less mon (mgr dead / never registered / digest
            # past TTL) says so EXPLICITLY instead of silently
            # omitting the section — absent data must never read as
            # "zero activity"
            out["pgmap"] = {
                "available": False,
                "status": "unavailable (no mgr digest)",
            }
            # instead of the panels simply vanishing, serve the last
            # retained history-ring cell for the io rates and
            # device_util, each annotated with its age — stale data
            # clearly labeled stale beats no data (ROADMAP
            # carry-forward)
            io_last: dict = {}
            age_max = 0.0
            for key, series in (("read_ops_s", "io.read_ops_s"),
                                ("write_ops_s", "io.write_ops_s"),
                                ("read_bytes_s", "io.read_bytes_s"),
                                ("write_bytes_s",
                                 "io.write_bytes_s")):
                cell = self.history.latest(series)
                if cell is not None:
                    io_last[key] = cell[0]
                    age_max = max(age_max, cell[1])
            if io_last:
                io_last["stale"] = True
                io_last["age_s"] = round(age_max, 1)
                out["pgmap"]["io_last"] = io_last
            du_last: dict = {}
            du_age = 0.0
            for chip in self.history.labels_for("device.busy_frac"):
                cell = self.history.latest("device.busy_frac",
                                           label=chip)
                if cell is None:
                    continue
                du_last[chip] = {"busy_frac": cell[0]}
                du_age = max(du_age, cell[1])
            if du_last:
                out["device_util_last"] = {
                    "stale": True, "age_s": round(du_age, 1),
                    "chips": du_last}
        else:
            totals = dig.get("totals") or {}
            out["pgmap"] = {
                "available": True,
                "num_pgs": dig.get("num_pgs", 0),
                "pg_states": dict(dig.get("pg_states") or {}),
                "data": {
                    "objects": int(totals.get("objects") or 0),
                    "bytes": int(totals.get("bytes") or 0),
                    "degraded": int(totals.get("degraded") or 0),
                    "misplaced": int(totals.get("misplaced") or 0),
                    "unfound": int(totals.get("unfound") or 0),
                },
                "io": {
                    "read_ops_s": float(
                        totals.get("read_ops_s") or 0.0),
                    "write_ops_s": float(
                        totals.get("write_ops_s") or 0.0),
                    "read_bytes_s": float(
                        totals.get("read_bytes_s") or 0.0),
                    "write_bytes_s": float(
                        totals.get("write_bytes_s") or 0.0),
                    "recovery_ops_s": float(
                        totals.get("recovery_ops_s") or 0.0),
                    "recovery_bytes_s": float(
                        totals.get("recovery_bytes_s") or 0.0),
                },
            }
            # report-freshness line: how stale the digest's inputs
            # are (daemon count, worst report age + who, daemons past
            # the staleness window, visible prune totals) — absent
            # reporters must never read as "all healthy and idle"
            rep = dig.get("reports")
            if rep:
                out["pgmap"]["reports"] = {
                    "daemons": int(rep.get("daemons") or 0),
                    "max_age": float(rep.get("max_age") or 0.0),
                    "max_age_daemon": rep.get("max_age_daemon"),
                    "stale": int(rep.get("stale") or 0),
                    "pruned_rows": (
                        int(rep.get("pruned_stale_rows") or 0)
                        + int(rep.get("pruned_pool_rows") or 0)),
                }
            # device-utilization line: per-chip windowed busy /
            # queue-wait / idle fractions from the digest, so chip
            # saturation is visible in one `status` call cluster-wide
            du = dig.get("device_util") or {}
            if du:
                out["device_util"] = {
                    int(chip): dict(row)
                    for chip, row in sorted(du.items(),
                                            key=lambda kv:
                                            int(kv[0]))}
            # cross-codec repair-bytes panel: the digest's per-codec
            # recovery-traffic totals rendered beside device_util, so
            # the locality win (LRC local repairs vs RS k-fetches) is
            # a `status` line, not a bench-only figure
            rt = dig.get("repair_traffic") or {}
            if rt:
                out["repair_traffic"] = {
                    str(codec): {
                        "read": int(row.get("read") or 0),
                        "moved": int(row.get("moved") or 0),
                        "objects": int(row.get("objects") or 0),
                        "targeted": int(row.get("targeted") or 0),
                        "full": int(row.get("full") or 0),
                    }
                    for codec, row in sorted(rt.items())}
            # data-reduction panel: the digest's per-pool dedup
            # totals (chunks stored vs deduped, logical bytes saved)
            # rendered beside repair_traffic — the dedup win is a
            # `status` line, not a bench-only figure
            # progress panel: in-flight background flows (recovery
            # drains, scrub sweeps) as fraction-complete rows — the
            # reference's `ceph -s` progress section
            prog = dig.get("progress") or {}
            if prog:
                out["progress"] = {
                    str(k): {"kind": row.get("kind"),
                             "done": int(row.get("done") or 0),
                             "total": int(row.get("total") or 0),
                             "fraction": float(
                                 row.get("fraction") or 0.0)}
                    for k, row in sorted(prog.items())}
            dd = dig.get("dedup_pools") or {}
            if dd:
                out["dedup"] = {
                    str(pid): {
                        "chunks_stored": int(
                            row.get("chunks_stored") or 0),
                        "chunks_deduped": int(
                            row.get("chunks_deduped") or 0),
                        "bytes_stored": int(
                            row.get("bytes_stored") or 0),
                        "bytes_saved": int(
                            row.get("bytes_saved") or 0),
                    }
                    for pid, row in sorted(dd.items())}
        return out

    def _pool_digest_rows(self) -> list[dict]:
        dig = self._digest_fresh()
        pools_dig = (dig.get("pools") or {}) if dig else {}
        rows = []
        for pid in sorted(self.osdmap.pools):
            pool = self.osdmap.pools[pid]
            row = {"id": pid, "name": pool.name}
            st = pools_dig.get(pid) or pools_dig.get(str(pid)) or {}
            for k in ("objects", "bytes", "degraded", "misplaced",
                      "unfound", "num_pgs"):
                row[k] = int(st.get(k) or 0)
            for k in ("read_ops_s", "write_ops_s", "read_bytes_s",
                      "write_bytes_s", "recovery_ops_s",
                      "recovery_bytes_s"):
                row[k] = float(st.get(k) or 0.0)
            rows.append(row)
        return rows

    def _cmd_df(self) -> dict:
        """`rados df`: real per-pool usage from the PGMap digest (the
        pre-stats build aliased `status` here), plus the per-OSD
        raw-capacity axis (store statfs riding MMgrReport)."""
        rows = self._pool_digest_rows()
        total = {k: sum(r[k] for r in rows)
                 for k in ("objects", "bytes", "degraded",
                           "misplaced", "unfound")}
        dig = self._digest_fresh()
        osd_rows = []
        for daemon, sf in sorted(
                ((dig.get("osd_stats") or {}) if dig else {}).items()):
            t = int(sf.get("total") or 0)
            u = int(sf.get("used") or 0)
            osd_rows.append({"name": daemon, "total": t, "used": u,
                             "available": max(0, t - u),
                             "util": (float(u) / t) if t else 0.0})
        return {"pools": rows, "total": total, "osds": osd_rows,
                "raw_total": sum(r["total"] for r in osd_rows),
                "raw_used": sum(r["used"] for r in osd_rows),
                "stats_available": dig is not None}

    def _cmd_pool_stats(self, cmd: dict) -> dict:
        """`ceph osd pool stats [pool]`: per-pool client IO and
        recovery rates."""
        rows = self._pool_digest_rows()
        want = cmd.get("pool")
        if want:
            rows = [r for r in rows if r["name"] == want]
            if not rows:
                raise ValueError("pool %r does not exist" % want)
        return {"pools": rows}

    def _pool_id(self, name: str) -> int:
        for pid, pool in self.osdmap.pools.items():
            if pool.name == name:
                return pid
        raise ValueError("pool %r does not exist" % name)

    def _cmd_pool_create(self, cmd: dict) -> dict:
        name = cmd["pool"]
        for pool in self.osdmap.pools.values():
            if pool.name == name:
                return {"pool_id": pool.id}  # idempotent
        ptype = cmd.get("pool_type", "replicated")
        pid = max(self.osdmap.pool_max, 0) + 1
        if self.pending_inc is not None and self.pending_inc.new_pools:
            pid = max(pid, max(self.pending_inc.new_pools) + 1)
        conf = self.ctx.conf
        pg_num = int(cmd.get("pg_num",
                             conf["osd_pool_default_pg_num"]))
        if ptype == "erasure":
            pname = cmd.get("erasure_code_profile", "default")
            profile = self.osdmap.erasure_code_profiles.get(pname)
            if profile is None and pname == "default":
                profile = dict(DEFAULT_EC_PROFILE)
                self._pending().new_erasure_code_profiles[pname] = \
                    profile
            if profile is None:
                raise ValueError("no erasure profile %r" % pname)
            k = int(profile.get("k", 2))
            m = int(profile.get("m", 1))
            n = k + m
            try:
                # the codec is the authority on shard count: LRC's
                # mapping adds local parities beyond k+m, so sizing
                # from the profile ints would under-provision the
                # acting set
                from ..ec.plugin import ErasureCodePluginRegistry
                codec = ErasureCodePluginRegistry.instance().factory(
                    profile.get("plugin", "jerasure"), dict(profile))
                k = codec.get_data_chunk_count()
                n = codec.get_chunk_count()
            except Exception:
                pass
            pool = PGPool(id=pid, name=name, type=POOL_TYPE_ERASURE,
                          size=n, min_size=k, pg_num=pg_num,
                          crush_rule=int(cmd.get("crush_rule", 1)),
                          erasure_code_profile=pname)
        else:
            pool = PGPool(id=pid, name=name,
                          type=POOL_TYPE_REPLICATED,
                          size=int(cmd.get("size",
                                           conf["osd_pool_default_size"])),
                          min_size=conf["osd_pool_default_min_size"],
                          pg_num=pg_num,
                          crush_rule=int(cmd.get("crush_rule", 0)))
        inc = self._pending()
        inc.new_pools[pid] = pool
        self._propose_pending()
        self.log_mon.append(
            "INF", "pool '%s' created (id %d, %s, pg_num %d)"
            % (name, pid, ptype, pg_num))
        return {"pool_id": pid}

    # -- snapshots (OSDMonitor pool snap / selfmanaged snap commands,
    # src/mon/OSDMonitor.cc prepare_command pool mksnap/rmsnap and
    # blocked-by-pool-type checks; snapids are pool-global and shared
    # between pool snaps and selfmanaged snaps, pg_pool_t::snap_seq) --

    def _pool_pending_copy(self, pid: int):
        """Deep copy of the pool folding in any not-yet-committed
        pending mutation (two snap creates in one proposal window must
        not hand out the same snapid)."""
        import copy
        base = None
        if self.pending_inc is not None:
            base = self.pending_inc.new_pools.get(pid)
        if base is None:
            base = self.osdmap.pools[pid]
        return copy.deepcopy(base)

    def _cmd_pool_mksnap(self, cmd: dict) -> dict:
        pid = self._pool_id(cmd["pool"])
        snapname = cmd["snap"]
        pool = self._pool_pending_copy(pid)
        if snapname in pool.snaps.values():
            sid = next(s for s, n in pool.snaps.items()
                       if n == snapname)
            return {"snapid": sid}     # idempotent
        sid = pool.snap_seq + 1
        pool.snap_seq = sid
        pool.snaps[sid] = snapname
        pool.last_change = self.osdmap.epoch + 1
        inc = self._pending()
        inc.new_pools[pid] = pool
        self._propose_pending()
        return {"snapid": sid}

    def _cmd_pool_rmsnap(self, cmd: dict) -> dict:
        pid = self._pool_id(cmd["pool"])
        snapname = cmd["snap"]
        pool = self._pool_pending_copy(pid)
        sid = next((s for s, n in pool.snaps.items()
                    if n == snapname), None)
        if sid is None:
            raise ValueError("snap %r does not exist" % snapname)
        del pool.snaps[sid]
        pool.removed_snaps.append(sid)
        pool.last_change = self.osdmap.epoch + 1
        inc = self._pending()
        inc.new_pools[pid] = pool
        self._propose_pending()
        return {}

    def _cmd_selfmanaged_snap_create(self, cmd: dict) -> dict:
        pid = self._pool_id(cmd["pool"])
        pool = self._pool_pending_copy(pid)
        sid = pool.snap_seq + 1
        pool.snap_seq = sid
        pool.last_change = self.osdmap.epoch + 1
        inc = self._pending()
        inc.new_pools[pid] = pool
        self._propose_pending()
        return {"snapid": sid}

    def _cmd_selfmanaged_snap_rm(self, cmd: dict) -> dict:
        pid = self._pool_id(cmd["pool"])
        sid = int(cmd["snapid"])
        pool = self._pool_pending_copy(pid)
        if sid in pool.removed_snaps:
            return {}
        pool.removed_snaps.append(sid)
        pool.snaps.pop(sid, None)
        pool.last_change = self.osdmap.epoch + 1
        inc = self._pending()
        inc.new_pools[pid] = pool
        self._propose_pending()
        return {}

    def _cmd_pool_set(self, cmd: dict) -> dict:
        pid = self._pool_id(cmd["pool"])
        import copy

        pool = copy.copy(self.osdmap.pools[pid])
        key, val = cmd["var"], cmd["val"]
        if key == "size":
            pool.size = int(val)
        elif key == "min_size":
            pool.min_size = int(val)
        elif key == "pg_num":
            # growth only, and pgp_num stays: children keep their
            # parent's placement (OSDs split in place — the reference
            # workflow of raising pg_num first, pgp_num later).  A
            # shrink would need PG merge machinery this build lacks.
            if int(val) < pool.pg_num:
                raise ValueError("pg_num can only grow "
                                 "(%d -> %s)" % (pool.pg_num, val))
            pool.pg_num = int(val)
        elif key == "pgp_num":
            if not 0 < int(val) <= pool.pg_num:
                raise ValueError("pgp_num must be in (0, pg_num]")
            pool.pgp_num = int(val)
        elif key == "erasure_code_profile":
            # profile swap: only onto a profile with the identical
            # coding parameters (same k/m/technique/w => same matrix).
            # Swapping the matrix under stored shards would corrupt
            # every future reconstruction; this is the rename/rollout
            # path (new profile object, same math), which exercises
            # codec-cache invalidation on every OSD.
            new = self.osdmap.erasure_code_profiles.get(str(val))
            if new is None:
                raise ValueError("no erasure profile %r" % val)
            if not pool.erasure_code_profile:
                raise ValueError("pool %s is not erasure" % pool.name)
            cur = self.osdmap.erasure_code_profiles.get(
                pool.erasure_code_profile, {})
            for fld in ("plugin", "k", "m", "technique", "w"):
                if str(cur.get(fld, "")) != str(new.get(fld, "")):
                    raise ValueError(
                        "profile %r differs from the pool's in %r — "
                        "swap requires identical coding parameters"
                        % (val, fld))
            pool.erasure_code_profile = str(val)
        elif key == "crush_rule":
            pool.crush_rule = int(val)
        elif key == "compression_mode":
            if val not in ("none", "force"):
                raise ValueError("compression_mode: none|force")
            pool.compression_mode = val
        elif key == "compression_algorithm":
            from ..compress import available

            if val not in available():
                raise ValueError("no compressor %r (have %s)"
                                 % (val, available()))
            pool.compression_algorithm = val
        elif key == "dedup_chunk_pool":
            if val in ("", "none", "-1", -1):
                pool.dedup_chunk_pool = -1
            else:
                cid = self._pool_id(str(val))
                chunk = self.osdmap.pools[cid]
                # the chunk store must be a plain replicated pool:
                # content-addressed chunk bytes under compression or
                # EC stripes would break the scrub's fingerprint
                # verification, and a dedup'd chunk pool would recurse
                if cid == pid:
                    raise ValueError("pool cannot dedup into itself")
                if pool.is_erasure() \
                        or pool.compression_mode != "none":
                    raise ValueError(
                        "dedup requires a plain replicated base pool"
                        " (no EC, compression off)")
                if chunk.is_erasure() \
                        or chunk.compression_mode != "none" \
                        or chunk.dedup_chunk_pool >= 0:
                    raise ValueError(
                        "chunk pool must be plain replicated"
                        " (no EC/compression/dedup)")
                pool.dedup_chunk_pool = cid
        else:
            raise ValueError("cannot set %r" % key)
        pool.last_change = self.osdmap.epoch + 1
        inc = self._pending()
        inc.new_pools[pid] = pool
        self._propose_pending()
        if key == "erasure_code_profile":
            self.log_mon.append(
                "INF", "pool '%s' erasure profile rolled to '%s'"
                % (pool.name, val))
        return {}


# state-mutating command prefixes that leave an audit-channel clog
# entry (the reference mon logs every command to the audit channel;
# read-only polls are exempt here — each audit entry costs a paxos
# commit)
_AUDIT_PREFIXES = frozenset((
    "osd pool create", "osd pool rm", "osd pool set",
    "osd erasure-code-profile set", "osd out", "osd in", "osd down",
    "osd pool mksnap", "osd pool rmsnap", "osd snap create",
    "osd snap rm", "config set", "config rm", "crash archive",
    "crash archive-all", "crash rm", "mgr register",
))
