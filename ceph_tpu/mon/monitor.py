"""Monitor: the cluster control plane (map authority).

Analog of src/mon/Monitor.cc + OSDMonitor.cc as one asyncio daemon:
the authoritative OSDMap evolves only through Incrementals committed
via the Paxos log (PaxosService::propose_pending pattern), and every
committed epoch is pushed to subscribers (clients and OSDs follow maps,
never each other).

Implemented service logic (OSDMonitor):
* boot      — MOSDBoot marks the osd EXISTS|UP at its addr and adds it
              to the default CRUSH root (OSDMonitor::preprocess_boot).
* failure   — MOSDFailure reports gated by reporter count + grace
              (OSDMonitor::check_failure, mon/OSDMonitor.cc:3171),
              then the osd is marked down in a new epoch.
* auto-out  — down for mon_osd_down_out_interval -> weight 0
              (OSDMonitor::tick, "will mark out" flow).
* pools     — create/rm/set replicated and erasure pools; erasure
              profiles live in the map (OSDMap::erasure_code_profiles).
* commands  — MMonCommand dict protocol ("osd pool create", "status",
              "osd out/in/down", "osd dump" ...), the mon CLI surface.

Map persistence: every commit stores the Incremental in the paxos log
and the full map at osdmap:full:<epoch> (OSDMonitor's full/inc dual
storage), so a restarted monitor resumes at its last epoch.
"""

from __future__ import annotations

import asyncio
import time

from ..models.crushmap import (CHOOSE_FIRSTN, CHOOSE_INDEP, EMIT, STRAW2,
                               TAKE, CrushMap)
from ..msg import Messenger
from ..msg.messages import (MMonCommand, MMonCommandAck, MMonGetMap,
                            MMonSubscribe, MOSDAlive, MOSDBoot,
                            MOSDFailure, MOSDMapMsg, MOSDOp)
from ..osd.osdmap import (CEPH_OSD_OUT, OSD_EXISTS, OSD_UP,
                          POOL_TYPE_ERASURE, POOL_TYPE_REPLICATED,
                          Incremental, OSDMap, PGPool)
from ..store.kv import KeyValueDB, MemKV
from ..utils import denc
from ..utils.context import Context
from .paxos import Paxos

DEFAULT_EC_PROFILE = {"plugin": "jerasure", "k": "2", "m": "1",
                      "technique": "reed_sol_van"}


class FailureReport:
    __slots__ = ("first", "last", "failed_for")

    def __init__(self, now: float, failed_for: float):
        self.first = now
        self.last = now
        self.failed_for = failed_for


class Monitor:
    def __init__(self, ctx: Context | None = None, name: str = "mon.0",
                 store: KeyValueDB | None = None, fsid: str = "tpu"):
        self.ctx = ctx or Context("mon")
        self.name = name
        self.fsid = fsid
        self.store = store or MemKV()
        self.store.open()
        self.paxos = Paxos(self.store)
        self.msgr = Messenger(name)
        self.msgr.add_dispatcher(self)
        self.osdmap = OSDMap()
        self.osdmap.fsid = fsid
        self.pending_inc: Incremental | None = None
        # conn -> epoch already sent (subscription state)
        self.subscribers: dict = {}
        # target osd -> reporter osd -> FailureReport
        self.failure_info: dict[int, dict[int, FailureReport]] = {}
        self.down_pending_out: dict[int, float] = {}
        self._tick_task = None
        self._load()

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        raw = self.store.get(b"osdmap:last_epoch")
        if raw is not None:
            epoch = denc.decode(raw)
            full = self.store.get(b"osdmap:full:%016d" % epoch)
            if full is not None:
                self.osdmap = OSDMap.decode(full)
        # a crash between paxos commit and map apply leaves a committed
        # blob the map never reflected: recover() replays it through
        # the same apply+persist path as a live commit
        self.paxos.on_commit.append(self._on_paxos_commit)
        self.paxos.recover()

    def _on_paxos_commit(self, version: int, blob: bytes) -> None:
        payload = denc.decode(blob)
        inc_d = payload.get("osdmap_inc")
        if inc_d is None:
            return
        inc = Incremental.from_dict(inc_d)
        if inc.epoch != self.osdmap.epoch + 1:
            return  # already reflected in the stored full map
        self.osdmap.apply_incremental(inc)
        self._store_map(inc)

    def _store_map(self, inc: Incremental) -> None:
        tx = self.store.get_transaction()
        tx.set(b"osdmap:inc:%016d" % inc.epoch, inc.encode())
        tx.set(b"osdmap:full:%016d" % self.osdmap.epoch,
               self.osdmap.encode())
        tx.set(b"osdmap:last_epoch", denc.encode(self.osdmap.epoch))
        self.store.submit_transaction(tx)

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1",
                    port: int = 0) -> str:
        addr = await self.msgr.bind(host, port)
        self._tick_task = self.msgr.spawn(self._tick_loop())
        self.ctx.log.info("mon", "%s serving at %s epoch %d"
                          % (self.name, addr, self.osdmap.epoch))
        return addr

    async def shutdown(self) -> None:
        await self.msgr.shutdown()
        self.store.close()

    @property
    def addr(self) -> str:
        return self.msgr.addr

    # -- pending incremental / commit -------------------------------------

    def _pending(self) -> Incremental:
        if self.pending_inc is None:
            self.pending_inc = self.osdmap.new_incremental()
        return self.pending_inc

    def _propose_pending(self) -> None:
        """PaxosService::propose_pending: commit the pending Incremental
        through paxos, apply it, persist, publish."""
        inc = self.pending_inc
        if inc is None:
            return
        self.pending_inc = None
        # the on_commit hook applies the incremental to the map and
        # persists both (same path live and during crash recovery)
        self.paxos.propose(denc.encode({"osdmap_inc": inc.to_dict()}))
        self.ctx.log.debug("mon", "committed epoch %d"
                           % self.osdmap.epoch)
        self._publish()

    def _publish(self) -> None:
        """Push incrementals to every subscriber past its known epoch."""
        for conn, have in list(self.subscribers.items()):
            if not conn.is_open:
                del self.subscribers[conn]
                continue
            if have >= self.osdmap.epoch:
                continue
            incs = self._collect_incs(have)
            conn.send(MOSDMapMsg(fsid=self.fsid, full=None,
                                 incrementals=incs))
            self.subscribers[conn] = self.osdmap.epoch

    def _collect_incs(self, have: int) -> list[bytes]:
        out = []
        for e in range(have + 1, self.osdmap.epoch + 1):
            raw = self.store.get(b"osdmap:inc:%016d" % e)
            if raw is None:
                return []  # gap: caller falls back to full map
            out.append(raw)
        return out

    def _send_map(self, conn, have: int = -1) -> None:
        if 0 <= have < self.osdmap.epoch:
            incs = self._collect_incs(have)
            if incs:
                conn.send(MOSDMapMsg(fsid=self.fsid, full=None,
                                     incrementals=incs))
                return
        conn.send(MOSDMapMsg(fsid=self.fsid, full=self.osdmap.encode(),
                             incrementals=[]))

    # -- dispatch ----------------------------------------------------------

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MMonGetMap):
            self._send_map(conn, msg.have)
        elif isinstance(msg, MMonSubscribe):
            self.subscribers[conn] = min(msg.start - 1,
                                         self.osdmap.epoch)
            self._send_map(conn, msg.start - 1)
            self.subscribers[conn] = self.osdmap.epoch
        elif isinstance(msg, MOSDBoot):
            self._handle_boot(conn, msg)
        elif isinstance(msg, MOSDFailure):
            self._handle_failure(conn, msg)
        elif isinstance(msg, MOSDAlive):
            self.failure_info.pop(msg.osd, None)
        elif isinstance(msg, MMonCommand):
            self._handle_command(conn, msg)
        else:
            return False
        return True

    def ms_handle_reset(self, conn) -> None:
        self.subscribers.pop(conn, None)

    # -- boot --------------------------------------------------------------

    def _handle_boot(self, conn, msg: MOSDBoot) -> None:
        osd, addr = msg.osd, msg.addr
        if (osd < self.osdmap.max_osd and self.osdmap.is_up(osd)
                and self.osdmap.osd_addrs.get(osd) == addr):
            return  # already up at that addr (preprocess_boot dup)
        inc = self._pending()
        if osd >= self.osdmap.max_osd and osd >= inc.new_max_osd:
            inc.new_max_osd = osd + 1
        known = osd < self.osdmap.max_osd
        cur_state = self.osdmap.osd_state[osd] if known else 0
        inc.new_up_client[osd] = addr
        if not (cur_state & OSD_EXISTS) or not known \
                or self.osdmap.is_out(osd):
            inc.new_weight[osd] = 0x10000
        if not self._in_crush(osd):
            inc.new_crush = self._crush_with(osd)
        self.failure_info.pop(osd, None)
        self.down_pending_out.pop(osd, None)
        self._propose_pending()
        self.ctx.log.info("mon", "osd.%d booted at %s (epoch %d)"
                          % (osd, addr, self.osdmap.epoch))

    def _in_crush(self, osd: int) -> bool:
        root = self.osdmap.crush.buckets.get(-1)
        return root is not None and osd in root.items

    def _crush_with(self, osd: int) -> CrushMap:
        """Flat default map: one straw2 root holding every known osd,
        one replicated rule (chooseleaf type 0 — the vstart dev-cluster
        shape) and one EC indep rule."""
        known = set()
        root = self.osdmap.crush.buckets.get(-1)
        if root is not None:
            known.update(root.items)
        pending = self.pending_inc
        if pending is not None:
            known.update(pending.new_up_client)
        known.add(osd)
        items = sorted(known)
        crush = CrushMap()
        crush.add_bucket(STRAW2, 1, items, [0x10000] * len(items),
                         id=-1)
        crush.add_rule([(TAKE, -1, 0), (CHOOSE_FIRSTN, 0, 0),
                        (EMIT, 0, 0)], id=0, name="replicated_rule")
        crush.add_rule([(TAKE, -1, 0), (CHOOSE_INDEP, 0, 0),
                        (EMIT, 0, 0)], id=1, name="erasure_rule")
        return crush

    # -- failure detection (OSDMonitor.cc:3171 check_failure) --------------

    def _handle_failure(self, conn, msg: MOSDFailure) -> None:
        target = msg.target
        reporter = int(msg.src.split(".", 1)[1]) if "." in msg.src else -1
        if (target >= self.osdmap.max_osd
                or not self.osdmap.is_up(target)):
            return
        now = time.monotonic()
        reports = self.failure_info.setdefault(target, {})
        rep = reports.get(reporter)
        if rep is None:
            reports[reporter] = FailureReport(now, msg.failed_for)
        else:
            rep.last = now
            rep.failed_for = max(rep.failed_for, msg.failed_for)
        self._check_failure(target)

    def _check_failure(self, target: int) -> None:
        reports = self.failure_info.get(target, {})
        min_reporters = self.ctx.conf["mon_osd_min_down_reporters"]
        grace = self.ctx.conf["heartbeat_grace"]
        if len(reports) < min_reporters:
            return
        if max(r.failed_for for r in reports.values()) < grace:
            return
        self.ctx.log.info("mon", "marking osd.%d down (%d reporters)"
                          % (target, len(reports)))
        inc = self._pending()
        inc.new_state[target] = OSD_UP  # xor clears UP
        del self.failure_info[target]
        self.down_pending_out[target] = time.monotonic()
        self._propose_pending()

    async def _tick_loop(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            self._tick()

    def _tick(self) -> None:
        """Auto-out down osds after the down-out interval."""
        now = time.monotonic()
        interval = self.ctx.conf["mon_osd_down_out_interval"]
        changed = False
        for osd, down_at in list(self.down_pending_out.items()):
            if self.osdmap.is_up(osd):
                del self.down_pending_out[osd]
                continue
            if now - down_at >= interval and self.osdmap.is_in(osd):
                self._pending().new_weight[osd] = CEPH_OSD_OUT
                del self.down_pending_out[osd]
                changed = True
                self.ctx.log.info("mon", "marking osd.%d out" % osd)
        if changed:
            self._propose_pending()

    # -- commands ----------------------------------------------------------

    def _handle_command(self, conn, msg: MMonCommand) -> None:
        cmd = msg.cmd or {}
        prefix = cmd.get("prefix", "")
        try:
            out = self._run_command(prefix, cmd)
            conn.send(MMonCommandAck(tid=msg.tid, result=0, out=out))
        except Exception as e:
            conn.send(MMonCommandAck(tid=msg.tid, result=-22,
                                     out={"error": str(e)}))

    def _run_command(self, prefix: str, cmd: dict) -> dict:
        if prefix == "osd pool create":
            return self._cmd_pool_create(cmd)
        if prefix == "osd pool rm":
            name = cmd["pool"]
            pid = self._pool_id(name)
            inc = self._pending()
            inc.old_pools.append(pid)
            self._propose_pending()
            return {}
        if prefix == "osd pool set":
            return self._cmd_pool_set(cmd)
        if prefix == "osd erasure-code-profile set":
            inc = self._pending()
            inc.new_erasure_code_profiles[cmd["name"]] = dict(
                cmd.get("profile", {}))
            self._propose_pending()
            return {}
        if prefix == "osd out":
            inc = self._pending()
            inc.new_weight[int(cmd["id"])] = CEPH_OSD_OUT
            self._propose_pending()
            return {}
        if prefix == "osd in":
            inc = self._pending()
            inc.new_weight[int(cmd["id"])] = 0x10000
            self._propose_pending()
            return {}
        if prefix == "osd down":
            osd = int(cmd["id"])
            if self.osdmap.is_up(osd):
                inc = self._pending()
                inc.new_state[osd] = OSD_UP
                self.down_pending_out[osd] = time.monotonic()
                self._propose_pending()
            return {}
        if prefix == "status":
            up = sum(1 for o in range(self.osdmap.max_osd)
                     if self.osdmap.is_up(o))
            inn = sum(1 for o in range(self.osdmap.max_osd)
                      if self.osdmap.is_in(o))
            return {"epoch": self.osdmap.epoch, "fsid": self.fsid,
                    "num_osds": self.osdmap.max_osd, "num_up_osds": up,
                    "num_in_osds": inn,
                    "pools": sorted(self.osdmap.pools)}
        if prefix == "osd dump":
            return self.osdmap.to_dict()
        raise ValueError("unknown command %r" % prefix)

    def _pool_id(self, name: str) -> int:
        for pid, pool in self.osdmap.pools.items():
            if pool.name == name:
                return pid
        raise ValueError("pool %r does not exist" % name)

    def _cmd_pool_create(self, cmd: dict) -> dict:
        name = cmd["pool"]
        for pool in self.osdmap.pools.values():
            if pool.name == name:
                return {"pool_id": pool.id}  # idempotent
        ptype = cmd.get("pool_type", "replicated")
        pid = max(self.osdmap.pool_max, 0) + 1
        if self.pending_inc is not None and self.pending_inc.new_pools:
            pid = max(pid, max(self.pending_inc.new_pools) + 1)
        conf = self.ctx.conf
        pg_num = int(cmd.get("pg_num",
                             conf["osd_pool_default_pg_num"]))
        if ptype == "erasure":
            pname = cmd.get("erasure_code_profile", "default")
            profile = self.osdmap.erasure_code_profiles.get(pname)
            if profile is None and pname == "default":
                profile = dict(DEFAULT_EC_PROFILE)
                self._pending().new_erasure_code_profiles[pname] = \
                    profile
            if profile is None:
                raise ValueError("no erasure profile %r" % pname)
            k = int(profile.get("k", 2))
            m = int(profile.get("m", 1))
            pool = PGPool(id=pid, name=name, type=POOL_TYPE_ERASURE,
                          size=k + m, min_size=k, pg_num=pg_num,
                          crush_rule=int(cmd.get("crush_rule", 1)),
                          erasure_code_profile=pname)
        else:
            pool = PGPool(id=pid, name=name,
                          type=POOL_TYPE_REPLICATED,
                          size=int(cmd.get("size",
                                           conf["osd_pool_default_size"])),
                          min_size=conf["osd_pool_default_min_size"],
                          pg_num=pg_num,
                          crush_rule=int(cmd.get("crush_rule", 0)))
        inc = self._pending()
        inc.new_pools[pid] = pool
        self._propose_pending()
        return {"pool_id": pid}

    def _cmd_pool_set(self, cmd: dict) -> dict:
        pid = self._pool_id(cmd["pool"])
        import copy

        pool = copy.copy(self.osdmap.pools[pid])
        key, val = cmd["var"], cmd["val"]
        if key == "size":
            pool.size = int(val)
        elif key == "min_size":
            pool.min_size = int(val)
        elif key == "pg_num":
            pool.pg_num = int(val)
            pool.pgp_num = int(val)
        elif key == "crush_rule":
            pool.crush_rule = int(val)
        else:
            raise ValueError("cannot set %r" % key)
        pool.last_change = self.osdmap.epoch + 1
        inc = self._pending()
        inc.new_pools[pid] = pool
        self._propose_pending()
        return {}
