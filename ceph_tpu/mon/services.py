"""Monitor services: centralized config, auth registry, health
checks, and the cluster log.

Analogs of the reference's PaxosService quartet
(src/mon/ConfigMonitor.cc, AuthMonitor.cc, HealthMonitor.cc,
LogMonitor.cc) collapsed to the same shape this framework's
OSDMonitor uses: every mutation is a small op list riding the SAME
paxos commit stream as map changes ("svc" payload beside
"osdmap_inc"), applied deterministically on every monitor (leader,
peons, and crash-recovery replay all run the identical apply path),
with the full service state persisted in the mon KV per commit.

* ConfigMonitor — the centralized option store (`config set/get/rm/
  dump`): values are scoped to "global", an entity type ("osd",
  "mon", "client"), or one daemon ("osd.2"); every commit pushes the
  resolved per-entity view to subscribed daemons (MConfig), which
  feed their Config's "mon" source — the layer utils/config.py
  always had a slot for.
* AuthMonitor — per-entity secrets + caps (`auth get-or-create/get/
  ls/del`).  The wire handshake still rides the shared cluster key
  (msg/auth.py documents that collapse); this registry is the
  durable, replicated identity database the cephx ticket flow would
  consume.
* HealthMonitor — DERIVED state, no paxos writes: aggregates osd
  liveness, quorum shape, stuck-pg hints, and the slow-op counts
  beaconed by OSDs (SLOW_OPS) into HEALTH_OK/WARN/ERR + check list
  (`health`).
* LogMonitor — the capped cluster log (`log` / `log last`): the mon
  itself appends lifecycle events (boots, mark-downs, auto-outs), so
  `log last` answers "what just happened" exactly like
  `ceph log last`.
"""

from __future__ import annotations

import os
import time

from ..utils import denc

CONFIG_KEY = b"svc:config"
AUTH_KEY = b"svc:auth"
LOG_KEY = b"svc:log"
HEALTH_KEY = b"svc:health"
CRASH_KEY = b"svc:crash"
EVENTS_KEY = b"svc:events"

LOG_CAP = 1000
EVENT_CAP = 1000


class ConfigMonitor:
    def __init__(self, mon):
        self.mon = mon
        # who -> {option name -> value}; who = "global" | type | id
        self.values: dict[str, dict[str, str]] = {}

    # -- persistence / replay ----------------------------------------------

    def load(self) -> None:
        raw = self.mon.store.get(CONFIG_KEY)
        if raw is not None:
            self.values = {w: dict(kv)
                           for w, kv in denc.decode(raw).items()}

    def apply(self, ops: list, tx) -> None:
        """Deterministic commit apply (every mon runs this)."""
        for op in ops:
            if op[0] == "set":
                _c, who, name, value = op
                self.values.setdefault(who, {})[name] = value
            elif op[0] == "rm":
                _c, who, name = op
                self.values.get(who, {}).pop(name, None)
                if who in self.values and not self.values[who]:
                    del self.values[who]
        tx.set(CONFIG_KEY, denc.encode(self.values))

    # -- resolution ---------------------------------------------------------

    def resolved_for(self, entity: str) -> dict[str, str]:
        """global < type < exact id (ConfigMonitor's mask order)."""
        etype = entity.split(".", 1)[0]
        out: dict[str, str] = {}
        for who in ("global", etype, entity):
            out.update(self.values.get(who, {}))
        return out

    def push(self, conn, entity: str) -> None:
        from ..msg.messages import MConfig

        conn.send(MConfig(values=self.resolved_for(entity)))

    def push_all(self) -> None:
        """After a config commit: every subscriber gets its fresh
        resolved view (the reference pushes MConfig on maps_update)."""
        for conn in list(self.mon.subscribers):
            if conn.is_open:
                self.push(conn, conn.peer_entity or "client")

    # -- commands -----------------------------------------------------------

    def command(self, prefix: str, cmd: dict):
        if prefix == "config set":
            who = cmd.get("who", "global")
            name, value = cmd["name"], str(cmd["value"])
            # validate against the shared schema BEFORE committing: a
            # poison name/value in the replicated store would chase
            # every daemon forever (daemons also skip defensively)
            from ..utils.config import DEFAULT_SCHEMA

            opt = next((o for o in DEFAULT_SCHEMA if o.name == name),
                       None)
            if opt is None:
                raise ValueError("unknown option %r" % name)
            opt.cast(value)             # raises on a bad value
            self.mon.queue_svc_op("config",
                                  ("set", who, name, value))
            return {}
        if prefix == "config rm":
            self.mon.queue_svc_op(
                "config", ("rm", cmd.get("who", "global"),
                           cmd["name"]))
            return {}
        if prefix == "config get":
            return {"values": self.resolved_for(cmd.get("who",
                                                        "global"))}
        if prefix == "config dump":
            return {"values": {w: dict(kv)
                               for w, kv in self.values.items()}}
        return None


class AuthMonitor:
    def __init__(self, mon):
        self.mon = mon
        # entity -> {"key": hex str, "caps": {service: capspec}}
        self.entities: dict[str, dict] = {}

    def load(self) -> None:
        raw = self.mon.store.get(AUTH_KEY)
        if raw is not None:
            self.entities = {e: dict(v)
                             for e, v in denc.decode(raw).items()}

    def apply(self, ops: list, tx) -> None:
        for op in ops:
            if op[0] == "add":
                _c, entity, key, caps = op
                self.entities[entity] = {"key": key,
                                         "caps": dict(caps or {})}
            elif op[0] == "caps":
                _c, entity, caps = op
                if entity in self.entities:
                    self.entities[entity]["caps"] = dict(caps or {})
            elif op[0] == "del":
                self.entities.pop(op[1], None)
        tx.set(AUTH_KEY, denc.encode(self.entities))

    def command(self, prefix: str, cmd: dict):
        if prefix == "auth get-or-create":
            entity = cmd["entity"]
            ent = self.entities.get(entity)
            if ent is not None:
                return {"entity": entity, "key": ent["key"]}
            # concurrent get-or-create for one entity: the pending
            # (queued but uncommitted) add must win, or the first
            # caller gets a key the registry never stores
            for op in self.mon.pending_svc.get("auth", []):
                if op[0] == "add" and op[1] == entity:
                    return {"entity": entity, "key": op[2]}
            key = os.urandom(16).hex()
            self.mon.queue_svc_op(
                "auth", ("add", entity, key,
                         dict(cmd.get("caps") or {})))
            return {"entity": entity, "key": key}
        if prefix == "auth get":
            ent = self.entities.get(cmd["entity"])
            if ent is None:
                raise ValueError("no such entity")
            return {"entity": cmd["entity"], "key": ent["key"],
                    "caps": dict(ent.get("caps") or {})}
        if prefix == "auth caps":
            if cmd["entity"] not in self.entities:
                raise ValueError("no such entity")
            self.mon.queue_svc_op(
                "auth", ("caps", cmd["entity"],
                         dict(cmd.get("caps") or {})))
            return {}
        if prefix == "auth del":
            self.mon.queue_svc_op("auth", ("del", cmd["entity"]))
            return {}
        if prefix == "auth ls":
            return {"entities": {
                e: {"caps": dict(v.get("caps") or {})}
                for e, v in sorted(self.entities.items())}}
        return None


class HealthMonitor:
    """Health checks: mostly derived on demand, but beacon-fed soft
    state (slow-op counts, device-fallback flags) is ALSO committed
    through paxos by the leader on every transition, so a freshly
    elected leader — including one that never saw a single beacon —
    reports SLOW_OPS / DEVICE_FALLBACK immediately instead of waiting
    one beacon round (closes the PR-2 gap).  Recent soft state wins
    over the committed snapshot (it is newer by construction); the
    committed state fills the gap until beacons reach the new
    leader."""

    # soft-state freshness window: beacons older than this defer to
    # the committed snapshot / other checks (OSD_DOWN covers daemons
    # whose beacons stopped entirely)
    SOFT_TTL = 30.0

    def __init__(self, mon):
        self.mon = mon
        # committed (paxos) snapshot: {"slow": {osd: n},
        #                              "devflb": {osd: 0 | 1+chip},
        #                              "pgdeg": n degraded objects,
        #                              "pgavail": n inactive pgs,
        #                              "scruberr": n scrub errors,
        #                              "pgdmg": n inconsistent pgs}
        # devflb values are chip-encoded (0 = on-device, 1+chip =
        # that mesh chip lost) so the health detail can name the
        # degraded chip even on a freshly elected leader.  slolat /
        # sloburn keep the VIOLATING TENANT NAMES committed (sorted
        # lists), so a fresh leader's SLO_LATENCY / SLO_BURN detail
        # still names them before any mgr digest reaches it.
        self.persisted: dict = {"slow": {}, "devflb": {},
                                "pgdeg": 0, "pgavail": 0,
                                "scruberr": 0, "pgdmg": 0,
                                "slolat": [], "sloburn": [],
                                "perfanom": [], "slowping": []}

    # -- persistence / replay ------------------------------------------

    def load(self) -> None:
        raw = self.mon.store.get(HEALTH_KEY)
        if raw is not None:
            d = denc.decode(raw)
            self.persisted = {
                "slow": {int(k): int(v)
                         for k, v in (d.get("slow") or {}).items()},
                "devflb": {int(k): int(v)
                           for k, v in
                           (d.get("devflb") or {}).items()},
                "pgdeg": int(d.get("pgdeg") or 0),
                "pgavail": int(d.get("pgavail") or 0),
                "scruberr": int(d.get("scruberr") or 0),
                "pgdmg": int(d.get("pgdmg") or 0),
                "slolat": sorted(str(t)
                                 for t in (d.get("slolat") or [])),
                "sloburn": sorted(str(t)
                                  for t in (d.get("sloburn") or [])),
                "perfanom": sorted(
                    str(t) for t in (d.get("perfanom") or [])),
                "slowping": sorted(
                    str(t) for t in (d.get("slowping") or []))}

    def apply(self, ops: list, tx) -> None:
        """Deterministic commit apply (every mon runs this)."""
        for op in ops:
            if op[0] == "slow":
                _c, osd, n = op
                if int(n):
                    self.persisted["slow"][int(osd)] = int(n)
                else:
                    self.persisted["slow"].pop(int(osd), None)
            elif op[0] == "devflb":
                _c, osd, flag = op
                if int(flag):
                    self.persisted["devflb"][int(osd)] = 1
                else:
                    self.persisted["devflb"].pop(int(osd), None)
            elif op[0] in ("pgdeg", "pgavail", "scruberr", "pgdmg"):
                self.persisted[op[0]] = int(op[1])
            elif op[0] in ("slolat", "sloburn", "perfanom",
                           "slowping"):
                self.persisted[op[0]] = sorted(
                    str(t) for t in (op[1] or []))
        tx.set(HEALTH_KEY, denc.encode(
            {"slow": dict(self.persisted["slow"]),
             "devflb": dict(self.persisted["devflb"]),
             "pgdeg": int(self.persisted["pgdeg"]),
             "pgavail": int(self.persisted["pgavail"]),
             "scruberr": int(self.persisted["scruberr"]),
             "pgdmg": int(self.persisted["pgdmg"]),
             "slolat": list(self.persisted["slolat"]),
             "sloburn": list(self.persisted["sloburn"]),
             "perfanom": list(self.persisted["perfanom"]),
             "slowping": list(self.persisted["slowping"])}))

    def _edge(self, level: str, check: str, message: str) -> None:
        """One health-check transition: clog it (the reference clogs
        every edge) AND mirror it onto the event bus, so a live
        watch-events cursor sees the raise/clear the moment it
        commits."""
        self.mon.log_mon.append(level, message)
        emit = getattr(self.mon, "emit_event", None)
        if emit is not None:
            emit("health_edge", message,
                 data={"check": check, "raised": level != "INF"})

    def maybe_commit(self, osd: int, slow: int, devflb: int) -> None:
        """Leader-side: stage a health svc op when a beacon changes
        the committed picture (transitions only — steady-state
        beacons cost no paxos rounds).  Pending-queue dedup keeps a
        beacon burst from stacking identical ops in one proposal."""
        pend = self.mon.pending_svc.get("health", [])

        def pending_val(kind):
            for op in reversed(pend):
                if op[0] == kind and int(op[1]) == osd:
                    return int(op[2])
            return None

        cur = pending_val("slow")
        if cur is None:
            cur = self.persisted["slow"].get(osd, 0)
        if int(slow) != cur:
            self.mon.queue_svc_op("health", ("slow", osd, int(slow)))
            # raise/clear edges are cluster-log events (the reference
            # clogs every health-check transition): committed beside
            # the health op, so every mon's `log last` shows them
            if (int(slow) > 0) != (cur > 0):
                if int(slow):
                    self._edge(
                        "WRN", "SLOW_OPS",
                        "Health check failed: %d slow ops on "
                        "osd.%d (SLOW_OPS)" % (int(slow), osd))
                else:
                    self._edge(
                        "INF", "SLOW_OPS",
                        "Health check cleared: SLOW_OPS "
                        "(osd.%d)" % osd)
        cur = pending_val("devflb")
        if cur is None:
            cur = self.persisted["devflb"].get(osd, 0)
        if int(devflb) != cur:
            self.mon.queue_svc_op("health",
                                  ("devflb", osd, int(devflb)))
            if int(devflb):
                self._edge(
                    "WRN", "DEVICE_FALLBACK",
                    "Health check failed: osd.%d on host "
                    "fallback, device chip %d lost "
                    "(DEVICE_FALLBACK)" % (osd, int(devflb) - 1))
            else:
                self._edge(
                    "INF", "DEVICE_FALLBACK",
                    "Health check cleared: DEVICE_FALLBACK "
                    "(osd.%d)" % osd)

    def maybe_commit_digest(self, degraded: int, inactive: int,
                            scrub_errors: int = 0,
                            damaged_pgs: int = 0) -> None:
        """Leader-side: persist PGMap-digest transitions (degraded
        objects / inactive PGs / scrub errors raise-and-clear)
        through paxos, like the beacon-fed checks — a freshly elected
        leader that never saw a digest reports PG_DEGRADED /
        PG_AVAILABILITY / OSD_SCRUB_ERRORS / PG_DAMAGED immediately.
        Only the raised/cleared EDGE commits (a jittery nonzero count
        does not burn a paxos round per digest)."""
        pend = self.mon.pending_svc.get("health", [])

        def pending_val(kind):
            for op in reversed(pend):
                if op[0] == kind:
                    return int(op[1])
            return None

        for kind, val, check, what in (
                ("pgdeg", int(degraded), "PG_DEGRADED",
                 "%d objects degraded"),
                ("pgavail", int(inactive), "PG_AVAILABILITY",
                 "%d pgs inactive"),
                ("scruberr", int(scrub_errors), "OSD_SCRUB_ERRORS",
                 "%d scrub errors"),
                ("pgdmg", int(damaged_pgs), "PG_DAMAGED",
                 "Possible data damage: %d pgs inconsistent")):
            cur = pending_val(kind)
            if cur is None:
                cur = int(self.persisted[kind])
            # commit on raise/clear edges and on big count moves; a
            # steady nonzero that wobbles (recovery draining) only
            # commits when it crosses zero
            if (val > 0) != (cur > 0):
                self.mon.queue_svc_op("health", (kind, val))
                if val:
                    self._edge(
                        "WRN", check,
                        "Health check failed: %s (%s)"
                        % (what % val, check))
                else:
                    self._edge(
                        "INF", check,
                        "Health check cleared: %s" % check)

    def maybe_commit_slo(self, lat_tenants: list,
                         burn_tenants: list) -> None:
        """Leader-side: persist the SLO-violating tenant SETS from
        the mgr digest through paxos — edges only (a steady violation
        burns no paxos rounds; the list commits when it CHANGES), so
        a freshly elected leader raises SLO_LATENCY / SLO_BURN with
        the offending tenants named before any digest reaches it."""
        pend = self.mon.pending_svc.get("health", [])

        def pending_val(kind):
            for op in reversed(pend):
                if op[0] == kind:
                    return list(op[1])
            return None

        for kind, val, check in (
                ("slolat", sorted(set(map(str, lat_tenants))),
                 "SLO_LATENCY"),
                ("sloburn", sorted(set(map(str, burn_tenants))),
                 "SLO_BURN")):
            cur = pending_val(kind)
            if cur is None:
                cur = list(self.persisted[kind])
            if val == cur:
                continue
            self.mon.queue_svc_op("health", (kind, val))
            if bool(val) != bool(cur):
                if val:
                    self._edge(
                        "WRN", check,
                        "Health check failed: tenant(s) %s "
                        "%s (%s)"
                        % (",".join(val),
                           "over latency objective"
                           if kind == "slolat"
                           else "burning SLO error budget", check))
                else:
                    self._edge(
                        "INF", check,
                        "Health check cleared: %s" % check)

    def maybe_commit_anomaly(self, anomalies: dict) -> None:
        """Leader-side: persist the ACTIVE PERF_ANOMALY series names
        from the mgr digest through paxos — same edges-only contract
        as the SLO sets (a steady anomaly burns no rounds; the name
        list commits when it changes), so a freshly elected leader
        still names the shifted series before any digest reaches
        it."""
        pend = self.mon.pending_svc.get("health", [])
        val = sorted(map(str, anomalies or ()))
        cur = None
        for op in reversed(pend):
            if op[0] == "perfanom":
                cur = list(op[1])
                break
        if cur is None:
            cur = list(self.persisted["perfanom"])
        if val == cur:
            return
        self.mon.queue_svc_op("health", ("perfanom", val))
        if bool(val) != bool(cur):
            if val:
                self._edge(
                    "WRN", "PERF_ANOMALY",
                    "Health check failed: sustained perf shift on "
                    "series %s (PERF_ANOMALY)" % ",".join(val))
            else:
                self._edge(
                    "INF", "PERF_ANOMALY",
                    "Health check cleared: PERF_ANOMALY")

    def maybe_commit_slow_ping(self, pairs) -> None:
        """Leader-side: persist the SLOW-PING PEER PAIRS (the network
        plane, osd/network.py) through paxos — edges only, like the
        SLO/anomaly sets: the "osd.A-osd.B" pair list commits when it
        CHANGES, so a freshly elected leader still names the worst
        peer pairs before any beacon reaches it."""
        pend = self.mon.pending_svc.get("health", [])
        val = sorted(map(str, pairs or ()))
        cur = None
        for op in reversed(pend):
            if op[0] == "slowping":
                cur = list(op[1])
                break
        if cur is None:
            cur = list(self.persisted["slowping"])
        if val == cur:
            return
        self.mon.queue_svc_op("health", ("slowping", val))
        if bool(val) != bool(cur):
            if val:
                self._edge(
                    "WRN", "OSD_SLOW_PING_TIME",
                    "Health check failed: slow heartbeat pings on "
                    "peer pair(s) %s (OSD_SLOW_PING_TIME)"
                    % ",".join(val))
            else:
                self._edge(
                    "INF", "OSD_SLOW_PING_TIME",
                    "Health check cleared: OSD_SLOW_PING_TIME")

    # -- merged beacon views -------------------------------------------

    def _merged(self, soft: dict, committed: dict) -> dict:
        """osd -> value: fresh soft state wins, committed snapshot
        fills in for daemons this mon has not heard from; daemons the
        map says are down are excluded (they surface as OSD_DOWN)."""
        import time as _t
        now = _t.monotonic()
        m = self.mon.osdmap
        out: dict[int, int] = {}
        for osd, v in committed.items():
            if osd < m.max_osd and m.is_up(osd):
                out[osd] = v
        for osd, (v, stamp) in soft.items():
            if now - stamp < self.SOFT_TTL:
                if v:
                    out[osd] = v
                else:
                    out.pop(osd, None)
        return out

    def checks(self) -> dict:
        m = self.mon.osdmap
        out: dict[str, dict] = {}
        down = [o for o in range(m.max_osd)
                if m.exists(o) and not m.is_up(o)]
        if down:
            out["OSD_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": "%d osds down" % len(down),
                "detail": ["osd.%d is down" % o for o in down[:10]]}
        out_osds = [o for o in range(m.max_osd)
                    if m.exists(o) and m.is_out(o)]
        if out_osds:
            out["OSD_OUT"] = {
                "severity": "HEALTH_WARN",
                "summary": "%d osds out" % len(out_osds),
                "detail": []}
        if self.mon.multi:
            quorum = (self.mon.elector.quorum
                      if self.mon.elector else set())
            total = len(self.mon.monmap)
            if self.mon.is_leader() and len(quorum) < total:
                out["MON_DOWN"] = {
                    "severity": "HEALTH_WARN",
                    "summary": "%d/%d mons in quorum"
                               % (len(quorum), total),
                    "detail": []}
        # SLOW_OPS (the reference's HealthMonitor check fed by
        # MOSDBeacon slow-op counts): raised while any live daemon
        # reports in-flight ops past osd_op_complaint_time — via a
        # recent beacon OR the paxos-committed snapshot a previous
        # leader left (so a fresh leader warns immediately); clears
        # as soon as later beacons report zero (a dead osd surfaces
        # as OSD_DOWN, not SLOW_OPS)
        slow = self._merged(getattr(self.mon, "osd_slow_ops", {}),
                            self.persisted["slow"])
        slow_daemons = sorted(o for o, n in slow.items() if n > 0)
        slow_total = sum(n for n in slow.values() if n > 0)
        if slow_total:
            out["SLOW_OPS"] = {
                "severity": "HEALTH_WARN",
                "summary": "%d slow ops, daemons %s"
                           % (slow_total,
                              ["osd.%d" % o
                               for o in slow_daemons[:10]]),
                "detail": ["osd.%d has %d ops past the complaint "
                           "threshold" % (o, slow[o])
                           for o in slow_daemons[:10]]}
            # per-tenant attribution (beacon soft state): name the
            # tenant owning the most slow ops so noisy-neighbor
            # triage starts from the health line, not a dump crawl
            import time as _tt
            tnow = _tt.monotonic()
            per_tenant: dict[str, int] = {}
            for osd, (tmap, stamp) in getattr(
                    self.mon, "osd_slow_tenants", {}).items():
                if tnow - stamp >= self.SOFT_TTL or osd not in slow:
                    continue
                for t, n in (tmap or {}).items():
                    if t:       # "" = tenant-less ops
                        per_tenant[t] = per_tenant.get(t, 0) + int(n)
            if per_tenant:
                worst = max(sorted(per_tenant),
                            key=lambda t: per_tenant[t])
                out["SLOW_OPS"]["worst_tenant"] = worst
                out["SLOW_OPS"]["detail"].append(
                    "worst tenant: %s (%d slow ops)"
                    % (worst, per_tenant[worst]))
        # DEVICE_FALLBACK: a daemon's mesh chip lost the accelerator
        # and is serving EC/mapping from the host paths — degraded
        # throughput, not degraded durability, and scoped to the
        # OSDs bound to the lost chip (the rest of the mesh keeps
        # serving on-device).  Raised while any live daemon reports
        # it (beacon or committed snapshot); the detail names the
        # degraded chip; clears when the chip heals and beacons say
        # so.  Values are chip-encoded: 1+chip.
        flb = self._merged(
            getattr(self.mon, "osd_device_fallback", {}),
            self.persisted["devflb"])
        flb_daemons = sorted(o for o, v in flb.items() if v)
        if flb_daemons:
            chips = sorted({int(flb[o]) - 1 for o in flb_daemons})
            out["DEVICE_FALLBACK"] = {
                "severity": "HEALTH_WARN",
                "summary": "%d daemons on host fallback (device "
                           "chips %s lost): %s"
                           % (len(flb_daemons), chips,
                              ["osd.%d" % o
                               for o in flb_daemons[:10]]),
                "chips": chips,
                "detail": ["osd.%d serving EC/mapping on the host "
                           "paths (chip %d)"
                           % (o, int(flb[o]) - 1)
                           for o in flb_daemons[:10]]}
        # PG_DEGRADED / PG_AVAILABILITY (the reference's PGMap-fed
        # health checks): a fresh mgr digest wins; the paxos-committed
        # snapshot a previous leader left fills in until digests reach
        # this mon (so a fresh leader warns immediately)
        import time as _t
        dig = getattr(self.mon, "mgr_digest", None)
        dig_stamp = getattr(self.mon, "mgr_digest_stamp", 0.0)
        fresh = (dig is not None
                 and _t.monotonic() - dig_stamp < self.SOFT_TTL)
        slo_detail: dict[str, dict] = {}
        if fresh:
            totals = dig.get("totals") or {}
            degraded = int(totals.get("degraded") or 0)
            unfound = int(totals.get("unfound") or 0)
            inactive = int(dig.get("inactive_pgs") or 0)
            scrub_errors = int(totals.get("scrub_errors") or 0)
            damaged = int(dig.get("inconsistent_pgs") or 0)
            slo_detail = dig.get("slo") or {}
            slo_lat = sorted(t for t, v in slo_detail.items()
                             if v.get("latency_violation"))
            slo_burn = sorted(t for t, v in slo_detail.items()
                              if v.get("burn_alert"))
            anom_detail = dig.get("anomalies") or {}
            anom = sorted(anom_detail)
        else:
            degraded = int(self.persisted["pgdeg"])
            unfound = 0
            inactive = int(self.persisted["pgavail"])
            scrub_errors = int(self.persisted["scruberr"])
            damaged = int(self.persisted["pgdmg"])
            # fresh-leader shape: the committed tenant sets carry the
            # warning until digests reach this mon
            slo_lat = list(self.persisted["slolat"])
            slo_burn = list(self.persisted["sloburn"])
            anom_detail = {}
            anom = list(self.persisted["perfanom"])
        if degraded or unfound:
            detail = ["%d object copies degraded" % degraded]
            if unfound:
                detail.append("%d objects unfound" % unfound)
            out["PG_DEGRADED"] = {
                "severity": ("HEALTH_ERR" if unfound
                             else "HEALTH_WARN"),
                "summary": "Degraded data redundancy: %d objects "
                           "degraded%s"
                           % (degraded,
                              (", %d unfound" % unfound)
                              if unfound else ""),
                "detail": detail}
        if inactive:
            out["PG_AVAILABILITY"] = {
                "severity": "HEALTH_WARN",
                "summary": "Reduced data availability: %d pgs "
                           "inactive" % inactive,
                "detail": []}
        # OSD_SCRUB_ERRORS / PG_DAMAGED (the reference's scrub-fed
        # health checks): raised while any PG's last scrub left a
        # nonzero residual inconsistency count — via a fresh digest
        # or the paxos-committed snapshot — and cleared ONLY when a
        # repair scrub drains the residual to zero (the reference's
        # "repair then re-scrub" contract)
        if scrub_errors:
            out["OSD_SCRUB_ERRORS"] = {
                "severity": "HEALTH_ERR",
                "summary": "%d scrub errors" % scrub_errors,
                "detail": []}
        if damaged:
            out["PG_DAMAGED"] = {
                "severity": "HEALTH_ERR",
                "summary": "Possible data damage: %d pgs "
                           "inconsistent" % damaged,
                "detail": ["%d scrub errors across %d pgs; run "
                           "`pg repair <pgid>` to rebuild from the "
                           "authoritative copies"
                           % (scrub_errors, damaged)]}
        # SLO_LATENCY / SLO_BURN (the tenant SLO plane, mgr/slo.py):
        # a tenant's windowed p99 over its latency objective raises
        # SLO_LATENCY; a sustained multi-window burn of its error
        # budget raises SLO_BURN.  A fresh digest carries the live
        # verdicts; the paxos-committed tenant sets fill in for a
        # freshly elected leader.
        if slo_lat:
            out["SLO_LATENCY"] = {
                "severity": "HEALTH_WARN",
                "summary": "%d tenant(s) over latency objective: %s"
                           % (len(slo_lat), slo_lat[:10]),
                "tenants": slo_lat,
                "detail": [
                    "tenant %s p99 %.1fms over target %.1fms"
                    % (t, (slo_detail.get(t) or {}).get("p99_ms", 0),
                       (slo_detail.get(t) or {}).get("target_ms", 0))
                    if t in slo_detail
                    else "tenant %s over latency objective "
                         "(committed edge)" % t
                    for t in slo_lat[:10]]}
        if slo_burn:
            out["SLO_BURN"] = {
                "severity": "HEALTH_WARN",
                "summary": "%d tenant(s) burning SLO error budget:"
                           " %s" % (len(slo_burn), slo_burn[:10]),
                "tenants": slo_burn,
                "detail": [
                    "tenant %s burn rates fast=%s slow=%s"
                    % (t, (slo_detail.get(t) or {}).get("burn_fast"),
                       (slo_detail.get(t) or {}).get("burn_slow"))
                    if t in slo_detail
                    else "tenant %s burning error budget "
                         "(committed edge)" % t
                    for t in slo_burn[:10]]}
        # PERF_ANOMALY (the history plane, mgr/history.py): a series
        # whose EWMA z-score ran hot for the sustain window.  A fresh
        # digest carries the live magnitude; the paxos-committed name
        # list fills in for a freshly elected leader.
        if anom:
            out["PERF_ANOMALY"] = {
                "severity": "HEALTH_WARN",
                "summary": "%d series shifted from baseline: %s"
                           % (len(anom), anom[:10]),
                "series": anom,
                "detail": [
                    "%s at %.4g vs baseline %.4g (z=%.1f)"
                    % (n, (anom_detail.get(n) or {}).get("value", 0),
                       (anom_detail.get(n) or {}).get("mean", 0),
                       (anom_detail.get(n) or {}).get("z", 0))
                    if n in anom_detail
                    else "%s shifted from baseline "
                         "(committed edge)" % n
                    for n in anom[:10]]}
        # OSD_SLOW_PING_TIME (the network plane, osd/network.py):
        # heartbeat RTT past the slow-ping threshold on a peer pair.
        # Fresh beacon soft state (mon.osd_net) carries the live RTT
        # magnitudes; the paxos-committed pair list fills in for a
        # freshly elected leader.
        tnow2 = _t.monotonic()
        ping_detail: dict[str, float] = {}
        ping_pairs: set[str] = set()
        saw_net = False
        for osd, (nrow, stamp) in getattr(
                self.mon, "osd_net", {}).items():
            if tnow2 - stamp >= self.SOFT_TTL:
                continue
            saw_net = True
            rtts = (nrow or {}).get("rtt_ms") or {}
            for peer in (nrow or {}).get("slow") or []:
                try:
                    p = int(peer)
                except (TypeError, ValueError):
                    continue
                pair = "osd.%d-osd.%d" % (min(osd, p), max(osd, p))
                ping_pairs.add(pair)
                ms = rtts.get(str(p))
                if ms is not None:
                    ping_detail[pair] = max(
                        ping_detail.get(pair, 0.0), float(ms))
        if saw_net:
            slow_pairs = sorted(ping_pairs)
        else:
            slow_pairs = list(self.persisted["slowping"])
        if slow_pairs:
            out["OSD_SLOW_PING_TIME"] = {
                "severity": "HEALTH_WARN",
                "summary": "Slow heartbeat pings on %d peer "
                           "pair(s): %s"
                           % (len(slow_pairs), slow_pairs[:10]),
                "pairs": slow_pairs,
                "detail": [
                    "%s heartbeat RTT %.1fms over threshold"
                    % (pr, ping_detail[pr])
                    if pr in ping_detail
                    else "%s slow heartbeat pings "
                         "(committed edge)" % pr
                    for pr in slow_pairs[:10]]}
        # RECENT_CRASH (the crash module's health check): any
        # un-archived crash report newer than mon_crash_warn_age.
        # The crash table is itself paxos-committed, so a freshly
        # elected leader warns with no extra edge state — the same
        # fresh-leader guarantee SLOW_OPS needs `persisted` for.
        crash_mon = getattr(self.mon, "crash_mon", None)
        if crash_mon is not None:
            warn_age = float(self.mon.ctx.conf.get(
                "mon_crash_warn_age", 14 * 24 * 3600.0))
            recent = crash_mon.unarchived(max_age=warn_age)
            if recent:
                out["RECENT_CRASH"] = {
                    "severity": "HEALTH_WARN",
                    "summary": "%d recent crash(es): daemons %s"
                               % (len(recent),
                                  sorted({str(r.get("entity"))
                                          for r in recent})[:10]),
                    "detail": ["%s crashed: %s: %s"
                               % (r.get("entity"), r.get("exc_type"),
                                  r.get("exc_msg"))
                               for r in recent[:10]]}
        if not m.pools and m.epoch > 0:
            pass                       # empty cluster is healthy
        return out

    def command(self, prefix: str, cmd: dict):
        if prefix != "health":
            return None
        checks = self.checks()
        if any(c["severity"] == "HEALTH_ERR"
               for c in checks.values()):
            status = "HEALTH_ERR"
        elif checks:
            status = "HEALTH_WARN"
        else:
            status = "HEALTH_OK"
        return {"status": status, "checks": checks}


class LogMonitor:
    """The capped cluster log, fed from two directions: direct
    mon-side appends (boot, mark-down, auto-out, health edges — via
    the mon's own LogClient) and MLog batches from every daemon's
    clog handle.  Entries are paxos-committed, so `log last` is
    identical on every monitor and survives leader elections;
    ``last_seq`` (per who) makes the apply idempotent against the
    LogClient's resend-until-acked delivery."""

    def __init__(self, mon):
        self.mon = mon
        self.entries: list[dict] = []       # capped ring
        self.last_seq: dict[str, int] = {}  # who -> committed seq
        # who -> boot incarnation of the committed seq: the dedup key
        # is the lexicographic (inc, seq) pair, so a daemon reborn on
        # a wiped store (fresh, larger incarnation; seqs restart at 1)
        # is never swallowed as a resend of its previous life
        self.last_inc: dict[str, int] = {}

    def committed_floor(self, who: str) -> tuple[int, int]:
        """(incarnation, seq) of the last committed entry for `who`."""
        return (self.last_inc.get(who, 0), self.last_seq.get(who, 0))

    def load(self) -> None:
        raw = self.mon.store.get(LOG_KEY)
        if raw is None:
            return
        d = denc.decode(raw)
        if isinstance(d, dict):
            self.entries = [dict(e) for e in (d.get("entries") or [])]
            self.last_seq = {w: int(s)
                             for w, s in (d.get("seq") or {}).items()}
            self.last_inc = {w: int(s)
                             for w, s in (d.get("inc") or {}).items()}
        else:                               # pre-clog bare list
            self.entries = [dict(e) for e in d]

    def apply(self, ops: list, tx) -> None:
        for op in ops:
            if op[0] != "append":
                continue
            e = dict(op[1])
            who = e.get("who") or "?"
            seq = int(e.get("seq") or 0)
            inc = int(e.get("inc") or 0)
            if seq:
                # resend dedup: a LogClient re-flush racing its own
                # ack must not commit the entry twice.  Pair order —
                # a newer incarnation always passes (and resets the
                # seq floor), same incarnation requires a higher seq
                if (inc, seq) <= self.committed_floor(who):
                    continue
                self.last_seq[who] = seq
                self.last_inc[who] = inc
            self.entries.append(e)
        if len(self.entries) > LOG_CAP:
            self.entries = self.entries[-LOG_CAP:]
        tx.set(LOG_KEY, denc.encode({"entries": self.entries,
                                     "seq": self.last_seq,
                                     "inc": self.last_inc}))

    def append(self, level: str, message: str, who: str | None = None,
               channel: str = "cluster") -> None:
        """Mon-side event (boot, mark-down, auto-out, health edges):
        routed through the mon's own clog handle so it gets a seq and
        the resend-until-acked delivery like every other daemon's
        entries; an explicit `who` (the client `log` command) queues
        directly (the command layer owns its own retry semantics)."""
        clog = getattr(self.mon, "clog", None)
        if who is None and clog is not None:
            clog.queue(level, message, channel)
            clog.flush()
            return
        self.mon.queue_svc_op("log", ("append", {
            "stamp": time.time(), "who": who or self.mon.name,
            "channel": channel, "level": level, "message": message}))

    def command(self, prefix: str, cmd: dict):
        if prefix == "log":
            self.append(cmd.get("level", "INF"),
                        str(cmd.get("message", "")),
                        who=cmd.get("who", "client"),
                        channel=cmd.get("channel", "cluster"))
            return {}
        if prefix == "log last":
            n = int(cmd.get("n", 20))
            lines = self.entries
            level = cmd.get("level")
            if level:
                lines = [e for e in lines if e.get("level") == level]
            channel = cmd.get("channel")
            if channel:
                lines = [e for e in lines
                         if e.get("channel", "cluster") == channel]
            return {"lines": lines[-n:]}
        return None


class CrashMonitor:
    """Paxos-committed crash table (the crash mgr module's store +
    `crash ls/info/archive` surface).  Because the table itself rides
    the same commit stream as map changes, a freshly elected leader
    that never heard a single report still serves `crash ls` and
    raises RECENT_CRASH immediately — the SLOW_OPS fresh-leader shape
    without separate edge state."""

    def __init__(self, mon):
        self.mon = mon
        self.reports: dict[str, dict] = {}   # crash_id -> report
        # clock hook: tests pin retention pruning to a virtual now
        self.clock = time.time

    def load(self) -> None:
        raw = self.mon.store.get(CRASH_KEY)
        if raw is not None:
            self.reports = {k: dict(v)
                            for k, v in denc.decode(raw).items()}

    def maybe_prune(self) -> None:
        """Leader-side auto-prune: ARCHIVED reports older than
        `mon_crash_retention` are removed through committed rm ops
        (every mon's table shrinks identically at apply) — the table
        stops growing without bound while un-archived reports stay
        forever (an operator never loses an unacknowledged
        post-mortem).  Runs from the mon tick and whenever fresh
        reports commit; retention <= 0 disables."""
        try:
            keep = float(self.mon.ctx.conf["mon_crash_retention"])
        except (KeyError, TypeError, ValueError):
            return
        if keep <= 0:
            return
        now = self.clock()
        pend = {op[1] for op in self.mon.pending_svc.get("crash", [])
                if op[0] == "rm"}
        for cid, r in sorted(self.reports.items()):
            if not r.get("archived") or cid in pend:
                continue
            if now - float(r.get("timestamp") or 0) > keep:
                self.mon.queue_svc_op("crash", ("rm", cid))
                self.mon.log_mon.append(
                    "INF", "crash %s pruned (archived, older than "
                    "retention)" % cid)

    def apply(self, ops: list, tx) -> None:
        for op in ops:
            if op[0] == "add":
                r = dict(op[1])
                cid = r.get("crash_id")
                if cid and cid not in self.reports:
                    r.setdefault("archived", 0)
                    self.reports[cid] = r
            elif op[0] == "archive":
                r = self.reports.get(op[1])
                if r is not None:
                    r["archived"] = 1
            elif op[0] == "rm":
                self.reports.pop(op[1], None)
        tx.set(CRASH_KEY, denc.encode(self.reports))

    def unarchived(self, max_age: float | None = None) -> list[dict]:
        """Un-archived reports (optionally only those newer than
        max_age seconds) — the RECENT_CRASH input."""
        now = time.time()
        out = [r for r in self.reports.values()
               if not r.get("archived")
               and (max_age is None
                    or now - float(r.get("timestamp") or 0) <= max_age)]
        out.sort(key=lambda r: float(r.get("timestamp") or 0))
        return out

    def _summary(self, r: dict) -> dict:
        return {"crash_id": r.get("crash_id"),
                "entity": r.get("entity"),
                "timestamp": r.get("timestamp"),
                "exc_type": r.get("exc_type"),
                "exc_msg": r.get("exc_msg"),
                "archived": bool(r.get("archived"))}

    def command(self, prefix: str, cmd: dict):
        if prefix == "crash ls":
            rows = sorted(self.reports.values(),
                          key=lambda r: float(r.get("timestamp") or 0))
            return {"crashes": [self._summary(r) for r in rows]}
        if prefix == "crash ls-new":
            return {"crashes": [self._summary(r)
                                for r in self.unarchived()]}
        if prefix == "crash info":
            r = self.reports.get(cmd.get("id"))
            if r is None:
                raise ValueError("no crash %r" % cmd.get("id"))
            return dict(r)
        if prefix == "crash archive":
            if cmd.get("id") not in self.reports:
                raise ValueError("no crash %r" % cmd.get("id"))
            self.mon.queue_svc_op("crash", ("archive", cmd["id"]))
            return {}
        if prefix == "crash archive-all":
            for cid, r in sorted(self.reports.items()):
                if not r.get("archived"):
                    self.mon.queue_svc_op("crash", ("archive", cid))
            return {}
        if prefix == "crash rm":
            self.mon.queue_svc_op("crash", ("rm", cmd.get("id")))
            return {}
        return None


class EventMonitor:
    """Bounded, sequence-numbered cluster event log — the backing
    store of the `rados watch-events` stream (the reference's
    `ceph -w`).  Events (health edges, clog ERR/WRN, osd boot / down /
    out, progress start/finish) commit through paxos as
    ``("emit", row)`` svc ops; the seq is assigned DETERMINISTICALLY
    at apply() time (``last_seq + 1``), so every monitor holds an
    identical contiguous sequence and a cursor survives a leader
    election with no gaps and no duplicate seqs.  Uncommitted pending
    events die with a failed leader — the committed stream stays
    contiguous, which is the cursor contract.  Stamps ride in the op
    payload (set at emit time on the leader), so every mon applies
    identical rows."""

    def __init__(self, mon):
        self.mon = mon
        self.events: list[dict] = []    # capped ring, seq ascending
        self.last_seq = 0

    def load(self) -> None:
        raw = self.mon.store.get(EVENTS_KEY)
        if raw is None:
            return
        d = denc.decode(raw)
        self.events = [dict(e) for e in (d.get("events") or [])]
        self.last_seq = int(d.get("last_seq") or 0)

    def apply(self, ops: list, tx) -> None:
        for op in ops:
            if op[0] != "emit":
                continue
            e = dict(op[1])
            self.last_seq += 1
            e["seq"] = self.last_seq
            self.events.append(e)
        if len(self.events) > EVENT_CAP:
            self.events = self.events[-EVENT_CAP:]
        tx.set(EVENTS_KEY, denc.encode(
            {"events": self.events, "last_seq": self.last_seq}))

    def emit(self, etype: str, message: str,
             data: dict | None = None) -> None:
        """Leader-side: stage one event for the next paxos round.
        Peons never originate events (their trigger sites — beacon
        edges, digest folds — only run on the leader anyway; this
        guard makes stray calls harmless)."""
        if not self.mon.is_leader():
            return
        row = {"type": str(etype), "message": str(message),
               "stamp": time.time()}
        if data:
            row["data"] = dict(data)
        self.mon.queue_svc_op("events", ("emit", row))

    def after(self, cursor: int, limit: int = 500) -> list[dict]:
        """Committed events with seq > cursor (the incremental read
        every MMonEvents batch and `events` command serves).  A
        cursor older than the ring floor simply starts at the floor —
        the ring is bounded; history that aged out is gone."""
        cursor = int(cursor)
        if not self.events or cursor >= self.last_seq:
            return []
        # ring is seq-ascending and contiguous: index directly
        floor = int(self.events[0]["seq"])
        start = max(0, cursor - floor + 1)
        return [dict(e) for e in self.events[start:start + limit]]

    def command(self, prefix: str, cmd: dict):
        if prefix != "events":
            return None
        rows = self.after(int(cmd.get("after", 0)),
                          limit=int(cmd.get("n", 500)))
        return {"events": rows, "last_seq": self.last_seq}
