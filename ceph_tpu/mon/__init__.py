"""L4 cluster control plane: monitor (map authority) + paxos log.

Analog of src/mon/ — see monitor.py (Monitor/OSDMonitor service logic)
and paxos.py (the durable consensus log).
"""

from .monitor import Monitor
from .paxos import Paxos

__all__ = ["Monitor", "Paxos"]
