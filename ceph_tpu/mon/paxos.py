"""Paxos commit log over a KeyValueDB.

The reference's monitor consensus (src/mon/Paxos.{h,cc}): one Paxos
instance per monitor replicates a single totally-ordered log of
transaction blobs; services (OSDMonitor etc.) encode their pending
state into one blob per round and apply it on commit
(src/mon/PaxosService.cc propose_pending -> Paxos::propose_new_value).

Store layout mirrors the reference (Paxos.cc get_store() keys):
    paxos:first_committed / paxos:last_committed  (u64 as denc int)
    paxos:<version>                               (tx blob)
    paxos:accepted_pn / paxos:pending_v / paxos:pending_pn

This class implements the proposer/acceptor state machine for a quorum
of size 1 synchronously (the collect/begin/accept/commit round degrades
to: bump pn, write pending, commit) while keeping the phase structure
and durable bookkeeping, so the multi-mon message exchange
(OP_COLLECT/OP_BEGIN/OP_ACCEPT/OP_COMMIT/OP_LEASE, Paxos.h:24-104) can
be layered on without changing the storage contract or callers.
"""

from __future__ import annotations

import asyncio
from typing import Callable

from ..store.kv import KeyValueDB, KVTransaction
from ..utils import denc

PREFIX = b"paxos:"


def _k(name: str) -> bytes:
    return PREFIX + name.encode()


def _kv(version: int) -> bytes:
    return PREFIX + b"v%016d" % version


class Paxos:
    """Durable, ordered log of committed transaction blobs."""

    def __init__(self, store: KeyValueDB, rank: int = 0,
                 quorum: int = 1):
        self.store = store
        self.rank = rank
        self.quorum = quorum
        self.first_committed = self._get_int("first_committed", 0)
        self.last_committed = self._get_int("last_committed", 0)
        self.accepted_pn = self._get_int("accepted_pn", 0)
        # commit subscribers (the services' refresh hook)
        self.on_commit: list[Callable[[int, bytes], None]] = []

    # -- storage helpers ---------------------------------------------------

    def _get_int(self, name: str, default: int) -> int:
        raw = self.store.get(_k(name))
        return denc.decode(raw) if raw is not None else default

    def get_version(self, version: int) -> bytes | None:
        return self.store.get(_kv(version))

    # -- proposer ----------------------------------------------------------

    def _next_pn(self) -> int:
        """Proposal numbers are globally unique per rank
        (Paxos::get_new_proposal_number)."""
        pn = (self.accepted_pn // 100 + 1) * 100 + self.rank
        return pn

    def propose(self, blob: bytes) -> int:
        """Run one consensus round for the next version; returns the
        committed version.  Quorum of one: the collect/begin/accept
        phases are all local, but every durable step is taken in the
        same order as the reference so recovery semantics match."""
        # phase 1 (collect): adopt a higher pn
        pn = self._next_pn()
        self.accepted_pn = pn
        version = self.last_committed + 1
        tx = self.store.get_transaction()
        tx.set(_k("accepted_pn"), denc.encode(pn))
        # phase 2 (begin): persist the pending value
        tx.set(_k("pending_v"), denc.encode(version))
        tx.set(_k("pending_pn"), denc.encode(pn))
        tx.set(_kv(version), blob)
        self.store.submit_transaction(tx)
        # phase 3 (commit): quorum of one has already accepted
        tx = self.store.get_transaction()
        tx.set(_k("last_committed"), denc.encode(version))
        if self.first_committed == 0:
            self.first_committed = 1
            tx.set(_k("first_committed"), denc.encode(1))
        tx.rmkey(_k("pending_v"))
        tx.rmkey(_k("pending_pn"))
        self.store.submit_transaction(tx)
        self.last_committed = version
        for cb in self.on_commit:
            cb(version, blob)
        return version

    def recover(self) -> None:
        """Crash recovery: an uncommitted pending value at
        last_committed+1 is re-committed (quorum of one: it was
        accepted by a majority, namely us — Paxos.cc handle_last
        uncommitted handling)."""
        raw = self.store.get(_k("pending_v"))
        if raw is None:
            return
        version = denc.decode(raw)
        if version != self.last_committed + 1:
            return
        blob = self.get_version(version)
        if blob is None:
            return
        tx = self.store.get_transaction()
        tx.set(_k("last_committed"), denc.encode(version))
        tx.rmkey(_k("pending_v"))
        tx.rmkey(_k("pending_pn"))
        self.store.submit_transaction(tx)
        self.last_committed = version
        for cb in self.on_commit:
            cb(version, blob)

    def trim(self, keep: int = 500) -> None:
        """Drop log entries older than keep versions
        (Paxos::trim, paxos_max_join_drift semantics simplified)."""
        floor = self.last_committed - keep
        if floor <= self.first_committed:
            return
        tx = self.store.get_transaction()
        tx.rm_range(_kv(self.first_committed), _kv(floor))
        tx.set(_k("first_committed"), denc.encode(floor))
        self.store.submit_transaction(tx)
        self.first_committed = floor

    # -- storage steps shared with the multi-mon protocol ------------------

    def store_pending(self, version: int, pn: int, blob: bytes) -> None:
        """OP_BEGIN's durable step on every quorum member."""
        tx = self.store.get_transaction()
        tx.set(_k("accepted_pn"), denc.encode(pn))
        tx.set(_k("pending_v"), denc.encode(version))
        tx.set(_k("pending_pn"), denc.encode(pn))
        tx.set(_kv(version), blob)
        self.store.submit_transaction(tx)
        self.accepted_pn = pn

    def store_commit(self, version: int, blob: bytes) -> None:
        """OP_COMMIT's durable step; fires the service refresh hook."""
        if version <= self.last_committed:
            return
        tx = self.store.get_transaction()
        tx.set(_kv(version), blob)
        tx.set(_k("last_committed"), denc.encode(version))
        if self.first_committed == 0:
            self.first_committed = 1
            tx.set(_k("first_committed"), denc.encode(1))
        tx.rmkey(_k("pending_v"))
        tx.rmkey(_k("pending_pn"))
        self.store.submit_transaction(tx)
        self.last_committed = version
        for cb in self.on_commit:
            cb(version, blob)

    def store_accepted_pn(self, pn: int) -> None:
        tx = self.store.get_transaction()
        tx.set(_k("accepted_pn"), denc.encode(pn))
        self.store.submit_transaction(tx)
        self.accepted_pn = pn

    def uncommitted(self) -> tuple[int, int, bytes] | None:
        """(version, pn, blob) of a pending-but-uncommitted value."""
        raw = self.store.get(_k("pending_v"))
        if raw is None:
            return None
        version = denc.decode(raw)
        if version != self.last_committed + 1:
            return None
        blob = self.get_version(version)
        if blob is None:
            return None
        pn = self._get_int("pending_pn", self.accepted_pn)
        return version, pn, blob


class PaxosRound:
    """Leader-side bookkeeping for one collect or begin phase."""

    __slots__ = ("pn", "version", "acks", "done", "uncommitted",
                 "peer_max_lc", "superseded")

    def __init__(self, pn: int, version: int | None = None):
        self.pn = pn
        self.version = version
        self.acks: set[int] = set()
        self.done = asyncio.Future()
        self.uncommitted: tuple[int, int, bytes] | None = None
        self.peer_max_lc = 0
        # highest accepted_pn a peer reported ABOVE our pn: a reign
        # we were partitioned through promised higher — retry the
        # collect from a pn past it (Paxos.cc handle_collect OP_LAST
        # with higher pn semantics)
        self.superseded = 0


class MultiPaxos:
    """The OP_COLLECT/OP_LAST/OP_BEGIN/OP_ACCEPT/OP_COMMIT/OP_LEASE
    exchange (Paxos.h:24-104) over a quorum, layered on the durable
    Paxos storage contract.

    The Monitor drives it: `mon` supplies rank, quorum membership and
    send_paxos(rank, op, **fields).  Only the elected leader proposes;
    peons answer collects/begins and learn commits.  The leader extends
    a read lease to the quorum (OP_LEASE); a monitor without a live
    lease (and not the leader) refuses consistent reads, which is what
    makes a partitioned minority unusable (Paxos.h lease comments)."""

    LEASE = 5.0
    LEASE_RENEW = 2.0

    def __init__(self, mon, paxos: Paxos):
        self.mon = mon
        self.px = paxos
        self.active = False          # leader: recovery done
        self.lease_until = 0.0       # peon: leader's lease
        self._round: PaxosRound | None = None
        self._reign_pn = 0           # pn latched by OUR collect phase
        self._lease_task = None
        self._lock = asyncio.Lock()

    # -- quorum helpers ----------------------------------------------------

    def _peers(self):
        return [r for r in self.mon.quorum_ranks()
                if r != self.mon.rank]

    def _majority(self) -> int:
        return len(self.mon.monmap) // 2 + 1

    # -- leader ------------------------------------------------------------

    async def leader_collect(self, reign_epoch: int | None = None
                             ) -> None:
        """Recovery phase after winning an election.  Retries with a
        higher pn when a peer's OP_LAST reveals a bigger accepted_pn
        (an interim reign we were partitioned through promised past
        us — without the retry every collect is silently ignored and
        recovery livelocks in 10s election churn).  ``reign_epoch``
        fences stale queued collects: if another election superseded
        this reign while we waited for the lock, abort instead of
        collecting for a dead reign."""
        async with self._lock:
            el = getattr(self.mon, "elector", None)
            for _attempt in range(4):
                if el is not None and reign_epoch is not None \
                        and el.epoch != reign_epoch:
                    raise IOError("paxos: reign superseded")
                pn = self.px._next_pn()
                self.px.store_accepted_pn(pn)
                # Latch this reign's pn: _begin proposes at exactly
                # this pn and refuses if a rival collect has moved
                # accepted_pn past it (Paxos.cc keeps begin at the
                # collect-phase pn; a stale co-leader re-using a
                # rival's pn could otherwise commit a different value
                # at the same version — split brain).
                self._reign_pn = pn
                rnd = PaxosRound(pn)
                rnd.acks.add(self.mon.rank)
                self._round = rnd
                for r in self._peers():
                    self.mon.send_paxos(
                        r, "collect", pn=pn,
                        last_committed=self.px.last_committed,
                        first_committed=self.px.first_committed)
                if len(rnd.acks) < self._majority():
                    await asyncio.wait_for(rnd.done, timeout=10.0)
                if rnd.superseded > pn:
                    # adopt the higher promise base and re-collect
                    self.px.store_accepted_pn(rnd.superseded)
                    continue
                # a peer ahead of us means a previous reign committed
                # past our log: its OP_LAST triggered a catch-up; wait
                # for those commits to land before taking over
                # (otherwise we would re-propose a stale value at an
                # already-taken version and livelock in election churn)
                deadline = asyncio.get_event_loop().time() + 10.0
                while self.px.last_committed < rnd.peer_max_lc:
                    if asyncio.get_event_loop().time() > deadline:
                        self._round = None
                        raise IOError("paxos: catch-up from peers "
                                      "timed out")
                    await asyncio.sleep(0.05)
                # re-propose any uncommitted value from the prior reign
                unc = rnd.uncommitted or self.px.uncommitted()
                self._round = None
                self.active = True
                if unc is not None \
                        and unc[0] == self.px.last_committed + 1:
                    await self._begin(unc[2])
                self._start_lease()
                return
            self._round = None
            raise IOError("paxos: collect lost %d pn races" % 4)

    async def propose(self, blob: bytes) -> int:
        """Leader-only: replicate one value; returns its version."""
        async with self._lock:
            if not self.active:
                raise IOError("paxos: not active (no quorum)")
            return await self._begin(blob)

    async def _begin(self, blob: bytes) -> int:
        pn = self._reign_pn
        if self.px.accepted_pn != pn:
            # a rival leader's collect superseded our reign between our
            # collect and this begin: abdicate instead of proposing at
            # a pn we no longer own
            self.active = False
            raise IOError("paxos: deposed (accepted_pn %d > reign %d)"
                          % (self.px.accepted_pn, pn))
        version = self.px.last_committed + 1
        self.px.store_pending(version, pn, blob)
        rnd = PaxosRound(pn, version)
        rnd.acks.add(self.mon.rank)
        self._round = rnd
        for r in self._peers():
            self.mon.send_paxos(r, "begin", pn=pn, version=version,
                                blob=blob)
        if len(rnd.acks) < self._majority():
            try:
                await asyncio.wait_for(rnd.done, timeout=10.0)
            except asyncio.TimeoutError:
                self._round = None
                self.active = False
                raise IOError("paxos: lost quorum during begin")
        self._round = None
        self.px.store_commit(version, blob)
        for r in self._peers():
            self.mon.send_paxos(r, "commit", version=version,
                                blob=blob)
        return version

    def _start_lease(self) -> None:
        if self._lease_task is None or self._lease_task.done():
            self._lease_task = self.mon.msgr.spawn(self._lease_loop())

    async def _lease_loop(self) -> None:
        while self.active and self.mon.is_leader():
            until = asyncio.get_event_loop().time() + self.LEASE
            self.lease_until = until
            for r in self._peers():
                self.mon.send_paxos(r, "lease", lease_until=until,
                                    last_committed=self.px.last_committed)
            await asyncio.sleep(self.LEASE_RENEW)

    # -- peon --------------------------------------------------------------

    def _send_commits_since(self, rank: int, peer_lc: int) -> None:
        """Share committed values a lagging peer is missing (the
        reference's share_state), in version order."""
        for v in range(peer_lc + 1, self.px.last_committed + 1):
            blob = self.px.get_version(v)
            if blob is not None:
                self.mon.send_paxos(rank, "commit", version=v,
                                    blob=blob)

    def handle(self, src_rank: int, op: str, f: dict) -> None:
        # Reign fencing (Paxos.cc checks mon->get_epoch() on every
        # phase message): drop messages stamped with a stale election
        # epoch, and leader-authority ops from anyone who is not the
        # leader we acknowledged — a deposed leader that missed the new
        # VICTORY cannot push begins/leases at a majority.
        el = getattr(self.mon, "elector", None)
        if el is not None:
            epoch = f.get("epoch") or 0
            # commit carries an already-chosen value (always safe to
            # learn); catchup merely requests commits — both pass so a
            # restarted mon with a stale epoch can still converge
            if op not in ("commit", "catchup") and epoch < el.epoch:
                return
            if op == "lease" and epoch > el.epoch:
                # a steady-state leadership assertion from a reign we
                # never elected: we were partitioned through a regime
                # change — rejoin via a fresh election (heals the
                # stale-ex-leader split brain, whose subscribers
                # would otherwise never see the newer reign's maps).
                # Only leases trigger this: in-flight round messages
                # (collect/last/begin/accept) can legitimately carry
                # a newer stamp mid-election, and re-electing on them
                # would churn instead of converge.
                el.note_newer_reign(epoch)
                return
            if op in ("begin", "lease") and epoch == el.epoch \
                    and el.leader is not None \
                    and src_rank != el.leader:
                return
        if op == "collect":
            pn = f["pn"]
            promised = pn > self.px.accepted_pn
            if promised:
                self.px.store_accepted_pn(pn)
            unc = self.px.uncommitted() if promised else None
            # ALWAYS reply, echoing our accepted_pn: a silent refusal
            # of a low pn (a healed ex-leader whose pn generator never
            # saw the interim reign's promises) would livelock its
            # recovery — the reply lets it retry from a higher pn
            self.mon.send_paxos(
                src_rank, "last", pn=pn,
                last_committed=self.px.last_committed,
                accepted_pn=self.px.accepted_pn,
                uncommitted=(list(unc[:2]) + [unc[2]]
                             if unc else None))
        elif op == "last":
            rnd = self._round
            if rnd is None or f["pn"] != rnd.pn:
                return
            apn = f.get("accepted_pn") or 0
            if apn > rnd.pn:
                # the peer promised a higher reign than our collect:
                # no promise for us — retry past its pn
                rnd.superseded = max(rnd.superseded, apn)
                if not rnd.done.done():
                    rnd.done.set_result(None)
                return
            rnd.acks.add(src_rank)
            unc = f.get("uncommitted")
            if unc is not None:
                v, pn_u, blob = unc[0], unc[1], unc[2]
                cur = rnd.uncommitted
                if v == self.px.last_committed + 1 and (
                        cur is None or pn_u > cur[1]):
                    rnd.uncommitted = (v, pn_u, blob)
            peer_lc = f.get("last_committed", 0)
            if peer_lc > self.px.last_committed:
                # the peer's reign committed past us: pull its commits
                # before we act as leader (leader_collect waits)
                rnd.peer_max_lc = max(rnd.peer_max_lc, peer_lc)
                self.mon.request_catchup(src_rank)
            else:
                # catch a lagging peon up with committed values
                self._send_commits_since(src_rank, peer_lc)
            if len(rnd.acks) >= self._majority() \
                    and not rnd.done.done():
                rnd.done.set_result(None)
        elif op == "begin":
            if f["pn"] >= self.px.accepted_pn:
                # catch up any gap first (commits may have been lost
                # with a dead connection)
                if f["version"] > self.px.last_committed + 1:
                    self.mon.request_catchup(src_rank)
                    return
                if f["version"] == self.px.last_committed + 1:
                    self.px.store_pending(f["version"], f["pn"],
                                          f["blob"])
                    self.mon.send_paxos(src_rank, "accept",
                                        pn=f["pn"],
                                        version=f["version"])
        elif op == "accept":
            rnd = self._round
            if rnd is None or f["pn"] != rnd.pn \
                    or f.get("version") != rnd.version:
                # a delayed accept from an earlier begin (same reign,
                # same pn) must not count toward this round's majority
                return
            rnd.acks.add(src_rank)
            if len(rnd.acks) >= self._majority() \
                    and not rnd.done.done():
                rnd.done.set_result(None)
        elif op == "commit":
            if f["version"] > self.px.last_committed + 1:
                # gap (a commit broadcast overtook a lost one): pull
                # the missing range instead of skipping versions —
                # store_commit would advance last_committed past the
                # hole and the osdmap would freeze at the gap epoch
                self.mon.request_catchup(src_rank)
                return
            self.px.store_commit(f["version"], f["blob"])
        elif op == "lease":
            self.lease_until = max(self.lease_until, f["lease_until"])
            if self.mon.elector is not None:
                self.mon.elector.note_leader_alive()
            if f.get("last_committed", 0) > self.px.last_committed:
                self.mon.request_catchup(src_rank)
        elif op == "catchup":
            # a peer asks for commits it is missing
            self._send_commits_since(src_rank,
                                     f.get("last_committed", 0))

    def lease_valid(self) -> bool:
        return (asyncio.get_event_loop().time() < self.lease_until)
