"""Paxos commit log over a KeyValueDB.

The reference's monitor consensus (src/mon/Paxos.{h,cc}): one Paxos
instance per monitor replicates a single totally-ordered log of
transaction blobs; services (OSDMonitor etc.) encode their pending
state into one blob per round and apply it on commit
(src/mon/PaxosService.cc propose_pending -> Paxos::propose_new_value).

Store layout mirrors the reference (Paxos.cc get_store() keys):
    paxos:first_committed / paxos:last_committed  (u64 as denc int)
    paxos:<version>                               (tx blob)
    paxos:accepted_pn / paxos:pending_v / paxos:pending_pn

This class implements the proposer/acceptor state machine for a quorum
of size 1 synchronously (the collect/begin/accept/commit round degrades
to: bump pn, write pending, commit) while keeping the phase structure
and durable bookkeeping, so the multi-mon message exchange
(OP_COLLECT/OP_BEGIN/OP_ACCEPT/OP_COMMIT/OP_LEASE, Paxos.h:24-104) can
be layered on without changing the storage contract or callers.
"""

from __future__ import annotations

from typing import Callable

from ..store.kv import KeyValueDB, KVTransaction
from ..utils import denc

PREFIX = b"paxos:"


def _k(name: str) -> bytes:
    return PREFIX + name.encode()


def _kv(version: int) -> bytes:
    return PREFIX + b"v%016d" % version


class Paxos:
    """Durable, ordered log of committed transaction blobs."""

    def __init__(self, store: KeyValueDB, rank: int = 0,
                 quorum: int = 1):
        self.store = store
        self.rank = rank
        self.quorum = quorum
        self.first_committed = self._get_int("first_committed", 0)
        self.last_committed = self._get_int("last_committed", 0)
        self.accepted_pn = self._get_int("accepted_pn", 0)
        # commit subscribers (the services' refresh hook)
        self.on_commit: list[Callable[[int, bytes], None]] = []

    # -- storage helpers ---------------------------------------------------

    def _get_int(self, name: str, default: int) -> int:
        raw = self.store.get(_k(name))
        return denc.decode(raw) if raw is not None else default

    def get_version(self, version: int) -> bytes | None:
        return self.store.get(_kv(version))

    # -- proposer ----------------------------------------------------------

    def _next_pn(self) -> int:
        """Proposal numbers are globally unique per rank
        (Paxos::get_new_proposal_number)."""
        pn = (self.accepted_pn // 100 + 1) * 100 + self.rank
        return pn

    def propose(self, blob: bytes) -> int:
        """Run one consensus round for the next version; returns the
        committed version.  Quorum of one: the collect/begin/accept
        phases are all local, but every durable step is taken in the
        same order as the reference so recovery semantics match."""
        # phase 1 (collect): adopt a higher pn
        pn = self._next_pn()
        self.accepted_pn = pn
        version = self.last_committed + 1
        tx = self.store.get_transaction()
        tx.set(_k("accepted_pn"), denc.encode(pn))
        # phase 2 (begin): persist the pending value
        tx.set(_k("pending_v"), denc.encode(version))
        tx.set(_k("pending_pn"), denc.encode(pn))
        tx.set(_kv(version), blob)
        self.store.submit_transaction(tx)
        # phase 3 (commit): quorum of one has already accepted
        tx = self.store.get_transaction()
        tx.set(_k("last_committed"), denc.encode(version))
        if self.first_committed == 0:
            self.first_committed = 1
            tx.set(_k("first_committed"), denc.encode(1))
        tx.rmkey(_k("pending_v"))
        tx.rmkey(_k("pending_pn"))
        self.store.submit_transaction(tx)
        self.last_committed = version
        for cb in self.on_commit:
            cb(version, blob)
        return version

    def recover(self) -> None:
        """Crash recovery: an uncommitted pending value at
        last_committed+1 is re-committed (quorum of one: it was
        accepted by a majority, namely us — Paxos.cc handle_last
        uncommitted handling)."""
        raw = self.store.get(_k("pending_v"))
        if raw is None:
            return
        version = denc.decode(raw)
        if version != self.last_committed + 1:
            return
        blob = self.get_version(version)
        if blob is None:
            return
        tx = self.store.get_transaction()
        tx.set(_k("last_committed"), denc.encode(version))
        tx.rmkey(_k("pending_v"))
        tx.rmkey(_k("pending_pn"))
        self.store.submit_transaction(tx)
        self.last_committed = version
        for cb in self.on_commit:
            cb(version, blob)

    def trim(self, keep: int = 500) -> None:
        """Drop log entries older than keep versions
        (Paxos::trim, paxos_max_join_drift semantics simplified)."""
        floor = self.last_committed - keep
        if floor <= self.first_committed:
            return
        tx = self.store.get_transaction()
        tx.rm_range(_kv(self.first_committed), _kv(floor))
        tx.set(_k("first_committed"), denc.encode(floor))
        self.store.submit_transaction(tx)
        self.first_committed = floor
