"""Persistent per-chip dispatch streams: continuous EC admission.

The flush batcher (ec.batcher) accumulates items per key and flushes a
whole batch as one dispatch — so under mixed client/recovery/scrub/
tenant load a small urgent op waits for whichever flush it rode: the
deadline window, the co-batched bulk, and the single all-or-nothing
retire.  The PR-10 utilization integrals (`queue_wait_frac`) measure
exactly that wait; this module removes it, following continuous
batching from LLM serving — the Ragged Paged Attention kernel
(arXiv:2604.15464) pages heterogeneous work through one compiled
program family instead of re-bucketing per flush, and the GF(2^w)
inner loops tolerate the fixed-geometry restructuring (the
XOR-scheduling results of arXiv:2108.02692).

One ``DispatchStream`` per ``ChipRuntime``:

* **continuous admission** — `submit` lands an op (one encode/delta/
  decode matmul request) in the stream with a weighted-fair virtual
  finish tag: class shares mirror ``osd.scheduler
  DEVICE_DISPATCH_WEIGHTS`` and tenant-stamped client ops order by
  their dmClock weight row (``osd_mclock_tenant_qos`` — reservation
  and limit stay host-side in the op scheduler; the device honors the
  proportional column).  The admission loop wakes on every arrival
  and slot completion (and at most ``device_stream_interval_us``
  apart) and packs whatever is resident into **slots**;
* **fixed-geometry slots** — a slot group is the tag-contiguous run
  of pending ops sharing one program family (matrix, w, class),
  capped at ``device_stream_slot_words``; its words stage across the
  same pow2 bucket ladder flush batching uses (``DeviceRuntime.
  ragged_plan``), so slot programs are the already-compiled bucket
  family and the <=8-program budget is untouched.  Oversized groups
  mesh-shard exactly like oversized flushes;
* **independent retire** — each slot dispatches as its own task and
  retires ITS ops' futures the moment it completes: an urgent client
  op never waits on a co-batched recovery stripe's flush, and a
  recovery slot in flight never blocks the next client slot's
  admission;
* **degradation** — a poisoned chip or failed dispatch host-encodes
  the slot's ops (bit-parity with the host codecs by construction,
  the same ``host_encode`` route flush batching degrades to), so
  every submitted future retires exactly once, mid-stream chip loss
  included.

Every slot carries a ``DispatchTicket`` stamped with the earliest
admitted op's arrival (queue_wait = arrival -> grant, the honest
figure) and ``stream=True``, so the flight recorder renders the
before/after on the same Perfetto device lanes.
"""

from __future__ import annotations

import asyncio
import heapq
import time


class StreamOp:
    """One admitted matmul request: [k, n] words awaiting parity."""

    __slots__ = ("matrix_key", "w", "klass", "tenant", "arr", "n",
                 "fut", "on_ticket", "t_arrive")

    def __init__(self, matrix_key, w, klass, tenant, arr, fut,
                 on_ticket):
        self.matrix_key = matrix_key
        self.w = int(w)
        self.klass = klass
        self.tenant = tenant
        self.arr = arr
        self.n = int(arr.shape[1])
        self.fut = fut
        self.on_ticket = on_ticket
        self.t_arrive = time.monotonic()

    @property
    def group_key(self):
        return (self.matrix_key, self.w, self.klass)


class DispatchStream:
    """The persistent admission loop of one mesh chip."""

    def __init__(self, chip):
        self.chip = chip
        self.rt = chip.rt
        self._heap: list = []           # (finish_tag, seq, op)
        self._seq = 0
        self._vt = 0.0                  # admission virtual clock
        self._finish: dict = {}         # book key -> finish tag
        self._wake = asyncio.Event()
        self._task = None
        self._slots_inflight = 0
        # telemetry (ChipRuntime.metrics: device_slot_occupancy,
        # device_admission_wait, device_stream_retires,
        # device_stream_pending)
        self.admitted = 0
        self.retired = 0
        self.slot_dispatches = 0
        self.slot_payload_words = 0
        self.slot_capacity_words = 0
        self.admission_wait_sum = 0.0
        self.admission_waits = 0

    # -- telemetry ---------------------------------------------------------

    @property
    def pending(self) -> int:
        return len(self._heap)

    @property
    def slot_occupancy(self) -> float:
        """Payload fraction of dispatched slot capacity (1.0 before
        the first slot: no capacity has been wasted yet)."""
        if not self.slot_capacity_words:
            return 1.0
        return self.slot_payload_words / self.slot_capacity_words

    @property
    def admission_wait_mean(self) -> float:
        if not self.admission_waits:
            return 0.0
        return self.admission_wait_sum / self.admission_waits

    # -- admission ---------------------------------------------------------

    def _tag(self, op: StreamOp) -> float:
        """Weighted-fair virtual finish tag: start-time fair queueing
        over (class, tenant) books with the mClock-mirrored class
        shares x the tenant's dmClock weight row."""
        from ..osd.scheduler import device_admission_weight
        key = ((op.klass, op.tenant)
               if op.tenant is not None and op.klass == "client-ec"
               else op.klass)
        w = device_admission_weight(op.klass, op.tenant,
                                    self.rt.tenant_qos)
        cost = 1.0 + op.n / 65536.0
        start = max(self._vt, self._finish.get(key, 0.0))
        fin = start + cost / max(w, 1e-9)
        self._finish[key] = fin
        return fin

    async def encode(self, matrix, w: int, data, klass: str,
                     on_ticket=None, tenant: str | None = None):
        """Stream-mode analog of DeviceBatcher.encode: admit the op
        and await its independently-retired parity slice."""
        matrix_key = tuple(tuple(r) for r in matrix)
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        op = StreamOp(matrix_key, w, klass, tenant, data, fut,
                      on_ticket)
        self._seq += 1
        heapq.heappush(self._heap, (self._tag(op), self._seq, op))
        self.admitted += 1
        self._wake.set()
        if self._task is None or self._task.done():
            self._task = loop.create_task(self._run())
        return await fut

    # -- the admission loop ------------------------------------------------

    async def _wait(self) -> None:
        self._wake.clear()
        try:
            await asyncio.wait_for(self._wake.wait(),
                                   self.rt.stream_interval)
        except asyncio.TimeoutError:
            pass

    async def _run(self) -> None:
        """Pack-and-dispatch until drained: each iteration admits the
        tag-ordered resident ops into slots and hands each slot to its
        own retire task.  Exits when idle (respawned by the next
        submit), so no task outlives the work."""
        try:
            while True:
                if not self._heap:
                    if self._slots_inflight == 0:
                        return
                    await self._wait()
                    continue
                if (self.chip.available and self._slots_inflight
                        >= self.rt.stream_max_slots):
                    # keep ops pending in the stream rather than deep
                    # in the device queue: a later-arriving urgent
                    # class can still overtake here
                    await self._wait()
                    continue
                group = self._take_group()
                self._slots_inflight += 1
                asyncio.get_event_loop().create_task(
                    self._slot_task(group))
                # yield one beat so concurrent arrivals land before
                # the next packing decision
                await asyncio.sleep(0)
        except asyncio.CancelledError:
            return              # loop teardown
        finally:
            self._task = None

    def _take_group(self) -> list:
        """The tag-contiguous run of pending ops sharing the head
        op's program family, capped at the slot-geometry words."""
        tag, _seq, op = heapq.heappop(self._heap)
        self._vt = max(self._vt, tag)
        group = [op]
        total = op.n
        cap = self.rt.stream_slot_words
        gkey = op.group_key
        while self._heap:
            t2, _s2, op2 = self._heap[0]
            if op2.group_key != gkey or total + op2.n > cap:
                break
            heapq.heappop(self._heap)
            self._vt = max(self._vt, t2)
            group.append(op2)
            total += op2.n
        return group

    async def _slot_task(self, group: list) -> None:
        """Dispatch one slot and retire its ops — independent of any
        other slot in flight.  Device loss/DeviceBusy degrade to the
        host codec inside the batcher's shared dispatch path; only a
        host-codec failure (a real codec error) reaches the futures
        as an exception."""
        from ..ec.batcher import DeviceBatcher, tenant_label
        op0 = group[0]
        n = sum(op.n for op in group)
        try:
            out, ticket = await DeviceBatcher.get().stream_dispatch(
                self.chip, op0.matrix_key, op0.w, op0.klass,
                [op.arr for op in group], n,
                tenant=tenant_label(op.tenant for op in group),
                t_enqueue=min(op.t_arrive for op in group))
        except Exception as e:
            for op in group:
                if not op.fut.cancelled():
                    op.fut.set_exception(
                        IOError("EC encode failed: %r" % e))
            return
        finally:
            self._slots_inflight -= 1
            self._wake.set()
        now = time.monotonic()
        granted = (ticket.t_admit if ticket is not None
                   and ticket.t_admit else now)
        self.slot_dispatches += 1
        self.slot_payload_words += n
        self.slot_capacity_words += (ticket.bucket
                                     if ticket is not None else n)
        off = 0
        for op in group:
            if not op.fut.cancelled():
                op.fut.set_result(out[:, off:off + op.n])
            off += op.n
            self.retired += 1
            self.admission_waits += 1
            self.admission_wait_sum += max(0.0,
                                           granted - op.t_arrive)
            if op.on_ticket is not None and ticket is not None:
                try:
                    op.on_ticket(ticket)
                except Exception:
                    pass    # attribution must never sink the slot
