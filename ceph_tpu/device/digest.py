"""Batched CRC32 digest lanes: the scrub plane's device kernel.

"GPUs as Storage System Accelerators" (arXiv:1202.3669, PAPERS.md) is
about exactly this offload — integrity checksumming is embarrassingly
parallel ACROSS objects but the host path computes one `zlib.crc32`
at a time on the event loop.  This module turns a scrub chunk's
digests (object bytes + attr blobs) into ONE device dispatch:

* **linearity decomposition** — CRC32 is affine over GF(2): with the
  standard byte-step ``s' = (s >> 8) ^ TAB[(s ^ b) & 0xff]``, byte
  ``b`` contributes ``L^t(TAB[b])`` where ``t`` is its trailing byte
  count and ``L(v) = (v >> 8) ^ TAB[v & 0xff]`` is the zero-byte
  advance, so ``crc32(m) = XOR_i T[len-1-i][m[i]] ^ Z[len]`` with
  ``T[t] = L^t(TAB)`` and ``Z[n] = crc32(0^n)``.  The position table
  is host-precomputed once per bucket width (cached, pow2 sizes) and
  the whole digest becomes one gather + XOR-reduce over
  ``[lanes, width]`` — zero sequential byte scan on device, and zero
  padding sensitivity (``T[t][0] == 0``, so the staged tail of a
  short lane contributes nothing whatever index it gathers).
* **chip-affine, pooled, admission-controlled** — lanes stage into a
  pooled buffer on the caller's affinity chip (the same discipline as
  EC flushes), admission rides the new ``background`` class (weight
  below recovery — a scrub storm cannot starve client EC dispatches),
  and compile accounting buckets (lanes, width) pow2 so steady state
  re-dispatches a handful of programs.
* **host fallback rides the poison/heal machinery** — DeviceBusy, a
  poisoned chip, an injected fault, or an oversized buffer (the
  position table is O(width), bounded at ``DEVICE_MAX_BYTES``)
  degrade to the `zlib.crc32` loop; a failed dispatch poisons ITS
  chip (per-chip DEVICE_FALLBACK health) and the probe loop heals it.

Bit-parity with ``zlib.crc32`` is exact by construction and pinned by
tests/test_scrub.py — the device digest and the host fallback are the
same function, so a scrub round may switch paths mid-flight (poison
injection) and still compare shards soundly.
"""

from __future__ import annotations

import functools
import os
import zlib

import numpy as np

from .runtime import DeviceBusy, DeviceRuntime, K_BACKGROUND

_POLY = np.uint32(0xEDB88320)
_FINAL = np.uint32(0xFFFFFFFF)

# position-table memory is O(width x 256 x 4B): bound the device path
# at 16 KiB lanes (a 16 MiB table); longer buffers take the host loop
DEVICE_MAX_BYTES = 1 << 14

_MIN_WIDTH = 256     # pow2 floor so tiny chunks share one program
_MIN_LANES = 8


def device_digest_enabled() -> bool:
    """Device digesting defaults to on where device EC offload is on
    (a real accelerator backend, or the CEPH_TPU_EC_OFFLOAD test
    override); CEPH_TPU_SCRUB_OFFLOAD=1/0 forces it independently."""
    v = os.environ.get("CEPH_TPU_SCRUB_OFFLOAD")
    if v is not None:
        return v not in ("0", "false", "no")
    from ..ec.batcher import device_offload_enabled
    return device_offload_enabled()


@functools.lru_cache(maxsize=1)
def _byte_table() -> np.ndarray:
    """The standard CRC32 byte table (TAB[b] = contribution of byte b
    processed last); linear in b over GF(2)."""
    tab = np.arange(256, dtype=np.uint32)
    for _ in range(8):
        tab = np.where(tab & 1, (tab >> np.uint32(1)) ^ _POLY,
                       tab >> np.uint32(1)).astype(np.uint32)
    return tab


@functools.lru_cache(maxsize=4)
def _tables(width: int) -> tuple[np.ndarray, np.ndarray]:
    """(T, Z) for one pow2 bucket width: T[t][b] = L^t(TAB[b]) (the
    per-position contribution table the device gathers) and
    Z[n] = crc32 of n zero bytes (the affine constant folded back in
    on the host).  Built once per width and cached."""
    tab = _byte_table()
    T = np.empty((width, 256), np.uint32)
    T[0] = tab
    for t in range(1, width):
        p = T[t - 1]
        T[t] = (p >> np.uint32(8)) ^ tab[p & np.uint32(0xFF)]
    Z = np.empty(width + 1, np.uint32)
    Z[0] = 0
    s = _FINAL
    for n in range(1, width + 1):
        s = (s >> np.uint32(8)) ^ tab[s & np.uint32(0xFF)]
        Z[n] = s ^ _FINAL
    return T, Z


@functools.lru_cache(maxsize=16)
def _device_table(width: int, chip_index: int):
    """The position table committed to one chip's device (uploaded
    once per (width, chip), like the EC coding matrices)."""
    import jax.numpy as jnp
    rt = DeviceRuntime.get()
    return rt.chip(chip_index).place(jnp.asarray(_tables(width)[0]))


@functools.lru_cache(maxsize=16)
def _kernel(lanes: int, width: int):
    """One jitted digest program per (lanes, width) bucket: gather
    each byte's positional contribution and XOR-reduce the lane."""
    import jax
    import jax.numpy as jnp

    def run(data, lens, table):
        pos = (lens[:, None]
               - jnp.int32(1)
               - jnp.arange(width, dtype=jnp.int32)[None, :])
        contrib = table[jnp.clip(pos, 0, width - 1),
                        data.astype(jnp.int32)]
        contrib = jnp.where(pos >= 0, contrib, jnp.uint32(0))
        return jax.lax.reduce(contrib, jnp.uint32(0),
                              jax.lax.bitwise_xor, (1,))

    return jax.jit(run)


def crc32_host(bufs) -> list[int]:
    """The host fallback (and the parity oracle): one zlib.crc32 per
    buffer — identical values to the device lanes by construction."""
    return [zlib.crc32(bytes(b)) & 0xFFFFFFFF for b in bufs]


def _pow2(n: int, floor: int) -> int:
    return 1 << max(int(n) - 1, floor - 1).bit_length()


async def crc32_batch(bufs, chip: int | None = None,
                      klass: str = K_BACKGROUND
                      ) -> tuple[list[int], str]:
    """Digest every buffer in one device dispatch on the caller's
    affinity chip; returns (digests, path) where path is "device" or
    "host".  Any degradation (offload disabled, chip lost, queue
    full, oversized buffer, mid-dispatch failure) lands on the host
    loop — the caller never sees the difference except in telemetry.
    """
    bufs = list(bufs)
    if not bufs:
        return [], "host"
    rt = DeviceRuntime.get()
    target = rt.route(chip)
    maxlen = max(len(b) for b in bufs)
    if (target is None or not target.available or maxlen == 0
            or maxlen > DEVICE_MAX_BYTES
            or not device_digest_enabled()):
        return crc32_host(bufs), "host"
    width = _pow2(maxlen, _MIN_WIDTH)
    lanes = _pow2(len(bufs), _MIN_LANES)
    total = sum(len(b) for b in bufs)
    ticket = target.open_ticket(klass, width, total)
    try:
        await target.admit(ticket)
    except DeviceBusy:
        return crc32_host(bufs), "host"
    stage = target.pool.lease((lanes, width), np.uint8)
    try:
        import jax.numpy as jnp
        lens = np.zeros(lanes, np.int32)
        for i, b in enumerate(bufs):
            a = np.frombuffer(bytes(b), np.uint8)
            stage[i, :a.size] = a
            lens[i] = a.size
        target.launch(ticket)           # injected-fault hook
        _t, z = _tables(width)
        lin = np.asarray(_kernel(lanes, width)(
            target.place(jnp.asarray(stage)),
            target.place(jnp.asarray(lens)),
            _device_table(width, target.index)))
        target.note_program("crc32", (lanes, width))
        target.finish(ticket, ok=True)
        # staging accounting in words, like the EC ladder
        target.note_staging(total // 4, (lanes * width) // 4)
        return [int(lin[i]) ^ int(z[lens[i]])
                for i in range(len(bufs))], "device"
    except Exception as e:
        # device loss mid-digest: poison THIS chip (per-chip
        # DEVICE_FALLBACK + probe heal) and finish the scrub on host
        target.finish(ticket, ok=False, error=e)
        target.poison(e)
        return crc32_host(bufs), "host"
    finally:
        target.pool.release(stage)
