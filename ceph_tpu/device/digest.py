"""Batched CRC32 digest lanes: the scrub plane's device kernel.

"GPUs as Storage System Accelerators" (arXiv:1202.3669, PAPERS.md) is
about exactly this offload — integrity checksumming is embarrassingly
parallel ACROSS objects but the host path computes one `zlib.crc32`
at a time on the event loop.  This module turns a scrub chunk's
digests (object bytes + attr blobs) into ONE device dispatch:

* **linearity decomposition** — CRC32 is affine over GF(2): with the
  standard byte-step ``s' = (s >> 8) ^ TAB[(s ^ b) & 0xff]``, byte
  ``b`` contributes ``L^t(TAB[b])`` where ``t`` is its trailing byte
  count and ``L(v) = (v >> 8) ^ TAB[v & 0xff]`` is the zero-byte
  advance, so ``crc32(m) = XOR_i T[len-1-i][m[i]] ^ Z[len]`` with
  ``T[t] = L^t(TAB)`` and ``Z[n] = crc32(0^n)``.  The position table
  is host-precomputed once per bucket width (cached, pow2 sizes) and
  the whole digest becomes one gather + XOR-reduce over
  ``[lanes, width]`` — zero sequential byte scan on device, and zero
  padding sensitivity (``T[t][0] == 0``, so the staged tail of a
  short lane contributes nothing whatever index it gathers).
* **chip-affine, pooled, admission-controlled** — lanes stage into a
  pooled buffer on the caller's affinity chip (the same discipline as
  EC flushes), admission rides the new ``background`` class (weight
  below recovery — a scrub storm cannot starve client EC dispatches),
  and compile accounting buckets (lanes, width) pow2 so steady state
  re-dispatches a handful of programs.
* **segment folding lifts the lane cap** — the position table is
  O(width), so lanes stay bounded at ``DEVICE_MAX_BYTES`` (16 KiB);
  a longer buffer splits into <= 16 KiB segments that digest as
  independent lanes of the same dispatch and recombine on host with
  ``crc32_combine`` (CRC32 over GF(2): shift the prefix crc through
  len(suffix) zero bytes by matrix square-and-multiply, xor the
  suffix crc — zlib's combine), bit-parity pinned against
  ``zlib.crc32``.
* **host fallback rides the poison/heal machinery** — DeviceBusy, a
  poisoned chip, an injected fault, or a batch whose staging would
  exceed ``DEVICE_MAX_STAGE_BYTES`` degrade to the `zlib.crc32`
  loop; a failed dispatch poisons ITS chip (per-chip DEVICE_FALLBACK
  health) and the probe loop heals it.

Bit-parity with ``zlib.crc32`` is exact by construction and pinned by
tests/test_scrub.py — the device digest and the host fallback are the
same function, so a scrub round may switch paths mid-flight (poison
injection) and still compare shards soundly.
"""

from __future__ import annotations

import functools
import os
import zlib

import numpy as np

from .runtime import DeviceBusy, DeviceRuntime, K_BACKGROUND

_POLY = np.uint32(0xEDB88320)
_FINAL = np.uint32(0xFFFFFFFF)

# position-table memory is O(width x 256 x 4B): bound the device
# LANE at 16 KiB (a 16 MiB table).  Longer buffers no longer fall to
# the host — they split into <= 16 KiB segments that digest as
# independent lanes in the same dispatch and recombine on the host
# with `crc32_combine` (CRC32 of a concatenation is the GF(2)-matrix
# shift of the prefix crc xor the suffix crc — zlib's combine trick),
# so the lane cap bounds the TABLE, not the buffer.
DEVICE_MAX_BYTES = 1 << 14

# total staged bytes (lanes x width) a single digest dispatch may
# occupy; a batch whose segment fan-out exceeds it takes the host loop
# (staging a GiB-class buffer through the pool would evict every
# EC staging buffer for one scrub chunk)
DEVICE_MAX_STAGE_BYTES = 1 << 25

_MIN_WIDTH = 256     # pow2 floor so tiny chunks share one program
_MIN_LANES = 8


def device_digest_enabled() -> bool:
    """Device digesting defaults to on where device EC offload is on
    (a real accelerator backend, or the CEPH_TPU_EC_OFFLOAD test
    override); CEPH_TPU_SCRUB_OFFLOAD=1/0 forces it independently."""
    v = os.environ.get("CEPH_TPU_SCRUB_OFFLOAD")
    if v is not None:
        return v not in ("0", "false", "no")
    from ..ec.batcher import device_offload_enabled
    return device_offload_enabled()


@functools.lru_cache(maxsize=1)
def _byte_table() -> np.ndarray:
    """The standard CRC32 byte table (TAB[b] = contribution of byte b
    processed last); linear in b over GF(2)."""
    tab = np.arange(256, dtype=np.uint32)
    for _ in range(8):
        tab = np.where(tab & 1, (tab >> np.uint32(1)) ^ _POLY,
                       tab >> np.uint32(1)).astype(np.uint32)
    return tab


@functools.lru_cache(maxsize=4)
def _tables(width: int) -> tuple[np.ndarray, np.ndarray]:
    """(T, Z) for one pow2 bucket width: T[t][b] = L^t(TAB[b]) (the
    per-position contribution table the device gathers) and
    Z[n] = crc32 of n zero bytes (the affine constant folded back in
    on the host).  Built once per width and cached."""
    tab = _byte_table()
    T = np.empty((width, 256), np.uint32)
    T[0] = tab
    for t in range(1, width):
        p = T[t - 1]
        T[t] = (p >> np.uint32(8)) ^ tab[p & np.uint32(0xFF)]
    Z = np.empty(width + 1, np.uint32)
    Z[0] = 0
    s = _FINAL
    for n in range(1, width + 1):
        s = (s >> np.uint32(8)) ^ tab[s & np.uint32(0xFF)]
        Z[n] = s ^ _FINAL
    return T, Z


@functools.lru_cache(maxsize=16)
def _device_table(width: int, chip_index: int):
    """The position table committed to one chip's device (uploaded
    once per (width, chip), like the EC coding matrices)."""
    import jax.numpy as jnp
    rt = DeviceRuntime.get()
    return rt.chip(chip_index).place(jnp.asarray(_tables(width)[0]))


@functools.lru_cache(maxsize=16)
def _kernel(lanes: int, width: int):
    """One jitted digest program per (lanes, width) bucket: gather
    each byte's positional contribution and XOR-reduce the lane."""
    import jax
    import jax.numpy as jnp

    def run(data, lens, table):
        pos = (lens[:, None]
               - jnp.int32(1)
               - jnp.arange(width, dtype=jnp.int32)[None, :])
        contrib = table[jnp.clip(pos, 0, width - 1),
                        data.astype(jnp.int32)]
        contrib = jnp.where(pos >= 0, contrib, jnp.uint32(0))
        return jax.lax.reduce(contrib, jnp.uint32(0),
                              jax.lax.bitwise_xor, (1,))

    return jax.jit(run)


def crc32_host(bufs) -> list[int]:
    """The host fallback (and the parity oracle): one zlib.crc32 per
    buffer — identical values to the device lanes by construction."""
    return [zlib.crc32(bytes(b)) & 0xFFFFFFFF for b in bufs]


# -- crc32_combine: GF(2)-matrix concatenation fold ----------------------


def _gf2_times(mat: list[int], vec: int) -> int:
    s = 0
    i = 0
    while vec:
        if vec & 1:
            s ^= mat[i]
        vec >>= 1
        i += 1
    return s


def _gf2_square(mat: list[int]) -> list[int]:
    return [_gf2_times(mat, mat[n]) for n in range(32)]


def crc32_combine(crc1: int, crc2: int, len2: int) -> int:
    """crc32(A + B) from crc32(A), crc32(B) and len(B) — zlib's
    crc32_combine ported exactly: advance crc1 through len2 zero
    bytes with square-and-multiply over the 32x32 GF(2) operator
    matrices, then xor crc2's contribution in.  Bit-parity with
    ``zlib.crc32`` is pinned by tests/test_flight_recorder.py; this
    is what lets the device digest lanes stay bounded at
    ``DEVICE_MAX_BYTES`` while whole chunks of any length fold from
    their segment digests on the host."""
    if len2 <= 0:
        return crc1 & 0xFFFFFFFF
    # odd = the one-zero-BIT advance operator
    odd = [0] * 32
    odd[0] = 0xEDB88320
    row = 1
    for n in range(1, 32):
        odd[n] = row
        row <<= 1
    even = _gf2_square(odd)         # 2 bits
    odd = _gf2_square(even)         # 4 bits
    crc1 &= 0xFFFFFFFF
    n = int(len2)
    while True:
        even = _gf2_square(odd)     # 8, 32, 128... zero bits
        if n & 1:
            crc1 = _gf2_times(even, crc1)
        n >>= 1
        if not n:
            break
        odd = _gf2_square(even)
        if n & 1:
            crc1 = _gf2_times(odd, crc1)
        n >>= 1
        if not n:
            break
    return (crc1 ^ crc2) & 0xFFFFFFFF


def _pow2(n: int, floor: int) -> int:
    return 1 << max(int(n) - 1, floor - 1).bit_length()


async def crc32_batch(bufs, chip: int | None = None,
                      klass: str = K_BACKGROUND
                      ) -> tuple[list[int], str]:
    """Digest every buffer in one device dispatch on the caller's
    affinity chip; returns (digests, path) where path is "device" or
    "host".  Any degradation (offload disabled, chip lost, queue
    full, oversized buffer, mid-dispatch failure) lands on the host
    loop — the caller never sees the difference except in telemetry.
    """
    bufs = list(bufs)
    if not bufs:
        return [], "host"
    rt = DeviceRuntime.get()
    target = rt.route(chip)
    maxlen = max(len(b) for b in bufs)
    if (target is None or not target.available or maxlen == 0
            or not device_digest_enabled()):
        return crc32_host(bufs), "host"
    # segment fold: buffers above the lane cap split into
    # <= DEVICE_MAX_BYTES segments, each a lane of the SAME dispatch;
    # whole-buffer digests recombine on host via crc32_combine, so
    # the O(width) position table stays bounded while chunks of any
    # length digest on-device
    segs: list[bytes] = []
    owner: list[tuple[int, int]] = []       # (buf index, seg len)
    for i, b in enumerate(bufs):
        bb = bytes(b)
        for off in range(0, len(bb), DEVICE_MAX_BYTES):
            s = bb[off:off + DEVICE_MAX_BYTES]
            segs.append(s)
            owner.append((i, len(s)))
    width = _pow2(max(len(s) for s in segs), _MIN_WIDTH)
    lanes = _pow2(len(segs), _MIN_LANES)
    if lanes * width > DEVICE_MAX_STAGE_BYTES:
        return crc32_host(bufs), "host"
    total = sum(len(b) for b in bufs)
    ticket = target.open_ticket(klass, width, total)
    try:
        await target.admit(ticket)
    except DeviceBusy:
        return crc32_host(bufs), "host"
    stage = target.pool.lease((lanes, width), np.uint8)
    try:
        import jax.numpy as jnp
        lens = np.zeros(lanes, np.int32)
        for i, s in enumerate(segs):
            a = np.frombuffer(s, np.uint8)
            stage[i, :a.size] = a
            lens[i] = a.size
        target.launch(ticket)           # injected-fault hook
        _t, z = _tables(width)
        lin = np.asarray(_kernel(lanes, width)(
            target.place(jnp.asarray(stage)),
            target.place(jnp.asarray(lens)),
            _device_table(width, target.index)))
        target.note_program("crc32", (lanes, width))
        target.finish(ticket, ok=True)
        # staging accounting in words, like the EC ladder
        target.note_staging(total // 4, (lanes * width) // 4)
        out: list[int] = [0] * len(bufs)
        seen: set[int] = set()
        for lane, (bi, seg_len) in enumerate(owner):
            seg_crc = int(lin[lane]) ^ int(z[lens[lane]])
            if bi not in seen:
                seen.add(bi)
                out[bi] = seg_crc
            else:
                out[bi] = crc32_combine(out[bi], seg_crc, seg_len)
        return out, "device"
    except Exception as e:
        # device loss mid-digest: poison THIS chip (per-chip
        # DEVICE_FALLBACK + probe heal) and finish the scrub on host
        target.finish(ticket, ok=False, error=e)
        target.poison(e)
        return crc32_host(bufs), "host"
    finally:
        target.pool.release(stage)
