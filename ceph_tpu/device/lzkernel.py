"""Vectorized LZ match planning: the compression plane's device kernel.

"GPUs as Storage System Accelerators" (arXiv:1202.3669, PAPERS.md)
measured compression offload profitable on accelerators a decade
before TPUs, and the reason the host path hurts here is the same
reason digests hurt: force-mode compression pools run `zlib.compress`
one blob at a time ON the daemon's event loop.  This module turns the
expensive phase of an LZ-class compressor — match FINDING — into one
batched device dispatch over fixed-size independent blocks, leaving
only the cheap sequential token emission on host (compress/tlz.py):

* **4-byte-gram rolling hash** — every position i hashes its 4-gram
  ``le32(data[i:i+4]) * 2654435761 >> (32 - HBITS)`` (the classic
  LZ4 multiplicative hash), fully parallel across positions and
  lanes.
* **match-candidate gather via composite-key sort** — the sequential
  hash-chain of a scalar LZ compressor ("most recent previous
  position with my hash") is recovered WITHOUT sequential state: sort
  positions by the composite key ``hash * width + pos`` (unique, so
  any sort — host or device — yields the identical order) and each
  position's candidate is its sorted predecessor when the hashes
  match.  One argsort + one shifted compare per lane.
* **vectorized match-length extension** — candidate/position byte
  agreement is evaluated for all ``MAX_MATCH`` offsets at once as a
  gather + compare + masked-cumprod-sum; the result is the exact
  greedy match length a scalar memcmp loop would have produced
  (capped at MAX_MATCH — the cap is part of the FORMAT, so host and
  device emit identical tokens).
* **fixed-geometry blocks on the pow2 lane ladder** — blocks are a
  fixed ``TLZ_BLOCK`` wide (mixed-size blobs become a ragged count of
  fixed blocks — the Ragged Paged Attention discipline,
  arXiv:2604.15464: variable-length work inside fixed-geometry
  programs), lanes bucket pow2 between ``_MIN_LANES`` and
  ``_MAX_LANES``, and oversized batches chunk into several dispatches
  of the SAME program, so the whole plane compiles at most
  ``log2(_MAX_LANES/_MIN_LANES)+1`` programs (4 — well inside the
  ≤8 budget).
* **admission + degradation identical to the digest plane** —
  dispatches ride the ``background`` class with DispatchTicket
  attribution; offload disabled, chip poisoned, DeviceBusy, or a
  mid-dispatch failure (which poisons THIS chip, per-chip
  DEVICE_FALLBACK + probe heal) all land on the pure-numpy
  `match_plan_host`, which is the same function by construction — the
  caller cannot tell the paths apart except in telemetry.

Bit-parity contract: `match_plan_host` and the jitted kernel compute
the identical (candidate, match-length) arrays — integer sort of
unique keys, exact uint8 compares — so compress/tlz.py emits
byte-identical blobs whichever path served the plan (pinned by
tests/test_tlz.py across seeded mixed-size corpora).
"""

from __future__ import annotations

import functools
import os

import numpy as np

from .runtime import DeviceBusy, DeviceRuntime, K_BACKGROUND

# block geometry: the format constants (compress/tlz.py embeds
# TLZ_BLOCK in the container header; MAX_MATCH bounds every emitted
# token's length) — changing either changes the wire format
TLZ_BLOCK = 4096            # bytes per independent block (lane width)
MAX_MATCH = 32              # match-extension cap (vectorization depth)
MIN_MATCH = 4               # shortest emitted match (the 4-gram)

_HBITS = 16                 # hash-table address bits
_HASH_MUL = np.uint32(2654435761)

_MIN_LANES = 8              # pow2 lane floor (tiny blobs share a program)
_MAX_LANES = 64             # lane cap: bigger batches chunk, not compile


def device_compress_enabled() -> bool:
    """Device match planning defaults to on where device EC offload
    is on (a real accelerator backend, or the CEPH_TPU_EC_OFFLOAD
    test override); CEPH_TPU_COMPRESS_OFFLOAD=1/0 forces it
    independently — the same gate shape as the digest plane."""
    v = os.environ.get("CEPH_TPU_COMPRESS_OFFLOAD")
    if v is not None:
        return v not in ("0", "false", "no")
    from ..ec.batcher import device_offload_enabled
    return device_offload_enabled()


def _pow2_lanes(n: int) -> int:
    return 1 << max(int(n) - 1, _MIN_LANES - 1).bit_length()


# -- host reference (and the device kernel's parity oracle) ---------------


def match_plan_host(blocks: np.ndarray,
                    lens: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(cand, mlen) for ``blocks`` [lanes, width] uint8 with per-lane
    valid lengths ``lens``: cand[l, i] is the most recent position
    j < i in lane l whose 4-gram hash equals position i's (-1 when
    none), mlen[l, i] the number of agreeing bytes from (j, i)
    forward, capped at MAX_MATCH and masked to the lane's valid
    length.  Pure numpy — this IS the host fallback, and the device
    kernel below is this function transcribed to jax."""
    lanes, width = blocks.shape
    idx = np.arange(width, dtype=np.int64)
    b = blocks.astype(np.uint32)
    g = [b[:, np.minimum(idx + t, width - 1)] for t in range(4)]
    v = g[0] | (g[1] << np.uint32(8)) | (g[2] << np.uint32(16)) \
        | (g[3] << np.uint32(24))
    h = ((v * _HASH_MUL) >> np.uint32(32 - _HBITS)).astype(np.int64)
    # composite key: unique per position, so ANY sort yields the same
    # order (this is what makes host and device orders identical)
    key = h * width + idx[None, :]
    order = np.argsort(key, axis=1)
    prev = np.concatenate(
        [np.full((lanes, 1), -1, np.int64), order[:, :-1]], axis=1)
    same = np.zeros((lanes, width), bool)
    same[:, 1:] = np.take_along_axis(h, order[:, 1:], 1) \
        == np.take_along_axis(h, order[:, :-1], 1)
    cand_sorted = np.where(same, prev, -1)
    cand = np.empty((lanes, width), np.int64)
    np.put_along_axis(cand, order, cand_sorted, axis=1)
    # vectorized match extension: masked leading-agreement count
    t = np.arange(MAX_MATCH, dtype=np.int64)
    gi = np.broadcast_to(np.minimum(idx[None, :, None] + t, width - 1),
                         (lanes, width, MAX_MATCH))
    gj = np.minimum(np.maximum(cand, 0)[:, :, None] + t, width - 1)
    li = np.take_along_axis(blocks, gi.reshape(lanes, -1),
                            1).reshape(lanes, width, MAX_MATCH)
    lj = np.take_along_axis(blocks, gj.reshape(lanes, -1),
                            1).reshape(lanes, width, MAX_MATCH)
    valid = (idx[None, :, None] + t) < lens.astype(np.int64)[:, None,
                                                             None]
    ok = (li == lj) & valid & (cand >= 0)[:, :, None]
    mlen = np.cumprod(ok.astype(np.int64), axis=2).sum(axis=2)
    return cand.astype(np.int32), mlen.astype(np.int32)


# -- device kernel ---------------------------------------------------------


@functools.lru_cache(maxsize=8)
def _kernel(lanes: int, width: int):
    """One jitted match-planning program per (lanes, width) bucket:
    hash, composite-key sort, predecessor gather, and the masked
    cumprod match extension — the exact arithmetic of
    `match_plan_host`."""
    import jax
    import jax.numpy as jnp

    def run(data, lens):
        idx = jnp.arange(width, dtype=jnp.int32)
        b = data.astype(jnp.uint32)
        g = [b[:, jnp.minimum(idx + t, width - 1)] for t in range(4)]
        v = g[0] | (g[1] << jnp.uint32(8)) \
            | (g[2] << jnp.uint32(16)) | (g[3] << jnp.uint32(24))
        h = ((v * jnp.uint32(_HASH_MUL))
             >> jnp.uint32(32 - _HBITS)).astype(jnp.int32)
        key = h * jnp.int32(width) + idx[None, :]
        order = jnp.argsort(key, axis=1).astype(jnp.int32)
        prev = jnp.concatenate(
            [jnp.full((lanes, 1), -1, jnp.int32), order[:, :-1]],
            axis=1)
        h_sorted = jnp.take_along_axis(h, order, 1)
        same = jnp.concatenate(
            [jnp.zeros((lanes, 1), bool),
             h_sorted[:, 1:] == h_sorted[:, :-1]], axis=1)
        cand_sorted = jnp.where(same, prev, jnp.int32(-1))
        lane_ix = jnp.arange(lanes, dtype=jnp.int32)[:, None]
        cand = jnp.zeros((lanes, width), jnp.int32).at[
            lane_ix, order].set(cand_sorted)
        t = jnp.arange(MAX_MATCH, dtype=jnp.int32)
        gi = jnp.broadcast_to(
            jnp.minimum(idx[None, :, None] + t, width - 1),
            (lanes, width, MAX_MATCH))
        gj = jnp.minimum(jnp.maximum(cand, 0)[:, :, None] + t,
                         width - 1)
        li = jnp.take_along_axis(
            data, gi.reshape(lanes, -1), 1).reshape(lanes, width,
                                                    MAX_MATCH)
        lj = jnp.take_along_axis(
            data, gj.reshape(lanes, -1), 1).reshape(lanes, width,
                                                    MAX_MATCH)
        valid = (idx[None, :, None] + t) < lens[:, None, None]
        ok = (li == lj) & valid & (cand >= 0)[:, :, None]
        mlen = jnp.cumprod(ok.astype(jnp.int32), axis=2).sum(axis=2)
        return cand, mlen

    return jax.jit(run)


def _stage_blocks(segs: list[bytes], lanes: int) -> tuple[np.ndarray,
                                                          np.ndarray]:
    lens = np.zeros(lanes, np.int32)
    stage = np.zeros((lanes, TLZ_BLOCK), np.uint8)
    for i, s in enumerate(segs):
        a = np.frombuffer(s, np.uint8)
        stage[i, :a.size] = a
        lens[i] = a.size
    return stage, lens


async def match_batch(segs: list[bytes], chip: int | None = None,
                      klass: str = K_BACKGROUND
                      ) -> tuple[np.ndarray, np.ndarray, str]:
    """Plan matches for every <= TLZ_BLOCK segment in device
    dispatches on the caller's affinity chip; returns
    (cand, mlen, path) where the arrays cover ``len(segs)`` lanes and
    path is "device" or "host".  Any degradation (offload disabled,
    chip lost, queue full, mid-dispatch failure — which poisons THIS
    chip) lands on the numpy reference, which computes the identical
    plan."""
    n = len(segs)
    if n == 0:
        return (np.zeros((0, TLZ_BLOCK), np.int32),
                np.zeros((0, TLZ_BLOCK), np.int32), "host")
    rt = DeviceRuntime.get()
    target = rt.route(chip)
    if target is None or not target.available \
            or not device_compress_enabled():
        stage, lens = _stage_blocks(segs, n)
        cand, mlen = match_plan_host(stage, lens)
        return cand, mlen, "host"
    cands: list[np.ndarray] = []
    mlens: list[np.ndarray] = []
    # oversized batches chunk into several dispatches of the same
    # lane-capped program family instead of compiling wider ones
    for lo in range(0, n, _MAX_LANES):
        segs_c = segs[lo:lo + _MAX_LANES]
        lanes = min(_pow2_lanes(len(segs_c)), _MAX_LANES)
        total = sum(len(s) for s in segs_c)
        ticket = target.open_ticket(klass, lanes, total)
        try:
            await target.admit(ticket)
        except DeviceBusy:
            stage, lens = _stage_blocks(segs_c, len(segs_c))
            c, m = match_plan_host(stage, lens)
            cands.append(c)
            mlens.append(m)
            target.host_fallbacks += 1
            continue
        stage = target.pool.lease((lanes, TLZ_BLOCK), np.uint8)
        try:
            import jax.numpy as jnp
            lens = np.zeros(lanes, np.int32)
            for i, s in enumerate(segs_c):
                a = np.frombuffer(s, np.uint8)
                stage[i, :a.size] = a
                lens[i] = a.size
            target.launch(ticket)       # injected-fault hook
            c, m = _kernel(lanes, TLZ_BLOCK)(
                target.place(jnp.asarray(stage)),
                target.place(jnp.asarray(lens)))
            c = np.asarray(c)[:len(segs_c)]
            m = np.asarray(m)[:len(segs_c)]
            target.note_program("tlz", (lanes, TLZ_BLOCK))
            target.finish(ticket, ok=True)
            target.note_staging(total // 4,
                                (lanes * TLZ_BLOCK) // 4)
            cands.append(c)
            mlens.append(m)
        except Exception as e:
            # device loss mid-compress: poison THIS chip (per-chip
            # DEVICE_FALLBACK + probe heal) and plan the rest on host
            target.finish(ticket, ok=False, error=e)
            target.poison(e)
            st, lens = _stage_blocks(segs_c, len(segs_c))
            c, m = match_plan_host(st, lens)
            cands.append(c)
            mlens.append(m)
            target.host_fallbacks += 1
            # remaining chunks go through route() again next loop —
            # but this chip is poisoned now, so serve them on host
            remaining = segs[lo + _MAX_LANES:]
            if remaining:
                st, lens = _stage_blocks(remaining, len(remaining))
                c, m = match_plan_host(st, lens)
                cands.append(c)
                mlens.append(m)
            return (np.concatenate(cands, 0),
                    np.concatenate(mlens, 0), "host")
        finally:
            target.pool.release(stage)
    return np.concatenate(cands, 0), np.concatenate(mlens, 0), "device"
