"""Per-process TPU device runtime: the shared substrate under both
accelerator hot paths (batched EC matmuls and bulk CRUSH mapping),
now mesh-aware.

Why a runtime at all (PAPERS: Ragged Paged Attention 2604.15464 for the
shape-bucket recipe; "GPUs as Storage System Accelerators" 1202.3669
for admission control): until this layer existed each hot path talked
to JAX ad hoc — every novel batch width recompiled, staging buffers
were allocated per flush, and nothing bounded device queue depth, so a
mapping storm could starve EC writes.  The runtime centralises four
concerns, each now **per chip** (mesh discipline from "Large Scale
Distributed Linear Algebra With TPUs", 2112.09017):

* **shape-bucketed compile cache** — flushes stage as a **bucket
  ladder** (`ragged_plan`): power-of-two segments covering the exact
  ragged flush total, so only the ladder's tail rounds up instead of
  the whole flush padding to its pow2 ceiling, while steady state
  still hits a handful of jitted programs; `note_program` is the
  compile counter the acceptance criteria assert against, and
  `warmup_ec` pre-compiles the common buckets at OSD boot.  Each chip
  accounts its own programs (a real mesh compiles per chip) and its
  staging waste (`bucket_waste_ratio`).
* **HBM staging pool** — bucket-sized arrays leased/released across
  flushes instead of allocated per flush (`BufferPool`), one pool per
  chip.
* **dispatch queue with admission backpressure** — bounded in-flight
  dispatches, weighted-fair across service classes (client-EC /
  recovery-EC / mapping — the weights mirror the mClock op-scheduler
  profile, osd/scheduler.py DEVICE_DISPATCH_WEIGHTS); queue-full
  surfaces as `DeviceBusy` so callers degrade to deadline-flush or
  the host path instead of piling device work.  One queue per chip,
  so one OSD's storm cannot starve a co-located OSD on another chip.
* **device-loss degradation** — a failed/poisoned dispatch flips
  *its chip* to fallback: only the OSDs whose affinity lands on that
  chip degrade to host paths (and beacon it, so the mon's
  DEVICE_FALLBACK detail names the chip), while the rest of the mesh
  keeps serving on-device.  A per-chip probe loop retries under
  ExpBackoff until the chip heals.

The mesh is enumerated once per runtime (ceph_tpu.device.mesh): real
chips on a TPU host, ``CEPH_TPU_MESH_CHIPS`` logical chips on CPU CI
(or a real forced count under
``XLA_FLAGS=--xla_force_host_platform_device_count=N``).  OSDs take
``chip_for(osd_id)`` affinity; oversized flushes shard column-wise
across every available chip (``shard_plan``) — the proven
collective-free split — and reassemble bit-identically.

Every dispatch carries a `DispatchTicket` (chip, class, bucket, bytes,
enqueue/launch/done stamps) that feeds the exporter
(`device_dispatch_seconds`, `device_queue_depth`,
`device_bucket_hit_ratio`, all labeled by ``chip``) and gives the
OpTracker exact per-op flush attribution.

Back-compat: the single-chip API (``DeviceRuntime.poison/heal/
inject_fault``, aggregate counters, ``pool``/``queue`` views) still
works — on a 1-chip mesh (plain CPU CI) behavior is identical to the
pre-mesh runtime.
"""

from __future__ import annotations

import asyncio
import heapq
import time

import numpy as np

from . import mesh
from ..trace import recorder as flight

# service classes (the device-side analog of the mClock op classes)
K_CLIENT_EC = "client-ec"
K_RECOVERY_EC = "recovery-ec"
K_MAPPING = "mapping"
# background integrity/maintenance work (scrub digests, pool
# compression pacing): weighted below recovery so an always-on scrub
# or a compressed-pool burst can never starve the data-path classes
K_BACKGROUND = "background"


class DeviceBusy(Exception):
    """Admission rejected: the dispatch queue is at its bound.  The
    caller degrades (deadline-flush later, or host fallback) instead
    of stacking more device work."""


class DeviceLost(Exception):
    """A dispatch failed at the device layer (or a fault was
    injected): the chip flips to host fallback."""


class DispatchTicket:
    """One device dispatch's identity + timeline.

    Stamps: t_enqueue (admission requested) -> t_admit (queue granted)
    -> t_launch (dispatch handed to the device) -> t_done.  queue_wait
    and device_s are the two stages the exporter and the OpTracker
    attribute separately.  `t_enqueue` may be passed explicitly so the
    wait an op spent *before* the dispatch existed counts too: the
    stream stamps the earliest admitted op's arrival, the flush path
    its batch's first append — queue_wait is then arrival->grant, not
    merely device-queue wait.  `chip` names the mesh chip the dispatch
    ran on (the exporter's chip label).  `tenant` attributes the
    dispatch to the tenant whose ops it carried — the single tenant
    when every batched item agreed, the literal "mixed" when a flush
    batched several tenants' stripes, None for tenant-less work
    (recovery, scrub, mapping).  `stream` marks a slot dispatch of the
    continuous per-chip stream (False: a legacy/degradation flush)."""

    __slots__ = ("seq", "klass", "bucket", "nbytes", "chip",
                 "t_enqueue", "t_admit", "t_launch", "t_done", "ok",
                 "error", "tenant", "stream")

    def __init__(self, seq: int, klass: str, bucket: int, nbytes: int,
                 chip: int = 0, tenant: str | None = None,
                 t_enqueue: float | None = None,
                 stream: bool = False):
        self.seq = seq
        self.klass = klass
        self.bucket = bucket
        self.nbytes = nbytes
        self.chip = chip
        self.tenant = tenant
        self.stream = bool(stream)
        self.t_enqueue = (time.monotonic() if t_enqueue is None
                          else float(t_enqueue))
        self.t_admit = 0.0
        self.t_launch = 0.0
        self.t_done = 0.0
        self.ok = False
        self.error: str | None = None

    @property
    def queue_wait(self) -> float:
        return max(0.0, (self.t_admit or self.t_enqueue)
                   - self.t_enqueue)

    @property
    def device_s(self) -> float:
        """Wall seconds of the device call itself (launch -> done)."""
        if not self.t_done or not self.t_launch:
            return 0.0
        return max(0.0, self.t_done - self.t_launch)

    def dump(self) -> dict:
        return {"seq": self.seq, "klass": self.klass,
                "bucket": self.bucket, "bytes": self.nbytes,
                "chip": self.chip, "tenant": self.tenant,
                "stream": self.stream,
                "queue_wait": self.queue_wait,
                "device_s": self.device_s, "ok": self.ok,
                "error": self.error}


class BufferPool:
    """Free-lists of bucket-sized staging arrays keyed (shape, dtype).

    The HBM-buffer-pool role scaled to this build's dispatch layer:
    flushes stage their padded batch into a leased array instead of
    allocating per flush, so steady state does zero per-flush
    allocation (tests pin `misses` flat while `hits` grows).  Leased
    arrays come back zeroed — bucket padding must be zero for GF
    bit-parity with the unpadded host encode."""

    def __init__(self, max_per_key: int = 4):
        self.max_per_key = max_per_key
        self._free: dict[tuple, list[np.ndarray]] = {}
        self.hits = 0
        self.misses = 0
        self.outstanding = 0

    def lease(self, shape: tuple, dtype) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        free = self._free.get(key)
        if free:
            arr = free.pop()
            arr[...] = 0
            self.hits += 1
        else:
            arr = np.zeros(shape, dtype=dtype)
            self.misses += 1
        self.outstanding += 1
        return arr

    def release(self, arr: np.ndarray) -> None:
        self.outstanding -= 1
        key = (arr.shape, arr.dtype.str)
        free = self._free.setdefault(key, [])
        if len(free) < self.max_per_key:
            free.append(arr)

    def clear(self) -> None:
        self._free.clear()


class DispatchQueue:
    """Bounded in-flight dispatches with weighted-fair admission.

    Start-time fair queueing over virtual time: each class keeps a
    finish tag advanced by cost/weight per grant, waiters are served
    in tag order — so under contention client-EC (weight 4) gets ~4x
    the grants of mapping (weight 1), mirroring how mClock shares OSD
    capacity.  `admit` parks the caller while the queue has room;
    once `max_queue` waiters are parked further admissions raise
    DeviceBusy — that is the backpressure edge the batcher and the
    mapper degrade on."""

    def __init__(self, weights: dict[str, float],
                 max_inflight: int = 2, max_queue: int = 64):
        self.weights = dict(weights)
        self.max_inflight = max(1, int(max_inflight))
        self.max_queue = max(0, int(max_queue))
        self.inflight = 0
        self._vt = 0.0                      # virtual clock
        self._finish: dict[str, float] = {}
        self._seq = 0
        # heap of (finish_tag, seq, klass, cost, future)
        self._waiters: list = []
        self.granted = {k: 0 for k in self.weights}
        self.rejected = 0

    @property
    def depth(self) -> int:
        return self.inflight + len(self._waiters)

    def _tag(self, klass: str, cost: float) -> float:
        w = self.weights.get(klass, 1.0)
        start = max(self._vt, self._finish.get(klass, 0.0))
        fin = start + cost / max(w, 1e-9)
        self._finish[klass] = fin
        return fin

    def _grant(self, klass: str) -> None:
        self.inflight += 1
        self.granted[klass] = self.granted.get(klass, 0) + 1

    def try_admit(self, klass: str, cost: float = 1.0) -> None:
        """Synchronous, non-blocking admission (the bulk mapper's
        path — it runs outside a coroutine).  Raises DeviceBusy when
        a grant would overtake parked waiters or exceed the bound."""
        if self.inflight >= self.max_inflight or self._waiters:
            self.rejected += 1
            raise DeviceBusy("device dispatch queue at depth %d"
                             % self.depth)
        self._vt = max(self._vt, self._finish.get(klass, 0.0))
        self._tag(klass, cost)
        self._grant(klass)

    async def admit(self, klass: str, cost: float = 1.0) -> None:
        if self.inflight < self.max_inflight and not self._waiters:
            self._tag(klass, cost)
            self._grant(klass)
            return
        if len(self._waiters) >= self.max_queue:
            self.rejected += 1
            raise DeviceBusy("device dispatch queue full (%d waiting)"
                             % len(self._waiters))
        fut = asyncio.get_event_loop().create_future()
        self._seq += 1
        heapq.heappush(self._waiters,
                       (self._tag(klass, cost), self._seq, klass,
                        cost, fut))
        await fut

    def release(self) -> None:
        self.inflight = max(0, self.inflight - 1)
        while self.inflight < self.max_inflight and self._waiters:
            tag, _seq, klass, _cost, fut = heapq.heappop(self._waiters)
            self._vt = max(self._vt, tag)
            if fut.cancelled():
                continue
            self._grant(klass)
            fut.set_result(None)


_MIN_BUCKET = 512          # words: floor so tiny flushes share one program
_TICKET_RING = 512
_HIST_BUCKETS = 32         # power-of-two microsecond histogram

# bucket-ladder cap: a ragged flush stages at most this many pow2
# segments (each an already-compiled bucket program); the tail-only
# rounding then bounds waste at ~n / 2^(cap-1) of the flush, while
# more segments would trade the padding win back for per-dispatch
# overhead
_RAGGED_MAX_SEGMENTS = 6

# words at/above which a flush shards across the mesh's available
# chips (the zero-collective stripe-axis split); conf
# device_shard_min_words overrides via configure()
_SHARD_MIN_WORDS = 1 << 19


class ChipRuntime:
    """One mesh chip's isolation domain: its own DispatchQueue,
    BufferPool, compile-cache accounting, ticket ring and
    fallback/poison state.  OSDs bind to a chip via
    ``DeviceRuntime.chip_for`` affinity; a poisoned chip degrades only
    its own OSDs to the host paths while the rest of the mesh keeps
    serving on-device."""

    def __init__(self, rt: "DeviceRuntime", index: int,
                 weights: dict[str, float], max_inflight: int,
                 max_queue: int):
        self.rt = rt
        self.index = int(index)
        self.queue = DispatchQueue(weights, max_inflight, max_queue)
        self.pool = BufferPool()
        # compile cache bookkeeping: program identity -> compiled once
        # (per chip: a real mesh compiles each program per chip)
        self.programs: set[tuple] = set()
        self.compile_count = 0
        self.bucket_hits = 0
        self.bucket_misses = 0
        # ragged staging accounting: payload vs bucket-padded words
        # per flush (the waste the bucket ladder exists to kill;
        # exported as device_bucket_waste_ratio per chip), plus the
        # counterfactual pad a whole-flush pow2 bucket would have
        # burned — the before/after the bench publishes
        self.staged_payload_words = 0
        self.staged_pad_words = 0
        self.staged_pow2_pad_words = 0
        # repair-traffic accounting (direction-3 codec plane): bytes
        # the recovery flows bound to this chip read from survivors
        # and pushed to rebuilt shards — the observable the
        # locality-aware codecs (LRC/SHEC/CLAY) exist to shrink
        self.repair_bytes_read = 0
        self.repair_bytes_moved = 0
        # compression-plane accounting: raw bytes whose match
        # planning dispatched on this chip vs the blob bytes emitted
        # from those plans (device/lzkernel + compress/tlz) — the
        # observable that says force-mode compression pools stopped
        # burning host CPU here
        self.compress_bytes_in = 0
        self.compress_bytes_out = 0
        # dedup-plane accounting: chunks and bytes whose content
        # fingerprints digested on this chip's CRC lanes — the
        # observable that says dedup fingerprinting stopped burning
        # host CPU here
        self.fingerprint_chunks = 0
        self.fingerprint_bytes = 0
        # dispatch telemetry
        self.tickets: list[DispatchTicket] = []     # bounded ring
        self.dispatch_buckets_us = [0] * _HIST_BUCKETS
        self.dispatches = 0
        self.dispatch_seconds = 0.0
        self.queue_wait_seconds = 0.0  # summed ticket queue waits
        self.host_fallbacks = 0        # flushes served by host codecs
        # device-loss state
        self.fallback = False
        self.fallback_reason: str | None = None
        self.fallback_count = 0
        self.heal_count = 0
        self._fault_budget = 0         # injected failures outstanding
        self._probe_task = None
        self._listeners: list = []     # on_state_change(fallback: bool)
        self._jdev = None              # lazy jax device handle
        self._jdev_resolved = False
        # continuous dispatch stream (device.stream): created lazily
        # on first stream-mode submit so flush-mode/loop-less callers
        # never pay for it
        self._stream = None

    @property
    def stream(self):
        """This chip's persistent dispatch stream (lazy)."""
        if self._stream is None:
            from .stream import DispatchStream
            self._stream = DispatchStream(self)
        return self._stream

    # -- placement ---------------------------------------------------------

    @property
    def jax_device(self):
        """The jax device backing this chip (lazy; None when logical
        chips share the process default device — placement is then a
        no-op, which is the cheap path on single-device CI)."""
        if not self._jdev_resolved:
            self._jdev_resolved = True
            devs = mesh.local_devices()
            if len(devs) > 1:
                self._jdev = devs[self.index % len(devs)]
        return self._jdev

    def place(self, arr):
        """Commit an array to this chip's device (computation follows
        data placement — the 2112.09017 dispatch discipline).  Returns
        the input unchanged when the mesh shares one physical
        device."""
        dev = self.jax_device
        if dev is None:
            return arr
        import jax
        return jax.device_put(arr, dev)

    # -- shape buckets / compile cache ------------------------------------

    def note_program(self, kind: str, key: tuple) -> bool:
        """Record a program dispatch; True when this (kind, key) had
        never compiled on THIS chip before.  The summed
        `compile_count` is the acceptance criterion's counter: a
        steady-state mixed workload must stay within a handful of
        distinct programs."""
        pk = (kind,) + tuple(key)
        if pk in self.programs:
            self.bucket_hits += 1
            return False
        self.programs.add(pk)
        self.compile_count += 1
        self.bucket_misses += 1
        return True

    def note_staging(self, payload_words: int,
                     padded_words: int) -> None:
        """Account one flush's staging: `payload_words` real columns
        staged into `padded_words` of bucket capacity.  The cumulative
        pad/(pad+payload) ratio is the padding-waste figure the
        exporter publishes and bench --device gates on; the pow2
        counterfactual records what rounding the whole flush to its
        pow2 ceiling (the pre-ragged behavior) would have padded."""
        self.staged_payload_words += max(0, int(payload_words))
        self.staged_pad_words += max(
            0, int(padded_words) - int(payload_words))
        self.staged_pow2_pad_words += max(
            0, DeviceRuntime.bucket_for(payload_words)
            - int(payload_words))

    def note_repair(self, bytes_read: int, bytes_moved: int) -> None:
        """Account one shard repair's traffic on this chip: survivor
        bytes sourced (`bytes_read` — what minimum_to_decode's
        minimal shard set actually fetched) and rebuilt bytes pushed
        (`bytes_moved`).  Exported as the chip-labeled
        device_repair_bytes_read/_moved series the repair-traffic
        bench leg gates on."""
        self.repair_bytes_read += max(0, int(bytes_read))
        self.repair_bytes_moved += max(0, int(bytes_moved))

    def note_compress(self, bytes_in: int, bytes_out: int) -> None:
        """Account one device-planned compression: raw bytes in,
        container bytes out.  Exported as the chip-labeled
        device_compress_bytes_in/_out series the compression bench
        leg and the thrasher's poison oracle read."""
        self.compress_bytes_in += max(0, int(bytes_in))
        self.compress_bytes_out += max(0, int(bytes_out))

    def note_fingerprint(self, chunks: int, nbytes: int) -> None:
        """Account one device-fingerprinted chunk batch on this chip.
        Exported as the chip-labeled device_fingerprint_chunks/_bytes
        series the dedup bench leg and `--dedup` gate read."""
        self.fingerprint_chunks += max(0, int(chunks))
        self.fingerprint_bytes += max(0, int(nbytes))

    # -- tickets -----------------------------------------------------------

    def open_ticket(self, klass: str, bucket: int, nbytes: int,
                    tenant: str | None = None,
                    t_enqueue: float | None = None,
                    stream: bool = False) -> DispatchTicket:
        return DispatchTicket(self.rt.next_seq(), klass, bucket,
                              nbytes, chip=self.index, tenant=tenant,
                              t_enqueue=t_enqueue, stream=stream)

    async def admit(self, ticket: DispatchTicket,
                    cost: float | None = None) -> None:
        await self.queue.admit(
            ticket.klass,
            cost if cost is not None
            else max(1.0, ticket.nbytes / 65536.0))
        ticket.t_admit = time.monotonic()

    def try_admit(self, ticket: DispatchTicket,
                  cost: float | None = None) -> None:
        self.queue.try_admit(
            ticket.klass,
            cost if cost is not None
            else max(1.0, ticket.nbytes / 65536.0))
        ticket.t_admit = time.monotonic()

    def launch(self, ticket: DispatchTicket) -> None:
        """Stamp launch; consumes one injected fault if armed (the
        deterministic chip-loss hook the thrasher uses)."""
        ticket.t_launch = time.monotonic()
        if self._fault_budget > 0:
            self._fault_budget -= 1
            raise DeviceLost("injected device fault (chip %d)"
                             % self.index)

    def finish(self, ticket: DispatchTicket, ok: bool = True,
               error: Exception | None = None) -> None:
        ticket.t_done = time.monotonic()
        ticket.ok = ok
        ticket.error = repr(error) if error is not None else None
        self.queue.release()
        self.tickets.append(ticket)
        if len(self.tickets) > _TICKET_RING:
            del self.tickets[:_TICKET_RING // 2]
        self.queue_wait_seconds += ticket.queue_wait
        if ok:
            self.dispatches += 1
            dt = ticket.device_s
            self.dispatch_seconds += dt
            us = max(1, int(dt * 1e6))
            i = min(_HIST_BUCKETS - 1, max(0, us.bit_length() - 1))
            self.dispatch_buckets_us[i] += 1
        # flight recorder: every completed ticket is a device-lane
        # span (the process ring the Perfetto export renders per chip)
        flight.note_ticket(ticket)

    # -- device-loss degradation ------------------------------------------

    @property
    def available(self) -> bool:
        return not self.fallback

    def add_listener(self, fn) -> None:
        """fn(fallback: bool) on every poison/heal transition of THIS
        chip (the OSD bound here uses it to beacon the state change
        immediately)."""
        self._listeners.append(fn)

    def _notify(self) -> None:
        for fn in list(self._listeners):
            try:
                fn(self.fallback)
            except Exception:
                pass        # observability must never sink the runtime

    def poison(self, reason) -> None:
        """Flip this chip to host fallback; a probe loop retries the
        device under ExpBackoff until it heals.  Other chips are
        untouched — their OSDs keep serving on-device."""
        if self.fallback:
            return
        self.fallback = True
        self.fallback_reason = repr(reason)
        self.fallback_count += 1
        self._notify()
        try:
            loop = asyncio.get_event_loop()
            if loop.is_running() and self._probe_task is None:
                self._probe_task = loop.create_task(self._probe_loop())
        except RuntimeError:
            pass            # no loop: heal() is manual (sync callers)

    def heal(self) -> None:
        if not self.fallback:
            return
        self.fallback = False
        self.fallback_reason = None
        self.heal_count += 1
        self._notify()

    def inject_fault(self, n: int = 1) -> None:
        """Arm n deterministic dispatch failures on this chip
        (thrasher hook); probes consume from the same budget, so the
        chip stays in fallback until the budget drains (or
        clear_faults())."""
        self._fault_budget += int(n)

    def clear_faults(self) -> None:
        self._fault_budget = 0

    def _run_probe(self) -> None:
        """One probe dispatch: trivially small device work on this
        chip; raises on failure.  Injected faults make probes fail
        too, so the fallback window is controllable in tests."""
        if self._fault_budget > 0:
            self._fault_budget -= 1
            raise DeviceLost("injected device fault (probe, chip %d)"
                             % self.index)
        import jax.numpy as jnp
        np.asarray(self.place(jnp.zeros((8,), jnp.uint8))
                   + jnp.uint8(1))

    async def _probe_loop(self) -> None:
        from ..utils.backoff import ExpBackoff
        bo = ExpBackoff(base=self.rt._probe_base,
                        cap=self.rt._probe_cap)
        try:
            while self.fallback:
                await bo.sleep()
                try:
                    self._run_probe()
                except Exception:
                    continue
                self.heal()
        finally:
            self._probe_task = None

    # -- telemetry ---------------------------------------------------------

    @property
    def bucket_hit_ratio(self) -> float:
        total = self.bucket_hits + self.bucket_misses
        return self.bucket_hits / total if total else 1.0

    @property
    def bucket_waste_ratio(self) -> float:
        """Fraction of staged bucket capacity that was padding (0.0
        with no flushes yet): the ragged batcher's observable win."""
        total = self.staged_payload_words + self.staged_pad_words
        return self.staged_pad_words / total if total else 0.0

    def utilization(self, window: float | None = None,
                    now: float | None = None) -> dict:
        """Windowed utilization integrals over the ticket ring — the
        per-chip busy/idle accounting arXiv:2112.09017 treats as the
        primary scaling signal:

        * ``busy_frac``  — chip-seconds of device time per wall
          second in the window (can exceed 1.0 while multiple
          dispatches are in flight);
        * ``queue_wait_frac`` — admission-wait seconds per wall
          second (the saturation leading indicator: latency is
          queueing, not compute);
        * ``idle_frac``  — max(0, 1 - busy_frac).

        Only the ticket overlap with the window counts (a dispatch
        straddling the window edge is clipped), so the figures are
        honest rates, not lifetime averages."""
        w = float(window if window is not None
                  else self.rt.util_window)
        t_now = time.monotonic() if now is None else now
        lo = t_now - w
        busy = qwait = 0.0
        for t in self.tickets:
            if not t.t_done or t.t_done <= lo:
                continue
            if t.ok:
                busy += min(t.device_s, t.t_done - lo)
            admit_end = t.t_admit or t.t_done
            if admit_end > lo:
                qwait += min(t.queue_wait, admit_end - lo)
        busy_frac = busy / w if w > 0 else 0.0
        qw_frac = qwait / w if w > 0 else 0.0
        return {"window_s": round(w, 3),
                "busy_frac": round(busy_frac, 4),
                "queue_wait_frac": round(qw_frac, 4),
                "idle_frac": round(max(0.0, 1.0 - busy_frac), 4)}

    def metrics(self) -> dict:
        util = self.utilization()
        # dispatch-stream telemetry (zeros/identity until the first
        # stream-mode submit creates the stream — metrics() must
        # never instantiate it)
        s = self._stream
        return {
            "device_queue_depth": self.queue.depth,
            "device_inflight": self.queue.inflight,
            "device_bucket_hit_ratio": round(self.bucket_hit_ratio, 4),
            "device_bucket_waste_ratio": round(self.bucket_waste_ratio,
                                               4),
            "device_compile_count": self.compile_count,
            "device_dispatches": self.dispatches,
            "device_host_fallbacks": self.host_fallbacks,
            "device_pool_hits": self.pool.hits,
            "device_pool_misses": self.pool.misses,
            "device_fallback": int(self.fallback),
            "device_fallback_count": self.fallback_count,
            "device_heal_count": self.heal_count,
            "device_queue_rejected": self.queue.rejected,
            # windowed utilization integrals (chip-labeled gauges:
            # saturation visible per chip, cluster-wide via the mgr)
            "device_util_busy": util["busy_frac"],
            "device_util_queue_wait": util["queue_wait_frac"],
            "device_util_idle": util["idle_frac"],
            # continuous dispatch stream: payload fraction of slot
            # capacity, mean arrival->slot-grant latency, ops retired
            # independently, and ops still pending admission
            "device_slot_occupancy": round(
                s.slot_occupancy if s is not None else 1.0, 4),
            "device_admission_wait": round(
                s.admission_wait_mean if s is not None else 0.0, 6),
            "device_stream_retires": s.retired if s is not None else 0,
            "device_stream_pending": s.pending if s is not None else 0,
            # repair-traffic plane: survivor bytes read / rebuilt
            # bytes pushed by the recovery flows bound to this chip
            "device_repair_bytes_read": self.repair_bytes_read,
            "device_repair_bytes_moved": self.repair_bytes_moved,
            # compression plane: raw bytes match-planned on this chip
            # vs emitted container bytes (ratio = in/out)
            "device_compress_bytes_in": self.compress_bytes_in,
            "device_compress_bytes_out": self.compress_bytes_out,
            # dedup plane: chunks / bytes content-fingerprinted on
            # this chip's CRC lanes
            "device_fingerprint_chunks": self.fingerprint_chunks,
            "device_fingerprint_bytes": self.fingerprint_bytes,
        }


class DeviceRuntime:
    """One per process (per event loop, with a loop-less fallback for
    synchronous callers such as the bulk mapper warming outside
    asyncio).  Both hot paths route dispatches through here — each
    onto a mesh chip (``ChipRuntime``): OSDs via ``chip_for``
    affinity, chip-less callers via ``route(None)`` (first available
    chip)."""

    _global: "DeviceRuntime | None" = None

    def __init__(self, weights: dict[str, float] | None = None,
                 max_inflight: int = 2, max_queue: int = 64,
                 chips: int | None = None):
        if weights is None:
            from ..osd.scheduler import DEVICE_DISPATCH_WEIGHTS
            weights = DEVICE_DISPATCH_WEIGHTS
        n = int(chips) if chips else mesh.chip_count()
        self._seq = 0
        self._probe_base = 0.05
        self._probe_cap = 1.0
        self.shard_min_words = _SHARD_MIN_WORDS
        self.util_window = 10.0     # utilization-integral window (s)
        # continuous dispatch stream (device.stream): mode + geometry.
        # "stream" is the architecture default — the flush batcher
        # survives behind "flush" as the degradation route and the
        # bench baseline
        self.dispatch_mode = "stream"
        self.stream_interval = 100e-6   # admission-loop idle tick (s)
        self.stream_slot_words = 1 << 19  # slot-group geometry cap
        self.stream_max_slots = 4         # in-flight slots per chip
        self.stream_weights = dict(weights)
        # per-tenant dmClock rows the stream orders admission by
        # (osd_mclock_tenant_qos; weight column only — reservation
        # and limit stay host-side in the op scheduler)
        self.tenant_qos: dict[str, tuple] = {}
        self.chips: list[ChipRuntime] = [
            ChipRuntime(self, i, weights, max_inflight, max_queue)
            for i in range(max(1, n))]

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def get(cls) -> "DeviceRuntime":
        """Loop-local instance (lifetime tracks the loop, same
        reasoning as DeviceBatcher.get); synchronous callers with no
        loop share a process-global instance."""
        try:
            loop = asyncio.get_event_loop()
        except RuntimeError:
            loop = None
        if loop is None:
            if cls._global is None:
                cls._global = cls()
            return cls._global
        inst = getattr(loop, "_ceph_tpu_device_runtime", None)
        if inst is None:
            inst = cls()
            loop._ceph_tpu_device_runtime = inst
        return inst

    @classmethod
    def reset(cls, chips: int | None = None) -> "DeviceRuntime":
        """Fresh instance bound to the current loop (tests); `chips`
        forces the logical mesh size regardless of environment."""
        inst = cls(chips=chips)
        try:
            loop = asyncio.get_event_loop()
            loop._ceph_tpu_device_runtime = inst
        except RuntimeError:
            cls._global = inst
        return inst

    def configure(self, conf) -> None:
        """Adopt daemon config (OSD boot): per-chip queue bounds +
        probe ramp + mesh shard threshold."""
        try:
            max_inflight = max(1, int(conf["device_max_inflight"]))
            max_queue = int(conf["device_queue_len"])
            for c in self.chips:
                c.queue.max_inflight = max_inflight
                c.queue.max_queue = max_queue
            self.probe_interval = float(conf["device_probe_interval"])
            self._probe_base = self.probe_interval / 4.0
            self._probe_cap = self.probe_interval
        except (KeyError, TypeError):
            pass
        try:
            self.shard_min_words = max(
                _MIN_BUCKET, int(conf["device_shard_min_words"]))
        except (KeyError, TypeError, ValueError):
            pass
        try:
            self.util_window = max(
                0.1, float(conf["device_util_window"]))
        except (KeyError, TypeError, ValueError):
            pass
        # dispatch-stream mode + geometry + per-tenant admission rows
        try:
            self.dispatch_mode = str(conf["device_dispatch_mode"])
            self.stream_interval = max(
                1e-6, int(conf["device_stream_interval_us"]) / 1e6)
            self.stream_slot_words = max(
                _MIN_BUCKET, int(conf["device_stream_slot_words"]))
            self.stream_max_slots = max(
                1, int(conf["device_stream_max_slots"]))
        except (KeyError, TypeError, ValueError):
            pass
        try:
            from ..osd.scheduler import parse_tenant_qos
            self.tenant_qos = parse_tenant_qos(
                str(conf.get("osd_mclock_tenant_qos", "") or ""))
        except Exception:
            pass
        # flush-mode tunables ride along: the loop's batcher adopts
        # the conf window/size triggers (the stream ignores both)
        try:
            from ..ec.batcher import DeviceBatcher
            bat = DeviceBatcher.get()
            bat.window_us = max(1, int(conf["ec_batch_flush_us"]))
            bat.max_batch_bytes = max(
                1 << 12, int(conf["ec_batch_max_bytes"]))
        except (KeyError, TypeError, ValueError, RuntimeError):
            pass

    # -- mesh placement ----------------------------------------------------

    @property
    def n_chips(self) -> int:
        return len(self.chips)

    def chip(self, index: int | None = None) -> ChipRuntime:
        """Chip by index (modulo the mesh), or the default chip."""
        if index is None:
            index = 0
        return self.chips[int(index) % len(self.chips)]

    def chip_for(self, osd_id: int) -> ChipRuntime:
        """The chip OSD `osd_id` binds to: deterministic modulo
        affinity, so co-located daemons land on distinct chips and a
        chip loss degrades a knowable OSD subset."""
        return self.chips[mesh.affinity(osd_id, len(self.chips))]

    def route(self, chip: int | None) -> ChipRuntime | None:
        """Resolve a dispatch target.  An explicit chip index is
        honored even while poisoned (the caller's affinity chip IS
        its isolation domain — it must degrade to host, not borrow a
        neighbor and erode the isolation story).  None picks the
        first available chip (chip-less callers: client-side codecs,
        warmup, bulk mapping outside a daemon) and returns None only
        when the whole mesh is down."""
        if chip is not None:
            return self.chips[int(chip) % len(self.chips)]
        for c in self.chips:
            if c.available:
                return c
        return None

    def chip_available(self, chip: int | None = None) -> bool:
        """Availability gate: explicit chip -> that chip's state;
        None -> any chip available."""
        if chip is not None:
            return self.chips[int(chip) % len(self.chips)].available
        return any(c.available for c in self.chips)

    def available_chips(self) -> list[ChipRuntime]:
        return [c for c in self.chips if c.available]

    def shard_plan(self, chip: ChipRuntime,
                   n_words: int) -> list[tuple[ChipRuntime, int, int]]:
        """Column ranges for one flush: [(chip, lo, hi)].  A flush at
        or above `shard_min_words` splits contiguously across the
        owning chip plus every other available chip — the stripe-axis
        split MULTICHIP_SCALING.json proves collective-free — and
        reassembles bit-identically (GF parity is column-independent).
        Below the threshold (or on a 1-chip mesh) the plan is the
        single owning chip."""
        n_words = int(n_words)
        targets = [chip] + [c for c in self.chips
                            if c.available and c is not chip]
        if n_words < self.shard_min_words or len(targets) == 1:
            return [(chip, 0, n_words)]
        per = -(-n_words // len(targets))       # ceil
        plan = []
        lo = 0
        for c in targets:
            hi = min(n_words, lo + per)
            if hi <= lo:
                break
            plan.append((c, lo, hi))
            lo = hi
        return plan

    def next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def note_program(self, kind: str, key: tuple) -> bool:
        """Chip-less compile accounting (the crush device mapper's
        deep hook has no chip context): attributed to the first
        available chip."""
        target = self.route(None) or self.chips[0]
        return target.note_program(kind, key)

    # -- shape buckets / warmup -------------------------------------------

    @staticmethod
    def bucket_for(n_words: int) -> int:
        """Pad target: next power of two >= n, floored at _MIN_BUCKET
        so micro-flushes share one program."""
        n = max(int(n_words), _MIN_BUCKET)
        return 1 << (n - 1).bit_length()

    @classmethod
    def ragged_plan(cls, n_words: int,
                    max_segments: int | None = None
                    ) -> list[tuple[int, int]]:
        """Bucket ladder for one ragged flush: [(lo, segment_bucket)]
        covering `n_words` columns with power-of-two segments (each an
        already-compiled bucket program, so the compile cache stays
        bounded).  Only the ladder's TAIL rounds up — greedy
        largest-pow2-first, final remainder to its own bucket — so a
        mixed-size flush wastes at most one small bucket instead of
        padding the whole total to the next power of two (the Ragged
        Paged Attention recipe, arXiv:2604.15464: one program family
        serving variable-length batches from packed buffers).  When
        the ladder would pad as much as the single pow2 bucket it
        degenerates to that bucket (one dispatch beats several for
        equal padding)."""
        n = max(int(n_words), 1)
        single = cls.bucket_for(n)
        cap = max_segments or _RAGGED_MAX_SEGMENTS
        plan: list[tuple[int, int]] = []
        lo = 0
        remaining = n
        while len(plan) < cap - 1 and remaining > _MIN_BUCKET:
            p = 1 << (remaining.bit_length() - 1)
            plan.append((lo, p))
            lo += p
            remaining -= p
        if remaining > 0:
            b = cls.bucket_for(remaining)
            plan.append((lo, b))
            lo += b
        if lo >= single:
            return [(0, single)]
        return plan

    async def warmup_ec(self, matrix, w: int,
                        buckets: tuple = (1024, 4096, 16384),
                        chip: int | None = None) -> None:
        """Pre-compile the common EC buckets for one coding matrix at
        boot — on the caller's affinity chip (OSD boot passes its
        own) — so the first client flushes hit the cache instead of
        paying a compile inside the write path."""
        from ..ec.batcher import DeviceBatcher
        target = self.route(chip)
        if target is None:
            return
        matrix_key = tuple(tuple(r) for r in matrix)
        k = len(matrix[0])
        dtype = {8: np.uint8, 16: np.uint16, 32: np.uint32}[int(w)]
        for b in buckets:
            if not target.available:
                return
            key = ("ec", matrix_key, int(w), int(b))
            if key in target.programs:
                continue
            try:
                enc = DeviceBatcher._encoder(matrix_key, int(w))
                buf = target.pool.lease((k, int(b)), dtype)
                try:
                    np.asarray(enc(target.place(buf)))
                finally:
                    target.pool.release(buf)
                target.note_program("ec",
                                    (matrix_key, int(w), int(b)))
            except Exception as e:      # warmup must never wedge boot
                target.poison(e)
                return
            await asyncio.sleep(0)      # yield between compiles

    # -- aggregate views (single-chip back-compat + telemetry) ------------

    def _sum(self, attr: str) -> int:
        return sum(getattr(c, attr) for c in self.chips)

    @property
    def compile_count(self) -> int:
        return self._sum("compile_count")

    @property
    def bucket_hits(self) -> int:
        return self._sum("bucket_hits")

    @property
    def bucket_misses(self) -> int:
        return self._sum("bucket_misses")

    @property
    def dispatches(self) -> int:
        return self._sum("dispatches")

    @property
    def dispatch_seconds(self) -> float:
        return sum(c.dispatch_seconds for c in self.chips)

    @property
    def host_fallbacks(self) -> int:
        return self._sum("host_fallbacks")

    @host_fallbacks.setter
    def host_fallbacks(self, v: int) -> None:
        # legacy `rt.host_fallbacks += 1` path: the default chip
        # absorbs the delta (mesh-aware callers count on their chip)
        others = sum(c.host_fallbacks for c in self.chips[1:])
        self.chips[0].host_fallbacks = max(0, int(v) - others)

    @property
    def fallback_count(self) -> int:
        return self._sum("fallback_count")

    @property
    def heal_count(self) -> int:
        return self._sum("heal_count")

    @property
    def programs(self) -> set:
        out: set = set()
        for c in self.chips:
            out |= c.programs
        return out

    @property
    def tickets(self) -> list[DispatchTicket]:
        out: list[DispatchTicket] = []
        for c in self.chips:
            out.extend(c.tickets)
        out.sort(key=lambda t: t.seq)
        return out

    @property
    def pool(self) -> BufferPool:
        """Default chip's staging pool (single-chip back-compat)."""
        return self.chips[0].pool

    @property
    def queue(self) -> DispatchQueue:
        """Default chip's dispatch queue (single-chip back-compat)."""
        return self.chips[0].queue

    @property
    def bucket_hit_ratio(self) -> float:
        total = self.bucket_hits + self.bucket_misses
        return self.bucket_hits / total if total else 1.0

    @property
    def bucket_waste_ratio(self) -> float:
        """Mesh-aggregate staging waste: padded words that carried no
        payload over total staged capacity."""
        pay = self._sum("staged_payload_words")
        pad = self._sum("staged_pad_words")
        return pad / (pay + pad) if (pay + pad) else 0.0

    @property
    def pow2_waste_ratio(self) -> float:
        """What the same flushes would have wasted under whole-flush
        pow2 bucketing (the counterfactual the ragged figure is
        gated against)."""
        pay = self._sum("staged_payload_words")
        pad = self._sum("staged_pow2_pad_words")
        return pad / (pay + pad) if (pay + pad) else 0.0

    @property
    def fallback(self) -> bool:
        """Whole-mesh loss: every chip poisoned.  Per-chip state is
        `chips[i].fallback` (what OSD beacons carry)."""
        return all(c.fallback for c in self.chips)

    @property
    def fallback_reason(self) -> str | None:
        for c in self.chips:
            if c.fallback_reason:
                return c.fallback_reason
        return None

    @property
    def available(self) -> bool:
        return any(c.available for c in self.chips)

    def add_listener(self, fn) -> None:
        """Mesh-wide listener (back-compat): fires on every chip's
        transition.  Per-OSD daemons register on their affinity chip
        instead."""
        for c in self.chips:
            c.add_listener(fn)

    def poison(self, reason) -> None:
        """Whole-mesh poison (back-compat / catastrophic loss): every
        chip flips to host fallback."""
        for c in self.chips:
            c.poison(reason)

    def heal(self) -> None:
        for c in self.chips:
            c.heal()

    def inject_fault(self, n: int = 1) -> None:
        """Arm n failures on EVERY chip (whole-device loss shape);
        chip-scoped injection is `chips[i].inject_fault`."""
        for c in self.chips:
            c.inject_fault(n)

    def clear_faults(self) -> None:
        for c in self.chips:
            c.clear_faults()

    # -- telemetry ---------------------------------------------------------

    def dispatch_pctls(self) -> dict:
        """p50/p99 (ms) over every chip's ticket ring."""
        samples = sorted(t.device_s for c in self.chips
                         for t in c.tickets if t.ok)
        if not samples:
            return {"n": 0}
        n = len(samples)

        def at(p):
            return round(samples[min(n - 1, int(p / 100.0 * n))] * 1e3,
                         4)

        return {"n": n, "p50": at(50), "p99": at(99)}

    def metrics(self) -> dict:
        """Mesh-aggregate metric map (the pre-mesh names; per-chip
        series come from prom_lines' chip label)."""
        return {
            "device_chips": len(self.chips),
            "device_queue_depth": sum(c.queue.depth
                                      for c in self.chips),
            "device_inflight": sum(c.queue.inflight
                                   for c in self.chips),
            "device_bucket_hit_ratio": round(self.bucket_hit_ratio, 4),
            "device_bucket_waste_ratio": round(self.bucket_waste_ratio,
                                               4),
            "device_compile_count": self.compile_count,
            "device_dispatches": self.dispatches,
            "device_host_fallbacks": self.host_fallbacks,
            "device_pool_hits": self._sum_pool("hits"),
            "device_pool_misses": self._sum_pool("misses"),
            "device_fallback": int(self.fallback),
            "device_fallback_count": self.fallback_count,
            "device_heal_count": self.heal_count,
            "device_queue_rejected": sum(c.queue.rejected
                                         for c in self.chips),
            "device_fallback_chips": sum(1 for c in self.chips
                                         if c.fallback),
        }

    def _sum_pool(self, attr: str) -> int:
        return sum(getattr(c.pool, attr) for c in self.chips)

    def prom_lines(self, prefix: str = "ceph_tpu") -> list[str]:
        """Prometheus exposition lines: every device series carries a
        ``chip`` label (one series per mesh chip), plus the unlabeled
        mesh-size gauge.  TYPE is emitted once per family across
        chips (the exposition rule utils.exporter lints)."""
        from ..utils.exporter import hist_lines
        lines = ["# HELP %s_device_chips chips in the device mesh"
                 % prefix,
                 "# TYPE %s_device_chips gauge" % prefix,
                 "%s_device_chips %d" % (prefix, len(self.chips))]
        typed: set[str] = set()
        hist_typed: set[str] = set()
        for c in self.chips:
            label = 'chip="%d"' % c.index
            for name, val in sorted(c.metrics().items()):
                base = "%s_%s" % (prefix, name)
                if base not in typed:
                    typed.add(base)
                    lines.append("# HELP %s per-chip %s" % (base, name))
                    lines.append("# TYPE %s gauge" % base)
                lines.append("%s{%s} %g" % (base, label, float(val)))
            lines.extend(hist_lines(
                "%s_device_dispatch_seconds" % prefix,
                c.dispatch_buckets_us, labels=label,
                typed=hist_typed,
                desc="per-chip dispatch wall time "
                     "(us pow2 buckets)"))
        return lines
