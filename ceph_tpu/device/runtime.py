"""Per-process TPU device runtime: the shared substrate under both
accelerator hot paths (batched EC matmuls and bulk CRUSH mapping).

Why a runtime at all (PAPERS: Ragged Paged Attention 2604.15464 for the
shape-bucket recipe; "GPUs as Storage System Accelerators" 1202.3669
for admission control): until this layer existed each hot path talked
to JAX ad hoc — every novel batch width recompiled, staging buffers
were allocated per flush, and nothing bounded device queue depth, so a
mapping storm could starve EC writes.  The runtime centralises four
concerns:

* **shape-bucketed compile cache** — batches pad to power-of-two
  word-count buckets so steady state hits a handful of jitted
  programs; `note_program` is the compile counter the acceptance
  criteria assert against, and `warmup_ec` pre-compiles the common
  buckets at OSD boot.
* **HBM staging pool** — bucket-sized arrays leased/released across
  flushes instead of allocated per flush (`BufferPool`).
* **dispatch queue with admission backpressure** — bounded in-flight
  dispatches, weighted-fair across service classes (client-EC /
  recovery-EC / mapping — the weights mirror the mClock op-scheduler
  profile, osd/scheduler.py DEVICE_DISPATCH_WEIGHTS); queue-full
  surfaces as `DeviceBusy` so callers degrade to deadline-flush or
  the host path instead of piling device work.
* **device-loss degradation** — a failed/poisoned dispatch flips the
  runtime to fallback (`available` False: the EC batcher encodes on
  the host codecs, PoolMapping takes the scalar mapper), OSD beacons
  carry the flag so the mon raises DEVICE_FALLBACK, and a probe loop
  retries under ExpBackoff until the device heals.

Every dispatch carries a `DispatchTicket` (class, bucket, bytes,
enqueue/launch/done stamps) that feeds the exporter
(`device_dispatch_seconds`, `device_queue_depth`,
`device_bucket_hit_ratio`) and gives the OpTracker exact per-op flush
attribution (the ticket IS the op's device-dispatch stage — no more
sampling the batcher's last flush time).
"""

from __future__ import annotations

import asyncio
import heapq
import time

import numpy as np

# service classes (the device-side analog of the mClock op classes)
K_CLIENT_EC = "client-ec"
K_RECOVERY_EC = "recovery-ec"
K_MAPPING = "mapping"


class DeviceBusy(Exception):
    """Admission rejected: the dispatch queue is at its bound.  The
    caller degrades (deadline-flush later, or host fallback) instead
    of stacking more device work."""


class DeviceLost(Exception):
    """A dispatch failed at the device layer (or a fault was
    injected): the runtime flips to host fallback."""


class DispatchTicket:
    """One device dispatch's identity + timeline.

    Stamps: t_enqueue (admission requested) -> t_admit (queue granted)
    -> t_launch (dispatch handed to the device) -> t_done.  queue_wait
    and device_s are the two stages the exporter and the OpTracker
    attribute separately."""

    __slots__ = ("seq", "klass", "bucket", "nbytes", "t_enqueue",
                 "t_admit", "t_launch", "t_done", "ok", "error")

    def __init__(self, seq: int, klass: str, bucket: int, nbytes: int):
        self.seq = seq
        self.klass = klass
        self.bucket = bucket
        self.nbytes = nbytes
        self.t_enqueue = time.monotonic()
        self.t_admit = 0.0
        self.t_launch = 0.0
        self.t_done = 0.0
        self.ok = False
        self.error: str | None = None

    @property
    def queue_wait(self) -> float:
        return max(0.0, (self.t_admit or self.t_enqueue)
                   - self.t_enqueue)

    @property
    def device_s(self) -> float:
        """Wall seconds of the device call itself (launch -> done)."""
        if not self.t_done or not self.t_launch:
            return 0.0
        return max(0.0, self.t_done - self.t_launch)

    def dump(self) -> dict:
        return {"seq": self.seq, "klass": self.klass,
                "bucket": self.bucket, "bytes": self.nbytes,
                "queue_wait": self.queue_wait,
                "device_s": self.device_s, "ok": self.ok,
                "error": self.error}


class BufferPool:
    """Free-lists of bucket-sized staging arrays keyed (shape, dtype).

    The HBM-buffer-pool role scaled to this build's dispatch layer:
    flushes stage their padded batch into a leased array instead of
    allocating per flush, so steady state does zero per-flush
    allocation (tests pin `misses` flat while `hits` grows).  Leased
    arrays come back zeroed — bucket padding must be zero for GF
    bit-parity with the unpadded host encode."""

    def __init__(self, max_per_key: int = 4):
        self.max_per_key = max_per_key
        self._free: dict[tuple, list[np.ndarray]] = {}
        self.hits = 0
        self.misses = 0
        self.outstanding = 0

    def lease(self, shape: tuple, dtype) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        free = self._free.get(key)
        if free:
            arr = free.pop()
            arr[...] = 0
            self.hits += 1
        else:
            arr = np.zeros(shape, dtype=dtype)
            self.misses += 1
        self.outstanding += 1
        return arr

    def release(self, arr: np.ndarray) -> None:
        self.outstanding -= 1
        key = (arr.shape, arr.dtype.str)
        free = self._free.setdefault(key, [])
        if len(free) < self.max_per_key:
            free.append(arr)

    def clear(self) -> None:
        self._free.clear()


class DispatchQueue:
    """Bounded in-flight dispatches with weighted-fair admission.

    Start-time fair queueing over virtual time: each class keeps a
    finish tag advanced by cost/weight per grant, waiters are served
    in tag order — so under contention client-EC (weight 4) gets ~4x
    the grants of mapping (weight 1), mirroring how mClock shares OSD
    capacity.  `admit` parks the caller while the queue has room;
    once `max_queue` waiters are parked further admissions raise
    DeviceBusy — that is the backpressure edge the batcher and the
    mapper degrade on."""

    def __init__(self, weights: dict[str, float],
                 max_inflight: int = 2, max_queue: int = 64):
        self.weights = dict(weights)
        self.max_inflight = max(1, int(max_inflight))
        self.max_queue = max(0, int(max_queue))
        self.inflight = 0
        self._vt = 0.0                      # virtual clock
        self._finish: dict[str, float] = {}
        self._seq = 0
        # heap of (finish_tag, seq, klass, cost, future)
        self._waiters: list = []
        self.granted = {k: 0 for k in self.weights}
        self.rejected = 0

    @property
    def depth(self) -> int:
        return self.inflight + len(self._waiters)

    def _tag(self, klass: str, cost: float) -> float:
        w = self.weights.get(klass, 1.0)
        start = max(self._vt, self._finish.get(klass, 0.0))
        fin = start + cost / max(w, 1e-9)
        self._finish[klass] = fin
        return fin

    def _grant(self, klass: str) -> None:
        self.inflight += 1
        self.granted[klass] = self.granted.get(klass, 0) + 1

    def try_admit(self, klass: str, cost: float = 1.0) -> None:
        """Synchronous, non-blocking admission (the bulk mapper's
        path — it runs outside a coroutine).  Raises DeviceBusy when
        a grant would overtake parked waiters or exceed the bound."""
        if self.inflight >= self.max_inflight or self._waiters:
            self.rejected += 1
            raise DeviceBusy("device dispatch queue at depth %d"
                             % self.depth)
        self._vt = max(self._vt, self._finish.get(klass, 0.0))
        self._tag(klass, cost)
        self._grant(klass)

    async def admit(self, klass: str, cost: float = 1.0) -> None:
        if self.inflight < self.max_inflight and not self._waiters:
            self._tag(klass, cost)
            self._grant(klass)
            return
        if len(self._waiters) >= self.max_queue:
            self.rejected += 1
            raise DeviceBusy("device dispatch queue full (%d waiting)"
                             % len(self._waiters))
        fut = asyncio.get_event_loop().create_future()
        self._seq += 1
        heapq.heappush(self._waiters,
                       (self._tag(klass, cost), self._seq, klass,
                        cost, fut))
        await fut

    def release(self) -> None:
        self.inflight = max(0, self.inflight - 1)
        while self.inflight < self.max_inflight and self._waiters:
            tag, _seq, klass, _cost, fut = heapq.heappop(self._waiters)
            self._vt = max(self._vt, tag)
            if fut.cancelled():
                continue
            self._grant(klass)
            fut.set_result(None)


_MIN_BUCKET = 512          # words: floor so tiny flushes share one program
_TICKET_RING = 512
_HIST_BUCKETS = 32         # power-of-two microsecond histogram


class DeviceRuntime:
    """One per process (per event loop, with a loop-less fallback for
    synchronous callers such as the bulk mapper warming outside
    asyncio).  Both hot paths route dispatches through here."""

    _global: "DeviceRuntime | None" = None

    def __init__(self, weights: dict[str, float] | None = None,
                 max_inflight: int = 2, max_queue: int = 64):
        if weights is None:
            from ..osd.scheduler import DEVICE_DISPATCH_WEIGHTS
            weights = DEVICE_DISPATCH_WEIGHTS
        self.queue = DispatchQueue(weights, max_inflight, max_queue)
        self.pool = BufferPool()
        # compile cache bookkeeping: program identity -> compiled once
        self.programs: set[tuple] = set()
        self.compile_count = 0
        self.bucket_hits = 0
        self.bucket_misses = 0
        # dispatch telemetry
        self._seq = 0
        self.tickets: list[DispatchTicket] = []     # bounded ring
        self.dispatch_buckets_us = [0] * _HIST_BUCKETS
        self.dispatches = 0
        self.dispatch_seconds = 0.0
        self.host_fallbacks = 0        # flushes served by host codecs
        # device-loss state
        self.fallback = False
        self.fallback_reason: str | None = None
        self.fallback_count = 0
        self.heal_count = 0
        self._fault_budget = 0         # injected failures outstanding
        self._probe_task = None
        self._probe_base = 0.05
        self._probe_cap = 1.0
        self._listeners: list = []     # on_state_change(fallback: bool)

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def get(cls) -> "DeviceRuntime":
        """Loop-local instance (lifetime tracks the loop, same
        reasoning as DeviceBatcher.get); synchronous callers with no
        loop share a process-global instance."""
        try:
            loop = asyncio.get_event_loop()
        except RuntimeError:
            loop = None
        if loop is None:
            if cls._global is None:
                cls._global = cls()
            return cls._global
        inst = getattr(loop, "_ceph_tpu_device_runtime", None)
        if inst is None:
            inst = cls()
            loop._ceph_tpu_device_runtime = inst
        return inst

    @classmethod
    def reset(cls) -> "DeviceRuntime":
        """Fresh instance bound to the current loop (tests)."""
        inst = cls()
        try:
            loop = asyncio.get_event_loop()
            loop._ceph_tpu_device_runtime = inst
        except RuntimeError:
            cls._global = inst
        return inst

    def configure(self, conf) -> None:
        """Adopt daemon config (OSD boot): queue bounds + probe ramp."""
        try:
            self.queue.max_inflight = max(
                1, int(conf["device_max_inflight"]))
            self.queue.max_queue = int(conf["device_queue_len"])
            self.probe_interval = float(conf["device_probe_interval"])
            self._probe_base = self.probe_interval / 4.0
            self._probe_cap = self.probe_interval
        except (KeyError, TypeError):
            pass

    # -- shape buckets / compile cache ------------------------------------

    @staticmethod
    def bucket_for(n_words: int) -> int:
        """Pad target: next power of two >= n, floored at _MIN_BUCKET
        so micro-flushes share one program."""
        n = max(int(n_words), _MIN_BUCKET)
        return 1 << (n - 1).bit_length()

    def note_program(self, kind: str, key: tuple) -> bool:
        """Record a program dispatch; True when this (kind, key) had
        never compiled before.  `compile_count` is the acceptance
        criterion's counter: a steady-state mixed workload must stay
        within a handful of distinct programs."""
        pk = (kind,) + tuple(key)
        if pk in self.programs:
            self.bucket_hits += 1
            return False
        self.programs.add(pk)
        self.compile_count += 1
        self.bucket_misses += 1
        return True

    @property
    def bucket_hit_ratio(self) -> float:
        total = self.bucket_hits + self.bucket_misses
        return self.bucket_hits / total if total else 1.0

    async def warmup_ec(self, matrix, w: int,
                        buckets: tuple = (1024, 4096, 16384)) -> None:
        """Pre-compile the common EC buckets for one coding matrix at
        boot so the first client flushes hit the cache instead of
        paying a compile inside the write path."""
        from ..ec.batcher import DeviceBatcher
        matrix_key = tuple(tuple(r) for r in matrix)
        k = len(matrix[0])
        dtype = {8: np.uint8, 16: np.uint16, 32: np.uint32}[int(w)]
        for b in buckets:
            if not self.available:
                return
            key = ("ec", matrix_key, int(w), int(b))
            if key[0:1] + key[1:] in self.programs:
                continue
            try:
                enc = DeviceBatcher._encoder(matrix_key, int(w))
                buf = self.pool.lease((k, int(b)), dtype)
                try:
                    np.asarray(enc(buf))
                finally:
                    self.pool.release(buf)
                self.note_program("ec", (matrix_key, int(w), int(b)))
            except Exception as e:          # warmup must never wedge boot
                self.poison(e)
                return
            await asyncio.sleep(0)          # yield between compiles

    # -- tickets -----------------------------------------------------------

    def open_ticket(self, klass: str, bucket: int,
                    nbytes: int) -> DispatchTicket:
        self._seq += 1
        return DispatchTicket(self._seq, klass, bucket, nbytes)

    async def admit(self, ticket: DispatchTicket,
                    cost: float | None = None) -> None:
        await self.queue.admit(
            ticket.klass,
            cost if cost is not None
            else max(1.0, ticket.nbytes / 65536.0))
        ticket.t_admit = time.monotonic()

    def try_admit(self, ticket: DispatchTicket,
                  cost: float | None = None) -> None:
        self.queue.try_admit(
            ticket.klass,
            cost if cost is not None
            else max(1.0, ticket.nbytes / 65536.0))
        ticket.t_admit = time.monotonic()

    def launch(self, ticket: DispatchTicket) -> None:
        """Stamp launch; consumes one injected fault if armed (the
        deterministic device-loss hook the thrasher uses)."""
        ticket.t_launch = time.monotonic()
        if self._fault_budget > 0:
            self._fault_budget -= 1
            raise DeviceLost("injected device fault")

    def finish(self, ticket: DispatchTicket, ok: bool = True,
               error: Exception | None = None) -> None:
        ticket.t_done = time.monotonic()
        ticket.ok = ok
        ticket.error = repr(error) if error is not None else None
        self.queue.release()
        self.tickets.append(ticket)
        if len(self.tickets) > _TICKET_RING:
            del self.tickets[:_TICKET_RING // 2]
        if ok:
            self.dispatches += 1
            dt = ticket.device_s
            self.dispatch_seconds += dt
            us = max(1, int(dt * 1e6))
            i = min(_HIST_BUCKETS - 1, max(0, us.bit_length() - 1))
            self.dispatch_buckets_us[i] += 1

    # -- device-loss degradation ------------------------------------------

    @property
    def available(self) -> bool:
        return not self.fallback

    def add_listener(self, fn) -> None:
        """fn(fallback: bool) on every poison/heal transition (the OSD
        uses it to beacon the state change immediately)."""
        self._listeners.append(fn)

    def _notify(self) -> None:
        for fn in list(self._listeners):
            try:
                fn(self.fallback)
            except Exception:
                pass        # observability must never sink the runtime

    def poison(self, reason) -> None:
        """Flip to host fallback; a probe loop retries the device
        under ExpBackoff until it heals."""
        if self.fallback:
            return
        self.fallback = True
        self.fallback_reason = repr(reason)
        self.fallback_count += 1
        self._notify()
        try:
            loop = asyncio.get_event_loop()
            if loop.is_running() and self._probe_task is None:
                self._probe_task = loop.create_task(self._probe_loop())
        except RuntimeError:
            pass            # no loop: heal() is manual (sync callers)

    def heal(self) -> None:
        if not self.fallback:
            return
        self.fallback = False
        self.fallback_reason = None
        self.heal_count += 1
        self._notify()

    def inject_fault(self, n: int = 1) -> None:
        """Arm n deterministic dispatch failures (thrasher hook);
        probes consume from the same budget, so the runtime stays in
        fallback until the budget drains (or clear_faults())."""
        self._fault_budget += int(n)

    def clear_faults(self) -> None:
        self._fault_budget = 0

    def _run_probe(self) -> None:
        """One probe dispatch: trivially small device work; raises on
        failure.  Injected faults make probes fail too, so the
        fallback window is controllable in tests."""
        if self._fault_budget > 0:
            self._fault_budget -= 1
            raise DeviceLost("injected device fault (probe)")
        import jax.numpy as jnp
        np.asarray(jnp.zeros((8,), jnp.uint8) + jnp.uint8(1))

    async def _probe_loop(self) -> None:
        from ..utils.backoff import ExpBackoff
        bo = ExpBackoff(base=self._probe_base, cap=self._probe_cap)
        try:
            while self.fallback:
                await bo.sleep()
                try:
                    self._run_probe()
                except Exception:
                    continue
                self.heal()
        finally:
            self._probe_task = None

    # -- telemetry ---------------------------------------------------------

    def dispatch_pctls(self) -> dict:
        """p50/p99 (ms) over the ticket ring's device times."""
        samples = sorted(t.device_s for t in self.tickets if t.ok)
        if not samples:
            return {"n": 0}
        n = len(samples)

        def at(p):
            return round(samples[min(n - 1, int(p / 100.0 * n))] * 1e3,
                         4)

        return {"n": n, "p50": at(50), "p99": at(99)}

    def metrics(self) -> dict:
        return {
            "device_queue_depth": self.queue.depth,
            "device_inflight": self.queue.inflight,
            "device_bucket_hit_ratio": round(self.bucket_hit_ratio, 4),
            "device_compile_count": self.compile_count,
            "device_dispatches": self.dispatches,
            "device_host_fallbacks": self.host_fallbacks,
            "device_pool_hits": self.pool.hits,
            "device_pool_misses": self.pool.misses,
            "device_fallback": int(self.fallback),
            "device_fallback_count": self.fallback_count,
            "device_heal_count": self.heal_count,
            "device_queue_rejected": self.queue.rejected,
        }

    def prom_lines(self, prefix: str = "ceph_tpu") -> list[str]:
        """Prometheus exposition lines (utils.exporter renderer)."""
        from ..utils.exporter import hist_lines
        lines = []
        for name, val in sorted(self.metrics().items()):
            base = "%s_%s" % (prefix, name)
            lines.append("# TYPE %s gauge" % base)
            lines.append("%s %g" % (base, float(val)))
        lines.extend(hist_lines("%s_device_dispatch_seconds" % prefix,
                                self.dispatch_buckets_us))
        return lines
