"""Local device-mesh enumeration for the mesh-aware DeviceRuntime.

The multi-chip dispatch discipline follows "Large Scale Distributed
Linear Algebra With Tensor Processing Units" (arXiv:2112.09017,
PAPERS.md): the host enumerates its local chips once, work is placed
per chip with plain `jax.device_put` (computation follows data), and
nothing in the hot path performs a cross-chip collective —
MULTICHIP_SCALING.json proves EC encode stays collective-free over the
stripe axis for every dp=1..8 program, which is exactly what makes
per-chip isolation sound: a chip's failure cannot wedge another chip's
in-flight program.

Chip count resolution, in priority order:

1. ``CEPH_TPU_MESH_CHIPS`` — explicit logical mesh size.  Logical
   chips beyond the physical device count map onto physical devices
   round-robin; this is how tier-1 CI exercises a 4-chip mesh on the
   single CPU "device" without restarting the process.
2. ``len(jax.local_devices())`` — the real mesh (a v5e host sees its
   local chips; CPU CI sees the forced count when launched under
   ``XLA_FLAGS=--xla_force_host_platform_device_count=N``).
3. 1 — jax unavailable or uninitializable (host-only builds).
"""

from __future__ import annotations

import os

MESH_ENV = "CEPH_TPU_MESH_CHIPS"
FORCE_HOST_FLAG = "--xla_force_host_platform_device_count"


def local_devices() -> list:
    """The process's jax devices ([] when jax is unusable).  Imported
    lazily: mesh construction must not force jax init on host-only
    paths that never dispatch."""
    try:
        import jax
        return list(jax.local_devices())
    except Exception:       # pragma: no cover - jax baked into image
        return []


def chip_count() -> int:
    """Logical mesh size for this process (see module docstring)."""
    env = os.environ.get(MESH_ENV)
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    devs = local_devices()
    return max(1, len(devs))


def device_for(chip_index: int):
    """The jax device backing logical chip `chip_index` (round-robin
    when logical chips outnumber physical devices), or None when jax
    has no devices to offer."""
    devs = local_devices()
    if not devs:
        return None
    return devs[chip_index % len(devs)]


def backend() -> str:
    """The jax backend serving this mesh ("cpu" when jax is unusable).
    The dispatch-stream bench gate keys its published comparisons on
    this: CPU-CI figures never gate a real-TPU run and vice versa."""
    try:
        import jax
        return str(jax.default_backend())
    except Exception:       # pragma: no cover - jax baked into image
        return "cpu"


def affinity(osd_id: int, n_chips: int) -> int:
    """OSD -> chip affinity: deterministic modulo placement, so
    co-located daemons land on distinct chips until the mesh is full
    and a chip loss maps to a knowable OSD subset."""
    return int(osd_id) % max(1, int(n_chips))


def describe() -> dict:
    """Mesh identity for trace/export metadata: how many chips this
    process sees, what backs them, and whether the count was forced
    (so an exported timeline records what hardware its device lanes
    actually ran on)."""
    devs = local_devices()
    out = {"chips": chip_count(),
           "physical_devices": len(devs),
           "forced": bool(os.environ.get(MESH_ENV))}
    if devs:
        out["platform"] = getattr(devs[0], "platform", "unknown")
    return out


def simulated_mesh_env(n: int, base: dict | None = None) -> dict:
    """Environment for a subprocess that should see `n` real host
    devices (the CI simulation recipe: XLA must be told before jax
    initializes, hence a fresh process)."""
    env = dict(base if base is not None else os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith(FORCE_HOST_FLAG)]
    flags.append("%s=%d" % (FORCE_HOST_FLAG, int(n)))
    env["XLA_FLAGS"] = " ".join(flags)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env
