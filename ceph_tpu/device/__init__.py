"""Unified TPU device runtime (see runtime.py for the design note).

Both accelerator hot paths — batched EC matmuls (ceph_tpu.ec.batcher)
and bulk CRUSH mapping (ceph_tpu.parallel.mapping) — route their
dispatches through the per-process DeviceRuntime: shape-bucketed
compile cache, pooled staging buffers, weighted admission
backpressure, and device-loss fallback to the host paths.
"""

from .runtime import (BufferPool, ChipRuntime, DeviceBusy,
                      DeviceLost, DeviceRuntime, DispatchQueue,
                      DispatchTicket, K_CLIENT_EC, K_MAPPING,
                      K_RECOVERY_EC)

__all__ = [
    "BufferPool", "ChipRuntime", "DeviceBusy", "DeviceLost",
    "DeviceRuntime", "DispatchQueue", "DispatchTicket",
    "K_CLIENT_EC", "K_MAPPING", "K_RECOVERY_EC",
]
