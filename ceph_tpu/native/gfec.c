/* Native GF(2^8) region arithmetic for the host EC path.
 *
 * The role isa-l's assembly plays in the reference
 * (src/erasure-code/isa/ErasureCodeIsa.cc:129 ec_encode_data): the
 * per-coefficient region multiply runs as the PSHUFB nibble-table
 * technique over AVX2 lanes, with a portable scalar fallback.  The
 * Python host codecs call this through ctypes (ceph_tpu/native) and
 * fall back to numpy when the shared object is unavailable; outputs
 * are bit-identical either way (pinned by tests/test_native_gfec.py).
 *
 * Built with: gcc -O3 -mavx2 -shared -fPIC gfec.c -o libgfec.so
 */
#include <stdint.h>
#include <string.h>

#ifdef __AVX2__
#include <immintrin.h>
#endif

static uint8_t MUL[256][256];
static int tables_ready = 0;

static uint8_t gf_mul1(uint8_t a, uint8_t b) {
    uint16_t r = 0, aa = a;
    int i;
    for (i = 0; i < 8; i++)
        if (b & (1 << i)) r ^= aa << i;
    for (i = 15; i >= 8; i--)
        if (r & (1 << i)) r ^= 0x11d << (i - 8);
    return (uint8_t)r;
}

void gfec_init(void) {
    int a, b;
    if (tables_ready) return;
    for (a = 0; a < 256; a++)
        for (b = 0; b < 256; b++)
            MUL[a][b] = gf_mul1((uint8_t)a, (uint8_t)b);
    tables_ready = 1;
}

/* dst ^= c * src over n bytes */
void gfec_region_mad(uint8_t *dst, const uint8_t *src, uint8_t c,
                     size_t n) {
    size_t i = 0;
    if (!tables_ready) gfec_init();
    if (c == 0) return;
    if (c == 1) {
        for (; i < n; i++) dst[i] ^= src[i];
        return;
    }
#ifdef __AVX2__
    {
        uint8_t lo_t[16], hi_t[16];
        __m256i lo, hi, mask;
        int j;
        for (j = 0; j < 16; j++) {
            lo_t[j] = MUL[c][j];
            hi_t[j] = MUL[c][j << 4];
        }
        lo = _mm256_broadcastsi128_si256(
            _mm_loadu_si128((const __m128i *)lo_t));
        hi = _mm256_broadcastsi128_si256(
            _mm_loadu_si128((const __m128i *)hi_t));
        mask = _mm256_set1_epi8(0x0f);
        for (; i + 32 <= n; i += 32) {
            __m256i s = _mm256_loadu_si256((const __m256i *)(src + i));
            __m256i l = _mm256_and_si256(s, mask);
            __m256i h = _mm256_and_si256(_mm256_srli_epi64(s, 4), mask);
            __m256i r = _mm256_xor_si256(_mm256_shuffle_epi8(lo, l),
                                         _mm256_shuffle_epi8(hi, h));
            __m256i d = _mm256_loadu_si256((const __m256i *)(dst + i));
            _mm256_storeu_si256((__m256i *)(dst + i),
                                _mm256_xor_si256(d, r));
        }
    }
#endif
    for (; i < n; i++) dst[i] ^= MUL[c][src[i]];
}

/* parity[m][n] = matrix[m][k] (x) data[k][n]; rows are contiguous.
 * data/parity are flat row-major buffers. */
void gfec_matmul(const uint8_t *matrix, int k, int m,
                 const uint8_t *data, uint8_t *parity, size_t n) {
    int i, j;
    if (!tables_ready) gfec_init();
    memset(parity, 0, (size_t)m * n);
    for (i = 0; i < m; i++)
        for (j = 0; j < k; j++)
            gfec_region_mad(parity + (size_t)i * n,
                            data + (size_t)j * n,
                            matrix[i * k + j], n);
}
