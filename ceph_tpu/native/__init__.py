"""Native (C) host kernels, loaded via ctypes.

The reference keeps its hot host loops in C/C++ (the crush core is
kernel-compatible C, EC rides isa-l assembly); this package is the
analog: small C sources compiled on first use into a per-checkout
shared object and exposed through ctypes, with every caller keeping a
pure-Python/numpy fallback (CEPH_TPU_NO_NATIVE=1 forces it).  Outputs
are bit-identical to the fallbacks by construction and pinned by
tests."""

from __future__ import annotations

import ctypes
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB = None
_TRIED = False


def _build(src: str, out: str) -> bool:
    flags = ["-O3", "-shared", "-fPIC"]
    # AVX2 when the host has it (the scalar path compiles regardless)
    try:
        with open("/proc/cpuinfo") as f:
            if "avx2" in f.read():
                flags.append("-mavx2")
    except OSError:
        pass
    # build to a process-unique temp and rename into place: concurrent
    # processes racing the first compile must never dlopen a
    # half-written .so
    tmp = "%s.%d.tmp" % (out, os.getpid())
    try:
        subprocess.run(["gcc", *flags, src, "-o", tmp], check=True,
                       capture_output=True, timeout=120)
        os.replace(tmp, out)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def lib():
    """The loaded libgfec, or None (missing compiler, forced off)."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("CEPH_TPU_NO_NATIVE"):
        return None
    so = os.path.join(_DIR, "libgfec.so")
    src = os.path.join(_DIR, "gfec.c")
    if not os.path.exists(so) or \
            os.path.getmtime(so) < os.path.getmtime(src):
        if not _build(src, so):
            return None
    try:
        L = ctypes.CDLL(so)
        L.gfec_init()
        L.gfec_matmul.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
        L.gfec_region_mad.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_ubyte,
            ctypes.c_size_t]
        _LIB = L
    except OSError:
        _LIB = None
    return _LIB
