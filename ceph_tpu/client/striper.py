"""Striper: one logical extent sharded over many RADOS objects.

Analog of Striper::file_to_extents (src/osdc/Striper.h:28-66 /
Striper.cc) + libradosstriper (src/libradosstriper/RadosStriperImpl.cc):
a file_layout_t (stripe_unit su, stripe_count sc, object_size os —
src/include/ceph_fs.h:70-78) round-robins su-sized blocks over sets of
sc objects, each object holding os bytes before the next object set
starts.  SURVEY §5 calls this the long-context analog: the extent math
is a closed-form integer transform, so the bulk mapping is expressed
vectorized over the block axis (numpy here; the same expressions run
under jnp for on-device batches).

Object naming follows libradosstriper: "<soid>.%016x" % objectno, with
the logical size kept as an xattr on object 0 (striper.layout carries
the layout so readers need no out-of-band metadata)."""

from __future__ import annotations

import numpy as np

SIZE_XATTR = "striper.size"
LAYOUT_XATTR = "striper.layout"


class FileLayout:
    """file_layout_t subset (stripe_unit, stripe_count, object_size)."""

    __slots__ = ("stripe_unit", "stripe_count", "object_size")

    def __init__(self, stripe_unit: int = 1 << 22,
                 stripe_count: int = 1,
                 object_size: int = 1 << 22):
        if stripe_unit <= 0 or stripe_count <= 0 or object_size <= 0:
            raise ValueError("layout fields must be positive")
        if object_size % stripe_unit:
            raise ValueError("object_size must be a multiple of "
                             "stripe_unit")
        self.stripe_unit = stripe_unit
        self.stripe_count = stripe_count
        self.object_size = object_size

    def encode(self) -> bytes:
        return b"%d:%d:%d" % (self.stripe_unit, self.stripe_count,
                              self.object_size)

    @classmethod
    def decode(cls, raw: bytes) -> "FileLayout":
        su, sc, os_ = (int(x) for x in raw.split(b":"))
        return cls(su, sc, os_)


def file_to_extents(layout: FileLayout, offset: int, length: int
                    ) -> list[tuple[int, int, int, int]]:
    """[(objectno, obj_off, len, file_off), ...] covering
    [offset, offset+length), merged per contiguous object run —
    Striper::file_to_extents' closed form, vectorized over the
    stripe-unit block axis:

        blockno   = off / su            stripeno  = blockno / sc
        stripepos = blockno % sc        setno     = stripeno / (os/su)
        objectno  = setno * sc + stripepos
        obj_off   = (stripeno % (os/su)) * su + off % su
    """
    if length <= 0:
        return []
    su = layout.stripe_unit
    sc = layout.stripe_count
    upo = layout.object_size // su          # stripe units per object
    first = offset // su
    last = (offset + length - 1) // su
    blockno = np.arange(first, last + 1, dtype=np.int64)
    stripeno = blockno // sc
    stripepos = blockno % sc
    setno = stripeno // upo
    objectno = setno * sc + stripepos
    in_obj = (stripeno % upo) * su
    # per-block source range within the file
    blk_start = np.maximum(blockno * su, offset)
    blk_end = np.minimum((blockno + 1) * su, offset + length)
    obj_off = in_obj + (blk_start - blockno * su)
    ext_len = blk_end - blk_start
    out: list[tuple[int, int, int, int]] = []
    for i in range(len(blockno)):
        o, oo, ln, fo = (int(objectno[i]), int(obj_off[i]),
                         int(ext_len[i]), int(blk_start[i]))
        if out and out[-1][0] == o \
                and out[-1][1] + out[-1][2] == oo \
                and out[-1][3] + out[-1][2] == fo:
            prev = out[-1]
            out[-1] = (prev[0], prev[1], prev[2] + ln, prev[3])
        else:
            out.append((o, oo, ln, fo))
    return out


class RadosStriper:
    """Striped object I/O over an IoCtx (libradosstriper surface)."""

    def __init__(self, ioctx, layout: FileLayout | None = None):
        self.io = ioctx
        self.layout = layout or FileLayout(stripe_unit=1 << 16,
                                           stripe_count=4,
                                           object_size=1 << 18)

    @staticmethod
    def _name(soid: str, objectno: int) -> str:
        return "%s.%016x" % (soid, objectno)

    async def _stored_layout(self, soid: str) -> FileLayout:
        """The layout the object was WRITTEN with (object-0 xattr);
        readers must not trust their own default — extents computed
        with a different layout silently map to the wrong objects."""
        try:
            raw = await self.io.getxattr(self._name(soid, 0),
                                         LAYOUT_XATTR)
            return FileLayout.decode(raw)
        except Exception:
            return self.layout

    async def write(self, soid: str, data: bytes,
                    offset: int = 0) -> None:
        import asyncio

        # appends/overwrites must honour the layout the object was
        # created with, not the handle's default
        layout = await self._stored_layout(soid)
        exts = file_to_extents(layout, offset, len(data))
        await asyncio.gather(*[
            self.io.write(self._name(soid, o),
                          data[fo - offset:fo - offset + ln], oo)
            for o, oo, ln, fo in exts])
        # logical size + layout ride object 0 (libradosstriper keeps
        # them in xattrs of the first object)
        size = 0
        try:
            size = await self.stat(soid)
        except Exception:
            pass
        new_size = max(size, offset + len(data))
        o0 = self._name(soid, 0)
        if not exts or exts[0][0] != 0:
            await self.io.write(o0, b"", 0)    # ensure object 0
        await self.io.setxattr(o0, SIZE_XATTR, b"%d" % new_size)
        await self.io.setxattr(o0, LAYOUT_XATTR, layout.encode())

    async def stat(self, soid: str) -> int:
        raw = await self.io.getxattr(self._name(soid, 0), SIZE_XATTR)
        return int(raw)

    async def read(self, soid: str, length: int = 0,
                   offset: int = 0) -> bytes:
        import asyncio

        layout = await self._stored_layout(soid)
        if length <= 0:
            length = max(0, await self.stat(soid) - offset)
        if length == 0:
            return b""
        exts = file_to_extents(layout, offset, length)

        async def fetch(o, oo, ln):
            try:
                return await self.io.read(self._name(soid, o), ln, oo)
            except Exception:
                return b""

        parts = await asyncio.gather(*[fetch(o, oo, ln)
                                       for o, oo, ln, _fo in exts])
        buf = bytearray(length)
        for (o, oo, ln, fo), part in zip(exts, parts):
            part = part[:ln]
            buf[fo - offset:fo - offset + len(part)] = part
        return bytes(buf)

    async def remove(self, soid: str) -> None:
        import asyncio

        try:
            size = await self.stat(soid)
        except Exception:
            size = 0
        layout = await self._stored_layout(soid)
        exts = file_to_extents(layout, 0, max(size, 1))
        objs = sorted({o for o, _oo, _ln, _fo in exts} | {0})

        async def rm(o):
            try:
                await self.io.remove(self._name(soid, o))
            except Exception:
                pass

        await asyncio.gather(*[rm(o) for o in objs])
