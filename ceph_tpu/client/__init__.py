"""L6 client access: librados-equivalent with client-side placement.

Analog of src/librados + src/osdc — see rados.py (RadosClient/IoCtx/
Objecter logic).
"""

from .rados import IoCtx, ObjectNotFound, RadosClient, RadosError

__all__ = ["RadosClient", "IoCtx", "RadosError", "ObjectNotFound"]
