"""librados-equivalent client: cluster handle, IoCtx, Objecter.

Analog of src/librados (RadosClient/IoCtx) over src/osdc/Objecter.cc:
the client computes placement itself from its subscribed OSDMap
(_calc_target, Objecter.cc:2776 — the same pg_to_up_acting_osds
pipeline every daemon runs), sends MOSDOp straight to the acting
primary, and owns all retry logic: on every new map epoch it re-targets
in-flight ops and resends those whose primary moved (handle_osd_map ->
_scan_requests, Objecter.cc:1303,2091); a connection reset requeues
everything that was in flight on that session (lossy client policy —
the reference's RESETSESSION handling).
"""

from __future__ import annotations

import asyncio
import random

from ..msg import Messenger
from ..msg.messenger import ms_compress_from_conf
from ..msg.messages import (MConfig, MMonCommand, MMonCommandAck,
                            MMonEvents, MMonSubscribe, MMonWatchEvents,
                            MOSDBackoff, MOSDMapMsg, MOSDOp, MOSDOpReply,
                            MWatchNotify)
from ..osd.osdmap import OSDMap, consume_map_payload, pg_t
from ..utils.backoff import ExpBackoff
from ..utils.context import Context


class RadosError(Exception):
    def __init__(self, code: int, detail=None):
        super().__init__("rados error %d: %r" % (code, detail))
        self.code = code
        self.detail = detail


class ObjectNotFound(RadosError):
    """ENOENT surface — a RadosError subclass so callers matching the
    documented errno contract (`except RadosError as e: e.code`)
    catch it too."""

    def __init__(self, oid):
        super().__init__(-2, oid)


class _InFlight:
    __slots__ = ("tid", "pool", "oid", "ops", "future", "target",
                 "pgid", "acting", "snapc", "snapid", "backoff",
                 "next_resend", "first_sent", "trace", "top",
                 "tenant")

    def __init__(self, tid, pool, oid, ops, future, snapc=None,
                 snapid=None, tenant=None):
        self.tid = tid
        self.pool = pool
        self.oid = oid
        self.ops = ops
        self.future = future
        self.target = -1        # osd the op was last sent to
        self.pgid = None
        self.acting: list = []  # acting set at send time
        self.snapc = snapc      # (seq, [snapids desc]) on writes
        self.snapid = snapid    # read-from-snapshot id
        self.backoff = None     # ExpBackoff ramp (set on first send)
        self.next_resend = 0.0  # loop.time() the resend tick may fire
        self.first_sent = 0.0
        self.trace = None       # cross-daemon span id (reqid_t role)
        self.top = None         # TrackedOp in the client's OpTracker
        self.tenant = tenant    # tenant key stamped on every send


class RadosClient:
    """Cluster handle (librados::Rados / RadosClient)."""

    # op resend ramp: base far above a healthy op round trip so only
    # genuinely lost ops (dropped frames, dead primaries the map has
    # not yet condemned) re-fire; cap bounds recovery latency
    OP_RESEND_BASE = 0.5
    OP_RESEND_CAP = 5.0

    def __init__(self, mon_addr, ctx: Context | None = None,
                 name: str = "client.0", seed: int | None = None):
        self.ctx = ctx or Context(name)
        # mon_addr: one address or the monmap address list; commands
        # and subscriptions fail over across them (MonClient hunting)
        self.mon_addrs = ([mon_addr] if isinstance(mon_addr, str)
                          else list(mon_addr))
        self._mon_i = 0
        # seeded mode: jittered waits (op resend, mon hunting) draw
        # from a deterministic stream, for replayable fault schedules
        self.rng = (random.Random("%s|%s" % (seed, name))
                    if seed is not None else random.Random())
        from ..msg.auth import AuthContext
        self.msgr = Messenger(
            name, auth=AuthContext.from_conf(self.ctx.conf),
            compress=ms_compress_from_conf(self.ctx.conf), seed=seed)
        self.msgr.add_dispatcher(self)
        # epoch-0 empty map is the universal incremental base
        self.osdmap: OSDMap = OSDMap()
        self._map_event = asyncio.Event()
        # (epoch, future) waiters resolved by _handle_map — the
        # event-driven wait_for_epoch (no fixed-interval polling)
        self._map_waiters: list = []
        self._tid = 0
        self._inflight: dict[int, _InFlight] = {}
        self._cmd_futures: dict[int, asyncio.Future] = {}
        # (pool, oid) -> callback(payload); re-registered on map change
        self._watch_cbs: dict[tuple, object] = {}
        # cluster event-bus subscription (watch_events): callback per
        # event row, cursor = highest seq delivered.  Seqs are
        # cluster-wide identical, so the cursor survives mon failover
        # — re-subscribing anywhere resumes with no gaps or dups
        self._event_cb = None
        self._event_cursor = 0
        # (pool, ps, oid|None) -> (primary_osd, backoff_id): PGs (oid
        # None) or single degraded objects an OSD told us to stop
        # resending to (MOSDBackoff); cleared on unblock, on a primary
        # change, or on that OSD's session reset
        self._backoffs: dict[tuple, tuple] = {}
        self._resend_task = None
        # client-side op tracking (Objecter's slice of the op span):
        # every submit registers with trace id "<entity>:<tid>", which
        # rides the MOSDOp envelope into the OSD pipeline
        from ..trace import OpTracker
        self.optracker = OpTracker(self.ctx, name)

    @property
    def mon_addr(self) -> str:
        return self.mon_addrs[self._mon_i % len(self.mon_addrs)]

    def _next_mon(self) -> None:
        self._mon_i = (self._mon_i + 1) % len(self.mon_addrs)

    # -- lifecycle ---------------------------------------------------------

    async def connect(self, timeout: float = 10.0) -> None:
        """Hunt through the monmap until a monitor answers the
        subscription (MonClient::hunt), pacing attempts with an
        exponential-backoff ramp + jitter instead of a fixed 2s tick
        so a mon flap does not synchronize every client's retry."""
        deadline = asyncio.get_running_loop().time() + timeout
        hunt = ExpBackoff(base=0.3, cap=2.0, rng=self.rng)
        while True:
            self.msgr.send_to(self.mon_addr, MMonSubscribe(start=1),
                              entity_hint="mon.0")
            left = deadline - asyncio.get_running_loop().time()
            if left <= 0:
                raise asyncio.TimeoutError("no monitor reachable")
            try:
                await asyncio.wait_for(self._map_event.wait(),
                                       min(hunt.next_delay(), left))
                if self._resend_task is None:
                    self._resend_task = self.msgr.spawn(
                        self._resend_loop())
                return
            except asyncio.TimeoutError:
                self._next_mon()

    async def shutdown(self) -> None:
        await self.msgr.shutdown()
        self._resend_task = None

    def io_ctx(self, pool_name: str,
               tenant: str | None = None) -> "IoCtx":
        for pid, pool in (self.osdmap.pools if self.osdmap else {}) \
                .items():
            if pool.name == pool_name:
                return IoCtx(self, pid, tenant=tenant)
        raise ValueError("no pool %r" % pool_name)

    # -- dispatch ----------------------------------------------------------

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MConfig):
            self.ctx.conf.apply_mon_values(msg.values or {})
            return True
        if isinstance(msg, MMonEvents):
            self._handle_events(msg)
            return True
        if isinstance(msg, MOSDMapMsg):
            self._handle_map(msg)
        elif isinstance(msg, MOSDOpReply):
            self._handle_reply(msg)
        elif isinstance(msg, MMonCommandAck):
            fut = self._cmd_futures.pop(msg.tid, None)
            if fut is not None and not fut.done():
                fut.set_result((msg.result, msg.out))
        elif isinstance(msg, MOSDBackoff):
            self._handle_backoff(conn, msg)
        elif isinstance(msg, MWatchNotify):
            cb = self._watch_cbs.get((msg.pool, msg.oid))
            if cb is not None:
                try:
                    cb(bytes(msg.payload or b""))
                except Exception:
                    pass
            # ack so the notifier completes
            conn.send(MWatchNotify(pool=msg.pool, ps=msg.ps,
                                   oid=msg.oid,
                                   notify_id=msg.notify_id,
                                   payload=None, ack=True))
        else:
            return False
        return True

    def ms_handle_reset(self, conn) -> None:
        """Lossy session died: re-target in-flight ops.  Ops whose
        interval is unchanged stay queued — a dead osd produces a new
        map epoch, which is what actually re-routes them (the
        reference's kick_requests-on-reset + wait-for-map behavior).
        A reset of the MON link also dropped our subscription on the
        mon side, so renew it."""
        if conn.peer_addr in self.mon_addrs:
            if conn.peer_addr == self.mon_addr:
                self._next_mon()
            self.msgr.send_to(self.mon_addr,
                              MMonSubscribe(start=self.osdmap.epoch + 1),
                              entity_hint="mon.0")
            if self._event_cb is not None:
                # resume the event stream from the cursor — every
                # mon holds the identical committed sequence
                self.msgr.send_to(
                    self.mon_addr,
                    MMonWatchEvents(start=self._event_cursor),
                    entity_hint="mon.0")
        else:
            # an OSD session reset dropped our in-memory watches on
            # that primary even if the map is unchanged: re-register
            self._rewatch()
            # its backoffs died with the session (the reference drops
            # Backoffs on con reset): resume resending to those PGs
            osd = next((o for o, a in self.osdmap.osd_addrs.items()
                        if a == conn.peer_addr), None)
            if osd is not None:
                for key in [k for k, (po, _i) in
                            self._backoffs.items() if po == osd]:
                    del self._backoffs[key]
        self._scan_requests()

    # -- backoffs (osd_backoff / Objecter Backoff tracking) ----------------

    def _handle_backoff(self, conn, msg: MOSDBackoff) -> None:
        oid = getattr(msg, "oid", None)
        key = (msg.pool, msg.ps, oid)
        osd = next((o for o, a in self.osdmap.osd_addrs.items()
                    if a == conn.peer_addr), -1)
        if msg.op == "block":
            cur = self._backoffs.get(key)
            if cur is None or cur[1] < msg.id:
                self._backoffs[key] = (osd, msg.id)
        elif msg.op == "unblock":
            cur = self._backoffs.get(key)
            if cur is not None and cur[1] <= msg.id:
                del self._backoffs[key]
                # released: re-arm parked ops for an immediate retry
                now = asyncio.get_running_loop().time()
                for op in self._inflight.values():
                    if op.pgid is not None and \
                            (op.pool, op.pgid.ps) == key[:2] and \
                            (oid is None or op.oid == oid):
                        op.next_resend = now

    # -- event bus (watch-events subscription) -----------------------------

    def watch_events(self, callback, start: int = 0) -> None:
        """Stream the mon's committed cluster events (the reference's
        `ceph -w`): callback(row) per event, rows are
        {seq, type, stamp, message, data?} in seq order.  `start` is
        the exclusive cursor (0 = everything still retained).  The
        subscription rides the mon session: resets re-subscribe from
        the cursor, and the resend ticker renews it."""
        self._event_cb = callback
        self._event_cursor = max(int(start), 0)
        self.msgr.send_to(self.mon_addr,
                          MMonWatchEvents(start=self._event_cursor),
                          entity_hint="mon.0")

    def unwatch_events(self) -> None:
        self._event_cb = None

    def _handle_events(self, msg: MMonEvents) -> None:
        """One MMonEvents batch: rows at or below the cursor are
        duplicates (a renewal racing a push) and drop; the callback
        sees each seq exactly once, in order."""
        cb = self._event_cb
        for row in (msg.events or []):
            seq = int(row.get("seq") or 0)
            if seq <= self._event_cursor:
                continue
            self._event_cursor = seq
            if cb is not None:
                try:
                    cb(dict(row))
                except Exception:
                    pass

    def _backed_off(self, op: _InFlight) -> bool:
        """Blocked by a PG-wide backoff or an object-scoped one
        naming this op's oid (the reference's hobject-ranged
        Backoff::contains check)."""
        if op.pgid is None:
            return False
        return ((op.pool, op.pgid.ps, None) in self._backoffs
                or (op.pool, op.pgid.ps, op.oid) in self._backoffs)

    # -- maps --------------------------------------------------------------

    def _handle_map(self, msg: MOSDMapMsg) -> None:
        self.osdmap, changed = consume_map_payload(
            self.osdmap, msg.full, msg.incrementals)
        # any map receipt (even the pre-boot epoch-0 one) proves the
        # mon link is up — connect() must not hang on a fresh cluster
        self._map_event.set()
        if self._map_waiters:
            epoch = self.osdmap.epoch
            still = []
            for want, fut in self._map_waiters:
                if epoch >= want:
                    if not fut.done():
                        fut.set_result(None)
                else:
                    still.append((want, fut))
            self._map_waiters = still
        if changed and self.osdmap.epoch > 0:
            # a backoff is scoped to the primary that issued it: a
            # mapping change hands the PG to a new primary whose ops
            # must flow (it sends its own backoff if still unready)
            for key in list(self._backoffs):
                pool_id, ps, _oid = key
                if pool_id not in self.osdmap.pools:
                    del self._backoffs[key]
                    continue
                _up, _upp, _acting, primary = \
                    self.osdmap.pg_to_up_acting_osds(
                        pg_t(pool_id, ps))
                if primary != self._backoffs[key][0]:
                    del self._backoffs[key]
            self._scan_requests()
            self._rewatch()

    def _rewatch(self) -> None:
        """Re-register every watch after a map change: a primary
        migration dropped the in-memory registration on the old
        primary (librados notify_resend / re-watch behavior)."""
        for (pool_id, oid) in list(self._watch_cbs):
            self.submit_op(pool_id, oid, [{"op": "watch"}])

    def _scan_requests(self) -> None:
        """Re-target in-flight ops; resend those whose interval changed
        (Objecter::_scan_requests).  Any acting-set change counts: a
        replica death aborts the primary's in-flight repops, so the op
        must be resent even when the primary itself is unchanged."""
        for op in list(self._inflight.values()):
            if not op.oid:
                continue    # pg-targeted ops (pgls) are fire-once
            primary, pgid, acting = self._calc_target(op.pool, op.oid)
            if (primary != op.target or pgid != op.pgid
                    or acting != op.acting):
                self._send_op(op)

    # -- op submission -----------------------------------------------------

    def _calc_target(self, pool_id: int, oid: str):
        pool = self.osdmap.pools[pool_id]
        raw = self.osdmap.object_locator_to_pg(oid, pool_id)
        pgid = pool.raw_pg_to_pg(raw)  # Objecter.cc:2830
        up, upp, acting, actingp = \
            self.osdmap.pg_to_up_acting_osds(pgid)
        return actingp, pgid, acting

    def submit_op(self, pool_id: int, oid: str, ops: list[dict],
                  snapc=None, snapid=None,
                  tenant: str | None = None) -> asyncio.Future:
        self._tid += 1
        fut = asyncio.get_running_loop().create_future()
        op = _InFlight(self._tid, pool_id, oid, ops, fut,
                       snapc=snapc, snapid=snapid, tenant=tenant)
        op.trace = "%s:%d" % (self.msgr.entity, self._tid)
        op.top = self.optracker.create(
            "client_op(tid=%d pool=%d %s [%s])"
            % (self._tid, pool_id, oid,
               ",".join(o.get("op", "?") for o in ops)),
            trace=op.trace, tenant=tenant)
        self._inflight[self._tid] = op
        self._send_op(op)
        return fut

    async def list_objects(self, pool_id: int) -> list[str]:
        """Enumerate every object in the pool by walking its PGs with
        pgls ops (rados ls / Objecter pool nlist)."""
        pool = self.osdmap.pools[pool_id]
        names: list[str] = []
        for ps in range(pool.pg_num):
            pgid = pool.raw_pg_to_pg(
                __import__("ceph_tpu.osd.osdmap",
                           fromlist=["pg_t"]).pg_t(pool_id, ps))
            up, upp, acting, actingp =                 self.osdmap.pg_to_up_acting_osds(pgid)
            if actingp < 0:
                continue
            addr = self.osdmap.osd_addrs.get(actingp)
            if not addr:
                continue
            self._tid += 1
            fut = asyncio.get_running_loop().create_future()
            op = _InFlight(self._tid, pool_id, "", [{"op": "pgls"}],
                           fut)
            op.target = actingp
            op.pgid = pgid
            op.acting = acting
            self._inflight[self._tid] = op
            self.msgr.send_to(addr, MOSDOp(
                tid=op.tid, pool=pool_id, ps=pgid.ps, oid="",
                snapc=None, ops=op.ops, epoch=self.osdmap.epoch,
                flags=0), entity_hint="osd.%d" % actingp)
            try:
                outs = await asyncio.wait_for(fut, 10.0)
                names.extend(outs[0].get("names", []))
            except asyncio.TimeoutError:
                self._inflight.pop(op.tid, None)
        return sorted(set(names))

    def _send_op(self, op: _InFlight) -> None:
        loop = asyncio.get_running_loop()
        if op.backoff is None:
            op.backoff = ExpBackoff(base=self.OP_RESEND_BASE,
                                    cap=self.OP_RESEND_CAP,
                                    rng=self.rng)
            op.first_sent = loop.time()
        op.next_resend = loop.time() + op.backoff.next_delay()
        primary, pgid, acting = self._calc_target(op.pool, op.oid)
        op.target = primary
        op.pgid = pgid
        op.acting = acting
        if primary < 0:
            if op.top is not None:
                op.top.mark_event("no_primary")
            return  # no acting primary yet: wait for the next map
        addr = self.osdmap.osd_addrs.get(primary)
        if not addr:
            return
        m = MOSDOp(
            tid=op.tid, pool=op.pool, ps=pgid.ps, oid=op.oid,
            snapc=op.snapc, snapid=op.snapid, ops=op.ops,
            epoch=self.osdmap.epoch, flags=0)
        m.trace = op.trace
        m.tenant = op.tenant    # rides the envelope into every layer
        if op.top is not None:
            op.top.mark_event("sent_osd.%d" % primary)
        self.msgr.send_to(addr, m, entity_hint="osd.%d" % primary)

    async def _resend_loop(self) -> None:
        """Objecter op-retry ticker: any op still in flight past its
        jittered exponential-backoff deadline is re-sent (a dropped
        frame or a silently dead primary otherwise strands it until a
        map change).  PGs under an active MOSDBackoff are skipped —
        the OSD parked the op and will answer; resending would spam a
        peering PG (exactly what backoff exists to stop).

        The same ticker renews the map subscription
        (MonClient::renew_subs): publication is fire-and-forget, so
        an epoch silently lost to a partition or dropped frame would
        otherwise leave this client stale until the next commit."""
        renew_at = 0.0
        while True:
            await asyncio.sleep(0.1)
            now = asyncio.get_running_loop().time()
            if now >= renew_at:
                renew_at = now + self.ctx.conf[
                    "mon_subscribe_renew_interval"]
                self.msgr.send_to(
                    self.mon_addr,
                    MMonSubscribe(start=self.osdmap.epoch + 1),
                    entity_hint="mon.0")
                if self._event_cb is not None:
                    # renewal doubles as loss repair: any committed
                    # events a dropped push missed come back now
                    # (the cursor dedups the overlap)
                    self.msgr.send_to(
                        self.mon_addr,
                        MMonWatchEvents(start=self._event_cursor),
                        entity_hint="mon.0")
            for op in list(self._inflight.values()):
                if not op.oid or op.future.done():
                    continue    # pg-targeted (pgls) ops are fire-once
                if op.next_resend > now or self._backed_off(op):
                    continue
                self._send_op(op)

    def _handle_reply(self, msg: MOSDOpReply) -> None:
        op = self._inflight.pop(msg.tid, None)
        if op is None or op.future.done():
            return
        if op.top is not None:
            op.top.finish("reply_r%d" % (msg.result or 0))
        if msg.result == 0:
            op.future.set_result(msg.outs)
        elif msg.result == -2:
            op.future.set_exception(ObjectNotFound(op.oid))
        else:
            op.future.set_exception(RadosError(msg.result, msg.outs))

    # -- mon commands ------------------------------------------------------

    async def mon_command(self, prefix: str, timeout: float = 10.0,
                          **args) -> dict:
        """Send to the current mon; on -EHOSTDOWN (a peon's redirect,
        possibly carrying the leader's address) or a timeout, hunt
        through the monmap until the leader answers."""
        cmd = {"prefix": prefix}
        cmd.update(args)
        deadline = asyncio.get_running_loop().time() + timeout
        last_exc = None
        # hunting ramp: early retries are quick (a peon redirect
        # usually resolves in one hop), later ones back off so a
        # quorum-less cluster is not hammered (MonClient
        # reopen_session backoff)
        hunt = ExpBackoff(base=0.5, cap=2.0, rng=self.rng)
        redirect = ExpBackoff(base=0.1, cap=1.0, rng=self.rng)
        for _attempt in range(6 * len(self.mon_addrs)):
            left = deadline - asyncio.get_running_loop().time()
            if left <= 0:
                break
            self._tid += 1
            tid = self._tid
            fut = asyncio.get_running_loop().create_future()
            self._cmd_futures[tid] = fut
            self.msgr.send_to(self.mon_addr,
                              MMonCommand(tid=tid, cmd=cmd),
                              entity_hint="mon.0")
            try:
                result, out = await asyncio.wait_for(
                    fut, min(max(hunt.next_delay(), 0.5), left))
            except asyncio.TimeoutError as e:
                last_exc = e
                self._next_mon()
                continue
            finally:
                self._cmd_futures.pop(tid, None)
            if result == -112:          # peon redirect
                leader = (out or {}).get("leader")
                if leader and leader in self.mon_addrs:
                    self._mon_i = self.mon_addrs.index(leader)
                else:
                    self._next_mon()
                await asyncio.sleep(min(redirect.next_delay(), left))
                continue
            if result != 0:
                raise RadosError(result, out)
            return out
        if last_exc is not None:
            raise RadosError(-110, {"error": "mon command timed out"})
        raise RadosError(-110, {"error": "no quorum"})

    async def wait_for_epoch(self, epoch: int,
                             timeout: float = 10.0) -> None:
        """Event-driven (no polling): _handle_map resolves the waiter
        the moment the epoch lands."""
        if self.osdmap is not None and self.osdmap.epoch >= epoch:
            return
        fut = asyncio.get_running_loop().create_future()
        self._map_waiters.append((epoch, fut))
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise TimeoutError("epoch %d not reached" % epoch) \
                from None
        finally:
            self._map_waiters = [(e, f) for e, f in self._map_waiters
                                 if f is not fut]


class IoCtx:
    """Per-pool I/O context (librados::IoCtx).

    Snapshots (librados snap API): writes carry a SnapContext — the
    pool's implicit one (pool snaps, Objecter::_op_submit) or a
    selfmanaged one set via set_selfmanaged_snapc; reads honor
    set_read_snap (IoCtx::snap_set_read)."""

    def __init__(self, client: RadosClient, pool_id: int,
                 tenant: str | None = None):
        self.client = client
        self.pool_id = pool_id
        # tenant key stamped on this handle's data-path ops: rides
        # the MOSDOp envelope into the OSD's tag books, the device
        # admission tickets, and the flight recorder's spans
        self.tenant = tenant
        self.read_snap: int | None = None    # snapid reads resolve at
        self.selfmanaged_snapc: tuple[int, list[int]] | None = None

    def _snapc(self):
        if self.selfmanaged_snapc is not None:
            return self.selfmanaged_snapc
        pool = (self.client.osdmap.pools.get(self.pool_id)
                if self.client.osdmap else None)
        if pool is not None and pool.snap_seq:
            return pool.snap_context()
        return None

    def set_read_snap(self, snapid: int | None) -> None:
        """Route subsequent reads to a snapshot (None = head)."""
        self.read_snap = snapid

    def set_selfmanaged_snapc(self, seq: int,
                              snaps: list[int] | None) -> None:
        """Application-managed write SnapContext (librados
        set_snap_write_context); snaps newest-first."""
        self.selfmanaged_snapc = ((int(seq),
                                   sorted(snaps or [], reverse=True))
                                  if seq else None)

    # -- pool snapshots (mon-managed ids) ---------------------------------

    async def _wait_pool(self, pred, timeout: float = 10.0) -> None:
        """Wait until the client's map reflects a pool mutation."""
        t0 = asyncio.get_running_loop().time()
        while not pred(self.client.osdmap.pools[self.pool_id]):
            if asyncio.get_running_loop().time() - t0 > timeout:
                raise TimeoutError("pool snap state never published")
            await asyncio.sleep(0.02)

    async def snap_create(self, name: str) -> int:
        pool = self.client.osdmap.pools[self.pool_id]
        res = await self.client.mon_command("osd pool mksnap",
                                            pool=pool.name, snap=name)
        sid = res["snapid"]
        await self._wait_pool(lambda p: sid in p.snaps)
        return sid

    async def snap_remove(self, name: str) -> None:
        pool = self.client.osdmap.pools[self.pool_id]
        sid = self.snap_lookup(name)
        await self.client.mon_command("osd pool rmsnap",
                                      pool=pool.name, snap=name)
        await self._wait_pool(lambda p: sid not in p.snaps)

    def snap_list(self) -> dict[int, str]:
        pool = self.client.osdmap.pools[self.pool_id]
        return dict(pool.snaps)

    def snap_lookup(self, name: str) -> int:
        for sid, n in self.snap_list().items():
            if n == name:
                return sid
        raise KeyError(name)

    # -- selfmanaged snapshots --------------------------------------------

    async def selfmanaged_snap_create(self) -> int:
        pool = self.client.osdmap.pools[self.pool_id]
        res = await self.client.mon_command("osd snap create",
                                            pool=pool.name)
        sid = res["snapid"]
        await self._wait_pool(lambda p: p.snap_seq >= sid)
        return sid

    async def selfmanaged_snap_remove(self, snapid: int) -> None:
        pool = self.client.osdmap.pools[self.pool_id]
        await self.client.mon_command("osd snap rm", pool=pool.name,
                                      snapid=int(snapid))

    # -- object I/O --------------------------------------------------------

    async def write(self, oid: str, data: bytes,
                    offset: int = 0) -> None:
        await self.client.submit_op(self.pool_id, oid, [
            {"op": "write", "offset": offset, "data": bytes(data)}],
            snapc=self._snapc(), tenant=self.tenant)

    async def write_full(self, oid: str, data: bytes) -> None:
        await self.client.submit_op(self.pool_id, oid, [
            {"op": "writefull", "data": bytes(data)}],
            snapc=self._snapc(), tenant=self.tenant)

    async def read(self, oid: str, length: int = 0,
                   offset: int = 0) -> bytes:
        outs = await self.client.submit_op(self.pool_id, oid, [
            {"op": "read", "offset": offset, "length": length}],
            snapid=self.read_snap, tenant=self.tenant)
        return outs[0]["data"]

    async def stat(self, oid: str) -> int:
        outs = await self.client.submit_op(self.pool_id, oid, [
            {"op": "stat"}], snapid=self.read_snap,
            tenant=self.tenant)
        return outs[0]["size"]

    async def remove(self, oid: str) -> None:
        await self.client.submit_op(self.pool_id, oid, [
            {"op": "delete"}], snapc=self._snapc(),
            tenant=self.tenant)

    async def truncate(self, oid: str, length: int) -> None:
        await self.client.submit_op(self.pool_id, oid, [
            {"op": "truncate", "length": int(length)}],
            snapc=self._snapc(), tenant=self.tenant)

    async def exec(self, oid: str, cls: str, method: str,
                   inp: dict | None = None) -> dict:
        """Run an in-OSD object-class method (librados exec /
        CEPH_OSD_OP_CALL): the primary routes it to the read or write
        interpreter by the method's registered RD/WR flags and returns
        the method's output dict.  Errors surface as RadosError with
        the method's errno-style code."""
        outs = await self.client.submit_op(self.pool_id, oid, [
            {"op": "call", "cls": cls, "method": method,
             "input": dict(inp or {})}], snapc=self._snapc())
        return outs[0].get("out", {})

    async def watch(self, oid: str, callback) -> None:
        """Register interest: callback(payload) runs on every notify
        (librados watch2).  The callback registers only after the
        primary accepted the watch — a failed op (e.g. unsupported
        pool type) must not leave a resend-forever stale entry."""
        await self.client.submit_op(self.pool_id, oid,
                                    [{"op": "watch"}])
        self.client._watch_cbs[(self.pool_id, oid)] = callback

    async def unwatch(self, oid: str) -> None:
        self.client._watch_cbs.pop((self.pool_id, oid), None)
        await self.client.submit_op(self.pool_id, oid,
                                    [{"op": "unwatch"}])

    async def notify(self, oid: str, payload: bytes = b"",
                     timeout: float = 5.0) -> int:
        """Deliver payload to every watcher; returns acked count
        (librados notify2)."""
        outs = await self.client.submit_op(self.pool_id, oid, [
            {"op": "notify", "payload": bytes(payload),
             "timeout": timeout}])
        return outs[0]["acked"]

    async def setxattr(self, oid: str, name: str, value: bytes) -> None:
        await self.client.submit_op(self.pool_id, oid, [
            {"op": "setxattr", "name": name, "value": bytes(value)}])

    async def getxattr(self, oid: str, name: str) -> bytes:
        outs = await self.client.submit_op(self.pool_id, oid, [
            {"op": "getxattr", "name": name}], snapid=self.read_snap)
        return outs[0]["value"]

    async def omap_rm(self, oid: str, keys: list[bytes]) -> None:
        await self.client.submit_op(self.pool_id, oid, [
            {"op": "omap-rm", "keys": [bytes(k) for k in keys]}])

    async def omap_set(self, oid: str, kv: dict) -> None:
        await self.client.submit_op(self.pool_id, oid, [
            {"op": "omap-set", "kv": dict(kv)}])

    async def omap_get(self, oid: str) -> dict:
        outs = await self.client.submit_op(self.pool_id, oid, [
            {"op": "omap-get"}])
        return outs[0]["kv"]
