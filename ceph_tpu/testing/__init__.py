"""In-process cluster harnesses for tests and the vstart CLI.

The teuthology/qa tier of this framework: `LocalCluster` boots real
daemons (mon quorum + OSDs + client) on loopback TCP inside one event
loop; `ClusterThrasher` drives it through seeded failure schedules
while a `Workload` keeps client traffic live and invariants checked.
"""

from .cluster import LocalCluster
from .thrasher import ClusterThrasher, Workload
from .traffic import TenantStream, TrafficGenerator

__all__ = ["LocalCluster", "ClusterThrasher", "Workload",
           "TenantStream", "TrafficGenerator"]
