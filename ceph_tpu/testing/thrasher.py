"""ClusterThrasher: seeded failure schedules against a LocalCluster.

The teuthology ``Thrasher`` (qa/tasks/ceph_manager.py) analog: drive
a live cluster through OSD kills/revives, out/in weight churn,
monitor partitions and map churn while a client `Workload` keeps
writing — and assert, after every round, the invariants a storage
system exists to keep:

* no acknowledged write is ever lost (every acked object reads back
  byte-identical);
* PGs reconverge to active+clean;
* the monitors re-form quorum.

Determinism: the entire action plan (which fault, which victim, how
long to hold it) is derived up front from ``random.Random(seed)``, so
a failing run is reproduced by re-running with the seed it printed.
``ClusterThrasher(cluster, seed=S).plan`` is a pure function of
(seed, rounds, actions, cluster shape).
"""

from __future__ import annotations

import asyncio
import random


class Workload:
    """Continuous client writes with acked-write tracking.

    Only writes whose ``write_full`` completed are recorded in
    ``acked`` — an in-flight write lost to a fault is not a violation
    (the client never saw the ack), a recorded one is."""

    def __init__(self, io, seed: int = 0, prefix: str = "thrash",
                 pace: float = 0.02):
        self.io = io
        self.prefix = prefix
        self.pace = pace
        self.rng = random.Random(seed)
        self.acked: dict[str, bytes] = {}
        self.write_failures: list[tuple[str, str]] = []
        self._seq = 0
        self._stop = False
        self._task: asyncio.Task | None = None

    def _payload(self, seq: int) -> bytes:
        # content derives from the seeded rng in sequence order, so a
        # replay writes identical bytes
        rep = self.rng.randrange(8, 64)
        return (b"%s|%d|" % (self.prefix.encode(), seq)) * rep

    async def write_one(self, timeout: float = 30.0) -> str | None:
        oid = "%s-%d" % (self.prefix, self._seq)
        data = self._payload(self._seq)
        self._seq += 1
        try:
            await asyncio.wait_for(self.io.write_full(oid, data),
                                   timeout)
        except Exception as e:            # unacked: not a loss
            self.write_failures.append((oid, repr(e)))
            return None
        self.acked[oid] = data
        return oid

    async def _run(self) -> None:
        while not self._stop:
            await self.write_one()
            await asyncio.sleep(self.pace)

    def start(self) -> "Workload":
        self._task = asyncio.ensure_future(self._run())
        return self

    async def stop(self) -> None:
        self._stop = True
        if self._task is not None:
            try:
                await asyncio.wait_for(self._task, 35.0)
            except asyncio.TimeoutError:
                self._task.cancel()
            self._task = None

    async def verify(self, sample: int | None = None) -> None:
        """Acknowledged writes read back byte-identical.  With
        ``sample``, checks a seeded random subset plus the newest 50
        (mid-thrash checks stay O(sample) while the acked set grows);
        without it, every acked write is read back."""
        items = list(self.acked.items())
        if sample is not None and len(items) > sample:
            # independent picker: must not consume self.rng (the
            # writer derives payload content from it in seq order)
            picker = random.Random((len(items), sample))
            chosen = picker.sample(items[:-50], sample - 50) \
                + items[-50:]
        else:
            chosen = items
        for oid, data in chosen:
            got = await asyncio.wait_for(self.io.read(oid), 30.0)
            assert got == data, \
                "acked write %s lost/corrupt (%d bytes -> %r...)" % (
                    oid, len(data), bytes(got[:32]))


class ClusterThrasher:
    """Seeded rounds of cluster abuse with invariant checks.

    actions: the action pool the plan draws from —
      kill_revive      — hard-stop an OSD, write through the hole,
                         revive it on the same store;
      kill_wipe_revive — hard-stop an OSD and revive it on a FRESH
                         (wiped) store: the disk-replacement flow —
                         backfill must repopulate it from scratch
                         while every acked write stays readable;
      out_in           — weight an OSD out (forcing remap + recovery)
                         and back in;
      mon_partition    — isolate one monitor bidirectionally, keep
                         writing under the degraded quorum, heal it
                         (multi-mon clusters only);
      map_churn        — burn map epochs (pool create/rm) to exercise
                         client/OSD map-chasing under load;
      pg_num_grow      — double a thrashed pool's pg_num (capped):
                         every OSD splits its PGs in place while the
                         workload keeps writing;
      pgp_num_grow     — grow pg_num then raise pgp_num to match on a
                         replicated thrashed pool: children take their
                         own placement, acting sets reshuffle, and
                         REAL backfill data movement must drain
                         (stats oracle: misplaced rises then hits 0)
                         with no lost acked writes;
      ec_profile_swap  — roll the thrashed EC pool onto a freshly
                         committed profile with identical coding
                         parameters (rename/rollout path: codec cache
                         invalidation on every OSD, zero data risk);
      device_fallback  — poison the WHOLE device mesh mid-round: the
                         workload must complete on the host codec /
                         scalar-mapper paths with zero lost acked
                         writes, DEVICE_FALLBACK must raise, and the
                         probe loops must heal it (warning clears);
      chip_loss        — poison ONE mesh chip mid-round: the OSDs
                         bound to it degrade to the host paths (the
                         per-chip DEVICE_FALLBACK detail names the
                         chip) while every surviving chip stays on
                         the device path (its fallback flag never
                         flips and it serves zero host fallbacks),
                         writes keep completing with zero lost acked
                         writes, and the probe loop heals only the
                         poisoned chip (warning clears);
      osd_crash        — crash an OSD on an injected exception: the
                         report must survive in its store, surface in
                         the committed `crash ls` after revive, raise
                         RECENT_CRASH, and clear via `crash archive`;
      mixed_rmw        — the ragged/parity-delta oracle (ROADMAP
                         direction 2): seeded rounds of interleaved
                         full-object rewrites and partial overwrites
                         (boundary-crossing offsets included) on the
                         same EC objects, issued concurrently so they
                         batch; afterwards every acked write reads
                         back exactly AND every stored shard —
                         delta-updated parity and incrementally
                         re-crc'd hinfo included — must be
                         BIT-IDENTICAL to the host codec's encode of
                         the final object contents;
      corrupt_shard    — the integrity-plane oracle, EC flavor: plant
                         seeded byte/attr/hinfo rot in stored EC
                         shards via the store, then prove the scrub
                         plane end to end — deep scrub finds EXACTLY
                         the planted set (write races confirmed away
                         by the recheck pass), PG_DAMAGED and
                         OSD_SCRUB_ERRORS raise through the
                         committed OSD->mgr->mon digest path, repair
                         scrubs drain the residual to zero, health
                         clears, and every planted object reads back
                         its original bytes;
      corrupt_replica  — the replicated-pool analog (byte rot or a
                         divergent xattr on one replica);
      corrupt_compressed — the compression-plane integrity oracle:
                         plant comp-size / blob rot in stored
                         compressed images on one replica of a
                         force-compression pool, prove the read path
                         REFUSES to serve truncated data (EIO, never
                         short bytes), deep scrub finds exactly the
                         planted set, repair drains it, and the
                         original bytes read back;
      poison_mid_compress — the compression-plane fault oracle: arm
                         a one-shot device fault on every live OSD's
                         affinity chip, then drive compressible
                         writefulls through a force-compression tlz
                         pool — the mid-dispatch loss must poison
                         only the dispatching chip, every write must
                         complete on the bit-identical host reference
                         (zero lost acked writes, futures retired
                         exactly once), every stored blob must
                         decompress to the original bytes, and the
                         chip must heal;
      repair_compare   — the repair-traffic oracle (ROADMAP
                         direction 3): rebuild the SAME planted
                         single-shard loss on an RS pool and an LRC
                         pool through the recovery path's targeted
                         minimal-set reconstruction, and demand the
                         LRC repair read strictly fewer survivor
                         bytes than the RS repair (the locality
                         property, measured — not assumed) while
                         both rebuilt shards are bit-identical to
                         the stored originals;
      bully_tenant     — the tenant SLO-plane oracle: mid-round, a
                         bully tenant floods the thrashed pool (many
                         tenant-stamped streams, wide windows) while
                         victim streams run a modest load through
                         the same shared client; every acked write
                         of BOTH tenants must read back
                         byte-identical, and the post-round SLO
                         oracle demands that once healthy no
                         VICTIM tenant is left holding a burn or
                         latency alert (the bully being throttled
                         at its dmClock limit tag is by design, not
                         a violation).
      net_degrade      — the network-plane oracle: hold every frame
                         between one seeded OSD pair ~80ms each way
                         (past the slow-ping bar, under the ping
                         period and far under the failure grace);
                         the leader must commit OSD_SLOW_PING_TIME
                         NAMING the pair, the raise must survive a
                         leader change, writes keep landing, and
                         lifting the delay clears the committed
                         edge.

    Slow-op oracle: after every round's health check, no live OSD may
    still hold an op in flight past osd_op_complaint_time — a healthy
    cluster with a stuck op means a requeue edge was lost somewhere.

    Event-plane oracles: every healthy round must end with ZERO
    un-archived crash reports in the committed table and no ERR-level
    entries in the committed cluster log (any ERR is an unexplained
    failure); kill/revive rounds must leave the victim's
    marked-down -> boot clog sequence committed in order.

    Integrity-plane oracle (scrub_oracle, on by default): every
    healthy round additionally deep-scrubs every thrashed pool and
    demands ZERO inconsistencies — with scrub always on, every
    thrash action is implicitly also a bit-rot regression test.
    """

    ALL_ACTIONS = ("kill_revive", "kill_wipe_revive", "out_in",
                   "mon_partition", "map_churn", "pg_num_grow",
                   "pgp_num_grow", "ec_profile_swap",
                   "device_fallback", "chip_loss", "osd_crash",
                   "mixed_rmw", "corrupt_shard", "corrupt_replica",
                   "corrupt_compressed", "poison_mid_compress",
                   "bully_tenant", "repair_compare", "net_degrade")

    def __init__(self, cluster, seed: int = 0, rounds: int = 3,
                 actions: tuple | list | None = None,
                 hold: float = 0.8):
        """``actions`` is either None (each round draws from the
        default pool), or an explicit round list whose items are
        action names (victim still seeded) or ``(action, arg)``
        tuples (fully pinned); ``rounds`` is ignored when an explicit
        list is given."""
        self.cluster = cluster
        self.seed = seed
        self.rng = random.Random(seed)
        self.hold = hold        # seconds a fault is held per round
        # the full plan is fixed up front: deterministic per seed
        self.plan = []
        if actions is not None:
            for item in actions:
                if isinstance(item, str):
                    self.plan.append(self._plan_one(item))
                else:
                    action, arg = item
                    self._plan_one(action)  # burn rng identically
                    self.plan.append((action, arg))
        else:
            pool = self._default_actions()
            for _ in range(rounds):
                self.plan.append(
                    self._plan_one(self.rng.choice(pool)))
        self.log: list[str] = []
        self._pool_ids: list = []
        # post-round deep-scrub-clean oracle (on by default: with
        # scrub always on, every action doubles as a rot regression
        # test); tests that deliberately leave rot behind turn it off
        self.scrub_oracle = True

    def _default_actions(self) -> list[str]:
        acts = ["kill_revive", "kill_wipe_revive", "out_in",
                "map_churn"]
        if self.cluster.n_mons >= 3:
            acts.append("mon_partition")
        return acts

    def _plan_one(self, action: str) -> tuple:
        if action in ("kill_revive", "kill_wipe_revive",
                      "osd_crash"):
            return (action, self.rng.randrange(self.cluster.n_osds))
        if action == "out_in":
            return (action, self.rng.randrange(self.cluster.n_osds))
        if action == "mon_partition":
            # never plan an isolated majority: one rank only
            return (action, self.rng.randrange(self.cluster.n_mons))
        if action in ("map_churn", "pg_num_grow", "pgp_num_grow",
                      "ec_profile_swap", "device_fallback",
                      "chip_loss", "mixed_rmw", "corrupt_shard",
                      "corrupt_replica", "corrupt_compressed",
                      "poison_mid_compress", "bully_tenant",
                      "repair_compare", "corrupt_dedup_index",
                      "poison_mid_chunk", "net_degrade"):
            return (action, self.rng.randrange(1 << 16))
        raise ValueError("unknown thrash action %r" % action)

    # -- execution ---------------------------------------------------------

    async def run(self, pool_ids, workloads) -> None:
        """Execute the plan round by round, checking invariants after
        each (every pool active+clean, every workload's acked writes
        intact, quorum re-formed).  ``pool_ids``/``workloads`` accept
        a single item or a list.  On any failure the seed is printed
        so the schedule can be replayed exactly."""
        pool_ids = (list(pool_ids) if isinstance(pool_ids, (list,
                                                            tuple))
                    else [pool_ids])
        workloads = (list(workloads) if isinstance(workloads,
                                                   (list, tuple))
                     else [workloads])
        self._pool_ids = pool_ids
        try:
            for n, step in enumerate(self.plan):
                self.log.append("round %d: %s" % (n, (step,)))
                await self._dispatch(step, workloads[0])
                await self._check_invariants(pool_ids, workloads)
        except BaseException:
            print("THRASH FAILED: seed=%r plan=%r log=%r"
                  % (self.seed, self.plan, self.log))
            raise

    async def _dispatch(self, step: tuple, workload: Workload) -> None:
        action, arg = step
        c = self.cluster
        if action in ("kill_revive", "kill_wipe_revive"):
            victim = arg
            await c.kill_osd(victim)
            await c.wait_osd_down(victim)
            await asyncio.sleep(self.hold)      # degraded writes
            await c.revive_osd(victim,
                               wipe=(action == "kill_wipe_revive"))
            await c.wait_osd_up(victim)
            # the event plane must record the round: a committed
            # marked-down entry for the victim, then a boot entry
            # AFTER it — the same sequence on every mon, since both
            # are paxos-committed (deterministic modulo stamps)
            await self._wait_clog_down_boot(c, victim)
        elif action == "osd_crash":
            victim = arg
            cid = await c.crash_osd(
                victim, "thrash: injected crash on osd.%d" % victim)
            assert cid is not None, "crash report was not recorded"
            await c.wait_osd_down(victim)
            await asyncio.sleep(self.hold)      # degraded writes
            await c.revive_osd(victim)
            await c.wait_osd_up(victim)
            # the report survives the daemon (store-persisted),
            # reaches the committed table, raises RECENT_CRASH, and
            # clears via archive
            await self._wait_crash_listed(c, cid)
            await self._wait_health_check(c, "RECENT_CRASH", True)
            await c.client.mon_command("crash archive", id=cid)
            await self._wait_health_check(c, "RECENT_CRASH", False)
            await self._wait_clog_down_boot(c, victim)
        elif action == "out_in":
            victim = arg
            await c.mark_out(victim)
            await asyncio.sleep(self.hold)      # remap + backfill
            await c.mark_in(victim)
        elif action == "mon_partition":
            rank = arg
            c.partition_mon(rank)
            # a structural leader() check would trust a partitioned
            # leader that does not yet know it lost quorum: probe
            # with a real command, which only a mon that can reach a
            # majority answers (survivors re-elect if the victim led)
            await asyncio.sleep(self.hold)
            await c.client.mon_command("status", timeout=30.0)
            assert (await workload.write_one()) is not None, \
                "write could not complete under mon partition"
            c.heal_mon(rank)
            await c.wait_quorum()
            await c.client.mon_command("status", timeout=30.0)
        elif action == "map_churn":
            name = "churn-%d" % arg
            await c.client.mon_command("osd pool create", pool=name,
                                       pg_num=1, size=1)
            await c.client.mon_command("osd pool rm", pool=name)
        elif action == "pg_num_grow":
            pid = self._pool_ids[arg % len(self._pool_ids)]
            pool = c.client.osdmap.pools.get(pid)
            if pool is None:
                return
            new = min(pool.pg_num * 2, 64)
            if new <= pool.pg_num:
                return              # already at the cap
            self.log.append("pg_num %s: %d -> %d"
                            % (pool.name, pool.pg_num, new))
            await c.client.mon_command("osd pool set", pool=pool.name,
                                       var="pg_num", val=new)
            await asyncio.sleep(self.hold)   # writes ride the split
        elif action == "pgp_num_grow":
            # backfill-aware placement growth: raise pg_num first
            # (in-place split, no movement), then raise pgp_num to
            # match — children get their OWN placement, the acting
            # sets reshuffle, and REAL data movement (pg_temp-pinned
            # backfill) must drain while the workload keeps writing.
            # Replicated pools only (EC acting sets are positional;
            # pinning them is out of scope, ROADMAP PR-3).
            pid = next(
                (p for p in self._pool_ids
                 if (c.client.osdmap.pools.get(p) is not None
                     and not c.client.osdmap.pools[p]
                     .erasure_code_profile)), None)
            if pid is None:
                return              # no replicated pool under thrash
            pool = c.client.osdmap.pools[pid]
            target_pg = pool.pg_num
            if pool.pgp_num >= pool.pg_num:
                target_pg = min(pool.pg_num * 2, 64)
                if target_pg <= pool.pg_num:
                    return          # already at the cap
                await c.client.mon_command(
                    "osd pool set", pool=pool.name,
                    var="pg_num", val=target_pg)
                await asyncio.sleep(self.hold)  # splits land
            self.log.append("pgp_num %s: %d -> %d"
                            % (pool.name, pool.pgp_num, target_pg))
            await c.client.mon_command(
                "osd pool set", pool=pool.name,
                var="pgp_num", val=target_pg)
            await asyncio.sleep(self.hold)   # movement under load
        elif action == "ec_profile_swap":
            pid = next(
                (p for p in self._pool_ids
                 if (c.client.osdmap.pools.get(p) is not None
                     and c.client.osdmap.pools[p]
                     .erasure_code_profile)), None)
            if pid is None:
                return              # no EC pool under thrash
            pool = c.client.osdmap.pools[pid]
            cur = dict(c.client.osdmap.erasure_code_profiles.get(
                pool.erasure_code_profile) or {})
            if not cur:
                return
            name = "thrash-swap-%d" % arg
            await c.client.mon_command("osd erasure-code-profile set",
                                       name=name, profile=cur)
            await c.client.mon_command("osd pool set", pool=pool.name,
                                       var="erasure_code_profile",
                                       val=name)
            self.log.append("ec profile %s -> %s"
                            % (pool.erasure_code_profile, name))
            assert (await workload.write_one()) is not None, \
                "write could not complete after EC profile swap"
        elif action == "device_fallback":
            from ..device.runtime import DeviceRuntime
            rt = DeviceRuntime.get()
            rt.inject_fault(1 << 30)     # probes keep failing too
            rt.poison("thrash: device_fallback round")
            # the workload must keep completing on the host paths
            for _ in range(5):
                assert (await workload.write_one()) is not None, \
                    "write could not complete on the host fallback"
            await self._wait_health_check(c, "DEVICE_FALLBACK", True)
            rt.clear_faults()            # next probe heals
            await self._wait_health_check(c, "DEVICE_FALLBACK", False)
            assert not rt.fallback, "runtime did not heal"
        elif action == "chip_loss":
            from ..device.runtime import DeviceRuntime
            rt = DeviceRuntime.get()
            victim = arg % rt.n_chips
            chip = rt.chips[victim]
            survivors = [sc for sc in rt.chips if sc is not chip]
            # survivors must never leave the device path: snapshot
            # their host-fallback counters before the loss
            pre_host = {sc.index: sc.host_fallbacks
                        for sc in survivors}
            chip.inject_fault(1 << 30)   # probes keep failing too
            chip.poison("thrash: chip_loss round (chip %d)" % victim)
            self.log.append("chip_loss: poisoned chip %d" % victim)
            # writes keep completing: PGs whose primary sits on the
            # lost chip encode on the host, the rest stay on-device
            for _ in range(5):
                assert (await workload.write_one()) is not None, \
                    "write could not complete through the chip loss"
            if any(o.device_chip is chip for o in c.live_osds):
                # an OSD is bound to the lost chip: the health check
                # must raise AND its detail must name exactly this
                # chip (per-chip DEVICE_FALLBACK)
                await self._wait_health_check(c, "DEVICE_FALLBACK",
                                              True)
                leader = c.leader()
                check = leader.health_mon.checks()["DEVICE_FALLBACK"]
                assert check.get("chips") == [victim], check
            for sc in survivors:
                assert not sc.fallback, \
                    "surviving chip %d left the device path" \
                    % sc.index
                assert sc.host_fallbacks == pre_host[sc.index], \
                    "surviving chip %d served host fallbacks " \
                    "during the chip loss" % sc.index
            chip.clear_faults()          # next probe heals
            await self._wait_health_check(c, "DEVICE_FALLBACK", False)
            assert not chip.fallback, "chip %d did not heal" % victim
            assert all(not sc.fallback for sc in survivors)
        elif action == "mixed_rmw":
            pid = next(
                (p for p in self._pool_ids
                 if (c.client.osdmap.pools.get(p) is not None
                     and c.client.osdmap.pools[p]
                     .erasure_code_profile)), None)
            if pid is None:
                return              # no EC pool under thrash
            await self._mixed_rmw_round(c, pid, arg)
        elif action == "bully_tenant":
            pid = self._pool_ids[arg % len(self._pool_ids)]
            if c.client.osdmap.pools.get(pid) is None:
                return
            await self._bully_tenant_round(c, pid, arg)
        elif action == "repair_compare":
            by_plugin: dict[str, int] = {}
            for p in self._pool_ids:
                pool = c.client.osdmap.pools.get(p)
                if pool is None or not pool.erasure_code_profile:
                    continue
                prof = c.client.osdmap.erasure_code_profiles.get(
                    pool.erasure_code_profile) or {}
                by_plugin.setdefault(
                    prof.get("plugin", "jerasure"), p)
            rs_pid = by_plugin.get("jerasure", by_plugin.get("isa"))
            lrc_pid = by_plugin.get("lrc")
            if rs_pid is None or lrc_pid is None:
                return              # needs both flavors under thrash
            await self._repair_compare_round(c, rs_pid, lrc_pid, arg)
        elif action == "corrupt_compressed":
            pid = next(
                (p for p in self._pool_ids
                 if (c.client.osdmap.pools.get(p) is not None
                     and c.client.osdmap.pools[p]
                     .compression_mode == "force"
                     and not c.client.osdmap.pools[p]
                     .erasure_code_profile)), None)
            if pid is None:
                return              # no compression pool under thrash
            await self._corrupt_compressed_round(c, pid, arg)
        elif action == "poison_mid_compress":
            pid = next(
                (p for p in self._pool_ids
                 if (c.client.osdmap.pools.get(p) is not None
                     and c.client.osdmap.pools[p]
                     .compression_mode == "force"
                     and not c.client.osdmap.pools[p]
                     .erasure_code_profile)), None)
            if pid is None:
                return              # no compression pool under thrash
            await self._poison_mid_compress_round(c, pid, arg)
        elif action == "net_degrade":
            await self._net_degrade_round(c, arg, workload)
        elif action == "corrupt_dedup_index":
            await self._corrupt_dedup_index_round(c, arg)
        elif action == "poison_mid_chunk":
            await self._poison_mid_chunk_round(c, arg)
        elif action in ("corrupt_shard", "corrupt_replica"):
            want_ec = action == "corrupt_shard"
            pid = next(
                (p for p in self._pool_ids
                 if (c.client.osdmap.pools.get(p) is not None
                     and bool(c.client.osdmap.pools[p]
                              .erasure_code_profile) == want_ec)),
                None)
            if pid is None:
                return              # no pool of that flavor
            await self._corrupt_round(c, pid, arg, ec=want_ec)
        else:
            raise ValueError(action)

    # tenants the bully rounds flood with: violations on these are by
    # design (the limit tag throttling them IS the mechanism), so the
    # post-round SLO oracle exempts them; every OTHER tenant must end
    # the round alert-free
    BULLY_TENANTS = frozenset({"bully", "other", "mixed"})

    async def _bully_tenant_round(self, c, pid: int,
                                  seed: int) -> None:
        """Noisy-neighbor flood mid-round: a bully tenant's stream
        fleet floods the thrashed pool while victim streams run a
        modest load through the same shared client.  Both tenants'
        acked writes must read back byte-identical (being throttled
        is never being lossy); the post-round SLO oracle in
        _check_invariants then demands no lingering victim alert."""
        from .traffic import TrafficGenerator
        pool = c.client.osdmap.pools[pid]
        gen = TrafficGenerator.build(
            c.client, pid,
            {"victim": {"streams": 2, "window": 2,
                        "obj_bytes": 2048, "n_objects": 8},
             "bully": {"streams": 6, "window": 6,
                       "obj_bytes": 4096, "n_objects": 8}},
            seed=seed)
        stats = await asyncio.wait_for(
            gen.run(max(self.hold, 1.0)), 120.0)
        self.log.append("bully_tenant on %s: %r"
                        % (pool.name,
                           {t: (s["n"], s["errors"])
                            for t, s in stats.items()}))
        for tenant, s in stats.items():
            assert s["n"] > 0, \
                "tenant %s completed zero ops under the flood" \
                % tenant
        # zero lost acked writes, bully included — throttling must
        # never become loss
        await asyncio.wait_for(gen.verify(), 120.0)

    async def _net_degrade_round(self, c, seed: int,
                                 workload) -> None:
        """Degrade one peer link mid-round: every frame between a
        seeded OSD pair is held ~80ms each way — past the 40ms
        slow-ping bar, under the 100ms ping period (no send-queue
        buildup, so the RTT stays stable and the clear is fast), and
        far under the 600ms failure grace (the pair must degrade,
        never die).  The leader must commit an OSD_SLOW_PING_TIME
        raise NAMING the pair, the raise must survive a leader
        change (it is paxos-committed), writes must keep landing,
        and lifting the delay must clear the committed edge."""
        osds = sorted(o.whoami for o in c.live_osds)
        if len(osds) < 2:
            return
        n = len(osds)
        ai = seed % n
        a = osds[ai]
        b = osds[(ai + 1 + (seed // n) % (n - 1)) % n]
        pair = "osd.%d-osd.%d" % (min(a, b), max(a, b))
        ea, eb = "osd.%d" % a, "osd.%d" % b
        c.injector(ea).add_rule(src=ea, dst=eb,
                                delay_p=1.0, delay=0.08)
        c.injector(eb).add_rule(src=eb, dst=ea,
                                delay_p=1.0, delay=0.08)
        self.log.append("net_degrade: delaying %s" % pair)
        try:
            # the committed raise must NAME the degraded pair
            await self._wait_health_check(
                c, "OSD_SLOW_PING_TIME", True, timeout=45.0)
            chk = c.leader().health_mon.checks()[
                "OSD_SLOW_PING_TIME"]
            assert pair in (chk.get("pairs") or ()), chk
            # a degraded link is not an outage: writes keep landing
            for _ in range(3):
                assert (await workload.write_one()) is not None, \
                    "write could not complete on the degraded link"
            if c.n_mons >= 3:
                # the edge is paxos-committed: losing the leader
                # must not lose the raise (the successor re-warns
                # from the committed pair list and its own beacon
                # soft state)
                old = c.leader().rank
                c.partition_mon(old)
                await c.client.mon_command("status", timeout=30.0)
                await self._wait_health_check(
                    c, "OSD_SLOW_PING_TIME", True, timeout=45.0)
                c.heal_mon(old)
                await c.wait_quorum()
        finally:
            c.injector(ea).clear_rules()
            c.injector(eb).clear_rules()
        # delay lifted: healthy pings resume within a period and the
        # committed edge must clear
        await self._wait_health_check(
            c, "OSD_SLOW_PING_TIME", False, timeout=45.0)

    async def _slo_oracle(self, c, timeout: float = 45.0) -> None:
        """Post-round tenant SLO oracle: once the cluster is healthy
        and the burn windows have decayed, neither SLO_LATENCY nor
        SLO_BURN may still name a non-bully tenant — a victim left
        holding an alert after the fault cleared means the QoS plane
        failed to protect it (or the engine failed to clear).  The
        bully's own alerts are exempt: being throttled at its limit
        tag is the mechanism working, not a violation."""
        from ..utils.backoff import wait_for

        def pred():
            leader = c.leader()
            if leader is None:
                return False
            checks = leader.health_mon.checks()
            for name in ("SLO_LATENCY", "SLO_BURN"):
                chk = checks.get(name)
                if chk is None:
                    continue
                victims = [t for t in chk.get("tenants", ())
                           if t not in self.BULLY_TENANTS]
                if victims:
                    return False
            return True

        await wait_for(pred, timeout,
                       what="victim-tenant SLO alerts cleared")

    async def _repair_compare_round(self, c, rs_pid: int,
                                    lrc_pid: int, seed: int) -> None:
        """Plant the same single-shard loss on an RS pool and an LRC
        pool, rebuild each through the recovery path's targeted
        minimal-set reconstruction (`ECPGBackend._reconstruct_shard`
        — the exact function `recover_peer_shards` dispatches), and
        compare the survivor bytes each repair read: the LRC round
        must read strictly fewer (its local group) than the RS round
        (k whole chunks), and both rebuilt shards must be
        bit-identical to the stored originals."""
        from ..device.runtime import K_RECOVERY_EC
        from ..osd.osdmap import pg_t
        from ..store.objectstore import hobject_t
        rng = random.Random("repaircmp-%r-%d" % (self.seed, seed))
        payload = rng.randbytes(rng.randrange(16, 49) * 1024)
        read_bytes: dict[str, int] = {}
        for label, pid in (("rs", rs_pid), ("lrc", lrc_pid)):
            pool = c.client.osdmap.pools[pid]
            io = c.client.io_ctx(pool.name)
            oid = "repaircmp-%d-%s" % (seed, label)
            await asyncio.wait_for(io.write_full(oid, payload), 30.0)
            await c.wait_health(pid, timeout=120.0)
            m = c.client.osdmap
            pgid = pool.raw_pg_to_pg(
                m.object_locator_to_pg(oid, pid))
            _up, _upp, acting, prim = m.pg_to_up_acting_osds(pgid)
            alive = {o.whoami: o for o in c.live_osds}
            primary = alive.get(prim)
            assert primary is not None, "primary osd.%s dead" % prim
            pg = primary.pgs[pg_t(pid, pgid.ps)]
            # the planted loss: a non-primary DATA-shard holder (the
            # shape where LRC's locality pays; data positions come
            # from the codec's chunk mapping)
            codec = primary.ec.codec(pool)
            mapping = codec.get_chunk_mapping()
            k = codec.get_data_chunk_count()
            data_pos = ([mapping[i] for i in range(k)] if mapping
                        else list(range(k)))
            cands = [j for j in data_pos
                     if j < len(acting) and acting[j] >= 0
                     and acting[j] != prim and acting[j] in alive]
            assert cands, "no non-primary data shard to lose"
            j = cands[rng.randrange(len(cands))]
            rec = await primary.ec._reconstruct_shard(
                pg, oid, j, K_RECOVERY_EC)
            assert rec is not None, (
                "targeted %s repair fell back to the full path"
                % label)
            shard, _size, _ver, _attrs, nread = rec
            holder = alive[acting[j]]
            hpg = holder.pgs[pg_t(pid, pgid.ps)]
            stored = holder.ec._local_shard(hpg, hobject_t(oid))
            assert stored is not None and stored[0] == j, \
                "victim osd.%d does not hold shard %d" \
                % (acting[j], j)
            assert bytes(stored[1]) == shard, (
                "%s targeted repair rebuilt shard %d wrong"
                % (label, j))
            read_bytes[label] = nread
        self.log.append("repair_compare: read_bytes=%r" % read_bytes)
        assert read_bytes["lrc"] < read_bytes["rs"], (
            "LRC single-shard repair read %d bytes, not fewer than"
            " the RS repair's %d for the same loss" % (
                read_bytes["lrc"], read_bytes["rs"]))

    async def _corrupt_round(self, c, pid: int, seed: int,
                             ec: bool) -> None:
        """Plant seeded corruption in stored copies via the store and
        prove the scrub plane repairs to clean: deep scrub detects
        EXACTLY the planted set, OSD_SCRUB_ERRORS + PG_DAMAGED raise
        through the committed digest path, repair drains the residual
        to zero, health clears, and the original bytes read back."""
        from ..osd.osdmap import pg_t
        from ..store.objectstore import NotFound, Transaction, \
            hobject_t
        pool = c.client.osdmap.pools[pid]
        io = c.client.io_ctx(pool.name)
        rng = random.Random("corrupt-%r-%d" % (self.seed, seed))
        payloads = {}
        for i in range(3):
            oid = "rot-%d-%d" % (seed, i)
            payloads[oid] = rng.randbytes(rng.randrange(2, 8) * 512)
            await asyncio.wait_for(
                io.write_full(oid, payloads[oid]), 30.0)
        await c.wait_health(pid, timeout=120.0)
        m = c.client.osdmap
        alive = {o.whoami: o for o in c.live_osds}
        planted: dict = {}          # ps -> set of planted oids
        for oid in sorted(payloads)[:2]:
            pgid = pool.raw_pg_to_pg(
                m.object_locator_to_pg(oid, pid))
            _up, _upp, acting, _prim = m.pg_to_up_acting_osds(pgid)
            members = [o for o in acting if o >= 0 and o in alive]
            victim = alive[members[rng.randrange(len(members))]]
            pg = victim.pgs[pg_t(pid, pgid.ps)]
            ho = hobject_t(oid)
            mode = rng.choice(["data", "attrs", "hinfo"] if ec
                              else ["data", "attrs"])
            t = Transaction()
            if mode == "data":
                data = bytearray(victim.store.read(pg.cid, ho))
                data[rng.randrange(len(data))] ^= 0xFF
                t.write(pg.cid, ho, 0, len(data), bytes(data))
            elif mode == "hinfo":
                # rotted integrity METADATA: still a parseable crc
                # vector, just the wrong one — the majority vote must
                # out it and repair must recompute it
                try:
                    raw = victim.store.getattr(pg.cid, ho,
                                               "ec_hinfo")
                except NotFound:
                    raw = b"0"
                t.setattr(pg.cid, ho, "ec_hinfo", b"1" + raw)
            elif ec:
                # divergent shard metadata (ec_ver): the (ver, size)
                # auth group loses this member even on shallow scrub
                t.setattr(pg.cid, ho, "ec_ver", b"rot.rot")
            else:
                # replicated attr rot: a divergent EXTRA xattr —
                # repair must remove it, not merge around it
                t.setattr(pg.cid, ho, "_rot", b"planted")
            victim.store.apply_transaction(t)
            planted.setdefault(pgid.ps, set()).add(oid)
            self.log.append("corrupt: %s %s on osd.%d (%s)"
                            % (oid, mode, victim.whoami, pg.pgid))
        all_planted = {o for s in planted.values() for o in s}
        # 1. deep scrub finds EXACTLY the planted set (recheck
        #    confirms away workload write races)
        found = set()
        for ps in sorted(planted):
            osd, pg = c.pg_primary(pid, ps)
            assert osd is not None and pg is not None, (pid, ps)
            res = await osd.scrubber.scrub_pg(pg, deep=True,
                                              recheck=True)
            got = {k for k in res["inconsistent"]}
            assert got == planted[ps], (
                "deep scrub of %s found %r, planted %r"
                % (pg.pgid, sorted(got), sorted(planted[ps])))
            found |= got
        assert found == all_planted, (found, all_planted)
        # 2. the health surface raises through the committed
        #    OSD -> mgr -> mon digest path
        if c.mgr is not None:
            await self._wait_health_check(c, "OSD_SCRUB_ERRORS", True)
            await self._wait_health_check(c, "PG_DAMAGED", True)
        # 3. repair drains the residual to zero (surgical: only the
        #    known-bad objects, so an in-flight workload write can
        #    never be "repaired" mid-replication)
        for ps in sorted(planted):
            osd, pg = c.pg_primary(pid, ps)
            res = await osd.scrubber.scrub_pg(pg, deep=True,
                                              repair=True,
                                              only=planted[ps])
            assert res["repaired"] >= 1, res
            assert res["residual"] == 0, res
        # ...and a re-scrub is CLEAN (repair idempotency: nothing
        # left to find, nothing left to fix)
        for ps in sorted(planted):
            osd, pg = c.pg_primary(pid, ps)
            res = await osd.scrubber.scrub_pg(pg, deep=True,
                                              recheck=True)
            assert not (set(res["inconsistent"]) & all_planted), res
            assert res["errors"] == 0, res
        # 4. health clears (only a successful repair scrub may clear)
        if c.mgr is not None:
            await self._wait_health_check(c, "OSD_SCRUB_ERRORS",
                                          False)
            await self._wait_health_check(c, "PG_DAMAGED", False)
        # 5. the original bytes survive the whole ordeal
        for oid, want in sorted(payloads.items()):
            got = await asyncio.wait_for(io.read(oid), 30.0)
            assert got == want, \
                "corrupt round lost %s after repair" % oid

    async def _corrupt_compressed_round(self, c, pid: int,
                                        seed: int) -> None:
        """Compression-plane integrity: plant comp-size / blob rot in
        one replica's stored compressed image, prove the read path
        refuses to serve truncated data (EIO), deep scrub finds
        EXACTLY the planted set, repair drains it to zero, and the
        original bytes read back."""
        from ..compress import OBJ_SIZE_ATTR
        from ..osd.osdmap import pg_t
        from ..store.objectstore import Transaction, hobject_t
        pool = c.client.osdmap.pools[pid]
        io = c.client.io_ctx(pool.name)
        rng = random.Random("corruptcomp-%r-%d" % (self.seed, seed))
        payloads = {}
        for i in range(3):
            oid = "comprot-%d-%d" % (seed, i)
            unit = bytes(rng.randrange(0x20, 0x7F)
                         for _ in range(16))
            payloads[oid] = unit * rng.randrange(256, 1500)
            await asyncio.wait_for(
                io.write_full(oid, payloads[oid]), 30.0)
        await c.wait_health(pid, timeout=120.0)
        m = c.client.osdmap
        alive = {o.whoami: o for o in c.live_osds}
        planted: dict = {}          # ps -> set of planted oids
        for idx, oid in enumerate(sorted(payloads)[:2]):
            pgid = pool.raw_pg_to_pg(
                m.object_locator_to_pg(oid, pid))
            _up, _upp, acting, prim = m.pg_to_up_acting_osds(pgid)
            members = [o for o in acting if o >= 0 and o in alive]
            # first plant lands on the PRIMARY so the read-path guard
            # is provably exercised; the second on a seeded member
            victim = alive[prim if idx == 0 and prim in alive
                           else members[rng.randrange(len(members))]]
            pg = victim.pgs[pg_t(pid, pgid.ps)]
            ho = hobject_t(oid)
            assert victim.store.getattr(pg.cid, ho, "comp-alg"), \
                "%s stored raw on osd.%d: payload did not" \
                " compress" % (oid, victim.whoami)
            mode = rng.choice(["size_attr", "blob"])
            t = Transaction()
            if mode == "size_attr":
                # comp-size disagrees with the decompressed length:
                # without the guard this SERVES wrong-length data
                t.setattr(pg.cid, ho, OBJ_SIZE_ATTR,
                          b"%d" % (len(payloads[oid]) + 7))
            else:
                # physically truncated blob: decompression fails
                blob = victim.store.read(pg.cid, ho)
                t.truncate(pg.cid, ho, 0)
                t.write(pg.cid, ho, 0, len(blob) // 2,
                        bytes(blob[:len(blob) // 2]))
            victim.store.apply_transaction(t)
            planted.setdefault(pgid.ps, set()).add(oid)
            self.log.append("corrupt_compressed: %s %s on osd.%d"
                            % (oid, mode, victim.whoami))
            if victim.whoami == prim:
                # the guard: a read THROUGH the rotted copy fails
                # with EIO — truncated/padded bytes are never served
                outs, res = victim._do_read_ops(
                    pg, oid, [{"op": "read"}])
                assert res == -5, (
                    "rotted compressed read returned %r, not EIO"
                    % ((outs, res),))
        all_planted = {o for s in planted.values() for o in s}
        # deep scrub finds EXACTLY the planted set, repair drains it,
        # a re-scrub is clean, and the original bytes survive
        for ps in sorted(planted):
            osd, pg = c.pg_primary(pid, ps)
            res = await osd.scrubber.scrub_pg(pg, deep=True,
                                              recheck=True)
            got = set(res["inconsistent"])
            assert got == planted[ps], (
                "deep scrub of %s found %r, planted %r"
                % (pg.pgid, sorted(got), sorted(planted[ps])))
        for ps in sorted(planted):
            osd, pg = c.pg_primary(pid, ps)
            res = await osd.scrubber.scrub_pg(pg, deep=True,
                                              repair=True,
                                              only=planted[ps])
            assert res["repaired"] >= 1, res
            assert res["residual"] == 0, res
        for ps in sorted(planted):
            osd, pg = c.pg_primary(pid, ps)
            res = await osd.scrubber.scrub_pg(pg, deep=True,
                                              recheck=True)
            assert not (set(res["inconsistent"]) & all_planted), res
        for oid, want in sorted(payloads.items()):
            got = await asyncio.wait_for(io.read(oid), 30.0)
            assert got == want, \
                "corrupt_compressed lost %s after repair" % oid

    async def _poison_mid_compress_round(self, c, pid: int,
                                         seed: int) -> None:
        """Chip loss mid-compress: arm a one-shot device fault on
        every live OSD's affinity chip, then drive compressible
        writefulls through the tlz pool — the dispatching chip
        poisons mid-flight, every write completes on the
        bit-identical host reference (zero lost acked writes), every
        stored blob decompresses to the original bytes, and the
        poisoned chips heal."""
        from ..compress import create
        from ..device.lzkernel import device_compress_enabled
        from ..device.runtime import DeviceRuntime
        from ..osd.osdmap import pg_t
        from ..store.objectstore import hobject_t
        from ..utils.backoff import wait_for
        pool = c.client.osdmap.pools[pid]
        if pool.compression_algorithm != "tlz":
            await c.client.mon_command(
                "osd pool set", pool=pool.name,
                var="compression_algorithm", val="tlz")
            await wait_for(
                lambda: all(
                    o.osdmap.pools.get(pid) is not None
                    and o.osdmap.pools[pid].compression_algorithm
                    == "tlz" for o in c.live_osds),
                30.0, what="tlz algorithm visible on every OSD")
            pool = c.client.osdmap.pools[pid]
        io = c.client.io_ctx(pool.name)
        rng = random.Random("poisoncomp-%r-%d" % (self.seed, seed))
        rt = DeviceRuntime.get()
        chips = {(o.device_chip if o.device_chip is not None
                  else rt.chip_for(o.whoami)) for o in c.live_osds}
        armed = device_compress_enabled()
        pre_poison = {ch.index: ch.fallback_count for ch in chips}
        if armed:
            for ch in chips:
                ch.inject_fault(1)
        payloads = {}
        for i in range(5):
            oid = "poisoncomp-%d-%d" % (seed, i)
            unit = bytes(rng.randrange(0x20, 0x7F)
                         for _ in range(12))
            payloads[oid] = unit * rng.randrange(300, 2000)
        try:
            # concurrent writefulls: the first dispatch consumes the
            # fault mid-compress; gather raises if ANY write is lost
            await asyncio.wait_for(asyncio.gather(*[
                io.write_full(oid, p)
                for oid, p in sorted(payloads.items())]), 60.0)
        finally:
            for ch in chips:
                ch.clear_faults()
        if armed:
            assert any(ch.fallback_count > pre_poison[ch.index]
                       for ch in chips), \
                "no chip consumed the armed mid-compress fault"
        # zero lost acked writes, and every stored blob decompresses
        # to the original bytes on every live replica
        m = c.client.osdmap
        alive = {o.whoami: o for o in c.live_osds}
        for oid, want in sorted(payloads.items()):
            got = await asyncio.wait_for(io.read(oid), 30.0)
            assert got == want, \
                "acked write %s lost through the chip poison" % oid
            pgid = pool.raw_pg_to_pg(
                m.object_locator_to_pg(oid, pid))
            _up, _upp, acting, _prim = m.pg_to_up_acting_osds(pgid)
            for o in acting:
                osd = alive.get(o)
                if osd is None:
                    continue
                pg = osd.pgs.get(pg_t(pid, pgid.ps))
                if pg is None:
                    continue
                ho = hobject_t(oid)
                algo = osd.store.getattr(pg.cid, ho, "comp-alg")
                assert algo == b"tlz", (oid, o, algo)
                blob = osd.store.read(pg.cid, ho)
                assert create("tlz").decompress(bytes(blob)) \
                    == want, (
                    "stored blob of %s on osd.%d does not decompress"
                    " to the original bytes" % (oid, o))
        self.log.append("poison_mid_compress: %d writes, armed=%r"
                        % (len(payloads), armed))
        # the probe loops heal every poisoned chip (faults cleared)
        await wait_for(lambda: all(not ch.fallback for ch in chips),
                       30.0, what="poisoned chips healed")

    async def _dedup_pool_pair(self, c, seed: int) -> tuple[int, int]:
        """(base pool id, chunk pool id) for the dedup rounds: an
        existing dedup binding if any pool has one, else an in-round
        pair created through the mon (both plain replicated) and
        waited visible on every live OSD."""
        from ..utils.backoff import wait_for
        for p, pool in sorted(c.client.osdmap.pools.items()):
            if getattr(pool, "dedup_chunk_pool", -1) >= 0:
                return p, pool.dedup_chunk_pool
        base = "dthrash-%d" % seed
        await c.client.mon_command("osd pool create", pool=base,
                                   pg_num=4)
        await c.client.mon_command("osd pool create",
                                   pool=base + "-chunks", pg_num=4)
        await c.client.mon_command("osd pool set", pool=base,
                                   var="dedup_chunk_pool",
                                   val=base + "-chunks")
        await wait_for(
            lambda: any(pl.name == base
                        and getattr(pl, "dedup_chunk_pool", -1) >= 0
                        for pl in c.client.osdmap.pools.values()),
            30.0, what="dedup binding visible on the client")
        pid = next(p for p, pl in c.client.osdmap.pools.items()
                   if pl.name == base)
        cpid = c.client.osdmap.pools[pid].dedup_chunk_pool
        await wait_for(
            lambda: all(
                o.osdmap is not None
                and o.osdmap.pools.get(pid) is not None
                and getattr(o.osdmap.pools[pid],
                            "dedup_chunk_pool", -1) == cpid
                for o in c.live_osds),
            30.0, what="dedup binding visible on every OSD")
        await c.wait_health(pid, timeout=120.0)
        await c.wait_health(cpid, timeout=120.0)
        return pid, cpid

    async def _corrupt_dedup_index_round(self, c, seed: int) -> None:
        """Chunk-store integrity: write a redundant corpus through a
        dedup pool, rot one content-addressed chunk object on ALL BUT
        ONE replica (identical junk, so plain majority voting would
        crown the rot), prove deep scrub detects EXACTLY the planted
        object, repair restores from the single copy that still
        matches its address, a re-scrub is clean, and every base
        object reads back byte-identical."""
        from ..dedup import CHUNK_MIN, parse_chunk_oid
        from ..osd.osdmap import pg_t
        from ..store.objectstore import Transaction, hobject_t
        pid, cpid = await self._dedup_pool_pair(c, seed)
        pool = c.client.osdmap.pools[pid]
        io = c.client.io_ctx(pool.name)
        rng = random.Random("dedrot-%r-%d" % (self.seed, seed))
        shared = rng.randbytes(5 * CHUNK_MIN)
        payloads = {}
        for i in range(4):
            oid = "dedrot-%d-%d" % (seed, i)
            payloads[oid] = shared + rng.randbytes(CHUNK_MIN // 2)
            await asyncio.wait_for(
                io.write_full(oid, payloads[oid]), 30.0)
        await c.wait_health(cpid, timeout=120.0)
        alive = {o.whoami: o for o in c.live_osds}
        # every content-addressed chunk object the store holds, via
        # the chunk-pool primaries' collections
        targets: list[tuple[int, str]] = []
        for o in c.live_osds:
            for pg in o.pgs.values():
                if pg.pool_id != cpid or not pg.is_primary():
                    continue
                for h in o.store.collection_list(pg.cid):
                    if parse_chunk_oid(h.name) is not None:
                        targets.append((pg.ps, h.name))
        assert targets, "no chunk objects landed in the chunk pool"
        targets.sort()
        ps, oid = targets[rng.randrange(len(targets))]
        m = c.client.osdmap
        _up, _upp, acting, _prim = m.pg_to_up_acting_osds(
            pg_t(cpid, ps))
        members = [o for o in acting if o >= 0 and o in alive]
        if len(members) < 2:
            return          # nothing to outvote on a 1-wide pool
        survivor = members[rng.randrange(len(members))]
        victims = [o for o in members if o != survivor]
        blob0 = alive[survivor].store.read(
            alive[survivor].pgs[pg_t(cpid, ps)].cid, hobject_t(oid))
        junk = rng.randbytes(len(blob0))        # same junk: majority
        for v in victims:
            osd = alive[v]
            pg = osd.pgs[pg_t(cpid, ps)]
            t = Transaction()
            t.truncate(pg.cid, hobject_t(oid), 0)
            t.write(pg.cid, hobject_t(oid), 0, len(junk), junk)
            osd.store.apply_transaction(t)
        self.log.append("corrupt_dedup_index: %s rotted on %r,"
                        " survivor osd.%d" % (oid, victims, survivor))
        osd, pg = c.pg_primary(cpid, ps)
        res = await osd.scrubber.scrub_pg(pg, deep=True, recheck=True)
        assert set(res["inconsistent"]) == {oid}, (
            "deep scrub of %s found %r, planted [%s]"
            % (pg.pgid, sorted(res["inconsistent"]), oid))
        res = await osd.scrubber.scrub_pg(pg, deep=True, repair=True,
                                          only={oid})
        assert res["repaired"] >= 1, res
        assert res["residual"] == 0, res
        res = await osd.scrubber.scrub_pg(pg, deep=True, recheck=True)
        assert oid not in set(res["inconsistent"]), res
        # the address-matching copy won: every replica holds the
        # original chunk bytes again, and the base corpus reads back
        for v in members:
            got = alive[v].store.read(
                alive[v].pgs[pg_t(cpid, ps)].cid, hobject_t(oid))
            assert bytes(got) == bytes(blob0), (
                "chunk %s on osd.%d not restored" % (oid, v))
        for boid, want in sorted(payloads.items()):
            got = await asyncio.wait_for(io.read(boid), 30.0)
            assert got == want, (
                "corrupt_dedup_index lost %s after repair" % boid)

    async def _poison_mid_chunk_round(self, c, seed: int) -> None:
        """Chip loss mid-chunk: arm a one-shot device fault on every
        live OSD's affinity chip, then drive chunkable writefulls
        through a dedup pool — the dispatching chip poisons
        mid-flight, every write completes on the bit-identical host
        reference (zero lost acked writes), every object reads back,
        and the poisoned chips heal."""
        from ..dedup import CHUNK_MIN, device_dedup_enabled
        from ..device.runtime import DeviceRuntime
        from ..utils.backoff import wait_for
        pid, _cpid = await self._dedup_pool_pair(c, seed)
        pool = c.client.osdmap.pools[pid]
        io = c.client.io_ctx(pool.name)
        rng = random.Random("poisonchunk-%r-%d" % (self.seed, seed))
        rt = DeviceRuntime.get()
        chips = {(o.device_chip if o.device_chip is not None
                  else rt.chip_for(o.whoami)) for o in c.live_osds}
        armed = device_dedup_enabled()
        pre_poison = {ch.index: ch.fallback_count for ch in chips}
        if armed:
            for ch in chips:
                ch.inject_fault(1)
        shared = rng.randbytes(3 * CHUNK_MIN)
        payloads = {}
        for i in range(5):
            oid = "poisonchunk-%d-%d" % (seed, i)
            payloads[oid] = shared + rng.randbytes(
                CHUNK_MIN // 4 * (i + 1))
        try:
            # concurrent writefulls: the first dispatch consumes the
            # fault mid-chunk; gather raises if ANY write is lost
            await asyncio.wait_for(asyncio.gather(*[
                io.write_full(oid, p)
                for oid, p in sorted(payloads.items())]), 60.0)
        finally:
            for ch in chips:
                ch.clear_faults()
        if armed:
            assert any(ch.fallback_count > pre_poison[ch.index]
                       for ch in chips), \
                "no chip consumed the armed mid-chunk fault"
        for oid, want in sorted(payloads.items()):
            got = await asyncio.wait_for(io.read(oid), 30.0)
            assert got == want, \
                "acked write %s lost through the chip poison" % oid
        self.log.append("poison_mid_chunk: %d writes, armed=%r"
                        % (len(payloads), armed))
        # the probe loops heal every poisoned chip (faults cleared)
        await wait_for(lambda: all(not ch.fallback for ch in chips),
                       30.0, what="poisoned chips healed")

    async def _mixed_rmw_round(self, c, pid: int, seed: int) -> None:
        """Interleaved full rewrites + partial overwrites on the same
        EC objects (seeded, one write per object per concurrent
        batch so the expected content is unambiguous), then the
        direction-2 oracle: every acked write reads back exactly and
        every stored shard is bit-identical to the host codec's
        encode of the final contents."""
        pool = c.client.osdmap.pools[pid]
        io = c.client.io_ctx(pool.name)
        rng = random.Random("mixed_rmw-%r-%d" % (self.seed, seed))
        model: dict[str, bytearray] = {}
        for i in range(4):
            oid = "mixedrmw-%d-%d" % (seed, i)
            size = rng.randrange(8, 33) * 1024
            data = rng.randbytes(size)
            await asyncio.wait_for(io.write_full(oid, data), 30.0)
            model[oid] = bytearray(data)
        oids = sorted(model)
        chunk = max(1, len(model[oids[0]]) // 2)
        for _step in range(5):
            batch = []
            for oid in oids:
                size = len(model[oid])
                roll = rng.random()
                if roll < 0.25:
                    batch.append((oid, rng.randbytes(size), None))
                elif roll < 0.5:
                    # chunk-boundary-crossing overwrite (the delta
                    # path must split it per column range)
                    ln = rng.randrange(256, 2048)
                    off = max(0, min(size - ln,
                                     chunk - ln // 2))
                    batch.append((oid, rng.randbytes(ln), off))
                else:
                    ln = rng.randrange(16, 4096)
                    off = rng.randrange(0, max(1, size - ln))
                    batch.append((oid, rng.randbytes(ln), off))
            # concurrent: partial overwrites across objects batch
            # into shared device dispatches
            await asyncio.wait_for(asyncio.gather(*[
                (io.write_full(oid, d) if off is None
                 else io.write(oid, d, off))
                for oid, d, off in batch]), 60.0)
            for oid, d, off in batch:   # all acked (gather raised
                if off is None:         # on any failure)
                    model[oid] = bytearray(d)
                else:
                    model[oid][off:off + len(d)] = d
        self.log.append("mixed_rmw: %d objects, 5 rounds" % len(oids))
        await c.wait_health(pid, timeout=120.0)
        for oid, want in sorted(model.items()):
            got = await asyncio.wait_for(io.read(oid), 30.0)
            assert got == bytes(want), \
                "acked mixed_rmw write lost/corrupt on %s" % oid
        await self._verify_ec_host_parity(c, pid, model)

    @staticmethod
    async def _verify_ec_host_parity(c, pid: int,
                                     objects: dict) -> None:
        """Every live acting member's stored shard of `objects` must
        be BIT-IDENTICAL to the host codec's encode of the expected
        payload — delta-updated parity shards and the incrementally
        maintained hinfo crcs included.  Run only on a healthy pool
        (recovery drained), so every member holds current bytes."""
        from ..ec.plugin import ErasureCodePluginRegistry
        from ..osd.ecbackend import HINFO_XATTR, hinfo_bytes
        from ..osd.osdmap import pg_t
        from ..store.objectstore import hobject_t
        m = c.client.osdmap
        pool = m.pools[pid]
        profile = dict(m.erasure_code_profiles.get(
            pool.erasure_code_profile) or {})
        codec = ErasureCodePluginRegistry.instance().factory(
            profile.get("plugin", "jerasure"), dict(profile))
        n = codec.get_chunk_count()
        osd_by_id = {o.whoami: o for o in c.live_osds}
        for oid, want in sorted(objects.items()):
            expected = codec.encode(set(range(n)), bytes(want))
            hinfo = hinfo_bytes(expected)
            pgid = pool.raw_pg_to_pg(
                m.object_locator_to_pg(oid, pid))
            _up, _upp, acting, _prim = m.pg_to_up_acting_osds(pgid)
            checked = 0
            for j, osd_id in enumerate(acting):
                osd = osd_by_id.get(osd_id)
                if osd is None:
                    continue
                pg = osd.pgs.get(pg_t(pid, pgid.ps))
                if pg is None:
                    continue
                local = osd.ec._local_shard(pg, hobject_t(oid))
                assert local is not None, \
                    "%s: osd.%d holds no shard" % (oid, osd_id)
                lj, buf, size, _ver, attrs = local
                assert lj == j, (oid, osd_id, lj, j)
                assert size == len(want), (oid, size, len(want))
                assert bytes(buf) == expected[j], (
                    "mixed_rmw: shard %d of %s on osd.%d diverged "
                    "from the host codec (%d bytes)"
                    % (j, oid, osd_id, len(buf)))
                assert attrs.get(HINFO_XATTR) == hinfo, (
                    "mixed_rmw: hinfo crc of %s shard %d diverged "
                    "from a host recompute" % (oid, j))
                checked += 1
            assert checked >= codec.get_data_chunk_count(), \
                "%s: only %d shards checkable" % (oid, checked)

    @staticmethod
    async def _wait_crash_listed(c, crash_id: str,
                                 timeout: float = 30.0) -> None:
        """Poll until the crash report is in the COMMITTED table of
        the leading mon (shipped from the revived daemon's store and
        paxos-committed)."""
        from ..utils.backoff import wait_for

        def pred():
            leader = c.leader()
            return (leader is not None
                    and crash_id in leader.crash_mon.reports)

        await wait_for(pred, timeout,
                       what="crash %s in committed table" % crash_id)

    @staticmethod
    async def _wait_clog_down_boot(c, victim: int,
                                   timeout: float = 30.0) -> None:
        """The committed cluster log must show the victim's
        marked-down entry followed by its boot entry (the expected
        event sequence of a kill/revive round, identical on every mon
        because both entries are paxos-committed)."""
        from ..utils.backoff import wait_for

        def pred():
            leader = c.leader()
            if leader is None:
                return False
            down_i = boot_i = -1
            for i, e in enumerate(leader.log_mon.entries):
                msg = e.get("message", "")
                if "osd.%d marked down" % victim in msg:
                    down_i = i
                elif "osd.%d boot" % victim in msg:
                    boot_i = i
            return 0 <= down_i < boot_i

        await wait_for(pred, timeout,
                       what="clog down->boot sequence for osd.%d"
                            % victim)

    @staticmethod
    async def _wait_health_check(c, check: str, present: bool,
                                 timeout: float = 30.0) -> None:
        """Poll the leading monitor's health checks until `check` is
        (or is no longer) raised."""
        from ..utils.backoff import wait_for

        def pred():
            leader = c.leader()
            if leader is None:
                return False
            return (check in leader.health_mon.checks()) == present

        await wait_for(pred, timeout,
                       what="%s %s" % (check,
                                       "raised" if present
                                       else "cleared"))

    async def _check_invariants(self, pool_ids: list,
                                workloads: list) -> None:
        c = self.cluster
        await c.wait_quorum()
        for pool_id in pool_ids:
            await c.wait_health(pool_id, timeout=120.0)
        for wl in workloads:
            await wl.verify(sample=300)
        # integrity oracle: an un-tampered healthy round deep-scrubs
        # CLEAN on every thrashed pool (recheck confirms away the
        # still-running workload's in-flight writes) — any residual
        # inconsistency is silent rot some action just manufactured
        if self.scrub_oracle and hasattr(c, "scrub_pool"):
            for pool_id in pool_ids:
                res = await c.scrub_pool(pool_id, deep=True,
                                         recheck=True)
                assert res["errors"] == 0, (
                    "deep scrub found inconsistencies after a "
                    "healthy round: %r" % res)
        # slow-op oracle: the cluster is healthy and every acked write
        # read back — nothing may still sit in an OSD's in-flight
        # table past the complaint threshold (a parked op whose
        # requeue edge was lost would hide here forever)
        if hasattr(c, "stuck_ops"):
            stuck = c.stuck_ops()
            assert not stuck, (
                "ops stuck past osd_op_complaint_time after the "
                "cluster went healthy: %r"
                % [(s["daemon"], s["desc"], round(s["age"], 1))
                   for s in stuck[:5]])
        # event-plane oracles: a healthy round ends with ZERO
        # un-archived crash reports in the committed table (a crash
        # round archives its own before getting here) and no
        # ERR-level clog entries — the framework reserves ERR for
        # genuinely unexplained failures, so any ERR is a bug
        leader = c.leader()
        if leader is not None and hasattr(leader, "crash_mon"):
            pending = [r.get("crash_id")
                       for r in leader.crash_mon.unarchived()]
            assert not pending, (
                "healthy round ended with un-archived crash "
                "reports: %r" % pending)
            errs = [e for e in leader.log_mon.entries
                    if e.get("level") == "ERR"]
            assert not errs, (
                "unexplained ERR-level cluster log entries after a "
                "healthy round: %r"
                % [(e.get("who"), e.get("message"))
                   for e in errs[:5]])
        # network-plane oracle: a healthy round (every fault lifted,
        # every acked write verified) must not leave a slow-ping
        # alert raised — in-process peer pings run far under the bar,
        # so a lingering OSD_SLOW_PING_TIME means the clear edge was
        # lost somewhere in the counter->beacon->paxos chain
        if leader is not None and hasattr(leader, "health_mon"):
            from ..utils.backoff import wait_for
            await wait_for(
                lambda: "OSD_SLOW_PING_TIME"
                        not in leader.health_mon.checks(),
                30.0, what="slow-ping alert cleared after a "
                           "healthy round")
        # stats-plane oracle (clusters running a mgr): the PGMap
        # digest — OSD stat rows -> mgr -> mon, never internal state —
        # must drain its degraded + misplaced counts to EXACTLY zero
        # once healthy, and a drain that was visibly degraded for
        # several samples must have shown a nonzero recovery rate
        # (data moved; the stats plane saw it move)
        # tenant SLO oracle: every round that ran with tenants must
        # end alert-free for the victims — a bully capped at its
        # limit is not a violation, a victim still burning after the
        # cluster healed is (the direction-1 QoS contract, asserted
        # from the committed health surface, not internal state)
        if getattr(c, "mgr", None) is not None:
            await self._slo_oracle(c)
        if getattr(c, "mgr", None) is not None \
                and hasattr(c, "wait_degraded_drained"):
            obs = await c.wait_degraded_drained(timeout=120.0)
            assert (c.degraded_objects() or 0) == 0, obs
            if obs["samples_degraded"] >= 3 \
                    and obs["max_recovery_rate"] <= 0.0:
                # the rate window can trail the drain by one report
                # period: give it a beat before calling it a miss
                for _ in range(30):
                    if c.recovery_rate() > 0.0:
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError(
                        "degraded objects drained but the stats "
                        "plane never showed a recovery rate: %r"
                        % obs)
