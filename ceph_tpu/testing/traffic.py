"""Tenant traffic generator: hundreds of client streams, one process.

The contended-workload half of ROADMAP direction 1 (Kim et al.,
arXiv:1709.05365: online-EC stores degrade under exactly this mix):
``TenantStream`` multiplexes one tenant's op stream over the SHARED
RadosClient messenger — no per-stream sockets or daemons — with a
bounded per-stream in-flight window (the Objecter-side admission
analog), and ``TrafficGenerator`` drives any number of streams
concurrently, folding per-tenant latency percentiles out the other
side.

The canonical scenario is the noisy neighbor: a bully tenant floods
(many streams, wide windows) while victims run a modest steady load —
with per-tenant dmClock rows configured (`osd_mclock_tenant_qos`),
the bully is throttled at its limit tag and the victims' p99 holds.
`bench.py --traffic` publishes exactly that figure behind a
regression gate; the thrasher's `bully_tenant` action replays it
mid-fault-schedule.

Acked-write tracking mirrors testing.thrasher.Workload: only writes
whose future resolved are recorded, and `verify()` reads every one
back byte-identical — a bully being throttled must never turn into a
bully losing acknowledged data.
"""

from __future__ import annotations

import asyncio
import random


def pctl_ms(samples: list[float], p: float) -> float:
    """p-quantile of latency samples, in ms (0.0 when empty)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(p * len(s)))] * 1e3


class TenantStream:
    """One tenant-stamped op stream with a bounded in-flight window.

    `window` concurrent slots each loop submit -> await; the op mix
    is seeded (`read_frac` of reads against already-acked objects,
    writes otherwise), so a schedule replays from its seed."""

    def __init__(self, client, pool_id: int, tenant: str,
                 prefix: str, window: int = 4,
                 obj_bytes: int = 4096, n_objects: int = 16,
                 read_frac: float = 0.0, seed: int = 0,
                 op_timeout: float = 30.0):
        self.client = client
        self.pool_id = pool_id
        self.tenant = tenant
        self.prefix = prefix
        self.window = max(1, int(window))
        self.obj_bytes = int(obj_bytes)
        self.n_objects = max(1, int(n_objects))
        self.read_frac = float(read_frac)
        self.op_timeout = float(op_timeout)
        self.rng = random.Random("%s|%s|%d" % (tenant, prefix, seed))
        self.latencies: list[float] = []    # seconds, completed ops
        self.errors = 0
        self.ops_done = 0
        self.acked: dict[str, bytes] = {}   # oid -> last acked bytes

    def _payload(self, oid: str) -> bytes:
        rep = self.rng.randrange(1, 4)
        base = ("%s|%s|%d|" % (self.prefix, oid,
                               self.rng.randrange(1 << 30))).encode()
        out = base * max(1, self.obj_bytes // max(1, len(base)) * rep)
        return out[:max(1, self.obj_bytes)]

    async def _one_op(self) -> None:
        oid = "%s-%d" % (self.prefix,
                         self.rng.randrange(self.n_objects))
        reads_ok = self.acked and self.rng.random() < self.read_frac
        t0 = asyncio.get_event_loop().time()
        try:
            if reads_ok:
                roid = self.rng.choice(sorted(self.acked))
                outs = await asyncio.wait_for(
                    self.client.submit_op(
                        self.pool_id, roid,
                        [{"op": "read", "offset": 0, "length": 0}],
                        tenant=self.tenant),
                    self.op_timeout)
                assert outs[0]["data"] == self.acked[roid], \
                    "acked write %s read back wrong bytes" % roid
            else:
                data = self._payload(oid)
                await asyncio.wait_for(
                    self.client.submit_op(
                        self.pool_id, oid,
                        [{"op": "writefull", "data": data}],
                        tenant=self.tenant),
                    self.op_timeout)
                self.acked[oid] = data
        except AssertionError:
            raise
        except Exception:
            self.errors += 1
            return
        self.latencies.append(
            asyncio.get_event_loop().time() - t0)
        self.ops_done += 1

    async def _slot(self, stop_at: float) -> None:
        loop = asyncio.get_event_loop()
        while loop.time() < stop_at:
            await self._one_op()

    async def run(self, duration: float) -> "TenantStream":
        stop_at = asyncio.get_event_loop().time() + float(duration)
        await asyncio.gather(*[self._slot(stop_at)
                               for _ in range(self.window)])
        return self

    async def verify(self) -> None:
        """Every acked write reads back byte-identical (the
        zero-lost-acked-writes oracle of the bully round)."""
        for oid, want in sorted(self.acked.items()):
            outs = await asyncio.wait_for(
                self.client.submit_op(
                    self.pool_id, oid,
                    [{"op": "read", "offset": 0, "length": 0}],
                    tenant=self.tenant), self.op_timeout)
            got = outs[0]["data"]
            assert got == want, \
                "acked write %s of tenant %s lost/corrupt" \
                % (oid, self.tenant)


class TrafficGenerator:
    """Run any number of TenantStreams concurrently over one shared
    client and fold per-tenant figures."""

    def __init__(self, streams: list[TenantStream]):
        self.streams = list(streams)

    @classmethod
    def build(cls, client, pool_id: int, tenants: dict[str, dict],
              seed: int = 0) -> "TrafficGenerator":
        """tenants: {tenant: {"streams": n, "window": w,
        "obj_bytes": b, "n_objects": o, "read_frac": f}} — hundreds
        of streams per process is the intended scale (each is just a
        few coroutines on the shared messenger)."""
        streams = []
        for tenant, spec in sorted(tenants.items()):
            for i in range(int(spec.get("streams", 1))):
                streams.append(TenantStream(
                    client, pool_id, tenant,
                    prefix="%s-s%d" % (tenant, i),
                    window=int(spec.get("window", 4)),
                    obj_bytes=int(spec.get("obj_bytes", 4096)),
                    n_objects=int(spec.get("n_objects", 16)),
                    read_frac=float(spec.get("read_frac", 0.0)),
                    seed=seed + i))
        return cls(streams)

    async def run(self, duration: float) -> dict[str, dict]:
        t0 = asyncio.get_event_loop().time()
        await asyncio.gather(*[s.run(duration)
                               for s in self.streams])
        wall = max(1e-9, asyncio.get_event_loop().time() - t0)
        return self.tenant_stats(wall)

    async def verify(self) -> None:
        for s in self.streams:
            await s.verify()

    def tenant_stats(self, wall_s: float) -> dict[str, dict]:
        """{tenant: {streams, n, errors, ops_s, p50_ms, p99_ms}}."""
        by_tenant: dict[str, list[TenantStream]] = {}
        for s in self.streams:
            by_tenant.setdefault(s.tenant, []).append(s)
        out: dict[str, dict] = {}
        for tenant, streams in sorted(by_tenant.items()):
            lats: list[float] = []
            for s in streams:
                lats.extend(s.latencies)
            out[tenant] = {
                "streams": len(streams),
                "n": len(lats),
                "errors": sum(s.errors for s in streams),
                "ops_s": round(len(lats) / wall_s, 2),
                "p50_ms": round(pctl_ms(lats, 0.50), 3),
                "p99_ms": round(pctl_ms(lats, 0.99), 3),
            }
        return out
