"""LocalCluster: one-process mon+OSD+client cluster harness.

The shared substrate under tests/test_cluster.py, the thrasher and
``python -m ceph_tpu.cli.vstart`` (the vstart.sh /
qa/standalone/ceph-helpers.sh analog): real daemons, real wire
protocol over loopback TCP, one event loop for determinism.

Fault surface: every daemon's messenger carries a seeded
`FaultInjector` (ceph_tpu.msg.faults) when the cluster is built with
a seed, so partitions and frame faults are scriptable per node and a
failure schedule replays from its seed.
"""

from __future__ import annotations

import asyncio

from ..client import RadosClient
from ..mon import Monitor
from ..msg.faults import FaultInjector
from ..osd.daemon import OSD
from ..utils.backoff import wait_for
from ..utils.context import Context

# dev-cluster pacing: tight heartbeats and auto-out so failure
# handling is observable in seconds, not minutes
FAST_CONF = {
    "heartbeat_interval": 0.1,
    "heartbeat_grace": 0.6,
    "mon_osd_down_out_interval": 1.0,
    "mon_osd_min_down_reporters": 1,
    "osd_pool_default_pg_num": 8,
    # EC sub-reads that race a just-killed member must widen to the
    # survivors in ~1s, not the production 10s — at dev-cluster
    # heartbeat pacing a thrash round would otherwise spend minutes
    # of recovery time burning timeouts
    "osd_ec_subop_timeout": 1.0,
    # publications lost to a partition must be repaired within a
    # thrash round, not the production 10s renewal period
    "mon_subscribe_renew_interval": 2.0,
    # op tracking at dev pacing: an op in flight 5s on a healthy dev
    # cluster is genuinely stuck (production default is 30s), and
    # beacons must carry the slow count to the mon within a round
    "osd_op_complaint_time": 5.0,
    "osd_beacon_report_interval": 0.25,
    "osd_op_history_size": 64,
    # network plane at dev pacing: 40ms heartbeat RTT is slow (an
    # injected net_degrade delay of ~80ms trips it; healthy in-proc
    # pings run far under 1ms), well below the 600ms grace so a slow
    # pair warns long before it is declared dead
    "osd_slow_ping_time_ms": 40.0,
    # stats plane at dev pacing: per-PG stat rows and PGMap digests
    # must cross OSD -> mgr -> mon within a thrash round
    "osd_mgr_report_interval": 0.3,
    "mgr_stats_period": 0.25,
    "mgr_stats_stale_after": 5.0,
    # stale-row compaction (visible prune counters) within a round:
    # rows mask out of the folds at 5s and are reclaimed at 6s
    "mgr_stats_prune_after": 6.0,
    # integrity plane at dev pacing: scrub is ALWAYS ON — every PG
    # shallow-scrubs every few seconds and deep-scrubs (digest vs
    # hinfo vote) soon after, so silent rot surfaces within a thrash
    # round; a straggling scrub replica is given ~1s + one retry
    # before being recorded unavailable
    "osd_scrub_interval": 3.0,
    "osd_deep_scrub_interval": 6.0,
    "osd_scrub_chunk_timeout": 1.0,
    # flight recorder at dev pacing: keep EVERY trace (production
    # samples 1-in-N; harness oracles assert complete span trees for
    # every acked write, so nothing may drop) and a short utilization
    # window so saturation integrals react within a round
    "flight_recorder_sample": 1,
    "device_util_window": 5.0,
    # continuous dispatch at dev pacing: the per-chip stream is the
    # default architecture; a tight admission tick and the production
    # slot geometry so urgent ops never wait a flush window, and the
    # flush-mode tunables pinned so mode-comparison tests are stable
    "device_dispatch_mode": "stream",
    "device_stream_interval_us": 100,
    "device_stream_slot_words": 1 << 19,
    "device_stream_max_slots": 4,
    "ec_batch_flush_us": 300,
    "ec_batch_max_bytes": 8 << 20,
    # tenant SLO plane at dev pacing: burn windows of seconds (not
    # SRE-scale minutes) so a bully round's burn both RAISES and
    # DECAYS within a thrash round, and a small min-ops floor so
    # short bursts still produce verdicts
    "slo_fast_window": 2.0,
    "slo_slow_window": 5.0,
    "slo_min_ops": 10,
    # history plane at dev pacing: a sub-second finest tier so `perf
    # history` rows fill (and mgr-death gaps are visible) within a
    # thrash round — production tiers are 5s/30s/5min
    "history_tiers": "0.5:120,2:120,10:288",
}


def free_ports(n: int) -> list[int]:
    import socket

    socks = []
    for _ in range(n):
        so = socket.socket()
        so.bind(("127.0.0.1", 0))
        socks.append(so)
    ports = [so.getsockname()[1] for so in socks]
    for so in socks:
        so.close()
    return ports


class LocalCluster:
    """n_mons monitors (a real quorum when >1) + n_osds OSDs + one
    RadosClient.  ``seed`` arms deterministic fault injection: each
    daemon gets a FaultInjector seeded from (seed, entity) and the
    client's retry jitter draws from the same stream family."""

    def __init__(self, n_osds: int = 3, n_mons: int = 1,
                 conf: dict | None = None, seed: int | None = None,
                 with_mgr: bool = False,
                 device_chips: int | None = None):
        self.n_osds = n_osds
        self.n_mons = n_mons
        self.conf = dict(FAST_CONF)
        self.conf.update(conf or {})
        self.seed = seed
        self.with_mgr = with_mgr
        # force the device-mesh size before daemons bind their chips
        # (None keeps the environment's mesh: CEPH_TPU_MESH_CHIPS /
        # jax device count — the tier-1 conftest forces 8)
        self.device_chips = device_chips
        self.mons: list[Monitor] = []
        self.monmap: list[tuple[str, str]] = []
        self.osds: list[OSD | None] = []
        self.mgr = None
        self.client: RadosClient | None = None

    # -- lifecycle ---------------------------------------------------------

    def _install_injector(self, msgr, entity: str) -> FaultInjector:
        if self.seed is None:
            inj = FaultInjector(0)
        else:
            import zlib
            inj = FaultInjector(
                self.seed ^ zlib.crc32(entity.encode()))
        msgr.fault_injector = inj
        return inj

    async def start(self) -> "LocalCluster":
        if self.device_chips is not None:
            from ..device.runtime import DeviceRuntime
            DeviceRuntime.reset(chips=self.device_chips)
        if self.n_mons > 1:
            self.monmap = [("mon.%d" % i, "127.0.0.1:%d" % po)
                           for i, po in
                           enumerate(free_ports(self.n_mons))]
            for name, _a in self.monmap:
                mon = Monitor(Context(name, conf_overrides=self.conf),
                              name=name, monmap=self.monmap)
                self._install_injector(mon.msgr, name)
                await mon.start()
                self.mons.append(mon)
            await self.wait_quorum()
        else:
            mon = Monitor(Context("mon", conf_overrides=self.conf))
            self._install_injector(mon.msgr, "mon.0")
            addr = await mon.start()
            self.mons = [mon]
            self.monmap = [("mon.0", addr)]
        for i in range(self.n_osds):
            await self._start_osd(i)
        for osd in self.osds:
            await osd.wait_for_boot()
        if self.with_mgr:
            from ..mgr import Manager
            self.mgr = Manager(self.mon_addrs,
                               Context("mgr",
                                       conf_overrides=self.conf))
            # the autonomous balancer would move PGs mid-thrash:
            # deterministic harness runs keep it off (enable
            # explicitly in balancer-focused tests)
            self.mgr.balancer_enabled = False
            self._install_injector(self.mgr.msgr, "mgr")
            await self.mgr.start()
        self.client = RadosClient(
            self.mon_addrs, seed=self.seed,
            ctx=Context("client.0", conf_overrides=self.conf))
        self._install_injector(self.client.msgr, "client.0")
        await self.client.connect()
        return self

    async def _start_osd(self, i: int, store=None) -> OSD:
        osd = OSD(i, self.mon_addrs,
                  Context("osd.%d" % i, conf_overrides=self.conf),
                  store=store)
        self._install_injector(osd.msgr, "osd.%d" % i)
        await osd.start()
        if i < len(self.osds):
            self.osds[i] = osd
        else:
            self.osds.append(osd)
        return osd

    async def stop(self) -> None:
        if self.client is not None:
            await self.client.shutdown()
        if self.mgr is not None:
            await self.mgr.shutdown()
        for osd in self.osds:
            if osd is not None and not osd.stopping:
                await osd.shutdown()
        for mon in self.mons:
            await mon.shutdown()

    @property
    def mon_addrs(self) -> list[str]:
        return [a for _n, a in self.monmap]

    @property
    def live_osds(self) -> list[OSD]:
        return [o for o in self.osds
                if o is not None and not o.stopping]

    # -- mon helpers -------------------------------------------------------

    def leader(self) -> Monitor | None:
        for m in self.mons:
            if m.is_leader() and (m.mpaxos is None or m.mpaxos.active):
                return m
        return None

    async def wait_quorum(self, timeout: float = 20.0) -> Monitor:
        await wait_for(lambda: self.leader() is not None, timeout,
                       what="mon quorum")
        return self.leader()

    def injector(self, entity: str) -> FaultInjector:
        """The FaultInjector of a daemon's messenger by entity name
        ("mon.1", "osd.2", "client")."""
        if entity.startswith("mon"):
            rank = int(entity.split(".")[1]) if "." in entity else 0
            return self.mons[rank].msgr.fault_injector
        if entity.startswith("osd"):
            return self.osds[int(entity.split(".")[1])] \
                .msgr.fault_injector
        if entity.startswith("mgr") and self.mgr is not None:
            return self.mgr.msgr.fault_injector
        return self.client.msgr.fault_injector

    def partition_mon(self, rank: int) -> None:
        """Cut mon.<rank> off from every peer (mons, osds, clients):
        a bidirectional network partition enforced by its own
        injector (outbound frames dropped at send, inbound at
        receive, redial handshakes refused)."""
        self.injector("mon.%d" % rank).isolate("mon.%d" % rank)

    def heal_mon(self, rank: int) -> None:
        self.injector("mon.%d" % rank).rejoin("mon.%d" % rank)

    # -- osd helpers -------------------------------------------------------

    async def kill_osd(self, i: int) -> None:
        """Hard-stop osd.i, keeping its store (the "disk")."""
        await self.osds[i].shutdown()

    async def crash_osd(self, i: int,
                        message: str = "injected crash") -> str | None:
        """Crash osd.i on an injected exception: the daemon writes a
        crash report (stack + LogRing tail) into its OWN store, then
        hard-stops — the post-mortem flow the mon's crash table and
        RECENT_CRASH exist for.  Returns the crash_id (the report
        ships on the next boot from the surviving store)."""
        osd = self.osds[i]
        cid = osd.simulate_crash(RuntimeError(message))
        await osd.shutdown()
        return cid

    async def revive_osd(self, i: int, timeout: float = 20.0,
                         wipe: bool = False) -> OSD:
        """Restart osd.i on its surviving store with a fresh
        messenger nonce (the reboot flow peers reset sessions for).
        ``wipe=True`` restarts it on a FRESH store instead (the
        disk-replacement flow): peering sees an empty osd and
        backfill must repopulate every PG it serves."""
        store = None if wipe else self.osds[i].store
        osd = await self._start_osd(i, store=store)
        await osd.wait_for_boot(timeout)
        return osd

    async def wait_osd_down(self, i: int,
                            timeout: float = 30.0) -> None:
        await wait_for(
            lambda: not self.client.osdmap.is_up(i), timeout,
            what="osd.%d down in map" % i)

    async def wait_osd_up(self, i: int, timeout: float = 30.0) -> None:
        await wait_for(lambda: self.client.osdmap.is_up(i), timeout,
                       what="osd.%d up in map" % i)

    async def mark_out(self, i: int) -> None:
        await self.client.mon_command("osd out", id=i)

    async def mark_in(self, i: int) -> None:
        await self.client.mon_command("osd in", id=i)

    # -- mgr helpers -------------------------------------------------------

    async def kill_mgr(self) -> None:
        """Hard-stop the manager: digests stop flowing, the mons'
        staleness clock starts, and history rings record a gap."""
        if self.mgr is not None:
            await self.mgr.shutdown()
            self.mgr = None

    async def revive_mgr(self):
        """Start a FRESH manager (new PGMap, new history rings — the
        mgr is soft state): daemons re-report within an interval and
        digests resume."""
        from ..mgr import Manager
        self.mgr = Manager(self.mon_addrs,
                           Context("mgr", conf_overrides=self.conf))
        self.mgr.balancer_enabled = False
        self._install_injector(self.mgr.msgr, "mgr")
        await self.mgr.start()
        return self.mgr

    # -- pools / health ----------------------------------------------------

    async def create_pool(self, name: str, pg_num: int = 8,
                          size: int | None = None,
                          pool_type: str = "replicated",
                          erasure_code_profile: str | None = None,
                          ) -> int:
        kw = {"pool": name, "pg_num": pg_num}
        if pool_type != "replicated":
            kw["pool_type"] = pool_type
            if erasure_code_profile:
                kw["erasure_code_profile"] = erasure_code_profile
        else:
            kw["size"] = (size if size is not None
                          else min(3, self.n_osds))
        out = await self.client.mon_command("osd pool create", **kw)
        leader = self.leader()
        if leader is not None:
            await self.client.wait_for_epoch(leader.osdmap.epoch)
        return out["pool_id"]

    # -- observability -----------------------------------------------------

    def set_clock_skew(self, entity: str, seconds: float) -> None:
        """Skew one daemon's clock (test hook for the offset
        normalization): both its op-tracker stamps and its outgoing
        frame stamps read monotonic()+seconds, exactly what a
        misaligned host clock would present."""
        if entity.startswith("osd"):
            d = self.osds[int(entity.split(".")[1])]
            d.msgr.clock_skew = seconds
            d.optracker.clock_skew = seconds
        elif entity.startswith("mon"):
            rank = int(entity.split(".")[1]) if "." in entity else 0
            self.mons[rank].msgr.clock_skew = seconds
            self.mons[rank].optracker.clock_skew = seconds
        else:
            self.client.msgr.clock_skew = seconds
            self.client.optracker.clock_skew = seconds

    def clock_offsets(self) -> dict[str, float]:
        """Per-daemon clock offset relative to the CLIENT's clock,
        solved from the per-peer estimates every messenger accumulates
        off frame send stamps (offset underestimates by one-way
        latency; the max over frames converges).  Daemons the client
        never exchanged frames with resolve transitively (replica ->
        primary -> client)."""
        msgrs = {}
        if self.client is not None:
            msgrs[self.client.msgr.entity] = self.client.msgr
        for o in self.live_osds:
            msgrs[o.msgr.entity] = o.msgr
        for m in self.mons:
            msgrs[m.msgr.entity] = m.msgr
        if self.mgr is not None:
            msgrs[self.mgr.msgr.entity] = self.mgr.msgr
        ref = (self.client.msgr.entity if self.client is not None
               else next(iter(msgrs), None))
        offsets: dict[str, float] = {ref: 0.0} if ref else {}
        # fixed-point sweep over both edge directions: m heard from s
        # with estimate (clock_s - clock_m)
        for _ in range(len(msgrs) + 1):
            changed = False
            for ent, msgr in msgrs.items():
                for src, est in msgr.clock_offsets.items():
                    if ent in offsets and src not in offsets \
                            and src in msgrs:
                        offsets[src] = offsets[ent] + est
                        changed = True
                    elif src in offsets and ent not in offsets:
                        offsets[ent] = offsets[src] - est
                        changed = True
            if not changed:
                break
        return offsets

    def op_timeline(self, trace: str) -> list[dict]:
        """Merge every daemon's tracked-op records for one trace id —
        a completed client write yields the full cross-daemon span:
        client submit/send, primary queue/execute/sub-op, replica (or
        EC shard) apply.  Stamps are normalized to the client's clock
        using the per-daemon offsets estimated from message send/recv
        stamps, so stage ordering survives skewed per-daemon clocks
        (the multi-host deployment shape); in-process daemons share
        one clock and normalize by ~0."""
        offsets = self.clock_offsets()
        out: list[dict] = []
        trackers = []
        if self.client is not None:
            trackers.append(self.client.optracker)
        # dead daemons contribute too (their historic rings survive
        # the stop — the diagnostics bundle merges a crashed
        # daemon's slice of the span); offsets default to 0 for
        # daemons no longer exchanging frames
        trackers += [o.optracker for o in self.osds if o is not None]
        trackers += [m.optracker for m in self.mons]
        for tr in trackers:
            for rec in tr.find(trace):
                off = offsets.get(rec.get("daemon"), 0.0)
                if off:
                    rec = dict(rec)
                    rec["initiated"] = rec["initiated"] - off
                    rec["events"] = [
                        {**e, "t": e["t"] - off}
                        for e in rec["events"]]
                    rec["clock_offset"] = off
                out.append(rec)
        return sorted(out, key=lambda d: d["initiated"])

    def export_trace(self, path: str | None = None,
                     traces: list | None = None) -> dict:
        """Merge every daemon's flight-recorder ring (dead daemons
        included — their rings survive the stop) plus the process
        device-ticket ring into ONE Chrome-trace / Perfetto JSON
        document, normalized onto the client's clock via the
        clock-offset solver.  ``traces`` filters op records to those
        trace ids (background + device spans always ride).  ``path``
        additionally writes the document to disk — the artifact you
        drop into https://ui.perfetto.dev."""
        from ..device import mesh
        from ..trace import recorder as flight

        rings: dict[str, list[dict]] = {}

        def take(entity: str, ctx) -> None:
            fr = getattr(ctx, "flight_recorder", None)
            if fr is None:
                return
            recs = [dict(r) for r in fr.records]
            if traces is not None:
                want = set(traces)
                recs = [r for r in recs
                        if r.get("kind") != "op"
                        or r.get("trace") in want]
            rings[entity] = recs

        if self.client is not None:
            take(self.client.msgr.entity, self.client.ctx)
        for osd in self.osds:
            if osd is not None:
                take("osd.%d" % osd.whoami, osd.ctx)
        for m in self.mons:
            take(m.msgr.entity, m.ctx)
        # per-peer wire-throughput counter tracks from the OSDs'
        # heartbeat-paced cumulative samples
        net: dict[str, list[dict]] = {}
        for osd in self.osds:
            if osd is None:
                continue
            ring = getattr(getattr(osd, "network", None),
                           "wire_ring", None)
            if ring:
                net["osd.%d" % osd.whoami] = [dict(r) for r in ring]
        doc = flight.chrome_trace(
            rings, offsets=self.clock_offsets(),
            device=flight.device_records(), net=net,
            meta={"seed": self.seed, "mesh": mesh.describe()})
        if path:
            import json
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    def stuck_ops(self) -> list[dict]:
        """In-flight ops past the complaint threshold on any live
        daemon — the thrasher's slow-op oracle: once the cluster is
        healthy again this must be empty."""
        out: list[dict] = []
        for osd in self.live_osds:
            out.extend(op.dump()
                       for op in osd.optracker.slow_in_flight())
        return out

    def collect_diagnostics(self, traces: list | None = None) -> dict:
        """The one-call diagnostics bundle: per-daemon perf dumps,
        in-flight/historic ops, LogRing tails (INCLUDING dead
        daemons' — the post-mortem context a crash would otherwise
        take with it), mon health/log/crash state, the pgmap digest,
        and merged cross-daemon op timelines — one JSON-able artifact
        to attach to any bug.  ``traces`` picks the op timelines to
        merge; by default the client's most recent historic ops."""
        import time as _t

        from ..utils.crash import pending_crashes, ring_tail

        out: dict = {"generated_at": _t.time(), "seed": self.seed,
                     "daemons": {}, "mons": {}}
        for osd in self.osds:
            if osd is None:
                continue
            name = "osd.%d" % osd.whoami
            d: dict = {"alive": not osd.stopping,
                       "epoch": osd.osdmap.epoch if osd.osdmap else 0,
                       "perf": osd.ctx.perf.dump(),
                       "ops_in_flight":
                           osd.optracker.dump_ops_in_flight(),
                       "historic_slow_ops":
                           osd.optracker.dump_historic_slow_ops(),
                       "ring_tail": ring_tail(osd.ctx.log.ring, 200),
                       "clog_pending": osd.clog.num_pending,
                       "clog_counts": dict(osd.clog.counts),
                       # the network block: per-peer wire telemetry
                       # (WireStats dumps) + heartbeat RTT tracking
                       "net": {
                           "wire": osd.msgr.net_dump(),
                           "rtt": osd.network.dump()}}
            try:
                d["statfs"] = osd.store.statfs()
                d["pending_crash_reports"] = [
                    r.get("crash_id")
                    for r in pending_crashes(osd.store)]
            except Exception:
                pass
            out["daemons"][name] = d
        for m in self.mons:
            health = m.health_mon.command("health", {})
            out["mons"][m.name] = {
                "leader": m.is_leader(),
                "epoch": m.osdmap.epoch,
                "health": health,
                "log_last": m.log_mon.entries[-100:],
                "crashes": [m.crash_mon._summary(r)
                            for r in m.crash_mon.reports.values()],
                "ring_tail": ring_tail(m.ctx.log.ring, 100)}
        if self.mgr is not None:
            out["mgr"] = {
                "daemons_reporting": sorted(
                    self.mgr.daemon_reports),
                "digests_sent": self.mgr.digests_sent,
                "clog_pending": self.mgr.clog.num_pending}
        out["pgmap_digest"] = self.digest()
        out["stuck_ops"] = self.stuck_ops()
        out["clock_offsets"] = self.clock_offsets()
        if self.client is not None:
            out["client"] = {
                "epoch": self.client.osdmap.epoch,
                "ops_in_flight":
                    self.client.optracker.dump_ops_in_flight()}
            if traces is None:
                traces = [r.trace
                          for r in self.client.optracker.historic[-3:]
                          if r.trace]
        out["op_timelines"] = {t: self.op_timeline(t)
                               for t in (traces or [])}
        return out

    async def wait_health(self, pool_id: int,
                          timeout: float = 30.0) -> None:
        """Every PG of the pool active+clean on the current primaries
        (no missing objects anywhere, epochs converged)."""
        await wait_for(lambda: self.healthy(pool_id), timeout,
                       what="pool %d active+clean" % pool_id)

    def healthy(self, pool_id: int) -> bool:
        from ..osd.osdmap import pg_t
        from ..osd.pg import STATE_ACTIVE

        m = None
        for osd in self.live_osds:
            if osd.osdmap is not None:
                if m is None or osd.osdmap.epoch > m.epoch:
                    m = osd.osdmap
        if m is None or pool_id not in m.pools:
            return False
        pool = m.pools[pool_id]
        alive = {o.whoami: o for o in self.live_osds}
        for ps in range(pool.pg_num):
            up, upp, acting, actingp = m.pg_to_up_acting_osds(
                pg_t(pool_id, ps))
            if actingp < 0 or actingp not in alive:
                return False
            prim = alive[actingp]
            if prim.osdmap is None or prim.osdmap.epoch != m.epoch:
                return False
            pg = prim.pgs.get(pg_t(pool_id, ps))
            if pg is None or pg.state != STATE_ACTIVE:
                return False
            if pg.missing or any(pm for pm in
                                 pg.peer_missing.values()):
                return False
        return True

    # -- integrity plane (scrub oracles) -----------------------------------

    def pg_primary(self, pool_id: int, ps: int):
        """(primary OSD object, its PG object) for one PG on the
        newest map a live daemon holds, or (None, None)."""
        from ..osd.osdmap import pg_t
        m = None
        for osd in self.live_osds:
            if osd.osdmap is not None:
                if m is None or osd.osdmap.epoch > m.epoch:
                    m = osd.osdmap
        if m is None or pool_id not in m.pools:
            return None, None
        _up, _upp, _acting, actingp = m.pg_to_up_acting_osds(
            pg_t(pool_id, ps))
        alive = {o.whoami: o for o in self.live_osds}
        osd = alive.get(actingp)
        if osd is None:
            return None, None
        return osd, osd.pgs.get(pg_t(pool_id, ps))

    async def scrub_pool(self, pool_id: int, deep: bool = True,
                         repair: bool = False,
                         recheck: bool = True) -> dict:
        """Scrub every PG of the pool on its live primary and fold
        the results — the thrasher's repair-to-clean oracle surface.
        recheck=True confirms inconsistencies across passes, so a
        still-running workload's in-flight writes never read as rot.
        """
        m = None
        for osd in self.live_osds:
            if osd.osdmap is not None:
                if m is None or osd.osdmap.epoch > m.epoch:
                    m = osd.osdmap
        out = {"errors": 0, "inconsistent": [], "repaired": 0,
               "unavailable": set()}
        if m is None or pool_id not in m.pools:
            return out
        for ps in range(m.pools[pool_id].pg_num):
            osd, pg = self.pg_primary(pool_id, ps)
            if osd is None or pg is None:
                continue
            res = await osd.scrubber.scrub_pg(
                pg, deep=deep, repair=repair, recheck=recheck)
            out["errors"] += res["errors"]
            out["inconsistent"].extend(res["inconsistent"])
            out["repaired"] += res["repaired"]
            out["unavailable"].update(res.get("unavailable") or ())
        out["unavailable"] = sorted(out["unavailable"])
        return out

    # -- cluster statistics plane (PGMap digest oracles) -------------------

    def digest(self) -> dict | None:
        """The freshest PGMap digest any live mon holds — the
        STATS-PLANE view of the cluster (OSD report -> mgr PGMap ->
        mon digest), deliberately not daemon-internal state, so
        oracles built on it exercise the whole pipeline."""
        best = None
        best_stamp = -1.0
        for m in self.mons:
            d = getattr(m, "mgr_digest", None)
            if d is not None and m.mgr_digest_stamp > best_stamp:
                best, best_stamp = d, m.mgr_digest_stamp
        return best

    def _digest_total(self, key: str):
        d = self.digest()
        if d is None:
            return None
        return (d.get("totals") or {}).get(key)

    def degraded_objects(self):
        """Degraded object-copy count from the digest (None until a
        digest arrives)."""
        v = self._digest_total("degraded")
        return None if v is None else int(v)

    def misplaced_objects(self):
        v = self._digest_total("misplaced")
        return None if v is None else int(v)

    def client_io_rate(self) -> float:
        """Client write+read ops/s from the digest (0.0 pre-digest)."""
        d = self.digest()
        if d is None:
            return 0.0
        t = d.get("totals") or {}
        return (float(t.get("read_ops_s") or 0.0)
                + float(t.get("write_ops_s") or 0.0))

    def recovery_rate(self) -> float:
        """Recovery objects/s from the digest (0.0 pre-digest)."""
        v = self._digest_total("recovery_ops_s")
        return 0.0 if v is None else float(v)

    # -- event bus (committed-stream oracle) -------------------------------

    def event_stream(self, start: int = 0) -> list[dict]:
        """Test oracle for the mon event bus: subscribes the harness
        client's cursor and returns the LIVE list rows append to —
        each committed event exactly once, in seq order, surviving
        mon failover (assert on seq contiguity for gap/dup checks)."""
        rows: list[dict] = []
        self.client.watch_events(rows.append, start=start)
        return rows

    async def wait_stats(self, pred, timeout: float = 30.0,
                         what: str = "stats condition") -> None:
        """Poll the digest until `pred(digest)` holds (pred receives
        the freshest digest, possibly None)."""
        await wait_for(lambda: pred(self.digest()), timeout,
                       what=what)

    async def wait_degraded_drained(
            self, timeout: float = 120.0) -> dict:
        """Stats oracle: wait until the digest reports EXACTLY zero
        degraded + misplaced objects, sampling the recovery rate on
        the way.  Returns {"max_degraded", "max_misplaced",
        "max_recovery_rate", "samples_degraded"} so callers can
        additionally assert the drain showed a live recovery rate."""
        import time as _t
        obs = {"max_degraded": 0, "max_misplaced": 0,
               "max_recovery_rate": 0.0, "samples_degraded": 0}
        deadline = _t.monotonic() + timeout
        while True:
            d = self.digest()
            if d is not None:
                deg = self.degraded_objects() or 0
                mis = self.misplaced_objects() or 0
                obs["max_degraded"] = max(obs["max_degraded"], deg)
                obs["max_misplaced"] = max(obs["max_misplaced"], mis)
                obs["max_recovery_rate"] = max(
                    obs["max_recovery_rate"], self.recovery_rate())
                if deg or mis:
                    obs["samples_degraded"] += 1
                else:
                    return obs      # drained (or never degraded)
            if _t.monotonic() > deadline:
                raise TimeoutError(
                    "degraded/misplaced never drained to zero: %r "
                    "(digest totals %r)"
                    % (obs, (d or {}).get("totals")))
            await asyncio.sleep(0.1)
