"""CephFS-lite: a POSIX-style filesystem over RADOS.

Condensed analog of the reference's CephFS tier (src/mds/MDSRank.h
metadata service + src/client/Client.cc POSIX client), reshaped for
this framework the way RBD-lite reshapes librbd:

* METADATA lives where the MDS keeps it — in RADOS omap objects:
  one dirfrag object per directory (``dir.<ino>``, omap: name ->
  dentry {ino, type, size, mtime...}, the CDir/CDentry store,
  src/mds/CDir.cc fetch/commit), an inode allocator object
  (``mds_inotable``, the InoTable role), and per-inode backtrace
  attrs for fsck-style reverse lookup.
* FILE DATA is striped over ``data.<ino>.<objno>`` objects with the
  SAME striper the reference's Client uses (file_to_extents).
* MUTATION ATOMICITY: every single-dentry mutation (create, mkdir,
  unlink, setattr) is ONE atomic omap/cls op on the dirfrag object —
  the role the MDS journal plays for single-dentry safety.  The
  cross-directory rename is two ops (link-then-unlink, source
  cleaned up second), which a crash can leave as a benign duplicate
  dentry — the documented gap the reference closes with its
  EUpdate journal entries; fsck() sweeps them.
* MDS PRESENCE: an ``MDSDaemon`` holds the active-mds cls_lock on the
  fs root object and renews it; clients operate library-mode (the
  libcephfs-with-embedded-client shape), while the lock provides the
  single-active-MDS failover semantic for daemon deployments.

Surface: CephFS.mkdir/create/open/write/read/readdir/stat/rename/
unlink/rmdir/truncate + fsck.
"""

from __future__ import annotations

import time

from ..client.striper import FileLayout, file_to_extents
from ..utils import denc

ROOT_INO = 1
INOTABLE_OID = "mds_inotable"
FS_ROOT_OID = "fs_root"

TYPE_DIR = "dir"
TYPE_FILE = "file"


class FSError(Exception):
    pass


class NotFoundError(FSError):
    pass


class NotEmptyError(FSError):
    pass


class ExistsError(FSError):
    pass


def _dir_oid(ino: int) -> str:
    return "dir.%x" % ino


def _data_name(ino: int, objno: int) -> str:
    return "data.%x.%08x" % (ino, objno)


class CephFS:
    """Filesystem handle (libcephfs mount analog)."""

    def __init__(self, ioctx, layout: FileLayout | None = None):
        self.io = ioctx
        self.layout = layout or FileLayout(stripe_unit=1 << 20,
                                           stripe_count=1,
                                           object_size=1 << 22)

    # -- bootstrap ----------------------------------------------------------

    async def mkfs(self) -> None:
        """Initialize the fs metadata (root dirfrag + ino table)."""
        from ..client.rados import RadosError

        try:
            await self.io.exec(INOTABLE_OID, "lock", "lock",
                               {"name": "mkfs", "cookie": "mkfs"})
        except RadosError as e:
            if e.code in (-16, -17):    # held by another / by us
                raise FSError("mkfs already ran") from None
            raise
        await self.io.omap_set(INOTABLE_OID,
                               {b"next_ino": b"%d" % (ROOT_INO + 1)})
        await self.io.omap_set(_dir_oid(ROOT_INO), {})
        await self.io.write_full(_dir_oid(ROOT_INO), b"")

    async def _alloc_ino(self) -> int:
        """InoTable allocation: atomic in-OSD increment via cls."""
        out = await self.io.exec(INOTABLE_OID, "fsmeta", "alloc_ino",
                                 {})
        return int(out["ino"])

    # -- dentries -----------------------------------------------------------

    async def _lookup(self, dir_ino: int, name: str) -> dict:
        from ..client.rados import RadosError

        try:
            kv = await self.io.omap_get(_dir_oid(dir_ino))
        except RadosError:
            raise NotFoundError("no such directory") from None
        raw = kv.get(name.encode())
        if raw is None:
            raise NotFoundError(name)
        return denc.decode(raw)

    async def _resolve(self, path: str) -> tuple[int, str, dict]:
        """Returns (parent dir ino, leaf name, leaf dentry); for "/"
        returns (0, "", root-dentry)."""
        parts = [p for p in path.split("/") if p]
        if not parts:
            return 0, "", {"ino": ROOT_INO, "type": TYPE_DIR}
        cur = ROOT_INO
        for p in parts[:-1]:
            d = await self._lookup(cur, p)
            if d["type"] != TYPE_DIR:
                raise FSError("%s: not a directory" % p)
            cur = d["ino"]
        leaf = parts[-1]
        return cur, leaf, await self._lookup(cur, leaf)

    async def _resolve_dir(self, path: str) -> int:
        _p, _n, d = await self._resolve(path)
        if d["type"] != TYPE_DIR:
            raise FSError("%s: not a directory" % path)
        return d["ino"]

    async def _parent_of(self, path: str) -> tuple[int, str]:
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise FSError("cannot operate on /")
        parent = "/".join(parts[:-1])
        return await self._resolve_dir("/" + parent), parts[-1]

    async def _link(self, dir_ino: int, name: str, dentry: dict,
                    exclusive: bool = True) -> None:
        """One atomic dentry insert (cls: fails EEXIST inside the
        OSD, so two racing creates cannot both win)."""
        from ..client.rados import RadosError

        try:
            await self.io.exec(_dir_oid(dir_ino), "fsmeta", "link",
                               {"name": name,
                                "dentry": denc.encode(dentry),
                                "exclusive": exclusive})
        except RadosError as e:
            if e.code == -17:
                raise ExistsError(name) from None
            if e.code == -2:
                raise NotFoundError("directory removed") from None
            raise

    # -- directory ops ------------------------------------------------------

    async def mkdir(self, path: str) -> int:
        dir_ino, name = await self._parent_of(path)
        ino = await self._alloc_ino()
        await self.io.omap_set(_dir_oid(ino), {})
        await self._link(dir_ino, name,
                         {"ino": ino, "type": TYPE_DIR,
                          "mtime": time.time()})
        # backtrace for fsck (the reference's backtrace xattr)
        await self.io.setxattr(_dir_oid(ino), "parent",
                               b"%d/%s" % (dir_ino, name.encode()))
        return ino

    async def readdir(self, path: str) -> dict[str, dict]:
        ino = await self._resolve_dir(path)
        kv = await self.io.omap_get(_dir_oid(ino))
        return {k.decode(): denc.decode(v)
                for k, v in sorted(kv.items())}

    async def rmdir(self, path: str) -> None:
        from ..client.rados import RadosError

        dir_ino, name = await self._parent_of(path)
        d = await self._lookup(dir_ino, name)
        if d["type"] != TYPE_DIR:
            raise FSError("%s: not a directory" % path)
        # atomic in-OSD empty-check + tombstone: a concurrent create
        # into this directory either lands before the seal (rmdir
        # fails ENOTEMPTY) or after it (the create fails) — never a
        # silently orphaned file
        try:
            await self.io.exec(_dir_oid(d["ino"]), "fsmeta",
                               "seal_empty", {})
        except RadosError as e:
            if e.code == -39:
                raise NotEmptyError(path) from None
            raise
        await self.io.omap_rm(_dir_oid(dir_ino), [name.encode()])
        # the sealed tombstone stays: removing it would let a racing
        # create() resurrect a fresh (unreachable) dirfrag through
        # link's ctx.create().  Tombstones are a few bytes each.
        try:
            await self.io.truncate(_dir_oid(d["ino"]), 0)
        except Exception:
            pass

    # -- file ops -----------------------------------------------------------

    async def create(self, path: str) -> "FSFile":
        dir_ino, name = await self._parent_of(path)
        ino = await self._alloc_ino()
        await self._link(dir_ino, name,
                         {"ino": ino, "type": TYPE_FILE, "size": 0,
                          "mtime": time.time()})
        return FSFile(self, dir_ino, name, ino, 0)

    async def open(self, path: str) -> "FSFile":
        dir_ino, name, d = await self._resolve(path)
        if d["type"] != TYPE_FILE:
            raise FSError("%s: not a file" % path)
        return FSFile(self, dir_ino, name, d["ino"],
                      int(d.get("size", 0)))

    async def stat(self, path: str) -> dict:
        _p, _n, d = await self._resolve(path)
        return dict(d)

    async def unlink(self, path: str) -> None:
        dir_ino, name = await self._parent_of(path)
        d = await self._lookup(dir_ino, name)
        if d["type"] == TYPE_DIR:
            raise FSError("%s: is a directory" % path)
        await self.io.omap_rm(_dir_oid(dir_ino), [name.encode()])
        await self._purge_data(d["ino"], int(d.get("size", 0)))

    async def _purge_data(self, ino: int, size: int) -> None:
        import asyncio

        objs = ({e[0] for e in file_to_extents(self.layout, 0,
                                               max(size, 1))})

        async def rm(o):
            try:
                await self.io.remove(_data_name(ino, o))
            except Exception:
                pass

        await asyncio.gather(*[rm(o) for o in objs])

    async def rename(self, src: str, dst: str) -> None:
        """Two-phase: link at the destination first, unlink the
        source second — a crash in between leaves a DUPLICATE dentry
        (both resolve to the same inode), never a lost file.  The
        reference makes this atomic via the MDS journal; fsck()
        reports leftovers."""
        norm = lambda p: "/" + "/".join(x for x in p.split("/") if x)
        if norm(dst).startswith(norm(src) + "/"):
            raise FSError("cannot move a directory into itself")
        sdir, sname = await self._parent_of(src)
        d = await self._lookup(sdir, sname)
        ddir, dname = await self._parent_of(dst)
        # refuse overwrite: silently replacing the destination would
        # orphan its inode/subtree with no reclamation path
        await self._link(ddir, dname, d, exclusive=True)
        if (sdir, sname) != (ddir, dname):
            await self.io.omap_rm(_dir_oid(sdir), [sname.encode()])
        if d["type"] == TYPE_DIR:
            await self.io.setxattr(
                _dir_oid(d["ino"]), "parent",
                b"%d/%s" % (ddir, dname.encode()))

    async def fsck(self) -> dict:
        """Duplicate-dentry sweep (the rename crash window): walks
        every dirfrag, reports inodes linked more than once."""
        seen: dict[int, list[str]] = {}
        visited: set[int] = set()
        stack = [(ROOT_INO, "/")]
        while stack:
            ino, prefix = stack.pop()
            if ino in visited:          # cycle guard
                continue
            visited.add(ino)
            kv = await self.io.omap_get(_dir_oid(ino))
            for k, v in kv.items():
                d = denc.decode(v)
                p = prefix.rstrip("/") + "/" + k.decode()
                seen.setdefault(d["ino"], []).append(p)
                if d["type"] == TYPE_DIR:
                    stack.append((d["ino"], p))
        dups = {i: sorted(ps) for i, ps in seen.items()
                if len(ps) > 1}
        return {"duplicates": dups, "inodes": len(seen)}


class FSFile:
    """Open file handle (Client::Fh): striped pread/pwrite, size
    maintained in the parent dentry on flush."""

    def __init__(self, fs: CephFS, dir_ino: int, name: str,
                 ino: int, size: int):
        self.fs = fs
        self.dir_ino = dir_ino
        self.name = name
        self.ino = ino
        self.size = size

    async def pwrite(self, offset: int, data: bytes) -> None:
        import asyncio

        exts = file_to_extents(self.fs.layout, offset, len(data))
        await asyncio.gather(*[
            self.fs.io.write(_data_name(self.ino, o),
                             data[fo - offset:fo - offset + ln], oo)
            for o, oo, ln, fo in exts])
        if offset + len(data) > self.size:
            self.size = offset + len(data)
            await self._flush_size()

    async def pread(self, offset: int, length: int) -> bytes:
        import asyncio

        length = max(0, min(length, self.size - offset))
        if length == 0:
            return b""
        exts = file_to_extents(self.fs.layout, offset, length)

        async def fetch(o, oo, ln):
            try:
                return await self.fs.io.read(
                    _data_name(self.ino, o), ln, oo)
            except Exception:
                return b""

        parts = await asyncio.gather(*[fetch(o, oo, ln)
                                       for o, oo, ln, _fo in exts])
        buf = bytearray(length)
        for (o, oo, ln, fo), part in zip(exts, parts):
            part = part[:ln]
            buf[fo - offset:fo - offset + len(part)] = part
        return bytes(buf)

    async def truncate(self, size: int) -> None:
        if size < self.size:
            old = file_to_extents(self.fs.layout, size,
                                  self.size - size)
            keep = ({e[0] for e in file_to_extents(self.fs.layout, 0,
                                                   size)}
                    if size else set())
            import asyncio

            async def rm(o):
                try:
                    await self.fs.io.remove(_data_name(self.ino, o))
                except Exception:
                    pass

            await asyncio.gather(*[rm(o) for o in
                                   {e[0] for e in old} - keep])
            # EVERY kept object trims to the smallest dropped offset
            # it holds (under striping more than one object straddles
            # the cut, and a stale tail would resurface as old bytes
            # after a later re-extend)
            cut: dict[int, int] = {}
            for o, oo, _ln, fo in old:
                if o in keep and fo >= size:
                    cut[o] = min(cut.get(o, 1 << 62), oo)
            for o, off in cut.items():
                try:
                    await self.fs.io.truncate(
                        _data_name(self.ino, o), off)
                except Exception:
                    pass
        self.size = size
        await self._flush_size()

    async def _flush_size(self) -> None:
        """Size/mtime propagate to the dentry (the cap-flush role)."""
        from ..client.rados import RadosError

        try:
            await self.fs.io.exec(
                _dir_oid(self.dir_ino), "fsmeta", "update_dentry",
                {"name": self.name, "ino": self.ino,
                 "set": {"size": self.size, "mtime": time.time()}})
        except RadosError as e:
            if e.code == -2:
                # the dentry moved (rename) or was re-owned: the data
                # write stands, the stale handle just cannot stamp
                # another file's metadata
                return
            raise


class MDSDaemon:
    """Single-active-MDS presence via cls_lock on the fs root
    (mds_lock role): hold + renew; a second daemon stays standby
    until the active one lapses (break_lock on takeover)."""

    def __init__(self, ioctx, name: str = "mds.a",
                 renew_interval: float = 2.0):
        self.io = ioctx
        self.name = name
        self.renew_interval = renew_interval
        self.active = False
        self._task = None

    async def try_become_active(self) -> bool:
        from ..client.rados import RadosError

        try:
            await self.io.exec(FS_ROOT_OID, "lock", "lock",
                               {"name": "mds_active",
                                "cookie": self.name})
            self.active = True
        except RadosError as e:
            if e.code != -16:
                raise
            self.active = False
        return self.active

    async def start(self, spawn) -> None:
        await self.try_become_active()
        self._task = spawn(self._renew_loop())

    async def _renew_loop(self) -> None:
        import asyncio
        import time as _time

        while True:
            await asyncio.sleep(self.renew_interval)
            if self.active:
                try:
                    await self.io.exec(FS_ROOT_OID, "lock", "lock",
                                       {"name": "mds_active",
                                        "cookie": self.name,
                                        "renew": True})
                except Exception:
                    self.active = False
            else:
                if not await self.try_become_active():
                    await self._maybe_break_stale(_time.time())

    async def _maybe_break_stale(self, now: float) -> None:
        """Crash takeover: a holder that stopped renewing (stamp
        older than 5 renew intervals) is forcibly broken — the
        break_lock path the reference MDSMonitor uses when an active
        MDS's beacon lapses."""
        try:
            info = await self.io.exec(FS_ROOT_OID, "lock",
                                      "get_info",
                                      {"name": "mds_active"})
        except Exception:
            return
        for holder in info.get("lockers", []):
            stamp = float(holder.get("stamp", 0) or 0)
            if stamp and now - stamp > 5 * self.renew_interval:
                try:
                    await self.io.exec(
                        FS_ROOT_OID, "lock", "break_lock",
                        {"name": "mds_active",
                         "locker": holder["locker"],
                         "cookie": holder["cookie"]})
                except Exception:
                    pass
                await self.try_become_active()
                return

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        if self.active:
            try:
                await self.io.exec(FS_ROOT_OID, "lock", "unlock",
                                   {"name": "mds_active",
                                    "cookie": self.name})
            except Exception:
                pass
            self.active = False
