"""RBD-lite: block images striped over RADOS objects.

Condensed analog of src/librbd (ImageCtx + the io/ dispatch layers)
over the striper: an image is a header object
(`rbd_header.<name>`: size + layout xattrs, the role rbd_header's
omap plays) plus data objects `rbd_data.<name>.<objectno>` addressed
by Striper::file_to_extents — the same object-map shape librbd uses
(`rbd_data.<image id>.<object no>`).  Reads of unwritten extents
return zeros (sparse images); writes allocate objects on demand.

Surface: RBD.create/remove/list/open -> Image.read/write/size/resize +
snapshots (snap_create/remove/list/set/rollback on RADOS selfmanaged
snaps — the librbd snapshot model: every image snapshot is a
selfmanaged pool snapid recorded in the header, writes carry the
image's SnapContext so data objects clone on first write,
librbd::Operations<I>::snap_create / snap_rollback).  Clones /
journaling / mirroring remain out of this slice."""

from __future__ import annotations

from ..client.striper import FileLayout, file_to_extents
from ..utils import denc

HEADER_PREFIX = "rbd_header."
DATA_PREFIX = "rbd_data."
DIR_OID = "rbd_directory"
SIZE_XATTR = "rbd.size"
LAYOUT_XATTR = "rbd.layout"
SNAPS_XATTR = "rbd.snaps"


class RBDError(Exception):
    pass


class RBD:
    """Pool-level image operations (librbd::RBD)."""

    def __init__(self, ioctx):
        self.io = ioctx

    async def create(self, name: str, size: int,
                     layout: FileLayout | None = None) -> None:
        """Header + directory registration ride cls_rbd methods: the
        exists check happens INSIDE the OSD, so two racing creates
        cannot both win (the race src/cls/rbd exists to close)."""
        from ..client.rados import RadosError

        layout = layout or FileLayout(stripe_unit=1 << 22,
                                      stripe_count=1,
                                      object_size=1 << 22)
        hdr = HEADER_PREFIX + name
        try:
            await self.io.exec(hdr, "rbd", "create",
                               {"size": size,
                                "layout": layout.encode()})
        except RadosError as e:
            if e.code == -17:
                raise RBDError("image %r exists" % name) from None
            raise
        # image directory: one omap row per image (rbd_directory)
        try:
            await self.io.exec(DIR_OID, "rbd", "dir_add",
                               {"name": name})
        except RadosError as e:
            if e.code != -17:
                raise

    async def list(self) -> list[str]:
        try:
            kv = await self.io.omap_get(DIR_OID)
        except Exception:
            return []
        return sorted(k.decode() for k in kv)

    async def remove(self, name: str) -> None:
        img = await self.open(name)
        # librbd refuses to remove an image that still has snapshots
        # or registered clone children — deleting a parent under its
        # clones is cross-image data loss
        kids = await self.io.exec(HEADER_PREFIX + name, "rbd",
                                  "children", {})
        if kids.get("children"):
            raise RBDError("image %r has clone children" % name)
        if img.snaps:
            raise RBDError("image %r has snapshots" % name)
        exts = file_to_extents(img.layout, 0, max(img._size, 1))
        import asyncio

        async def rm(o):
            try:
                await self.io.remove(img._data_name(o))
            except Exception:
                pass

        await asyncio.gather(*[rm(o) for o in
                               {e[0] for e in exts}])
        from ..client.rados import RadosError

        if img.parent is not None:
            # deregister from the parent so its snap unpins
            try:
                await self.io.exec(
                    HEADER_PREFIX + img.parent.name, "rbd",
                    "child_rm", {"snapid": img.parent_snapid,
                                 "name": name})
            except RadosError as e:
                if e.code != -2:
                    raise
        try:
            await self.io.remove(HEADER_PREFIX + name)
        except RadosError as e:
            if e.code != -2:
                raise
        try:
            await self.io.exec(DIR_OID, "rbd", "dir_remove",
                               {"name": name})
        except RadosError as e:
            if e.code != -2:
                raise

    async def clone(self, parent_name: str, parent_snap: str,
                    clone_name: str) -> None:
        """Snapshot-parent clone (librbd::clone /
        DeepCopyRequest-free COW path): the clone starts as a header
        pointing at (parent, snapid, overlap); data objects
        materialize on first write (copy-up) and reads fall through
        to the parent below the overlap."""
        from ..client.rados import RadosError

        parent = await self.open(parent_name)
        rec = parent.snaps.get(parent_snap)
        if rec is None:
            raise RBDError("no snap %r on %r"
                           % (parent_snap, parent_name))
        sid, psize = int(rec["id"]), int(rec["size"])
        hdr = HEADER_PREFIX + clone_name
        try:
            await self.io.exec(hdr, "rbd", "create",
                               {"size": psize,
                                "layout": parent.layout.encode()})
        except RadosError as e:
            if e.code == -17:
                raise RBDError("image %r exists"
                               % clone_name) from None
            raise
        # registration order matters for crash safety: the child
        # link on the PARENT lands first, so from the moment a clone
        # header could carry a parent pointer, the snap is already
        # unremovable; a crash in between leaves only a stray child
        # entry (unpinnable via child_rm), never a clone whose parent
        # snap can vanish under it
        await self.io.exec(HEADER_PREFIX + parent_name, "rbd",
                           "child_add", {"snapid": sid,
                                         "name": clone_name})
        try:
            await self.io.exec(hdr, "rbd", "set_parent",
                               {"image": parent_name, "snapid": sid,
                                "overlap": psize})
        except Exception:
            try:
                await self.io.exec(HEADER_PREFIX + parent_name,
                                   "rbd", "child_rm",
                                   {"snapid": sid,
                                    "name": clone_name})
            except Exception:
                pass
            raise
        try:
            await self.io.exec(DIR_OID, "rbd", "dir_add",
                               {"name": clone_name})
        except RadosError as e:
            if e.code != -17:
                raise

    async def open(self, name: str) -> "Image":
        hdr = HEADER_PREFIX + name
        try:
            meta = await self.io.exec(hdr, "rbd", "get_metadata", {})
            size = int(meta["size"])
            layout = FileLayout.decode(bytes(meta["layout"]))
        except Exception:
            raise RBDError("image %r does not exist" % name)
        snaps = dict(meta.get("snaps") or {})
        parent_meta = meta.get("parent")
        # each image gets its OWN IoCtx: snap context and read-snap
        # state are per-image (a shared ioctx would let one image's
        # _apply_snapc clobber another's write snapc)
        from ..client.rados import IoCtx
        img_io = IoCtx(self.io.client, self.io.pool_id)
        img = Image(img_io, name, size, layout, snaps)
        if parent_meta:
            pimg = await self.open(parent_meta["image"])
            # route the parent handle's reads at the snapshot
            psnap = next((n for n, r in pimg.snaps.items()
                          if int(r["id"]) == int(parent_meta
                                                 ["snapid"])), None)
            if psnap is not None:
                pimg.set_snap(psnap)
                img.parent = pimg
                img.parent_snapid = int(parent_meta["snapid"])
                img.overlap = int(parent_meta["overlap"])
        img._apply_snapc()
        return img


class Image:
    """One open image (librbd::Image): offset/length block I/O."""

    def __init__(self, ioctx, name: str, size: int,
                 layout: FileLayout, snaps: dict | None = None):
        self.io = ioctx
        self.name = name
        self._size = size
        self.layout = layout
        # name -> {"id": selfmanaged snapid, "size": image size then}
        self.snaps: dict = snaps or {}
        # clone linkage (parent Image handle pinned at the snap,
        # overlap = parent size at clone time); None = standalone
        self.parent: "Image | None" = None
        self.parent_snapid = 0
        self.overlap = 0

    def _data_name(self, objectno: int) -> str:
        return "%s%s.%016x" % (DATA_PREFIX, self.name, objectno)

    def size(self) -> int:
        return self._size

    # -- snapshots (librbd snap_create/rollback over selfmanaged
    # RADOS snaps; every data-object write carries the image snapc) --

    def _apply_snapc(self) -> None:
        ids = sorted((int(s["id"]) for s in self.snaps.values()),
                     reverse=True)
        self.io.set_selfmanaged_snapc(ids[0] if ids else 0, ids)

    def snap_list(self) -> dict[str, dict]:
        return dict(self.snaps)

    async def snap_create(self, snapname: str) -> int:
        """Selfmanaged snapid from the mon, then the header's snap
        table is edited by cls_rbd.snap_add — the exists check runs
        in-OSD, so racing snap_creates cannot both record."""
        from ..client.rados import RadosError

        if snapname in self.snaps:
            raise RBDError("snap %r exists" % snapname)
        sid = await self.io.selfmanaged_snap_create()
        try:
            await self.io.exec(HEADER_PREFIX + self.name, "rbd",
                               "snap_add", {"name": snapname,
                                            "snapid": sid,
                                            "size": self._size})
        except RadosError as e:
            # losing a snap_add race must not leak the allocated
            # snapid into the pool's snap bookkeeping forever
            try:
                await self.io.selfmanaged_snap_remove(sid)
            except Exception:
                pass
            if e.code == -17:
                raise RBDError("snap %r exists" % snapname) from None
            raise
        self.snaps[snapname] = {"id": sid, "size": self._size}
        self._apply_snapc()
        return sid

    async def snap_remove(self, snapname: str) -> None:
        rec = self.snaps.get(snapname)
        if rec is None:
            raise RBDError("no snap %r" % snapname)
        from ..client.rados import RadosError

        # clone children pin their parent snap: refuse before any
        # cluster-side state changes (the cls snap_remove gate
        # re-checks inside the atomic header edit)
        kids = await self.io.exec(HEADER_PREFIX + self.name, "rbd",
                                  "children", {})
        if any(int(c["snapid"]) == int(rec["id"])
               for c in kids.get("children", [])):
            raise RBDError("snap %r has clone children" % snapname)
        # cluster-side removal next: if the mon command fails the
        # header still records the snapid and removal can be retried
        # (dropping the record first would leak the clones forever)
        await self.io.selfmanaged_snap_remove(int(rec["id"]))
        try:
            await self.io.exec(HEADER_PREFIX + self.name, "rbd",
                               "snap_remove", {"name": snapname})
        except RadosError as e:
            if e.code != -2:
                # transient failure: the header still records the
                # snap — surface it so the caller retries rather
                # than silently resurrecting a dead snapid on reopen
                raise
        self.snaps.pop(snapname, None)
        self._apply_snapc()

    def set_snap(self, snapname: str | None) -> None:
        """Route reads to a snapshot (librbd snap_set); None = head.
        The image size follows the snapshot's recorded size, so reads
        through a pinned handle are bounded by what existed AT the
        snap — a later head resize must not clamp (or extend) them."""
        if snapname is None:
            self.io.set_read_snap(None)
            if getattr(self, "_head_size", None) is not None:
                self._size = self._head_size
                self._head_size = None
            return
        rec = self.snaps.get(snapname)
        if rec is None:
            raise RBDError("no snap %r" % snapname)
        if getattr(self, "_head_size", None) is None:
            self._head_size = self._size
        self._size = int(rec["size"])
        self.io.set_read_snap(int(rec["id"]))

    async def snap_rollback(self, snapname: str) -> None:
        """Restore head contents from a snapshot
        (librbd::Operations::snap_rollback): every data object is
        rewritten from its state at the snap (absent then = removed
        now), then the size reverts."""
        import asyncio

        rec = self.snaps.get(snapname)
        if rec is None:
            raise RBDError("no snap %r" % snapname)
        sid = int(rec["id"])
        snap_size = int(rec["size"])
        span = max(self._size, snap_size)
        objs = ({e[0] for e in file_to_extents(self.layout, 0, span)}
                if span else set())
        osz = self.layout.object_size

        async def roll(o):
            name = self._data_name(o)
            self.io.set_read_snap(sid)
            try:
                old = await self.io.read(name, osz, 0)
            except Exception:
                old = b""
            finally:
                self.io.set_read_snap(None)
            if old:
                await self.io.write_full(name, old)
            else:
                try:
                    await self.io.remove(name)
                except Exception:
                    pass

        await asyncio.gather(*[roll(o) for o in sorted(objs)])
        self._size = snap_size
        await self.io.exec(HEADER_PREFIX + self.name, "rbd",
                           "set_size", {"size": snap_size})

    async def resize(self, new_size: int) -> None:
        if new_size < self._size:
            # librbd shrink: drop whole objects past the new end AND
            # truncate the boundary object — a stale tail would
            # resurface as old data after a later grow (sparse reads
            # must see zeros)
            import asyncio

            old = file_to_extents(self.layout, new_size,
                                  self._size - new_size)
            keep = ({e[0] for e in
                     file_to_extents(self.layout, 0, new_size)}
                    if new_size > 0 else set())

            async def rm(o):
                try:
                    await self.io.remove(self._data_name(o))
                except Exception:
                    pass

            await asyncio.gather(*[
                rm(o) for o in {e[0] for e in old} - keep])
            # every kept straddling object trims to its smallest
            # dropped offset (striping can cut through several)
            cut: dict[int, int] = {}
            for o, oo, _ln, fo in old:
                if o in keep and fo >= new_size:
                    cut[o] = min(cut.get(o, 1 << 62), oo)
            for o, off in cut.items():
                try:
                    await self.io.truncate(self._data_name(o), off)
                except Exception:
                    pass
        self._size = new_size
        await self.io.exec(HEADER_PREFIX + self.name, "rbd",
                           "set_size", {"size": new_size})

    async def _copy_up(self, objectno: int) -> None:
        """librbd copy-up: materialize a clone object from the
        parent's SNAPSHOT before a partial write, so the untouched
        remainder of the block survives.  Reads the parent's DATA
        OBJECT directly (striping-exact for any stripe_count — the
        clone shares the parent's layout, so object numbering and
        interleave agree byte for byte)."""
        from ..client.rados import ObjectNotFound

        try:
            block = await self.parent.io.read(
                self.parent._data_name(objectno),
                self.layout.object_size, 0)
        except ObjectNotFound:
            return                      # parent never wrote it
        if block:
            await self.io.write_full(self._data_name(objectno),
                                     block)

    async def write(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self._size:
            raise RBDError("write past image end (%d > %d)"
                           % (offset + len(data), self._size))
        import asyncio

        from ..client.rados import ObjectNotFound

        exts = file_to_extents(self.layout, offset, len(data))
        osz = self.layout.object_size
        # group per object: one copy-up decision per object, and the
        # object's extents apply IN ORDER after it (two concurrent
        # copy-ups in one gather could clobber each other's writes)
        by_obj: dict[int, list] = {}
        for o, oo, ln, fo in exts:
            by_obj.setdefault(o, []).append((oo, ln, fo))

        async def put(o, pieces):
            whole = any(oo == 0 and ln == osz for oo, ln, _ in pieces)
            if self.parent is not None and not whole:
                # copy-up no-ops when the parent never wrote the
                # object, so no overlap math is needed here (file
                # offsets and object numbers interleave under
                # striping — the object read is the exact unit)
                try:
                    await self.io.stat(self._data_name(o))
                except ObjectNotFound:
                    await self._copy_up(o)
            for oo, ln, fo in pieces:
                await self.io.write(
                    self._data_name(o),
                    data[fo - offset:fo - offset + ln], oo)

        await asyncio.gather(*[put(o, pieces)
                               for o, pieces in by_obj.items()])

    async def read(self, offset: int, length: int) -> bytes:
        length = max(0, min(length, self._size - offset))
        if length == 0:
            return b""
        import asyncio

        from ..client.rados import ObjectNotFound

        exts = file_to_extents(self.layout, offset, length)

        async def fetch(o, oo, ln, fo):
            try:
                return await self.io.read(self._data_name(o), ln, oo)
            except ObjectNotFound:
                # COW fall-through: below the overlap the parent's
                # snapshot serves the bytes; past it, sparse zeros
                if self.parent is not None and fo < self.overlap:
                    cov = min(ln, self.overlap - fo)
                    return await self.parent.read(fo, cov)
                return b""
            except Exception:
                return b""     # unwritten extent: sparse zeros

        parts = await asyncio.gather(*[fetch(o, oo, ln, fo)
                                       for o, oo, ln, fo in exts])
        buf = bytearray(length)
        for (o, oo, ln, fo), part in zip(exts, parts):
            part = part[:ln]
            buf[fo - offset:fo - offset + len(part)] = part
        return bytes(buf)

    async def flatten(self) -> None:
        """Sever the parent link by materializing every still-COW
        object below the overlap (librbd::Operations::flatten)."""
        if self.parent is None:
            raise RBDError("image has no parent")
        import asyncio

        from ..client.rados import ObjectNotFound

        objs = ({e[0] for e in file_to_extents(self.layout, 0,
                                               self.overlap)}
                if self.overlap else set())
        osz = self.layout.object_size

        async def mat(o):
            try:
                await self.io.stat(self._data_name(o))
            except ObjectNotFound:
                await self._copy_up(o)

        await asyncio.gather(*[mat(o) for o in sorted(objs)])
        await self.io.exec(HEADER_PREFIX + self.name, "rbd",
                           "remove_parent", {})
        await self.io.exec(HEADER_PREFIX + self.parent.name, "rbd",
                           "child_rm", {"snapid": self.parent_snapid,
                                        "name": self.name})
        self.parent = None
        self.parent_snapid = 0
        self.overlap = 0

    async def discard(self, offset: int, length: int) -> None:
        """Zero a range by dropping fully-covered objects and zeroing
        partial ones (librbd discard).  On a clone, objects under the
        parent overlap are ZEROED, never removed — removal would
        resurrect the parent's bytes through the COW fall-through."""
        import asyncio

        exts = file_to_extents(self.layout, offset, length)
        full, partial = [], []
        osz = self.layout.object_size
        for o, oo, ln, fo in exts:
            covered = (self.parent is not None
                       and fo - oo < self.overlap)
            if oo == 0 and ln == osz and not covered:
                full.append(o)
            else:
                partial.append((ln, fo))

        async def rm(o):
            try:
                await self.io.remove(self._data_name(o))
            except Exception:
                pass

        await asyncio.gather(*[rm(o) for o in full])
        # partial zeroing routes through write() so clone objects get
        # their copy-up before the zeros land
        await asyncio.gather(*[self.write(fo, b"\0" * ln)
                               for ln, fo in partial])
