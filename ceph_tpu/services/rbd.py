"""RBD-lite: block images striped over RADOS objects.

Condensed analog of src/librbd (ImageCtx + the io/ dispatch layers)
over the striper: an image is a header object
(`rbd_header.<name>`: size + layout xattrs, the role rbd_header's
omap plays) plus data objects `rbd_data.<name>.<objectno>` addressed
by Striper::file_to_extents — the same object-map shape librbd uses
(`rbd_data.<image id>.<object no>`).  Reads of unwritten extents
return zeros (sparse images); writes allocate objects on demand.

Surface: RBD.create/remove/list/open -> Image.read/write/size/resize/
flatten-free sparse semantics.  Snapshots/clones/journaling are out of
this slice (SURVEY build plan step 9: "thin block layer as first
consumer")."""

from __future__ import annotations

from ..client.striper import FileLayout, file_to_extents

HEADER_PREFIX = "rbd_header."
DATA_PREFIX = "rbd_data."
DIR_OID = "rbd_directory"
SIZE_XATTR = "rbd.size"
LAYOUT_XATTR = "rbd.layout"


class RBDError(Exception):
    pass


class RBD:
    """Pool-level image operations (librbd::RBD)."""

    def __init__(self, ioctx):
        self.io = ioctx

    async def create(self, name: str, size: int,
                     layout: FileLayout | None = None) -> None:
        layout = layout or FileLayout(stripe_unit=1 << 22,
                                      stripe_count=1,
                                      object_size=1 << 22)
        hdr = HEADER_PREFIX + name
        try:
            await self.io.stat(hdr)
            raise RBDError("image %r exists" % name)
        except RBDError:
            raise
        except Exception:
            pass
        await self.io.write_full(hdr, b"")
        await self.io.setxattr(hdr, SIZE_XATTR, b"%d" % size)
        await self.io.setxattr(hdr, LAYOUT_XATTR, layout.encode())
        # image directory: one omap row per image (rbd_directory)
        await self.io.omap_set(DIR_OID, {name.encode(): b"1"})

    async def list(self) -> list[str]:
        try:
            kv = await self.io.omap_get(DIR_OID)
        except Exception:
            return []
        return sorted(k.decode() for k in kv)

    async def remove(self, name: str) -> None:
        img = await self.open(name)
        exts = file_to_extents(img.layout, 0, max(img._size, 1))
        import asyncio

        async def rm(o):
            try:
                await self.io.remove(img._data_name(o))
            except Exception:
                pass

        await asyncio.gather(*[rm(o) for o in
                               {e[0] for e in exts}])
        try:
            await self.io.remove(HEADER_PREFIX + name)
        except Exception:
            pass
        await self.io.omap_rm(DIR_OID, [name.encode()])

    async def open(self, name: str) -> "Image":
        hdr = HEADER_PREFIX + name
        try:
            size = int(await self.io.getxattr(hdr, SIZE_XATTR))
            layout = FileLayout.decode(
                await self.io.getxattr(hdr, LAYOUT_XATTR))
        except Exception:
            raise RBDError("image %r does not exist" % name)
        return Image(self.io, name, size, layout)


class Image:
    """One open image (librbd::Image): offset/length block I/O."""

    def __init__(self, ioctx, name: str, size: int,
                 layout: FileLayout):
        self.io = ioctx
        self.name = name
        self._size = size
        self.layout = layout

    def _data_name(self, objectno: int) -> str:
        return "%s%s.%016x" % (DATA_PREFIX, self.name, objectno)

    def size(self) -> int:
        return self._size

    async def resize(self, new_size: int) -> None:
        if new_size < self._size:
            # librbd shrink: drop whole objects past the new end AND
            # truncate the boundary object — a stale tail would
            # resurface as old data after a later grow (sparse reads
            # must see zeros)
            import asyncio

            old = file_to_extents(self.layout, new_size,
                                  self._size - new_size)
            keep = ({e[0] for e in
                     file_to_extents(self.layout, 0, new_size)}
                    if new_size > 0 else set())

            async def rm(o):
                try:
                    await self.io.remove(self._data_name(o))
                except Exception:
                    pass

            await asyncio.gather(*[
                rm(o) for o in {e[0] for e in old} - keep])
            for o, oo, _ln, fo in old:
                if o in keep and fo == new_size:
                    try:
                        await self.io.truncate(self._data_name(o), oo)
                    except Exception:
                        pass
                    break
        self._size = new_size
        await self.io.setxattr(HEADER_PREFIX + self.name, SIZE_XATTR,
                               b"%d" % new_size)

    async def write(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self._size:
            raise RBDError("write past image end (%d > %d)"
                           % (offset + len(data), self._size))
        import asyncio

        exts = file_to_extents(self.layout, offset, len(data))
        await asyncio.gather(*[
            self.io.write(self._data_name(o),
                          data[fo - offset:fo - offset + ln], oo)
            for o, oo, ln, fo in exts])

    async def read(self, offset: int, length: int) -> bytes:
        length = max(0, min(length, self._size - offset))
        if length == 0:
            return b""
        import asyncio

        exts = file_to_extents(self.layout, offset, length)

        async def fetch(o, oo, ln):
            try:
                return await self.io.read(self._data_name(o), ln, oo)
            except Exception:
                return b""     # unwritten extent: sparse zeros

        parts = await asyncio.gather(*[fetch(o, oo, ln)
                                       for o, oo, ln, _fo in exts])
        buf = bytearray(length)
        for (o, oo, ln, fo), part in zip(exts, parts):
            part = part[:ln]
            buf[fo - offset:fo - offset + len(part)] = part
        return bytes(buf)

    async def discard(self, offset: int, length: int) -> None:
        """Zero a range by dropping fully-covered objects and zeroing
        partial ones (librbd discard)."""
        import asyncio

        exts = file_to_extents(self.layout, offset, length)
        full, partial = [], []
        osz = self.layout.object_size
        for o, oo, ln, fo in exts:
            (full if (oo == 0 and ln == osz) else partial).append(
                (o, oo, ln))

        async def rm(o):
            try:
                await self.io.remove(self._data_name(o))
            except Exception:
                pass

        await asyncio.gather(*[rm(o) for o, _oo, _ln in full])
        await asyncio.gather(*[
            self.io.write(self._data_name(o), b"\0" * ln, oo)
            for o, oo, ln in partial])
