"""Services on RADOS (the reference's L7): RBD-lite block images."""
